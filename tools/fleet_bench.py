#!/usr/bin/env python
"""Scaled-out load generator + chaos gate for the fleet serving tier
(SERVING.md "Fleet tier & continuous batching").

Two phases, both seeded and deterministic in shape:

1. **Fleet chaos load**: N client threads hammer a ``Router`` over
   ``--replicas`` ModelServer replicas; one replica is killed abruptly
   mid-load (in-flight futures fail typed and are transparently
   requeued by the router) and the supervisor restarts it. Gates:

   - zero dropped or untyped futures — every submitted request
     resolves with a result or a typed ServingError;
   - every successful result is bit-identical to a fault-free
     single-executor reference;
   - the p99 request latency holds the ``--slo`` bound *through* the
     kill;
   - the killed replica comes back (supervisor restart) and serves
     bit-identical outputs post-recovery.

2. **Continuous-batching decode**: the same ragged sequence set is
   decoded through a continuous-admission :class:`DecodeEngine` and a
   stop-and-wait one (identical compiled step program). Gates: tokens
   bit-identical to each other AND to a per-sequence (one slot at a
   time) decode; continuous tokens/s beats stop-and-wait.

3. **Self-driving fleet** (SERVING.md "Self-driving fleet"): one
   replica + supervisor + :class:`Autoscaler`; a traffic ramp must
   scale the fleet out within a window, a mid-load kill must
   re-balance with zero dropped/untyped futures and bit-identical
   results, p99 must hold through both, idle must scale back to the
   floor, and a placement-budget overcommit must be rejected with a
   typed ``PlacementInfeasible`` naming the exceeded budget.

4. **AOT cold start**: a fresh replica's ``warmup()`` against a
   sealed ``PTPU_AOT_CACHE`` store must be measurably faster than the
   compiling cold start, bit-identical, with store save + hit
   journalled (gated via ``obs_report --require autoscale`` and
   ``--require coldstart``).

5. **Paged KV-cache + disaggregated prefill** (SERVING.md "Paged
   KV-cache & disaggregated prefill"): the same ragged set decoded
   paged vs slotted at EQUAL KV bytes must be bit-identical and
   faster with ~3x the sequences resident; then prompts stream
   through ``role='prefill'`` replicas into a local paged decode
   engine with one prefill replica killed mid-load — zero failures,
   oracle-exact tokens, p99 held, ``obs_report --require kvcache``
   green, and one trace tree spanning the prefill->decode hop.

6. **Telemetry plane** (OBSERVABILITY.md "Telemetry plane, SLOs &
   flight recorder"): a fleet's scrape endpoint is discovered from
   its ``PTPU_TELEMETRY_DIR`` port file and aggregated mid-load; a
   replica kill must dump a postmortem bundle ``postmortem.py`` can
   render; retiring the dead endpoint must drop its series from the
   merged exposition; a shed storm must breach the shed-ratio SLO's
   burn rate and recover once drained (gated via ``obs_report
   --require telemetry`` and ``--require slo``).

7. **Cross-host elastic fleet** (RESILIENCE.md "Cross-host
   elasticity"): a traffic ramp makes the autoscaler grow the fleet
   across the host boundary — a remote cell process spawned through
   ``RemoteBackend``, AOT-warmed from the parent's sealed store and
   heartbeating into the fleet dir; the remote "host" is SIGKILLed
   mid-load and must be detected inside the heartbeat window by the
   liveness probe (not an RPC deadline), every in-flight future typed
   or requeued bit-identically, p99 held, the supervisor rebuilding
   it through the same backend, and idle returning the fleet to the
   local floor (gated via ``obs_report --require remote_elastic``).

``--smoke`` runs a short schedule of both phases, writes an
observability journal and validates it via ``obs_report.py --require
fleet`` AND ``--require tracing`` semantics — including that the
kill-mid-load requeue leaves a span tree ``trace_report.py`` can
reconstruct end to end (``fleet/request -> fleet/requeue ->
serving/request``) — exiting nonzero if any invariant breaks; the CI
gate alongside ``chaos_bench.py --smoke`` and
``serve_bench.py --smoke``.

    python tools/fleet_bench.py --replicas 3            # full run
    python tools/fleet_bench.py --replicas 3 --smoke    # CI gate
    python tools/fleet_bench.py --replicas 2 --mesh 2   # sharded
"""
import argparse
import collections
import json
import os
import subprocess
import sys
import tempfile
import threading
import time

os.environ.setdefault('JAX_PLATFORMS', 'cpu')
sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

import numpy as np  # noqa: E402

IN_DIM, OUT_DIM = 16, 4


def _force_cpu():
    import jax
    try:
        jax.config.update('jax_platforms', 'cpu')
    except Exception:
        pass


def _build_artifact(workdir, seed=7, in_dim=IN_DIM, hidden=32,
                    out_dim=OUT_DIM, depth=1):
    import paddle_tpu.fluid as fluid
    exe = fluid.Executor(fluid.CPUPlace())
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = seed
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data(name='x', shape=[in_dim],
                                  dtype='float32')
            h = x
            for _ in range(depth):
                h = fluid.layers.fc(input=h, size=hidden, act='relu')
            y = fluid.layers.fc(input=h, size=out_dim, act=None)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        d = os.path.join(workdir, 'model')
        fluid.io.save_inference_model(d, ['x'], [y], exe,
                                      main_program=main)
    return d


def _reference_fn(model_dir):
    import paddle_tpu.fluid as fluid
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    prog, _, fetch_vars = fluid.io.load_inference_model(
        model_dir, exe, scope=scope)

    def run(x):
        out, = exe.run(prog, feed={'x': x}, fetch_list=fetch_vars,
                       scope=scope)
        return np.asarray(out)
    return run


def _percentile(xs, q):
    if not xs:
        return 0.0
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(q * len(xs)))]


def run_fleet_chaos(replicas=3, n_requests=120, clients=4, max_batch=8,
                    seed=1, slo_p99=2.5, mesh=1, kill=True):
    """Phase 1. Returns a result dict with ``problems`` (empty == all
    invariants held)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fleet import Router
    from paddle_tpu.serving import ModelServer, ServingError

    problems = []
    rng = np.random.RandomState(seed)
    inputs = [rng.randn(int(rng.randint(1, max_batch + 1)),
                        IN_DIM).astype('float32')
              for _ in range(n_requests)]
    partitioners = [None] * replicas
    if mesh and mesh > 1:
        from paddle_tpu.partition import dp_partitioners
        partitioners = dp_partitioners(replicas, mesh)

    with tempfile.TemporaryDirectory(prefix='fleet_bench_') as workdir:
        artifact = _build_artifact(workdir)
        reference = _reference_fn(artifact)
        expected = [reference(x) for x in inputs]

        def factory(rid):
            return ModelServer(place=fluid.CPUPlace(),
                               max_batch_size=max_batch,
                               max_queue_depth=max(64, n_requests),
                               partitioner=partitioners[rid],
                               watchdog_poll=0.02)

        router = Router(factory, replicas=replicas, poll_interval=0.05)
        outcomes = [None] * n_requests
        latencies = [None] * n_requests
        kill_at = n_requests // 2
        submitted = threading.Semaphore(0)
        t_start = time.monotonic()
        with router:
            router.load_model('m', artifact)

            def client(cid):
                for i in range(cid, n_requests, clients):
                    t0 = time.monotonic()
                    give_up = t0 + 30.0
                    req = None
                    while req is None:
                        try:
                            req = router.submit('m', {'x': inputs[i]})
                        except ServingError:
                            if time.monotonic() > give_up:
                                outcomes[i] = ('stuck', None)
                                break
                            time.sleep(0.01)
                    submitted.release()
                    if req is None:
                        continue
                    try:
                        out, = req.result(timeout=60.0)
                        outcomes[i] = ('ok', np.asarray(out))
                    except ServingError as e:
                        outcomes[i] = ('typed_error', e)
                    except Exception as e:  # noqa: BLE001 — judged
                        outcomes[i] = ('untyped_error', e)
                    latencies[i] = time.monotonic() - t0

            threads = [threading.Thread(target=client, args=(c,),
                                        daemon=True)
                       for c in range(clients)]
            for t in threads:
                t.start()
            victim = None
            if kill:
                # wait until half the load is in flight, then yank a
                # placed replica out from under it. Holding the
                # victim's batcher first guarantees the kill strands
                # queued requests (sub-ms batches would otherwise
                # drain before the SIGKILL lands), so the requeue
                # path — and its trace spans — provably exercise
                for _ in range(kill_at):
                    submitted.acquire()
                # ties in load score break toward the lowest replica
                # id, so that's where idle-time traffic lands — pick
                # it as the victim so the pause provably queues work
                victim = min(router.placement('m'))
                vsrv = router.replica(victim).server
                vsrv.pause('m')
                give_up = time.monotonic() + 10.0
                while vsrv.queue_depth('m') == 0 and \
                        time.monotonic() < give_up:
                    time.sleep(0.002)
                router.kill_replica(victim)
            for t in threads:
                t.join(120.0)
            wall = time.monotonic() - t_start

            # post-recovery: the supervisor must bring the victim back
            recovered_exact = None
            if victim is not None:
                give_up = time.monotonic() + 30.0
                while time.monotonic() < give_up and \
                        router.replica(victim).state != 'active':
                    time.sleep(0.05)
                rep = router.replica(victim)
                if rep.state != 'active':
                    problems.append(
                        'killed replica %d never restarted (state %r)'
                        % (victim, rep.state))
                    recovered_exact = False
                else:
                    out, = rep.server.infer('m', {'x': inputs[0]},
                                            timeout=30.0)
                    recovered_exact = np.array_equal(
                        np.asarray(out), expected[0])
                    if not recovered_exact:
                        problems.append(
                            'restarted replica %d output differs from '
                            'the reference' % victim)
            fleet_stats = router.stats()
            health = router.health()

        # ---- invariants --------------------------------------------------
        ok = sum(1 for o in outcomes if o and o[0] == 'ok')
        typed = sum(1 for o in outcomes if o and o[0] == 'typed_error')
        untyped = [repr(o[1]) for o in outcomes
                   if o and o[0] == 'untyped_error']
        dropped = sum(1 for o in outcomes if o is None) + \
            sum(1 for o in outcomes if o and o[0] == 'stuck')
        if untyped:
            problems.append('untyped client errors: %s' % untyped[:3])
        if dropped:
            problems.append('%d request(s) dropped/stuck' % dropped)
        if typed:
            # the router requeues replica failures internally; a typed
            # error surfacing means it ran out of healthy replicas,
            # which a 1-kill schedule over >=2 replicas must not hit
            problems.append(
                '%d request(s) failed typed despite %d surviving '
                'replica(s)' % (typed, replicas - 1))
        mismatches = sum(
            1 for i, o in enumerate(outcomes)
            if o and o[0] == 'ok' and
            not np.array_equal(o[1], expected[i]))
        if mismatches:
            problems.append(
                '%d result(s) differ from the fault-free reference'
                % mismatches)
        lats = [l for l in latencies if l is not None]
        p50, p99 = _percentile(lats, 0.50), _percentile(lats, 0.99)
        if p99 > slo_p99:
            problems.append(
                'p99 latency %.3fs exceeds the %.2fs SLO through the '
                'kill' % (p99, slo_p99))

    requeues = sum(r['restarts'] for r in
                   fleet_stats['replicas'].values())
    return {
        'config': {'replicas': replicas, 'n_requests': n_requests,
                   'clients': clients, 'max_batch': max_batch,
                   'seed': seed, 'slo_p99': slo_p99, 'mesh': mesh or 1,
                   'killed_replica': victim},
        'outcomes': {'ok': ok, 'typed_errors': typed,
                     'untyped_errors': len(untyped),
                     'dropped': dropped,
                     'recovered_bit_identical': recovered_exact,
                     'replica_restarts': requeues},
        'latency': {'p50_s': round(p50, 4), 'p99_s': round(p99, 4),
                    'max_s': round(max(lats), 4) if lats else 0.0},
        'throughput_rps': round(len(lats) / wall, 2) if wall else 0.0,
        'fleet': fleet_stats,
        'final_status': health['status'],
        'problems': problems,
    }


def run_decode_phase(slots=8, n_sequences=48, max_len=32, seed=3,
                     min_speedup=1.0):
    """Phase 2: continuous vs stop-and-wait decode over one ragged
    sequence set; exactness + tokens/s gates."""
    from paddle_tpu.fleet import DecodeEngine, recurrent_fc_cell

    problems = []
    rng = np.random.RandomState(seed)
    # heavily ragged: mostly short sequences, a long straggler per
    # slot-group — the occupancy hole stop-and-wait pays for
    lengths = [int(rng.randint(1, max_len // 4)) for _ in
               range(n_sequences)]
    for i in range(0, n_sequences, slots):
        lengths[i] = max_len
    hidden = 32
    inits = [{'h': rng.randn(hidden).astype('float32')}
             for _ in range(n_sequences)]

    def run_mode(admission):
        cell, specs = recurrent_fc_cell(dict_size=200, word_dim=16,
                                        hidden=hidden)
        eng = DecodeEngine(cell, specs, slots=slots, max_len=max_len,
                           end_id=None, seed=seed, admission=admission)
        eng.decode(init_states=inits[0], max_new_tokens=2)   # warm
        t0 = time.monotonic()
        reqs = [eng.submit(init_states=inits[i],
                           max_new_tokens=lengths[i])
                for i in range(n_sequences)]
        outs = [r.result(timeout=300.0) for r in reqs]
        wall = time.monotonic() - t0
        stats = eng.stats()
        eng.close()
        return outs, wall, stats

    cont, cont_wall, cont_stats = run_mode('continuous')
    sw, sw_wall, sw_stats = run_mode('stop_and_wait')

    # per-sequence reference: each sequence decoded alone
    cell, specs = recurrent_fc_cell(dict_size=200, word_dim=16,
                                    hidden=hidden)
    with DecodeEngine(cell, specs, slots=slots, max_len=max_len,
                      end_id=None, seed=seed) as eng:
        ref = [eng.decode(init_states=inits[i],
                          max_new_tokens=lengths[i], timeout=300.0)
               for i in range(n_sequences)]

    if not all(np.array_equal(a, b) for a, b in zip(cont, ref)):
        problems.append('continuous decode differs from per-sequence '
                        'decode')
    if not all(np.array_equal(a, b) for a, b in zip(sw, ref)):
        problems.append('stop-and-wait decode differs from '
                        'per-sequence decode')
    tokens = sum(lengths)
    cont_tps = tokens / cont_wall if cont_wall else 0.0
    sw_tps = tokens / sw_wall if sw_wall else 0.0
    speedup = cont_tps / sw_tps if sw_tps else 0.0
    if speedup <= min_speedup:
        problems.append(
            'continuous decode %.1f tok/s is not faster than '
            'stop-and-wait %.1f tok/s (speedup %.2fx <= %.2fx) at a '
            'ragged length distribution'
            % (cont_tps, sw_tps, speedup, min_speedup))
    return {
        'config': {'slots': slots, 'sequences': n_sequences,
                   'max_len': max_len, 'seed': seed,
                   'tokens': tokens},
        'continuous': {'tokens_per_sec': round(cont_tps, 1),
                       'steps': cont_stats['steps'],
                       'mean_occupancy':
                       round(cont_stats['mean_occupancy'], 4)},
        'stop_and_wait': {'tokens_per_sec': round(sw_tps, 1),
                          'steps': sw_stats['steps'],
                          'mean_occupancy':
                          round(sw_stats['mean_occupancy'], 4)},
        'speedup': round(speedup, 2),
        'exact_vs_per_sequence': not problems,
        'problems': problems,
    }


def run_autoscale_phase(max_replicas=3, n_requests=96, clients=4,
                        max_batch=8, seed=5, slo_p99=5.0,
                        scale_window_s=20.0, idle_window_s=25.0):
    """Closed-loop self-driving fleet phase (SERVING.md "Self-driving
    fleet"): start at ONE replica under a supervisor + autoscaler,
    ramp traffic until the autoscaler scales out, kill a replica
    mid-load (supervisor repairs, ring re-balances), then go idle and
    watch it scale back to the floor. Gates:

    - scale-up happens inside ``scale_window_s`` of sustained ramp;
    - the killed replica's work re-balances (no dropped/untyped
      futures) and every result is bit-identical to the fault-free
      reference;
    - p99 holds ``slo_p99`` through ramp + kill;
    - the fleet returns to one replica inside ``idle_window_s`` once
      traffic stops;
    - a placement-budget rejection is typed (PlacementInfeasible
      naming the exceeded budget), never an OOM-by-overcommit.
    """
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fleet import (Autoscaler, PlacementBudget,
                                  PlacementInfeasible, Router)
    from paddle_tpu.serving import ModelServer, ServingError

    problems = []
    rng = np.random.RandomState(seed)
    # requests heavy enough (milliseconds of matmul each) that a
    # client window of them is a real sustained queue on one replica —
    # a featherweight model drains faster than Python can submit and
    # the ramp would never register
    auto_in, auto_batch = 512, 128
    inputs = [rng.randn(auto_batch, auto_in).astype('float32')
              for _ in range(n_requests)]

    with tempfile.TemporaryDirectory(prefix='fleet_auto_') as workdir:
        artifact = _build_artifact(workdir, seed=seed, in_dim=auto_in,
                                   hidden=1024, out_dim=OUT_DIM,
                                   depth=2)
        reference = _reference_fn(artifact)
        expected = [reference(x) for x in inputs]

        def factory(rid):
            return ModelServer(place=fluid.CPUPlace(),
                               max_batch_size=auto_batch,
                               max_queue_depth=max(64, n_requests),
                               watchdog_poll=0.05)

        router = Router(factory, replicas=1, poll_interval=0.05,
                        placement_budget=PlacementBudget(
                            hbm_bytes=1 << 30))
        scaler = Autoscaler(router, min_replicas=1,
                            max_replicas=max_replicas,
                            high_queue=1.5, low_queue=0.25,
                            sustain=2, up_cooldown=0.5,
                            down_cooldown=1.0, interval=0.05)
        outcomes = [None] * n_requests
        latencies = [None] * n_requests
        t_start = time.monotonic()
        with router:
            router.load_model('m', artifact, hbm_bytes=1 << 20)

            # ---- ledger-informed admission control is typed --------------
            try:
                router.load_model('hog', artifact, hbm_bytes=2 << 30)
                problems.append('placement budget admitted a model '
                                'whose demand exceeds the HBM budget')
            except PlacementInfeasible as e:
                if 'hbm_bytes' not in str(e):
                    problems.append(
                        'PlacementInfeasible does not name the '
                        'exceeded budget: %r' % (e,))

            scaler.start()
            try:
                def client(cid):
                    # sliding submit window: each client keeps a batch
                    # of requests in flight, so the single replica's
                    # queue stays over the high watermark (sustained
                    # ramp) until the fleet grows to absorb it
                    pending = collections.deque()

                    def reap(down_to):
                        while len(pending) > down_to:
                            i, req, t0 = pending.popleft()
                            try:
                                out, = req.result(timeout=60.0)
                                outcomes[i] = ('ok', np.asarray(out))
                            except ServingError as e:
                                outcomes[i] = ('typed_error', e)
                            except Exception as e:  # noqa: BLE001
                                outcomes[i] = ('untyped_error', e)
                            latencies[i] = time.monotonic() - t0

                    for i in range(cid, n_requests, clients):
                        t0 = time.monotonic()
                        give_up = t0 + 30.0
                        req = None
                        while req is None:
                            try:
                                req = router.submit(
                                    'm', {'x': inputs[i]})
                            except ServingError:
                                if time.monotonic() > give_up:
                                    outcomes[i] = ('stuck', None)
                                    break
                                time.sleep(0.01)
                        if req is None:
                            continue
                        pending.append((i, req, t0))
                        reap(16)
                    reap(0)

                threads = [threading.Thread(target=client, args=(c,),
                                            daemon=True)
                           for c in range(clients)]
                for t in threads:
                    t.start()

                # gate 1: scale-up inside the window
                give_up = time.monotonic() + scale_window_s
                while time.monotonic() < give_up and \
                        scaler.scale_ups == 0:
                    time.sleep(0.05)
                scaled_up_s = time.monotonic() - t_start
                if scaler.scale_ups == 0:
                    problems.append(
                        'autoscaler never scaled out within %.0fs of '
                        'sustained ramp' % scale_window_s)

                # chaos mid-load: kill the newest replica; the
                # supervisor owns the repair, the ring re-balances
                killed = None
                if scaler.scale_ups:
                    with router._lock:
                        killed = max(router._replicas)
                    router.kill_replica(killed, abrupt=True)
                for t in threads:
                    t.join(120.0)

                # gate 4: idle -> back to the floor
                give_up = time.monotonic() + idle_window_s
                while time.monotonic() < give_up and \
                        len(router.stats()['replicas']) > 1:
                    time.sleep(0.1)
                final_replicas = len(router.stats()['replicas'])
                if final_replicas > 1:
                    problems.append(
                        'fleet never scaled back to the 1-replica '
                        'floor within %.0fs idle (still %d)'
                        % (idle_window_s, final_replicas))
            finally:
                scaler.stop()
            fleet_stats = router.stats()

        # ---- invariants --------------------------------------------------
        ok = sum(1 for o in outcomes if o and o[0] == 'ok')
        typed = sum(1 for o in outcomes if o and o[0] == 'typed_error')
        untyped = [repr(o[1]) for o in outcomes
                   if o and o[0] == 'untyped_error']
        dropped = sum(1 for o in outcomes if o is None) + \
            sum(1 for o in outcomes if o and o[0] == 'stuck')
        if untyped:
            problems.append('untyped client errors: %s' % untyped[:3])
        if dropped:
            problems.append('%d request(s) dropped/stuck' % dropped)
        if typed:
            problems.append('%d request(s) failed typed despite the '
                            'supervisor' % typed)
        mismatches = sum(
            1 for i, o in enumerate(outcomes)
            if o and o[0] == 'ok' and
            not np.array_equal(o[1], expected[i]))
        if mismatches:
            problems.append(
                '%d result(s) differ from the fault-free reference '
                'across scale-out + kill' % mismatches)
        lats = [l for l in latencies if l is not None]
        p50, p99 = _percentile(lats, 0.50), _percentile(lats, 0.99)
        if p99 > slo_p99:
            problems.append('p99 latency %.3fs exceeds the %.2fs SLO '
                            'through the ramp + kill' % (p99, slo_p99))

    return {
        'config': {'max_replicas': max_replicas,
                   'n_requests': n_requests, 'clients': clients,
                   'seed': seed, 'slo_p99': slo_p99,
                   'killed_replica': killed},
        'outcomes': {'ok': ok, 'typed_errors': typed,
                     'untyped_errors': len(untyped),
                     'dropped': dropped,
                     'scale_ups': scaler.scale_ups,
                     'scale_downs': scaler.scale_downs,
                     'scaled_up_after_s': round(scaled_up_s, 2),
                     'final_replicas': final_replicas},
        'latency': {'p50_s': round(p50, 4), 'p99_s': round(p99, 4)},
        'fleet': fleet_stats,
        'problems': problems,
    }


def run_coldstart_phase(min_speedup=1.5, seed=11):
    """AOT cold-start phase: warm a model on one server (compiles +
    seals the executables to the store), then measure a FRESH server's
    ``warmup()`` against the same store vs one compiling from scratch.
    Gates: warm warmup is ``min_speedup``x faster than cold, outputs
    bit-identical, and the store recorded both a save and a hit."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu import observability as obs
    from paddle_tpu.fleet import coldstart
    from paddle_tpu.serving import ModelServer

    problems = []
    rng = np.random.RandomState(seed)
    x = rng.randn(4, IN_DIM).astype('float32')

    def counter(name):
        m = obs.default_registry().get(name)
        return m.value if m is not None else 0

    def timed_warmup(store_dir, artifact):
        with coldstart.cache_scope(store_dir):
            with ModelServer(place=fluid.CPUPlace(),
                             max_batch_size=8) as srv:
                srv.load_model('m', artifact)
                t0 = time.monotonic()
                srv.warmup('m')
                wall = time.monotonic() - t0
                out = np.asarray(srv.submit(
                    'm', {'x': x}).result(timeout=30.0)[0])
        return wall, out

    with tempfile.TemporaryDirectory(prefix='fleet_cold_') as workdir:
        artifact = _build_artifact(workdir, seed=seed)
        store_dir = os.path.join(workdir, 'aot')
        saves0 = counter('coldstart_saves_total')
        hits0 = counter('coldstart_hits_total')
        # cold: fills the store (compile + seal)
        cold_wall, ref = timed_warmup(store_dir, artifact)
        if counter('coldstart_saves_total') <= saves0:
            problems.append('cold warmup sealed nothing to the AOT '
                            'store')
        # warm: a fresh replica (new server + executor, fresh compile
        # cache) deserializes instead of recompiling
        warm_wall, out = timed_warmup(store_dir, artifact)
        if counter('coldstart_hits_total') <= hits0:
            problems.append('warm warmup never hit the AOT store')
        if not np.array_equal(ref, out):
            problems.append('AOT-warmed replica output differs from '
                            'the compiling replica')
        speedup = cold_wall / warm_wall if warm_wall else float('inf')
        if speedup < min_speedup:
            problems.append(
                'AOT warm start %.1fms is not measurably faster than '
                'the %.1fms cold compile (%.2fx < %.2fx)'
                % (warm_wall * 1e3, cold_wall * 1e3, speedup,
                   min_speedup))
    return {
        'config': {'seed': seed, 'min_speedup': min_speedup},
        'cold_warmup_ms': round(cold_wall * 1e3, 1),
        'warm_warmup_ms': round(warm_wall * 1e3, 1),
        'speedup': round(speedup, 2),
        'bit_identical': np.array_equal(ref, out),
        'problems': problems,
    }


def _read_coldstart(journal_path):
    """(hits, saves, deserialize_wall_s) from a cell's own journal —
    each spawned cell writes its OWN file (trace_report merges them),
    so the parent checks the child's AOT behavior post-hoc here."""
    hits = saves = 0
    wall = 0.0
    try:
        with open(journal_path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(rec, dict) or \
                        rec.get('ev') != 'coldstart':
                    continue
                if rec.get('action') == 'hit':
                    hits += 1
                    wall += rec.get('dur_s', 0.0)
                elif rec.get('action') == 'save':
                    saves += 1
    except OSError:
        pass
    return hits, saves, wall


def run_remote_elastic_phase(clients=3, seed=13, slo_p99=10.0,
                             hb_window=2.0, scale_window_s=120.0,
                             detect_slack_s=3.0,
                             recovery_window_s=150.0,
                             idle_window_s=40.0):
    """Cross-host elastic fleet phase (RESILIENCE.md "Cross-host
    elasticity"): one local replica under supervisor + autoscaler with
    a fill-local-then-go-remote :class:`ReplicaBackend`; a traffic
    ramp forces the next replica across the host boundary — a cell
    PROCESS provisioned through :class:`RemoteBackend`, heartbeating
    into the fleet dir, its warmup replay AOT-warmed from the parent's
    sealed store. Then the remote "host" is SIGKILLed mid-load.
    Gates:

    - the remote replica comes up ACTIVE inside ``scale_window_s``
      and its warmup HIT the AOT store (child journal), with the
      deserialize wall measurably under the parent's cold compile;
    - the loss is detected inside the ``hb_window`` heartbeat window
      (+``detect_slack_s`` for one beat + one supervisor poll) by the
      liveness probe — the replica is unroutable without waiting on
      an RPC deadline;
    - every in-flight request resolves typed or transparently
      requeued, every result bit-identical to the fault-free
      reference, p99 inside ``slo_p99`` through spawn + kill +
      rebuild;
    - the supervisor rebuilds the replica through the same remote
      backend (fresh pid, fresh host id, AOT-warm again) and it
      serves bit-identical outputs;
    - idle traffic returns the fleet to the 1-replica local floor
      inside ``idle_window_s`` (the autoscaler retires the remote).

    The journal side of the same story is gated by ``obs_report
    --require remote_elastic`` (spawn_remote + in-window host_lost +
    requeue + retire).
    """
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fleet import (Autoscaler, RemoteBackend,
                                  ReplicaBackend, Router, coldstart)
    from paddle_tpu.serving import ModelServer, ServingError

    problems = []
    rng = np.random.RandomState(seed)
    re_in, re_batch = 512, 128
    pool = [rng.randn(re_batch, re_in).astype('float32')
            for _ in range(24)]

    with tempfile.TemporaryDirectory(prefix='fleet_rem_') as workdir:
        artifact = _build_artifact(workdir, seed=seed, in_dim=re_in,
                                   hidden=1024, out_dim=OUT_DIM,
                                   depth=2)
        reference = _reference_fn(artifact)
        expected = [reference(x) for x in pool]
        store_dir = os.path.join(workdir, 'aot')
        hb_dir = os.path.join(workdir, 'hb')

        def factory(rid):
            return ModelServer(place=fluid.CPUPlace(),
                               max_batch_size=re_batch,
                               max_queue_depth=256,
                               watchdog_poll=0.05)

        with coldstart.cache_scope(store_dir):
            # cold baseline: compile + seal the store in the parent —
            # the remote spawn below must beat this wall by
            # deserializing instead of recompiling
            t0 = time.monotonic()
            with ModelServer(place=fluid.CPUPlace(),
                             max_batch_size=re_batch) as srv:
                srv.load_model('m', artifact)
                srv.warmup('m')
            cold_wall = time.monotonic() - t0

            backend = RemoteBackend(hb_dir, window=hb_window,
                                    startup_grace=120.0,
                                    spawn_timeout=150.0,
                                    # the cell must accept the same
                                    # request envelope as the local
                                    # replicas it stands in for
                                    env={'PTPU_CELL_MAX_BATCH':
                                         str(re_batch),
                                         'PTPU_CELL_MAX_QUEUE': '256'})
            router = Router(factory, replicas=1, poll_interval=0.05,
                            remote_backend=backend)
            scaler = Autoscaler(
                router, min_replicas=1, max_replicas=2,
                high_queue=1.5, low_queue=0.25, sustain=2,
                up_cooldown=0.5, down_cooldown=1.0, interval=0.05,
                replica_backend=ReplicaBackend(local_max=1))

            results = []
            res_lock = threading.Lock()
            stop_load = threading.Event()
            t_start = time.monotonic()

            with router:
                router.load_model('m', artifact)
                scaler.start()
                try:
                    def client(cid):
                        pending = collections.deque()

                        def reap(down_to):
                            while len(pending) > down_to:
                                i, req, t0 = pending.popleft()
                                try:
                                    out, = req.result(timeout=120.0)
                                    rec = ('ok', i, np.asarray(out),
                                           time.monotonic() - t0)
                                except ServingError as e:
                                    rec = ('typed_error', i, e,
                                           time.monotonic() - t0)
                                except Exception as e:  # noqa: BLE001
                                    rec = ('untyped_error', i, e,
                                           time.monotonic() - t0)
                                with res_lock:
                                    results.append(rec)

                        k = cid
                        while not stop_load.is_set():
                            i = k % len(pool)
                            k += clients
                            try:
                                req = router.submit('m',
                                                    {'x': pool[i]})
                            except ServingError:
                                time.sleep(0.01)
                                continue
                            pending.append((i, req, time.monotonic()))
                            reap(8)
                        reap(0)

                    threads = [threading.Thread(target=client,
                                                args=(c,), daemon=True)
                               for c in range(clients)]
                    for t in threads:
                        t.start()

                    # gate 1: the ramp crosses the host boundary —
                    # an ACTIVE replica with backend='remote' inside
                    # the window
                    def remote_rep():
                        with router._lock:
                            for rep in router._replicas.values():
                                if rep.backend == 'remote':
                                    return rep
                        return None

                    give_up = time.monotonic() + scale_window_s
                    rep = None
                    while time.monotonic() < give_up:
                        rep = remote_rep()
                        if rep is not None and rep.state == 'active':
                            break
                        time.sleep(0.05)
                    scaled_up_s = time.monotonic() - t_start
                    spawned = rep is not None and rep.state == 'active'
                    detected_s = rebuilt_s = None
                    victim_journal = rebuilt_journal = None
                    if not spawned:
                        problems.append(
                            'the autoscaler never grew the fleet '
                            'across the host boundary within %.0fs '
                            'of sustained ramp' % scale_window_s)
                    else:
                        rid, victim = rep.id, rep.server
                        victim_journal = victim.journal_path
                        time.sleep(1.0)   # get load in flight on it

                        # chaos: SIGKILL the remote "host" mid-load
                        victim.kill()
                        t_kill = time.monotonic()

                        # gate 2: the liveness probe makes it
                        # unroutable inside the heartbeat window
                        give_up = t_kill + hb_window + detect_slack_s
                        while time.monotonic() < give_up:
                            with router._lock:
                                r2 = router._replicas.get(rid)
                                gone = (r2 is None
                                        or r2.server is not victim
                                        or r2.state != 'active')
                            if gone:
                                detected_s = \
                                    time.monotonic() - t_kill
                                break
                            time.sleep(0.005)
                        if detected_s is None:
                            problems.append(
                                'SIGKILLed remote host still routable '
                                '%.1fs later — outside its %.1fs '
                                'heartbeat window'
                                % (hb_window + detect_slack_s,
                                   hb_window))

                        # gate 3: the supervisor rebuilds it through
                        # the same backend (fresh pid, AOT-warm)
                        give_up = t_kill + recovery_window_s
                        while time.monotonic() < give_up:
                            with router._lock:
                                r2 = router._replicas.get(rid)
                                back = (r2 is not None
                                        and r2.server is not victim
                                        and r2.state == 'active')
                            if back:
                                rebuilt_s = time.monotonic() - t_kill
                                rebuilt_journal = \
                                    r2.server.journal_path
                                break
                            time.sleep(0.05)
                        if rebuilt_s is None:
                            problems.append(
                                'the supervisor never rebuilt the '
                                'lost remote replica within %.0fs'
                                % recovery_window_s)
                        else:
                            time.sleep(1.0)  # serve through the
                            # rebuilt cell so bit-identity covers it

                    stop_load.set()
                    for t in threads:
                        t.join(180.0)

                    # gate 4: idle -> back to the local floor (the
                    # autoscaler retires the remote replica)
                    give_up = time.monotonic() + idle_window_s
                    while time.monotonic() < give_up and \
                            len(router.stats()['replicas']) > 1:
                        time.sleep(0.1)
                    final = router.stats()['replicas']
                    if len(final) > 1:
                        problems.append(
                            'fleet never scaled back to the 1-replica '
                            'local floor within %.0fs idle (still %d)'
                            % (idle_window_s, len(final)))
                    elif remote_rep() is not None:
                        problems.append(
                            'the scale-in retired the LOCAL replica '
                            'and kept the remote one — the floor '
                            'must be local')
                finally:
                    scaler.stop()

        # ---- invariants --------------------------------------------------
        ok = sum(1 for r in results if r[0] == 'ok')
        typed = [repr(r[2]) for r in results if r[0] == 'typed_error']
        untyped = [repr(r[2]) for r in results
                   if r[0] == 'untyped_error']
        stuck = sum(1 for t in threads if t.is_alive())
        if not ok:
            problems.append('no request ever completed')
        if typed:
            problems.append(
                '%d request(s) failed typed despite requeue + '
                'supervisor: %s' % (len(typed), typed[:3]))
        if untyped:
            problems.append('untyped client errors: %s' % untyped[:3])
        if stuck:
            problems.append('%d client thread(s) stuck past the '
                            'join bound' % stuck)
        mismatches = sum(
            1 for r in results if r[0] == 'ok'
            and not np.array_equal(r[2], expected[r[1]]))
        if mismatches:
            problems.append(
                '%d result(s) differ from the fault-free reference '
                'across remote scale-out + host kill + rebuild'
                % mismatches)
        lats = [r[3] for r in results]
        p50, p99 = _percentile(lats, 0.50), _percentile(lats, 0.99)
        if p99 > slo_p99:
            problems.append('p99 latency %.3fs exceeds the %.2fs SLO '
                            'through spawn + kill + rebuild'
                            % (p99, slo_p99))

        # ---- AOT-warm gates (each cell journals to its OWN file) ---------
        aot = {'cold_compile_ms': round(cold_wall * 1e3, 1),
               'hits': 0, 'saves': 0, 'warm_wall_ms': None}
        if victim_journal:
            hits, saves, warm_wall = _read_coldstart(victim_journal)
            aot.update(hits=hits, saves=saves,
                       warm_wall_ms=round(warm_wall * 1e3, 1))
            if not hits:
                problems.append(
                    'the remote replica warmup never hit the sealed '
                    'AOT store — the cross-host cold start '
                    'recompiled from scratch')
            elif warm_wall >= cold_wall:
                problems.append(
                    'AOT-warm remote startup deserialize %.0fms is '
                    'not measurably faster than the %.0fms cold '
                    'compile' % (warm_wall * 1e3, cold_wall * 1e3))
            if rebuilt_journal:
                rhits, _, _ = _read_coldstart(rebuilt_journal)
                if not rhits:
                    problems.append(
                        'the REBUILT remote replica never hit the '
                        'AOT store — the supervisor repair path '
                        'lost the cache export')

    return {
        'config': {'clients': clients, 'seed': seed,
                   'slo_p99': slo_p99, 'hb_window_s': hb_window},
        'outcomes': {'ok': ok, 'typed_errors': len(typed),
                     'untyped_errors': len(untyped), 'stuck': stuck,
                     'scaled_up_after_s': round(scaled_up_s, 2),
                     'detected_after_s':
                         round(detected_s, 3)
                         if detected_s is not None else None,
                     'rebuilt_after_s':
                         round(rebuilt_s, 2)
                         if rebuilt_s is not None else None,
                     'final_replicas': len(final)},
        'aot': aot,
        'latency': {'p50_s': round(p50, 4), 'p99_s': round(p99, 4)},
        'problems': problems,
    }


def run_kvcache_phase(seed=3, n_sequences=96, n_prompts=12,
                      min_speedup=1.0, min_resident_ratio=2.9,
                      slo_p99=30.0):
    """Paged KV-cache + disaggregated prefill phase (SERVING.md
    "Paged KV-cache & disaggregated prefill").

    Part A — paged vs slotted at EQUAL KV bytes: the same ragged
    sequence set decodes through the PR 9 slotted engine (8 slots x
    dense ``max_len`` KV) and a paged engine whose page pool holds the
    same bytes but serves 24 resident sequences. Gates: tokens
    bit-identical to the slotted engine AND to a per-sequence (slots=1)
    decode; paged tokens/s beats slotted; sequences-resident capacity
    ratio exceeds ``min_resident_ratio``.

    Part B — disaggregated prefill as placement: a Router over
    ``role='prefill'`` replicas plus a serve replica; prompts stream
    through :class:`DisaggregatedDecoder` (prefill remote-to-the-
    engine, decode local), one prefill replica is killed mid-load.
    Gates: every request completes bit-identical to the slotted
    oracle through the kill; p99 holds; the journal holds the
    ``kvcache`` events the obs gate requires and a trace tree
    spanning the prefill->decode hop.
    """
    import paddle_tpu.kvcache as kvc
    from paddle_tpu.fleet import Router
    from paddle_tpu.fleet.decode import (DecodeEngine,
                                         attention_history_cell)

    problems = []
    dict_size, word_dim, hidden, max_len = 64, 16, 32, 32
    page_size, num_pages = 8, 32
    slotted_slots, paged_slots = 8, 24
    # equal KV bytes by construction: 8 slots x 32 positions dense ==
    # 32 pages x 8 positions pooled
    assert slotted_slots * max_len == num_pages * page_size
    spec = kvc.stock_spec(dict_size, word_dim=word_dim, hidden=hidden,
                          max_len=max_len, page_size=page_size,
                          num_pages=num_pages, seed=seed)
    rng = np.random.RandomState(seed)
    # heavily ragged: mostly short, a half-max straggler per eighth —
    # the shape where dense per-slot KV strands the most memory (the
    # slotted engine commits max_len positions per admission either
    # way; the paged one commits ceil(len/page_size) pages)
    lengths = [int(rng.randint(1, 7)) for _ in range(n_sequences)]
    for i in range(0, n_sequences, 8):
        lengths[i] = max_len // 2
    firsts = [int(rng.randint(1, dict_size)) for _ in
              range(n_sequences)]

    def run_slotted(slots):
        cell, specs = attention_history_cell(
            dict_size, word_dim=word_dim, hidden=hidden,
            max_len=max_len)
        eng = DecodeEngine(cell, specs, slots=slots, max_len=max_len,
                           seed=seed)
        eng.decode(first_id=1, max_new_tokens=2)   # warm the compile
        t0 = time.monotonic()
        reqs = [eng.submit(first_id=firsts[i],
                           max_new_tokens=lengths[i])
                for i in range(n_sequences)]
        outs = [r.result(timeout=300.0) for r in reqs]
        wall = time.monotonic() - t0
        stats = eng.stats()
        eng.close()
        return outs, wall, stats

    def run_paged():
        eng, pool = kvc.make_paged_engine(spec, slots=paged_slots)
        eng.decode(first_id=1, max_new_tokens=2)   # warm the compile
        t0 = time.monotonic()
        reqs = [eng.submit(first_id=firsts[i],
                           max_new_tokens=lengths[i])
                for i in range(n_sequences)]
        outs = [r.result(timeout=300.0) for r in reqs]
        wall = time.monotonic() - t0
        stats = eng.stats()
        eng.close()
        return outs, wall, stats

    slotted, slotted_wall, slotted_stats = run_slotted(slotted_slots)
    paged, paged_wall, paged_stats = run_paged()
    if not all(np.array_equal(a, b) for a, b in zip(paged, slotted)):
        problems.append('paged decode differs from the slotted engine')
    # per-sequence reference: one slot at a time
    per_seq, _, _ = run_slotted(1)
    if not all(np.array_equal(a, b) for a, b in zip(paged, per_seq)):
        problems.append('paged decode differs from per-sequence decode')

    tokens = sum(lengths)
    paged_tps = tokens / paged_wall if paged_wall else 0.0
    slotted_tps = tokens / slotted_wall if slotted_wall else 0.0
    speedup = paged_tps / slotted_tps if slotted_tps else 0.0
    if speedup <= min_speedup:
        problems.append(
            'paged decode %.1f tok/s is not faster than slotted '
            '%.1f tok/s (%.2fx <= %.2fx) at equal KV bytes on a '
            'ragged length distribution'
            % (paged_tps, slotted_tps, speedup, min_speedup))
    resident_ratio = paged_slots / float(slotted_slots)
    if resident_ratio <= min_resident_ratio:
        problems.append(
            'paged engine holds %.1fx the slotted resident sequences '
            'at equal KV bytes (<= %.1fx bound)'
            % (resident_ratio, min_resident_ratio))

    # ---- part B: disaggregated prefill through the Router ---------------
    # slotted oracle for prompt continuations: a greedy prefix of the
    # slotted decode IS a teacher-forced prompt, so prefilling it must
    # reproduce the remaining tokens exactly
    mnt = 12
    oracle = {}
    cell, specs = attention_history_cell(dict_size, word_dim=word_dim,
                                         hidden=hidden, max_len=max_len)
    with DecodeEngine(cell, specs, slots=4, max_len=max_len,
                      seed=seed) as eng:
        for p in range(1, n_prompts + 1):
            oracle[p] = eng.decode(first_id=p, max_new_tokens=mnt,
                                   timeout=300.0)

    def factory(rid):
        if rid < 2:
            return kvc.PrefillServer()
        from paddle_tpu.serving import ModelServer
        return ModelServer()

    results = [None] * n_prompts
    latencies = [None] * n_prompts
    router = Router(factory, replicas=3, replication=2,
                    poll_interval=0.05)
    with router:
        pf_ids = router.register_prefill('pf', spec, warmup=False)
        if not all(router.replica(r).role == 'prefill'
                   for r in pf_ids):
            problems.append('prefill model placed on a non-prefill '
                            'replica: %s' % pf_ids)
        dec = kvc.DisaggregatedDecoder(router, 'pf', spec,
                                       slots=paged_slots)
        dec.decode([1], 2, timeout=120.0)          # warm the compile

        def client(i):
            # prompt: first token + a greedy prefix of the oracle
            k = 1 + (i % 4)
            p = i + 1
            prompt = np.concatenate([[p], oracle[p][:k - 1]])
            t0 = time.monotonic()
            try:
                out = dec.decode(prompt, mnt - k + 1, timeout=120.0)
                results[i] = ('ok', out, k)
            except Exception as e:  # noqa: BLE001 — judged below
                results[i] = ('error', e, k)
            latencies[i] = time.monotonic() - t0

        threads = [threading.Thread(target=client, args=(i,),
                                    daemon=True)
                   for i in range(n_prompts)]
        for t in threads[:n_prompts // 2]:
            t.start()
        # mid-load chaos: yank a prefill replica; routed prompts fail
        # typed (ServerClosed) and requeue onto the survivor
        router.kill_replica(pf_ids[0])
        for t in threads[n_prompts // 2:]:
            t.start()
        for t in threads:
            t.join(240.0)
        dec.close()

    failed = [repr(r[1]) for r in results if r and r[0] == 'error']
    if failed:
        problems.append('disagg request(s) failed through the prefill '
                        'kill: %s' % failed[:3])
    hung = sum(1 for r in results if r is None)
    if hung:
        problems.append('%d disagg request(s) never resolved' % hung)
    mismatches = 0
    for i, r in enumerate(results):
        if r is None or r[0] != 'ok':
            continue
        _, out, k = r
        if not np.array_equal(out, oracle[i + 1][k - 1:]):
            mismatches += 1
    if mismatches:
        problems.append('%d disagg result(s) differ from the slotted '
                        'oracle' % mismatches)
    lats = [l for l in latencies if l is not None]
    p99 = _percentile(lats, 0.99)
    if p99 > slo_p99:
        problems.append('disagg p99 %.3fs exceeds the %.2fs bound '
                        'through the prefill-replica kill'
                        % (p99, slo_p99))

    return {
        'config': {'seed': seed, 'sequences': n_sequences,
                   'prompts': n_prompts, 'max_len': max_len,
                   'page_size': page_size, 'num_pages': num_pages,
                   'slotted_slots': slotted_slots,
                   'paged_slots': paged_slots, 'tokens': tokens},
        'paged': {'tokens_per_sec': round(paged_tps, 1),
                  'steps': paged_stats['steps'],
                  'pool': paged_stats.get('pool')},
        'slotted': {'tokens_per_sec': round(slotted_tps, 1),
                    'steps': slotted_stats['steps']},
        'decode_paged_speedup': round(speedup, 2),
        'sequences_resident_ratio': round(resident_ratio, 2),
        'disagg': {'ok': sum(1 for r in results
                             if r and r[0] == 'ok'),
                   'failed': len(failed), 'hung': hung,
                   'p99_s': round(p99, 4),
                   'killed_prefill_replica': pf_ids[0]},
        'problems': problems,
    }


def run_telemetry_phase(replicas=2, n_requests=64, clients=3,
                        max_batch=8, seed=9, shed_target=24,
                        slo_windows=(2.0, 8.0)):
    """Fleet telemetry-plane phase (OBSERVABILITY.md "Telemetry
    plane, SLOs & flight recorder"): a live fleet is scraped, killed,
    retired, and budget-accounted end to end.

    - **serve + discover**: the process stands up its scrape endpoint
      publishing a ``PTPU_TELEMETRY_DIR`` port file; a
      :class:`TelemetryAggregator` must discover it from the directory
      alone and scrape real ``serving_*`` series mid-load
      (``fleet_qps`` goes positive). In-process replicas share one
      scrape surface, so each is additionally registered as a
      ``replica=<id>``-labelled endpoint — the same label-stamped
      republish the multi-host launcher contract produces.
    - **kill -> bundle**: one replica is killed mid-load with the
      flight recorder's bundle directory configured; the
      ``replica_kill`` trip must dump a postmortem bundle naming the
      victim, and ``tools/postmortem.py`` must render it (exit 0).
    - **retire**: retiring the victim's endpoint must remove every
      series carrying its label from the merged exposition.
    - **SLO burn**: a shed storm (servers paused, queue flooded past
      admission) must drive the shed-ratio SLO's burn rate past
      breach across every window, and draining the storm must recover
      it — both transitions journalled for the ``obs_report
      --require slo`` gate. The engine's ``slo_burn_rate`` gauge
      rides the same scrape surface the aggregator merges.
    """
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fleet import Router
    from paddle_tpu.observability import flight, telemetry
    from paddle_tpu.observability.slo import SLO, SLOEngine
    from paddle_tpu.serving import ModelServer, ServingError

    problems = []
    rng = np.random.RandomState(seed)
    inputs = [rng.randn(int(rng.randint(1, max_batch + 1)),
                        IN_DIM).astype('float32')
              for _ in range(n_requests)]

    with tempfile.TemporaryDirectory(prefix='fleet_tel_') as workdir:
        artifact = _build_artifact(workdir, seed=seed)
        tel_dir = os.path.join(workdir, 'telemetry')
        bundle_dir = os.path.join(workdir, 'flight')
        prev_flight = flight.configure(bundle_dir)
        flight.clear()
        srv_tel = telemetry.serve_telemetry(port_dir=tel_dir,
                                            name='serve')
        engine = SLOEngine(
            [SLO.ratio('shed_ratio',
                       bad='serving_requests_shed_total',
                       total='serving_requests_submitted_total',
                       objective=0.98)],
            windows=slo_windows)
        agg = telemetry.TelemetryAggregator()
        outcomes = [None] * n_requests
        submitted = threading.Semaphore(0)
        stop_scraping = threading.Event()
        scrape_summaries = []
        peak = {'qps': 0.0, 'burn': 0.0}

        def factory(rid):
            return ModelServer(place=fluid.CPUPlace(),
                               max_batch_size=max_batch,
                               max_queue_depth=max(64, n_requests),
                               watchdog_poll=0.02)

        try:
            router = Router(factory, replicas=replicas,
                            poll_interval=0.05)
            with router:
                router.load_model('m', artifact)

                # discovery: the published port file alone is enough
                stems = agg.add_dir(tel_dir)
                if 'serve' not in stems:
                    problems.append(
                        'PTPU_TELEMETRY_DIR discovery found %r, not '
                        'the published "serve" endpoint' % (stems,))
                for rid in router.placement('m'):
                    agg.add_endpoint('replica-%d' % rid, srv_tel.port,
                                     replica=str(rid))
                n_endpoints = len(agg.endpoints())

                def client(cid):
                    for i in range(cid, n_requests, clients):
                        give_up = time.monotonic() + 30.0
                        req = None
                        while req is None:
                            try:
                                req = router.submit('m',
                                                    {'x': inputs[i]})
                            except ServingError:
                                if time.monotonic() > give_up:
                                    outcomes[i] = ('stuck', None)
                                    break
                                time.sleep(0.01)
                        submitted.release()
                        if req is None:
                            continue
                        try:
                            req.result(timeout=60.0)
                            outcomes[i] = ('ok', None)
                        except ServingError as e:
                            outcomes[i] = ('typed_error', e)
                        except Exception as e:  # noqa: BLE001
                            outcomes[i] = ('untyped_error', e)
                        # pace the load so it spans several scrapes
                        time.sleep(0.02)

                def scraper():
                    while not stop_scraping.is_set():
                        s = agg.scrape_once(timeout=5.0)
                        scrape_summaries.append(s)
                        peak['qps'] = max(peak['qps'],
                                          s['fleet_qps'])
                        engine.tick()
                        stop_scraping.wait(0.05)

                threads = [threading.Thread(target=client, args=(c,),
                                            daemon=True)
                           for c in range(clients)]
                for t in threads:
                    t.start()
                scr = threading.Thread(target=scraper, daemon=True)
                scr.start()

                # ---- kill mid-load: the trip must dump a bundle ----
                for _ in range(n_requests // 2):
                    submitted.acquire()
                victim = min(router.placement('m'))
                vsrv = router.replica(victim).server
                vsrv.pause('m')
                give_up = time.monotonic() + 10.0
                while vsrv.queue_depth('m') == 0 and \
                        time.monotonic() < give_up:
                    time.sleep(0.002)
                router.kill_replica(victim)
                bundle_path = flight.last_bundle()
                for t in threads:
                    t.join(120.0)
                stop_scraping.set()
                scr.join(30.0)

                if bundle_path is None:
                    problems.append('replica kill tripped no '
                                    'postmortem bundle')
                else:
                    try:
                        bundle = flight.read_bundle(bundle_path)
                    except (OSError, ValueError) as e:
                        bundle = None
                        problems.append('kill bundle unreadable: %r'
                                        % (e,))
                    if bundle is not None:
                        if bundle['reason'] != 'replica_kill':
                            problems.append(
                                'kill bundle reason is %r, not '
                                'replica_kill' % (bundle['reason'],))
                        if bundle['context'].get('replica') != victim:
                            problems.append(
                                'kill bundle names replica %r, not '
                                'the victim %d'
                                % (bundle['context'].get('replica'),
                                   victim))
                    pm = subprocess.run(
                        [sys.executable,
                         os.path.join(
                             os.path.dirname(os.path.abspath(
                                 __file__)), 'postmortem.py'),
                         bundle_path],
                        capture_output=True, text=True)
                    if pm.returncode != 0 or \
                            'replica_kill' not in pm.stdout:
                        problems.append(
                            'postmortem.py could not render the kill '
                            'bundle (rc %d): %s'
                            % (pm.returncode,
                               (pm.stderr or pm.stdout)[-200:]))

                # ---- retire: the victim's series must vanish -------
                agg.scrape_once(timeout=5.0)
                removed = agg.retire('replica-%d' % victim)
                if removed <= 0:
                    problems.append('retiring the killed replica '
                                    'endpoint removed no series')
                agg.scrape_once(timeout=5.0)
                # only the victim endpoint stamps replica=<victim>
                # with no host label — the surviving host endpoint
                # republishes the router's own per-replica gauges
                # (e.g. fleet_replica_state{replica=...}) under
                # host=serve, and those rightly survive the retire
                leftover = [
                    name for name, entry in
                    agg.registry.snapshot().items()
                    for s in entry['series']
                    if s['labels'].get('replica') == str(victim) and
                    'host' not in s['labels']]
                if leftover:
                    problems.append(
                        'retired replica %d still has %d series in '
                        'the merged exposition (e.g. %s)'
                        % (victim, len(leftover), leftover[0]))

                # ---- shed storm -> breach -> drain -> recovery -----
                stormed = sorted(router.placement('m'))
                for rid in stormed:
                    router.replica(rid).server.pause('m')
                backlog, sheds = [], 0
                give_up = time.monotonic() + 30.0
                while sheds < shed_target and \
                        time.monotonic() < give_up:
                    try:
                        backlog.append(
                            router.submit('m', {'x': inputs[0]}))
                    except ServingError:
                        sheds += 1
                        r = engine.tick()['shed_ratio']
                        peak['burn'] = max(peak['burn'],
                                           r['burn_rate'])
                if sheds < shed_target:
                    problems.append(
                        'shed storm produced only %d/%d sheds'
                        % (sheds, shed_target))
                breached = False
                give_up = time.monotonic() + 10.0
                while time.monotonic() < give_up:
                    r = engine.tick()['shed_ratio']
                    peak['burn'] = max(peak['burn'], r['burn_rate'])
                    if r['breached']:
                        breached = True
                        break
                    time.sleep(0.05)
                if not breached:
                    problems.append(
                        'shed storm never drove the SLO burn rate '
                        'past breach (peak %.2fx)' % peak['burn'])
                for rid in stormed:
                    router.replica(rid).server.resume('m')
                for fut in backlog:
                    try:
                        fut.result(timeout=60.0)
                    except ServingError:
                        pass
                t_rec = time.monotonic()
                give_up = t_rec + max(slo_windows) * 3 + 5.0
                recovered = False
                while time.monotonic() < give_up:
                    if not engine.tick()['shed_ratio']['breached']:
                        recovered = True
                        break
                    time.sleep(0.1)
                recover_s = time.monotonic() - t_rec
                if breached and not recovered:
                    problems.append(
                        'SLO burn never recovered within %.0fs of the '
                        'storm draining' % (give_up - t_rec))
        finally:
            stop_scraping.set()
            srv_tel.close()
            flight.configure(prev_flight)

        # ---- invariants --------------------------------------------------
        untyped = [repr(o[1]) for o in outcomes
                   if o and o[0] == 'untyped_error']
        dropped = sum(1 for o in outcomes
                      if o is None or o[0] == 'stuck')
        if untyped:
            problems.append('untyped client errors: %s' % untyped[:3])
        if dropped:
            problems.append('%d request(s) dropped/stuck' % dropped)
        if not any(s['scraped'] == s['endpoints'] and s['endpoints']
                   for s in scrape_summaries):
            problems.append('no mid-load scrape reached every '
                            'endpoint')
        if peak['qps'] <= 0.0:
            problems.append('fleet_qps never went positive across '
                            '%d mid-load scrapes'
                            % len(scrape_summaries))

    return {
        'config': {'replicas': replicas, 'n_requests': n_requests,
                   'clients': clients, 'seed': seed,
                   'slo_windows': list(slo_windows),
                   'shed_target': shed_target,
                   'killed_replica': victim},
        'endpoints': n_endpoints,
        'scrapes': len(scrape_summaries),
        'peak_fleet_qps': round(peak['qps'], 2),
        'bundle': bundle_path,
        'retired_series': removed,
        'slo': {'sheds': sheds,
                'peak_burn': round(peak['burn'], 2),
                'breached': breached,
                'recovered_after_s': round(recover_s, 2)},
        'problems': problems,
    }


def check_disagg_trace(journal_path):
    """Tracing gate for the disaggregation phase: at least one
    ``kvcache/request`` root must reconstruct with BOTH legs under it
    — the routed prefill (``fleet/request`` parenting a closed
    ``kvcache/prefill``) and the local continuation
    (``decode/request``) — one tree spanning the prefill->decode hop.
    Returns a list of problems."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from trace_report import build_store
    store = build_store([journal_path])
    roots = store.by_kind('kvcache/request').get('kvcache/request', [])
    for sp in roots:
        kids = [store.spans[c]
                for c in store.children.get(sp['span'], [])]
        has_decode = any(k['name'] == 'decode/request' for k in kids)
        has_prefill = False
        for hop in kids:
            if hop['name'] != 'fleet/request':
                continue
            under = [store.spans[c]
                     for c in store.children.get(hop['span'], [])]
            if any(u['name'] == 'kvcache/prefill' and u['closed']
                   for u in under):
                has_prefill = True
        if has_decode and has_prefill:
            return []
    if not roots:
        return ['tracing: journal holds no kvcache/request span — '
                'the disaggregated path is not traced']
    return ['tracing: %d kvcache/request span(s) found but none '
            'reconstructs a full kvcache/request -> {fleet/request '
            '-> kvcache/prefill, decode/request} tree spanning the '
            'hop' % len(roots)]


def check_requeue_trace(journal_path):
    """Tracing gate for the kill-mid-load smoke: the journal must hold
    at least one requeued request whose span tree reconstructs end to
    end — a ``fleet/request`` root with a ``fleet/requeue`` hop child
    that itself parents a ``serving/request`` attempt on the replica
    the request was moved to. Returns a list of problems."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from trace_report import build_store
    store = build_store([journal_path])
    requeued = 0
    for sp in store.by_kind('fleet/request').get('fleet/request', []):
        if not sp['fields'].get('requeues'):
            continue
        requeued += 1
        hops = [store.spans[c]
                for c in store.children.get(sp['span'], [])
                if store.spans[c]['name'] == 'fleet/requeue']
        for hop in hops:
            under = [store.spans[c]
                     for c in store.children.get(hop['span'], [])]
            if any(u['name'] == 'serving/request' and u['closed']
                   for u in under):
                return []
    if requeued == 0:
        return ['tracing: journal holds no requeued fleet/request '
                'span despite the kill — requeue hops are not traced']
    return ['tracing: %d requeued fleet/request span(s) found but '
            'none reconstructs a full fleet/request -> fleet/requeue '
            '-> serving/request tree' % requeued]


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split('\n')[0])
    ap.add_argument('--replicas', type=int, default=3)
    ap.add_argument('--requests', type=int, default=240)
    ap.add_argument('--clients', type=int, default=4)
    ap.add_argument('--max-batch', type=int, default=8)
    ap.add_argument('--seed', type=int, default=1)
    ap.add_argument('--slo', type=float, default=2.5,
                    help='p99 request-latency bound (seconds), held '
                         'through the replica kill')
    ap.add_argument('--mesh', type=int, default=1,
                    help='devices per replica: shard each replica '
                         'over its own disjoint dp mesh')
    ap.add_argument('--no-kill', action='store_true',
                    help='skip the chaos kill (pure load run)')
    ap.add_argument('--no-decode-phase', action='store_true')
    ap.add_argument('--no-autoscale-phase', action='store_true')
    ap.add_argument('--no-coldstart-phase', action='store_true')
    ap.add_argument('--no-kvcache-phase', action='store_true')
    ap.add_argument('--no-telemetry-phase', action='store_true')
    ap.add_argument('--no-remote-phase', action='store_true',
                    help='skip the cross-host elastic phase (spawns '
                         'real cell processes)')
    ap.add_argument('--smoke', action='store_true',
                    help='short seeded schedule; exit nonzero if any '
                         'fleet or decode invariant breaks')
    ap.add_argument('--journal', default=None, metavar='PATH',
                    help='write an observability run journal here '
                         '(default under --smoke: a temp file, gated '
                         'via obs_report --require fleet)')
    ap.add_argument('--json', default=None,
                    help='write the full result dict to this path')
    args = ap.parse_args(argv)
    if args.replicas < 2 and not args.no_kill:
        ap.error('--replicas must be >= 2 for the kill phase '
                 '(use --no-kill)')
    need = args.replicas * args.mesh
    if args.mesh > 1 and 'xla_force_host_platform_device_count' not in \
            os.environ.get('XLA_FLAGS', ''):
        os.environ['XLA_FLAGS'] = (
            os.environ.get('XLA_FLAGS', '') +
            ' --xla_force_host_platform_device_count=%d' % need).strip()
    _force_cpu()

    from paddle_tpu import observability

    journal_path = args.journal
    if args.smoke and journal_path is None:
        fd, journal_path = tempfile.mkstemp(prefix='fleet_bench_',
                                            suffix='.jsonl')
        os.close(fd)

    jctx = observability.journal(journal_path) if journal_path \
        else None
    _perf_prev = None
    try:
        if jctx is not None:
            jctx.__enter__()
            # journalled runs also ledger every in-process replica
            # compile (OBSERVABILITY.md "Performance observatory") so
            # the perf smoke gate below has records to validate
            _perf_prev = observability.perf.enable_capture(True)
        if args.smoke:
            fleet = run_fleet_chaos(
                replicas=args.replicas, n_requests=96,
                clients=args.clients, max_batch=args.max_batch,
                seed=args.seed, slo_p99=args.slo, mesh=args.mesh,
                kill=not args.no_kill)
            decode = None if args.no_decode_phase else \
                run_decode_phase(slots=8, n_sequences=32, max_len=24,
                                 seed=3)
            autoscale = None if args.no_autoscale_phase else \
                run_autoscale_phase(max_replicas=3, n_requests=72,
                                    clients=args.clients,
                                    max_batch=args.max_batch)
            cold = None if args.no_coldstart_phase else \
                run_coldstart_phase()
            kvcache = None if args.no_kvcache_phase else \
                run_kvcache_phase(seed=3, n_sequences=72, n_prompts=8)
            telemetry = None if args.no_telemetry_phase else \
                run_telemetry_phase(replicas=2, n_requests=64,
                                    clients=3,
                                    max_batch=args.max_batch)
            remote = None if args.no_remote_phase else \
                run_remote_elastic_phase(clients=args.clients,
                                         seed=args.seed)
        else:
            fleet = run_fleet_chaos(
                replicas=args.replicas, n_requests=args.requests,
                clients=args.clients, max_batch=args.max_batch,
                seed=args.seed, slo_p99=args.slo, mesh=args.mesh,
                kill=not args.no_kill)
            decode = None if args.no_decode_phase else \
                run_decode_phase(slots=8, n_sequences=64, max_len=32,
                                 seed=3)
            autoscale = None if args.no_autoscale_phase else \
                run_autoscale_phase(max_replicas=max(3, args.replicas),
                                    n_requests=args.requests,
                                    clients=args.clients,
                                    max_batch=args.max_batch)
            cold = None if args.no_coldstart_phase else \
                run_coldstart_phase()
            kvcache = None if args.no_kvcache_phase else \
                run_kvcache_phase(seed=3)
            telemetry = None if args.no_telemetry_phase else \
                run_telemetry_phase(replicas=2,
                                    n_requests=args.requests,
                                    clients=args.clients,
                                    max_batch=args.max_batch)
            remote = None if args.no_remote_phase else \
                run_remote_elastic_phase(clients=args.clients,
                                         seed=args.seed,
                                         slo_p99=max(10.0, args.slo))
    finally:
        if jctx is not None:
            observability.perf.enable_capture(_perf_prev)
            jctx.__exit__(None, None, None)

    problems = list(fleet['problems'])
    if decode is not None:
        problems += decode['problems']
    if autoscale is not None:
        problems += autoscale['problems']
    if cold is not None:
        problems += cold['problems']
    if kvcache is not None:
        problems += kvcache['problems']
    if telemetry is not None:
        problems += telemetry['problems']
    if remote is not None:
        problems += remote['problems']
    if journal_path:
        print('journal written to %s' % journal_path)
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from obs_report import check_journal
        problems += check_journal(journal_path, require='fleet')
        # tracing rides the same journal: completed spans must exist,
        # and the kill phase must leave a reconstructable requeue tree
        problems += check_journal(journal_path, require='tracing')
        # perf rides it too: every replica compile must have ledgered
        problems += check_journal(journal_path, require='perf')
        if autoscale is not None:
            # the closed loop must have acted, not just observed
            problems += check_journal(journal_path,
                                      require='autoscale')
        if cold is not None:
            problems += check_journal(journal_path,
                                      require='coldstart')
        if kvcache is not None:
            # paged pools + at least one disaggregated prompt must
            # have journalled, and the prefill->decode hop must leave
            # one reconstructable trace tree
            problems += check_journal(journal_path, require='kvcache')
            problems += check_disagg_trace(journal_path)
        if telemetry is not None:
            # the plane must have scraped under load, and the shed
            # storm must have journalled both SLO transitions
            problems += check_journal(journal_path,
                                      require='telemetry')
            problems += check_journal(journal_path, require='slo')
        if remote is not None:
            # the whole cross-host lifecycle must have journalled:
            # spawn_remote, an in-window host_lost, a requeue and the
            # scale-in retire
            problems += check_journal(journal_path,
                                      require='remote_elastic')
        if args.smoke and not args.no_kill:
            problems += check_requeue_trace(journal_path)

    results = {'fleet': fleet, 'decode': decode,
               'autoscale': autoscale, 'coldstart': cold,
               'kvcache': kvcache, 'telemetry': telemetry,
               'remote': remote, 'problems': problems}
    if args.json:
        with open(args.json, 'w') as f:
            json.dump(results, f, indent=2, sort_keys=True,
                      default=repr)

    o, l = fleet['outcomes'], fleet['latency']
    print('fleet%s: %d ok, %d typed, %d untyped, %d dropped | '
          'p50 %.0fms p99 %.0fms | %.1f req/s | restarts %d, '
          'recovered_bit_identical=%s'
          % (' (mesh=%d)' % args.mesh if args.mesh > 1 else '',
             o['ok'], o['typed_errors'], o['untyped_errors'],
             o['dropped'], l['p50_s'] * 1e3, l['p99_s'] * 1e3,
             fleet['throughput_rps'], o['replica_restarts'],
             o['recovered_bit_identical']))
    if decode is not None:
        print('decode: continuous %.1f tok/s (occ %.0f%%) vs '
              'stop-and-wait %.1f tok/s (occ %.0f%%) -> %.2fx, '
              'exact=%s'
              % (decode['continuous']['tokens_per_sec'],
                 100 * decode['continuous']['mean_occupancy'],
                 decode['stop_and_wait']['tokens_per_sec'],
                 100 * decode['stop_and_wait']['mean_occupancy'],
                 decode['speedup'], decode['exact_vs_per_sequence']))
    if autoscale is not None:
        ao = autoscale['outcomes']
        print('autoscale: %d ok, %d scale-ups (first after %.1fs), '
              '%d scale-downs, final fleet %d | p99 %.0fms'
              % (ao['ok'], ao['scale_ups'], ao['scaled_up_after_s'],
                 ao['scale_downs'], ao['final_replicas'],
                 autoscale['latency']['p99_s'] * 1e3))
    if cold is not None:
        print('coldstart: cold warmup %.0fms -> AOT-warmed %.0fms '
              '(%.1fx), bit_identical=%s'
              % (cold['cold_warmup_ms'], cold['warm_warmup_ms'],
                 cold['speedup'], cold['bit_identical']))
    if kvcache is not None:
        kd = kvcache['disagg']
        print('kvcache: paged %.1f tok/s vs slotted %.1f tok/s '
              '(%.2fx) at %.1fx sequences-resident | disagg %d ok '
              '%d failed through prefill kill, p99 %.0fms'
              % (kvcache['paged']['tokens_per_sec'],
                 kvcache['slotted']['tokens_per_sec'],
                 kvcache['decode_paged_speedup'],
                 kvcache['sequences_resident_ratio'],
                 kd['ok'], kd['failed'], kd['p99_s'] * 1e3))
    if telemetry is not None:
        ts = telemetry['slo']
        print('telemetry: %d endpoints, %d scrapes, peak %.1f req/s '
              '| kill bundle %s | retired %d series | slo peak burn '
              '%.1fx, recovered in %.1fs'
              % (telemetry['endpoints'], telemetry['scrapes'],
                 telemetry['peak_fleet_qps'],
                 'rendered' if telemetry['bundle'] else 'MISSING',
                 telemetry['retired_series'], ts['peak_burn'],
                 ts['recovered_after_s']))
    if remote is not None:
        ro, ra = remote['outcomes'], remote['aot']
        print('remote: %d ok through spawn+kill+rebuild | scaled out '
              'in %.1fs, loss detected in %ss, rebuilt in %ss, final '
              'fleet %d | AOT warm %s hits (deser %sms vs cold '
              '%.0fms) | p99 %.0fms'
              % (ro['ok'], ro['scaled_up_after_s'],
                 ro['detected_after_s'], ro['rebuilt_after_s'],
                 ro['final_replicas'], ra['hits'],
                 ra['warm_wall_ms'], ra['cold_compile_ms'],
                 remote['latency']['p99_s'] * 1e3))
    if problems:
        print('FLEET INVARIANTS BROKEN:', file=sys.stderr)
        for p in problems:
            print('  - %s' % p, file=sys.stderr)
        return 1
    print('fleet OK (kill mid-load held every invariant)')
    return 0


if __name__ == '__main__':
    sys.exit(main())
