#!/usr/bin/env python
"""Scaled-out load generator + chaos gate for the fleet serving tier
(SERVING.md "Fleet tier & continuous batching").

Two phases, both seeded and deterministic in shape:

1. **Fleet chaos load**: N client threads hammer a ``Router`` over
   ``--replicas`` ModelServer replicas; one replica is killed abruptly
   mid-load (in-flight futures fail typed and are transparently
   requeued by the router) and the supervisor restarts it. Gates:

   - zero dropped or untyped futures — every submitted request
     resolves with a result or a typed ServingError;
   - every successful result is bit-identical to a fault-free
     single-executor reference;
   - the p99 request latency holds the ``--slo`` bound *through* the
     kill;
   - the killed replica comes back (supervisor restart) and serves
     bit-identical outputs post-recovery.

2. **Continuous-batching decode**: the same ragged sequence set is
   decoded through a continuous-admission :class:`DecodeEngine` and a
   stop-and-wait one (identical compiled step program). Gates: tokens
   bit-identical to each other AND to a per-sequence (one slot at a
   time) decode; continuous tokens/s beats stop-and-wait.

``--smoke`` runs a short schedule of both phases, writes an
observability journal and validates it via ``obs_report.py --require
fleet`` AND ``--require tracing`` semantics — including that the
kill-mid-load requeue leaves a span tree ``trace_report.py`` can
reconstruct end to end (``fleet/request -> fleet/requeue ->
serving/request``) — exiting nonzero if any invariant breaks; the CI
gate alongside ``chaos_bench.py --smoke`` and
``serve_bench.py --smoke``.

    python tools/fleet_bench.py --replicas 3            # full run
    python tools/fleet_bench.py --replicas 3 --smoke    # CI gate
    python tools/fleet_bench.py --replicas 2 --mesh 2   # sharded
"""
import argparse
import json
import os
import sys
import tempfile
import threading
import time

os.environ.setdefault('JAX_PLATFORMS', 'cpu')
sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

import numpy as np  # noqa: E402

IN_DIM, OUT_DIM = 16, 4


def _force_cpu():
    import jax
    try:
        jax.config.update('jax_platforms', 'cpu')
    except Exception:
        pass


def _build_artifact(workdir, seed=7):
    import paddle_tpu.fluid as fluid
    exe = fluid.Executor(fluid.CPUPlace())
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = seed
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data(name='x', shape=[IN_DIM],
                                  dtype='float32')
            h = fluid.layers.fc(input=x, size=32, act='relu')
            y = fluid.layers.fc(input=h, size=OUT_DIM, act=None)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        d = os.path.join(workdir, 'model')
        fluid.io.save_inference_model(d, ['x'], [y], exe,
                                      main_program=main)
    return d


def _reference_fn(model_dir):
    import paddle_tpu.fluid as fluid
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    prog, _, fetch_vars = fluid.io.load_inference_model(
        model_dir, exe, scope=scope)

    def run(x):
        out, = exe.run(prog, feed={'x': x}, fetch_list=fetch_vars,
                       scope=scope)
        return np.asarray(out)
    return run


def _percentile(xs, q):
    if not xs:
        return 0.0
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(q * len(xs)))]


def run_fleet_chaos(replicas=3, n_requests=120, clients=4, max_batch=8,
                    seed=1, slo_p99=2.5, mesh=1, kill=True):
    """Phase 1. Returns a result dict with ``problems`` (empty == all
    invariants held)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fleet import Router
    from paddle_tpu.serving import ModelServer, ServingError

    problems = []
    rng = np.random.RandomState(seed)
    inputs = [rng.randn(int(rng.randint(1, max_batch + 1)),
                        IN_DIM).astype('float32')
              for _ in range(n_requests)]
    partitioners = [None] * replicas
    if mesh and mesh > 1:
        from paddle_tpu.partition import dp_partitioners
        partitioners = dp_partitioners(replicas, mesh)

    with tempfile.TemporaryDirectory(prefix='fleet_bench_') as workdir:
        artifact = _build_artifact(workdir)
        reference = _reference_fn(artifact)
        expected = [reference(x) for x in inputs]

        def factory(rid):
            return ModelServer(place=fluid.CPUPlace(),
                               max_batch_size=max_batch,
                               max_queue_depth=max(64, n_requests),
                               partitioner=partitioners[rid],
                               watchdog_poll=0.02)

        router = Router(factory, replicas=replicas, poll_interval=0.05)
        outcomes = [None] * n_requests
        latencies = [None] * n_requests
        kill_at = n_requests // 2
        submitted = threading.Semaphore(0)
        t_start = time.monotonic()
        with router:
            router.load_model('m', artifact)

            def client(cid):
                for i in range(cid, n_requests, clients):
                    t0 = time.monotonic()
                    give_up = t0 + 30.0
                    req = None
                    while req is None:
                        try:
                            req = router.submit('m', {'x': inputs[i]})
                        except ServingError:
                            if time.monotonic() > give_up:
                                outcomes[i] = ('stuck', None)
                                break
                            time.sleep(0.01)
                    submitted.release()
                    if req is None:
                        continue
                    try:
                        out, = req.result(timeout=60.0)
                        outcomes[i] = ('ok', np.asarray(out))
                    except ServingError as e:
                        outcomes[i] = ('typed_error', e)
                    except Exception as e:  # noqa: BLE001 — judged
                        outcomes[i] = ('untyped_error', e)
                    latencies[i] = time.monotonic() - t0

            threads = [threading.Thread(target=client, args=(c,),
                                        daemon=True)
                       for c in range(clients)]
            for t in threads:
                t.start()
            victim = None
            if kill:
                # wait until half the load is in flight, then yank a
                # placed replica out from under it. Holding the
                # victim's batcher first guarantees the kill strands
                # queued requests (sub-ms batches would otherwise
                # drain before the SIGKILL lands), so the requeue
                # path — and its trace spans — provably exercise
                for _ in range(kill_at):
                    submitted.acquire()
                # ties in load score break toward the lowest replica
                # id, so that's where idle-time traffic lands — pick
                # it as the victim so the pause provably queues work
                victim = min(router.placement('m'))
                vsrv = router.replica(victim).server
                vsrv.pause('m')
                give_up = time.monotonic() + 10.0
                while vsrv.queue_depth('m') == 0 and \
                        time.monotonic() < give_up:
                    time.sleep(0.002)
                router.kill_replica(victim)
            for t in threads:
                t.join(120.0)
            wall = time.monotonic() - t_start

            # post-recovery: the supervisor must bring the victim back
            recovered_exact = None
            if victim is not None:
                give_up = time.monotonic() + 30.0
                while time.monotonic() < give_up and \
                        router.replica(victim).state != 'active':
                    time.sleep(0.05)
                rep = router.replica(victim)
                if rep.state != 'active':
                    problems.append(
                        'killed replica %d never restarted (state %r)'
                        % (victim, rep.state))
                    recovered_exact = False
                else:
                    out, = rep.server.infer('m', {'x': inputs[0]},
                                            timeout=30.0)
                    recovered_exact = np.array_equal(
                        np.asarray(out), expected[0])
                    if not recovered_exact:
                        problems.append(
                            'restarted replica %d output differs from '
                            'the reference' % victim)
            fleet_stats = router.stats()
            health = router.health()

        # ---- invariants --------------------------------------------------
        ok = sum(1 for o in outcomes if o and o[0] == 'ok')
        typed = sum(1 for o in outcomes if o and o[0] == 'typed_error')
        untyped = [repr(o[1]) for o in outcomes
                   if o and o[0] == 'untyped_error']
        dropped = sum(1 for o in outcomes if o is None) + \
            sum(1 for o in outcomes if o and o[0] == 'stuck')
        if untyped:
            problems.append('untyped client errors: %s' % untyped[:3])
        if dropped:
            problems.append('%d request(s) dropped/stuck' % dropped)
        if typed:
            # the router requeues replica failures internally; a typed
            # error surfacing means it ran out of healthy replicas,
            # which a 1-kill schedule over >=2 replicas must not hit
            problems.append(
                '%d request(s) failed typed despite %d surviving '
                'replica(s)' % (typed, replicas - 1))
        mismatches = sum(
            1 for i, o in enumerate(outcomes)
            if o and o[0] == 'ok' and
            not np.array_equal(o[1], expected[i]))
        if mismatches:
            problems.append(
                '%d result(s) differ from the fault-free reference'
                % mismatches)
        lats = [l for l in latencies if l is not None]
        p50, p99 = _percentile(lats, 0.50), _percentile(lats, 0.99)
        if p99 > slo_p99:
            problems.append(
                'p99 latency %.3fs exceeds the %.2fs SLO through the '
                'kill' % (p99, slo_p99))

    requeues = sum(r['restarts'] for r in
                   fleet_stats['replicas'].values())
    return {
        'config': {'replicas': replicas, 'n_requests': n_requests,
                   'clients': clients, 'max_batch': max_batch,
                   'seed': seed, 'slo_p99': slo_p99, 'mesh': mesh or 1,
                   'killed_replica': victim},
        'outcomes': {'ok': ok, 'typed_errors': typed,
                     'untyped_errors': len(untyped),
                     'dropped': dropped,
                     'recovered_bit_identical': recovered_exact,
                     'replica_restarts': requeues},
        'latency': {'p50_s': round(p50, 4), 'p99_s': round(p99, 4),
                    'max_s': round(max(lats), 4) if lats else 0.0},
        'throughput_rps': round(len(lats) / wall, 2) if wall else 0.0,
        'fleet': fleet_stats,
        'final_status': health['status'],
        'problems': problems,
    }


def run_decode_phase(slots=8, n_sequences=48, max_len=32, seed=3,
                     min_speedup=1.0):
    """Phase 2: continuous vs stop-and-wait decode over one ragged
    sequence set; exactness + tokens/s gates."""
    from paddle_tpu.fleet import DecodeEngine, recurrent_fc_cell

    problems = []
    rng = np.random.RandomState(seed)
    # heavily ragged: mostly short sequences, a long straggler per
    # slot-group — the occupancy hole stop-and-wait pays for
    lengths = [int(rng.randint(1, max_len // 4)) for _ in
               range(n_sequences)]
    for i in range(0, n_sequences, slots):
        lengths[i] = max_len
    hidden = 32
    inits = [{'h': rng.randn(hidden).astype('float32')}
             for _ in range(n_sequences)]

    def run_mode(admission):
        cell, specs = recurrent_fc_cell(dict_size=200, word_dim=16,
                                        hidden=hidden)
        eng = DecodeEngine(cell, specs, slots=slots, max_len=max_len,
                           end_id=None, seed=seed, admission=admission)
        eng.decode(init_states=inits[0], max_new_tokens=2)   # warm
        t0 = time.monotonic()
        reqs = [eng.submit(init_states=inits[i],
                           max_new_tokens=lengths[i])
                for i in range(n_sequences)]
        outs = [r.result(timeout=300.0) for r in reqs]
        wall = time.monotonic() - t0
        stats = eng.stats()
        eng.close()
        return outs, wall, stats

    cont, cont_wall, cont_stats = run_mode('continuous')
    sw, sw_wall, sw_stats = run_mode('stop_and_wait')

    # per-sequence reference: each sequence decoded alone
    cell, specs = recurrent_fc_cell(dict_size=200, word_dim=16,
                                    hidden=hidden)
    with DecodeEngine(cell, specs, slots=slots, max_len=max_len,
                      end_id=None, seed=seed) as eng:
        ref = [eng.decode(init_states=inits[i],
                          max_new_tokens=lengths[i], timeout=300.0)
               for i in range(n_sequences)]

    if not all(np.array_equal(a, b) for a, b in zip(cont, ref)):
        problems.append('continuous decode differs from per-sequence '
                        'decode')
    if not all(np.array_equal(a, b) for a, b in zip(sw, ref)):
        problems.append('stop-and-wait decode differs from '
                        'per-sequence decode')
    tokens = sum(lengths)
    cont_tps = tokens / cont_wall if cont_wall else 0.0
    sw_tps = tokens / sw_wall if sw_wall else 0.0
    speedup = cont_tps / sw_tps if sw_tps else 0.0
    if speedup <= min_speedup:
        problems.append(
            'continuous decode %.1f tok/s is not faster than '
            'stop-and-wait %.1f tok/s (speedup %.2fx <= %.2fx) at a '
            'ragged length distribution'
            % (cont_tps, sw_tps, speedup, min_speedup))
    return {
        'config': {'slots': slots, 'sequences': n_sequences,
                   'max_len': max_len, 'seed': seed,
                   'tokens': tokens},
        'continuous': {'tokens_per_sec': round(cont_tps, 1),
                       'steps': cont_stats['steps'],
                       'mean_occupancy':
                       round(cont_stats['mean_occupancy'], 4)},
        'stop_and_wait': {'tokens_per_sec': round(sw_tps, 1),
                          'steps': sw_stats['steps'],
                          'mean_occupancy':
                          round(sw_stats['mean_occupancy'], 4)},
        'speedup': round(speedup, 2),
        'exact_vs_per_sequence': not problems,
        'problems': problems,
    }


def check_requeue_trace(journal_path):
    """Tracing gate for the kill-mid-load smoke: the journal must hold
    at least one requeued request whose span tree reconstructs end to
    end — a ``fleet/request`` root with a ``fleet/requeue`` hop child
    that itself parents a ``serving/request`` attempt on the replica
    the request was moved to. Returns a list of problems."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from trace_report import build_store
    store = build_store([journal_path])
    requeued = 0
    for sp in store.by_kind('fleet/request').get('fleet/request', []):
        if not sp['fields'].get('requeues'):
            continue
        requeued += 1
        hops = [store.spans[c]
                for c in store.children.get(sp['span'], [])
                if store.spans[c]['name'] == 'fleet/requeue']
        for hop in hops:
            under = [store.spans[c]
                     for c in store.children.get(hop['span'], [])]
            if any(u['name'] == 'serving/request' and u['closed']
                   for u in under):
                return []
    if requeued == 0:
        return ['tracing: journal holds no requeued fleet/request '
                'span despite the kill — requeue hops are not traced']
    return ['tracing: %d requeued fleet/request span(s) found but '
            'none reconstructs a full fleet/request -> fleet/requeue '
            '-> serving/request tree' % requeued]


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split('\n')[0])
    ap.add_argument('--replicas', type=int, default=3)
    ap.add_argument('--requests', type=int, default=240)
    ap.add_argument('--clients', type=int, default=4)
    ap.add_argument('--max-batch', type=int, default=8)
    ap.add_argument('--seed', type=int, default=1)
    ap.add_argument('--slo', type=float, default=2.5,
                    help='p99 request-latency bound (seconds), held '
                         'through the replica kill')
    ap.add_argument('--mesh', type=int, default=1,
                    help='devices per replica: shard each replica '
                         'over its own disjoint dp mesh')
    ap.add_argument('--no-kill', action='store_true',
                    help='skip the chaos kill (pure load run)')
    ap.add_argument('--no-decode-phase', action='store_true')
    ap.add_argument('--smoke', action='store_true',
                    help='short seeded schedule; exit nonzero if any '
                         'fleet or decode invariant breaks')
    ap.add_argument('--journal', default=None, metavar='PATH',
                    help='write an observability run journal here '
                         '(default under --smoke: a temp file, gated '
                         'via obs_report --require fleet)')
    ap.add_argument('--json', default=None,
                    help='write the full result dict to this path')
    args = ap.parse_args(argv)
    if args.replicas < 2 and not args.no_kill:
        ap.error('--replicas must be >= 2 for the kill phase '
                 '(use --no-kill)')
    need = args.replicas * args.mesh
    if args.mesh > 1 and 'xla_force_host_platform_device_count' not in \
            os.environ.get('XLA_FLAGS', ''):
        os.environ['XLA_FLAGS'] = (
            os.environ.get('XLA_FLAGS', '') +
            ' --xla_force_host_platform_device_count=%d' % need).strip()
    _force_cpu()

    from paddle_tpu import observability

    journal_path = args.journal
    if args.smoke and journal_path is None:
        fd, journal_path = tempfile.mkstemp(prefix='fleet_bench_',
                                            suffix='.jsonl')
        os.close(fd)

    jctx = observability.journal(journal_path) if journal_path \
        else None
    _perf_prev = None
    try:
        if jctx is not None:
            jctx.__enter__()
            # journalled runs also ledger every in-process replica
            # compile (OBSERVABILITY.md "Performance observatory") so
            # the perf smoke gate below has records to validate
            _perf_prev = observability.perf.enable_capture(True)
        if args.smoke:
            fleet = run_fleet_chaos(
                replicas=args.replicas, n_requests=96,
                clients=args.clients, max_batch=args.max_batch,
                seed=args.seed, slo_p99=args.slo, mesh=args.mesh,
                kill=not args.no_kill)
            decode = None if args.no_decode_phase else \
                run_decode_phase(slots=8, n_sequences=32, max_len=24,
                                 seed=3)
        else:
            fleet = run_fleet_chaos(
                replicas=args.replicas, n_requests=args.requests,
                clients=args.clients, max_batch=args.max_batch,
                seed=args.seed, slo_p99=args.slo, mesh=args.mesh,
                kill=not args.no_kill)
            decode = None if args.no_decode_phase else \
                run_decode_phase(slots=8, n_sequences=64, max_len=32,
                                 seed=3)
    finally:
        if jctx is not None:
            observability.perf.enable_capture(_perf_prev)
            jctx.__exit__(None, None, None)

    problems = list(fleet['problems'])
    if decode is not None:
        problems += decode['problems']
    if journal_path:
        print('journal written to %s' % journal_path)
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from obs_report import check_journal
        problems += check_journal(journal_path, require='fleet')
        # tracing rides the same journal: completed spans must exist,
        # and the kill phase must leave a reconstructable requeue tree
        problems += check_journal(journal_path, require='tracing')
        # perf rides it too: every replica compile must have ledgered
        problems += check_journal(journal_path, require='perf')
        if args.smoke and not args.no_kill:
            problems += check_requeue_trace(journal_path)

    results = {'fleet': fleet, 'decode': decode, 'problems': problems}
    if args.json:
        with open(args.json, 'w') as f:
            json.dump(results, f, indent=2, sort_keys=True,
                      default=repr)

    o, l = fleet['outcomes'], fleet['latency']
    print('fleet%s: %d ok, %d typed, %d untyped, %d dropped | '
          'p50 %.0fms p99 %.0fms | %.1f req/s | restarts %d, '
          'recovered_bit_identical=%s'
          % (' (mesh=%d)' % args.mesh if args.mesh > 1 else '',
             o['ok'], o['typed_errors'], o['untyped_errors'],
             o['dropped'], l['p50_s'] * 1e3, l['p99_s'] * 1e3,
             fleet['throughput_rps'], o['replica_restarts'],
             o['recovered_bit_identical']))
    if decode is not None:
        print('decode: continuous %.1f tok/s (occ %.0f%%) vs '
              'stop-and-wait %.1f tok/s (occ %.0f%%) -> %.2fx, '
              'exact=%s'
              % (decode['continuous']['tokens_per_sec'],
                 100 * decode['continuous']['mean_occupancy'],
                 decode['stop_and_wait']['tokens_per_sec'],
                 100 * decode['stop_and_wait']['mean_occupancy'],
                 decode['speedup'], decode['exact_vs_per_sequence']))
    if problems:
        print('FLEET INVARIANTS BROKEN:', file=sys.stderr)
        for p in problems:
            print('  - %s' % p, file=sys.stderr)
        return 1
    print('fleet OK (kill mid-load held every invariant)')
    return 0


if __name__ == '__main__':
    sys.exit(main())
