#!/usr/bin/env python
"""Repo-specific static lint over the paddle_tpu sources (ANALYSIS.md
"Repo lint"). Stdlib ``ast`` only — no third-party linter, runs
anywhere the tree is checked out.

    python tools/lint_repo.py              # human report
    python tools/lint_repo.py --json -     # machine output
    python tools/lint_repo.py --list       # rules + scope

Rules (each encodes a convention the codebase actually relies on):

- ``bare-except``: ``except:`` swallows KeyboardInterrupt/SystemExit;
  every intentional broad handler here spells ``except Exception``.
- ``lock-outside-with``: ``<lock>.acquire()`` called outside a ``with``
  item — an exception between acquire and release deadlocks the
  executor cache / journal writer; the codebase takes locks only via
  context managers.
- ``unguarded-emit``: calling ``.emit`` on a journal OBJECT
  (``get_journal().emit``, ``self.journal.emit``) without a
  ``journal_active()`` / ``is not None`` guard — the module-level
  ``observability.emit`` / ``_obs.emit`` helper is the None-safe entry
  point and is always allowed.
- ``dup-metric-name``: the same raw metric-name literal passed to
  ``counter()``/``histogram()``/``gauge()`` from more than one of the
  ``serving/``, ``fleet/``, ``multihost/`` packages — cross-subsystem
  metric names must live in ONE place or the schemas drift apart.

The embedded ``ALLOWLIST`` pins known, accepted occurrences (ratchet
style): the tool exits nonzero only on violations NOT in the allowlist,
and reports stale allowlist entries so the pin shrinks over time.
tests/test_lint.py runs this over the tree and asserts zero new
violations.
"""
import argparse
import ast
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCOPE = ('paddle_tpu', 'tools')
METRIC_PACKAGES = ('serving', 'fleet', 'multihost')
METRIC_FACTORIES = ('counter', 'histogram', 'gauge')

# rule:path:detail -> accepted occurrences. Add entries ONLY with a
# review note; the lint test pins this set.
ALLOWLIST = frozenset({
})


def _src(node):
    try:
        return ast.unparse(node)
    except Exception:
        return ast.dump(node)


class Violation(object):
    def __init__(self, rule, path, line, detail):
        self.rule, self.path, self.line, self.detail = \
            rule, path, line, detail

    def key(self):
        return '%s:%s:%s' % (self.rule, self.path, self.detail)

    def render(self):
        return '%s:%d: [%s] %s' % (self.path, self.line, self.rule,
                                   self.detail)

    def as_dict(self):
        return {'rule': self.rule, 'path': self.path,
                'line': self.line, 'detail': self.detail}


def _parents(tree):
    par = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            par[child] = node
    return par


def _with_item_calls(tree):
    """Call nodes used as ``with`` context expressions (directly or via
    contextlib helpers wrapping them)."""
    calls = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.With, getattr(ast, 'AsyncWith',
                                               ast.With))):
            for item in node.items:
                for sub in ast.walk(item.context_expr):
                    if isinstance(sub, ast.Call):
                        calls.add(id(sub))
    return calls


def _guarded(node, parents):
    """Is ``node`` under an ``if`` whose test mentions the journal
    guard idiom (``journal_active()`` / an ``is not None`` check)?"""
    cur = node
    while cur in parents:
        cur = parents[cur]
        if isinstance(cur, ast.If):
            test = _src(cur.test)
            if 'journal_active' in test or 'is not None' in test:
                return True
    return False


def lint_file(path, relpath):
    with open(path) as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=relpath)
    except SyntaxError as e:
        return [Violation('parse-error', relpath, e.lineno or 0,
                          str(e))], {}
    parents = _parents(tree)
    with_calls = _with_item_calls(tree)
    out = []
    metrics = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            out.append(Violation('bare-except', relpath, node.lineno,
                                 'bare except: catches SystemExit/'
                                 'KeyboardInterrupt; use except '
                                 'Exception'))
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute):
            recv = _src(node.func.value)
            if node.func.attr == 'acquire' \
                    and 'lock' in recv.lower() \
                    and id(node) not in with_calls:
                out.append(Violation(
                    'lock-outside-with', relpath, node.lineno,
                    '%s.acquire() outside a with item' % recv))
            if node.func.attr == 'emit' and 'journal' in recv.lower() \
                    and not _guarded(node, parents):
                out.append(Violation(
                    'unguarded-emit', relpath, node.lineno,
                    '%s.emit() with no journal_active()/None guard '
                    '(use observability.emit)' % recv))
            if node.func.attr in METRIC_FACTORIES and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                metrics.setdefault(node.args[0].value, []).append(
                    (relpath, node.args[0].lineno))
    return out, metrics


def _package_of(relpath):
    parts = relpath.split(os.sep)
    if len(parts) >= 2 and parts[0] == 'paddle_tpu' \
            and parts[1] in METRIC_PACKAGES:
        return parts[1]
    return None


def lint_tree(root=REPO):
    violations = []
    metric_sites = {}        # literal -> {package: [(path, line)]}
    for top in SCOPE:
        for dirpath, dirnames, filenames in os.walk(
                os.path.join(root, top)):
            dirnames[:] = [d for d in dirnames
                           if d != '__pycache__']
            for fn in sorted(filenames):
                if not fn.endswith('.py'):
                    continue
                path = os.path.join(dirpath, fn)
                relpath = os.path.relpath(path, root)
                found, metrics = lint_file(path, relpath)
                violations.extend(found)
                pkg = _package_of(relpath)
                if pkg:
                    for name, sites in metrics.items():
                        metric_sites.setdefault(
                            name, {}).setdefault(pkg, []).extend(sites)
    for name, by_pkg in sorted(metric_sites.items()):
        if len(by_pkg) < 2:
            continue
        for pkg, sites in sorted(by_pkg.items()):
            path, line = sites[0]
            violations.append(Violation(
                'dup-metric-name', path, line,
                'metric literal %r defined in %d packages (%s); hoist '
                'the name to one shared module'
                % (name, len(by_pkg), ', '.join(sorted(by_pkg)))))
    return violations


def main(argv=None):
    ap = argparse.ArgumentParser(description='paddle_tpu repo lint')
    ap.add_argument('--json', nargs='?', const='-', default=None,
                    help='write report as JSON (path or - for stdout)')
    ap.add_argument('--list', action='store_true',
                    help='print the rules and scope, then exit')
    args = ap.parse_args(argv)
    if args.list:
        print('scope: %s' % ', '.join(SCOPE))
        print('rules: bare-except, lock-outside-with, unguarded-emit, '
              'dup-metric-name (across %s)'
              % '/'.join(METRIC_PACKAGES))
        return 0
    violations = lint_tree()
    new = [v for v in violations if v.key() not in ALLOWLIST]
    seen = {v.key() for v in violations}
    stale = sorted(ALLOWLIST - seen)
    report = {'violations': [v.as_dict() for v in new],
              'allowlisted': len(violations) - len(new),
              'stale_allowlist': stale}
    if args.json:
        text = json.dumps(report, indent=2, sort_keys=True)
        if args.json == '-':
            print(text)
        else:
            with open(args.json, 'w') as f:
                f.write(text + '\n')
    else:
        for v in new:
            print(v.render())
        if stale:
            print('stale allowlist entries (remove them):')
            for k in stale:
                print('  ' + k)
        print('%d violation(s), %d allowlisted, %d stale pin(s)'
              % (len(new), len(violations) - len(new), len(stale)))
    return 1 if new else 0


if __name__ == '__main__':
    sys.exit(main())
