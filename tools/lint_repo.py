#!/usr/bin/env python
"""Repo-specific static lint over the paddle_tpu sources (ANALYSIS.md
"Repo lint"). Stdlib ``ast`` only — no third-party linter, runs
anywhere the tree is checked out.

    python tools/lint_repo.py              # human report
    python tools/lint_repo.py --json -     # machine output
    python tools/lint_repo.py --list       # rules + scope

Rules (each encodes a convention the codebase actually relies on):

- ``bare-except``: ``except:`` swallows KeyboardInterrupt/SystemExit;
  every intentional broad handler here spells ``except Exception``.
- ``lock-outside-with``: ``<lock>.acquire()`` called outside a ``with``
  item — an exception between acquire and release deadlocks the
  executor cache / journal writer; the codebase takes locks only via
  context managers.
- ``unguarded-emit``: calling ``.emit`` on a journal OBJECT
  (``get_journal().emit``, ``self.journal.emit``) without a
  ``journal_active()`` / ``is not None`` guard — the module-level
  ``observability.emit`` / ``_obs.emit`` helper is the None-safe entry
  point and is always allowed.
- ``dup-metric-name``: the same raw metric-name literal passed to
  ``counter()``/``histogram()``/``gauge()`` from more than one of the
  ``serving/``, ``fleet/``, ``multihost/``, ``observability/``
  packages (the last covers the tracing series) — cross-subsystem
  metric names must live in ONE place or the schemas drift apart.
- ``span-not-ended``: a ``start_span()`` call that is not a ``with``
  item, not returned, not passed on, and not bound to a name that the
  enclosing scope later ``.end()``s, aliases, or hands off — a span
  begun and dropped journals a ``span_begin`` with no ``span_end``,
  which trace_report/obs_report then report as a crashed-looking
  unclosed span. The ``x = start_span(...) if cond else None`` idiom
  and cross-method handoffs (``slot.span = x``) are recognized.
- ``direct-cost-analysis``: a ``.cost_analysis()`` call outside
  ``paddle_tpu/observability/perf.py`` — XLA's cost model is read in
  ONE place (the perf observatory, OBSERVABILITY.md "Performance
  observatory") so key-spelling quirks (``'bytes accessed'``,
  list-wrapped results) and roofline constants never fork. New callers
  go through ``observability.perf`` (``capture_compiled`` /
  ``program_ledger``); ``Executor.cost_analysis`` is the one pinned
  legacy entry point.
- ``jit-on-warmup-path``: a direct ``jax.jit()``/``pjit()`` call in
  ``paddle_tpu/serving/`` or ``paddle_tpu/fleet/`` outside
  ``fleet/coldstart.py`` — replica warmup compiles must flow through
  ``Executor.run`` so the ``PTPU_AOT_CACHE`` cold-start store
  (SERVING.md "Self-driving fleet") can serve them; a bypassing jit
  silently turns millisecond warm starts back into recompiles.
- ``http-outside-telemetry``: an ``http.server`` import (or an
  ``HTTPServer``/``ThreadingHTTPServer`` stand-up) outside
  ``paddle_tpu/observability/telemetry.py`` — the telemetry plane is
  the ONE sanctioned HTTP surface (OBSERVABILITY.md "Telemetry
  plane"), so exposition format, handler timeouts and port-file
  publication cannot fork; the multihost remote protocol is a raw
  loopback socket on purpose and stays out of this rule's scope.
- ``blocking-socket-recv``: a ``.settimeout(None)`` call (re-arming a
  socket into blocking mode), or a ``sock.recv(n)``-style read outside
  ``paddle_tpu/multihost/remote.py``'s guarded frame reader — the
  remote RPC plane is partition-tolerant only because every socket
  read sits under a deadline with torn-frame detection
  (RESILIENCE.md "Cross-host elasticity"); a timeout-less recv loop
  anywhere else can hang a fleet thread forever on a silent peer.
  Zero-argument ``.recv()`` (pipes/queues) is out of scope by
  construction.
- ``hardcoded-schedule``: a Pallas block/tile size assigned from a
  bare literal (``block_h = 8``, ``tile_n = 256 if ... else 128``)
  inside ``paddle_tpu/ops/`` — kernel schedules are the autotuner's
  search space (COMPILER.md "Schedule search"), so block/tile numbers
  must resolve through ``compiler.tuning`` lookups
  (``conv_schedule()`` / ``apply_entry`` overrides) or arrive as
  function parameters; a literal baked into the kernel body is a
  schedule the tuner can never move. The two flash-attention
  dtype-default sites predate the tuner and are allowlist-pinned.
- ``kv-alloc-outside-pool``: a raw numpy buffer allocation
  (``np.zeros``/``empty``/``full``/``ones``) bound to a KV-named
  target in ``paddle_tpu/serving/`` or ``paddle_tpu/fleet/`` — KV
  cache storage is owned by ``paddle_tpu/kvcache/`` (the PagePool),
  so the placement budget's ``kv_bytes`` axis and the
  ``kvcache_pool_*`` gauges account every resident KV byte; a
  side-channel KV buffer is memory the fleet schedules blind to
  (SERVING.md "Paged KV-cache & disaggregated prefill").

The embedded ``ALLOWLIST`` pins known, accepted occurrences (ratchet
style): the tool exits nonzero only on violations NOT in the allowlist,
and reports stale allowlist entries so the pin shrinks over time.
tests/test_lint.py runs this over the tree and asserts zero new
violations.
"""
import argparse
import ast
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCOPE = ('paddle_tpu', 'tools')
METRIC_PACKAGES = ('serving', 'fleet', 'multihost', 'observability')
METRIC_FACTORIES = ('counter', 'histogram', 'gauge')
# packages on the serving warmup path: compiles here must flow through
# the Executor (whose miss path consults the AOT cold-start store) —
# a direct jax.jit/pjit would silently bypass PTPU_AOT_CACHE and turn
# millisecond warm starts back into full recompiles. fleet/coldstart.py
# is the one sanctioned compile site (the seal path itself).
JIT_FORBIDDEN_PACKAGES = ('serving', 'fleet')
JIT_SANCTIONED = os.path.join('paddle_tpu', 'fleet', 'coldstart.py')
# packages where KV-cache bytes must come from the kvcache.PagePool
# (so kv_bytes placement budgeting and the pool gauges see them) —
# a raw numpy KV buffer here is memory the fleet schedules blind to
KV_FORBIDDEN_PACKAGES = ('serving', 'fleet')
KV_ALLOC_FNS = ('zeros', 'empty', 'full', 'ones', 'zeros_like',
                'empty_like', 'full_like', 'ones_like')
# the one sanctioned http.server stand-up: the telemetry plane owns
# every scrape endpoint so exposition/handler behavior never forks.
# (The remote-cell pickle protocol is a raw socket, not http — scoping
# this rule to http.server keeps it out of scope by construction.)
TELEMETRY_SANCTIONED = os.path.join('paddle_tpu', 'observability',
                                    'telemetry.py')
# the one sanctioned byte-level socket reader: remote.py's _recv_exact
# runs every recv under the connection deadline with torn-frame
# accounting — a raw sized recv anywhere else is a thread that can
# block forever on a partitioned peer
RECV_SANCTIONED = os.path.join('paddle_tpu', 'multihost', 'remote.py')
# the package whose block/tile assignments must come from the tuner:
# a literal schedule constant in a kernel body is a knob the
# autotuner (compiler/tuning.py) can never move
SCHEDULE_PACKAGE = os.path.join('paddle_tpu', 'ops') + os.sep
SCHEDULE_NAME_PREFIXES = ('block_', 'tile_')
HTTP_SERVER_CLASSES = ('HTTPServer', 'ThreadingHTTPServer',
                       'BaseHTTPRequestHandler')

# rule:path:detail -> accepted occurrences. Add entries ONLY with a
# review note; the lint test pins this set.
ALLOWLIST = frozenset({
    # Executor.cost_analysis is the public pre-observatory API; its
    # body is the single pinned direct reader outside perf.py
    'direct-cost-analysis:paddle_tpu/executor.py:'
    'comp.cost_analysis()',
    # flash-attention dtype defaults predate the schedule tuner; the
    # tuner overrides them via apply_entry (flash_block_q/k knobs), so
    # the literals are reachable-but-tunable. New kernels resolve
    # schedules through compiler.tuning (conv_schedule()) instead.
    'hardcoded-schedule:paddle_tpu/ops/pallas_kernels.py:'
    'block_q = 1024 if q.dtype == jnp.bfloat16 else 512',
    'hardcoded-schedule:paddle_tpu/ops/pallas_kernels.py:'
    'block_k = 1024',
})


def _src(node):
    try:
        return ast.unparse(node)
    except Exception:
        return ast.dump(node)


class Violation(object):
    def __init__(self, rule, path, line, detail):
        self.rule, self.path, self.line, self.detail = \
            rule, path, line, detail

    def key(self):
        return '%s:%s:%s' % (self.rule, self.path, self.detail)

    def render(self):
        return '%s:%d: [%s] %s' % (self.path, self.line, self.rule,
                                   self.detail)

    def as_dict(self):
        return {'rule': self.rule, 'path': self.path,
                'line': self.line, 'detail': self.detail}


def _parents(tree):
    par = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            par[child] = node
    return par


def _with_item_calls(tree):
    """Call nodes used as ``with`` context expressions (directly or via
    contextlib helpers wrapping them)."""
    calls = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.With, getattr(ast, 'AsyncWith',
                                               ast.With))):
            for item in node.items:
                for sub in ast.walk(item.context_expr):
                    if isinstance(sub, ast.Call):
                        calls.add(id(sub))
    return calls


def _guarded(node, parents):
    """Is ``node`` under an ``if`` whose test mentions the journal
    guard idiom (``journal_active()`` / an ``is not None`` check)?"""
    cur = node
    while cur in parents:
        cur = parents[cur]
        if isinstance(cur, ast.If):
            test = _src(cur.test)
            if 'journal_active' in test or 'is not None' in test:
                return True
    return False


def _enclosing_scope(node, parents):
    """Nearest enclosing function (or the module) — the region scanned
    for what happens to a span after start_span()."""
    cur = node
    while cur in parents:
        cur = parents[cur]
        if isinstance(cur, (ast.FunctionDef,
                            getattr(ast, 'AsyncFunctionDef',
                                    ast.FunctionDef), ast.Lambda,
                            ast.Module)):
            return cur
    return cur


def _names_in(node):
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _span_disposition(call, parents):
    """How a start_span() call's result leaves the call site: 'with',
    'returned', 'escaped' (argument of another call / stored on an
    attribute or subscript), ('named', name) for a plain name binding
    (possibly through ``... if cond else None``), or 'dropped'."""
    cur = call
    while cur in parents:
        parent = parents[cur]
        if isinstance(parent, ast.withitem):
            return 'with'
        if isinstance(parent, ast.Return):
            return 'returned'
        if isinstance(parent, ast.Call) and cur is not parent.func:
            return 'escaped'        # callee owns it now
        if isinstance(parent, ast.keyword):
            return 'escaped'
        if isinstance(parent, (ast.Assign, ast.AnnAssign)):
            targets = parent.targets \
                if isinstance(parent, ast.Assign) else [parent.target]
            if all(isinstance(t, ast.Name) for t in targets):
                return ('named', targets[0].id)
            return 'escaped'        # self.x = / slot[i] = handoff
        if isinstance(parent, ast.Expr):
            return 'dropped'
        if isinstance(parent, (ast.stmt, ast.FunctionDef, ast.Module)):
            return 'dropped'
        cur = parent            # IfExp / BoolOp / ternary wrappers
    return 'dropped'


def _span_name_consumed(scope, name, defining_call):
    """Does ``scope`` end, return, alias, or hand off the span bound to
    ``name``? ``.end()`` and ``__exit__`` count as closing; a return,
    a re-assignment of the value elsewhere (``slot.span = x``), or
    passing the name into another call counts as ownership transfer."""
    for node in ast.walk(scope):
        if isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Attribute) \
                    and func.attr in ('end', '__exit__') \
                    and isinstance(func.value, ast.Name) \
                    and func.value.id == name:
                return True
            args = list(node.args) + [k.value for k in node.keywords]
            for a in args:
                if any(sub is defining_call
                       for sub in ast.walk(a)):
                    continue        # the defining site itself
                if name in _names_in(a):
                    return True
        elif isinstance(node, ast.Return) and node.value is not None:
            if name in _names_in(node.value):
                return True
        elif isinstance(node, (ast.Assign, ast.AnnAssign)):
            value = node.value
            if value is None or any(sub is defining_call
                                    for sub in ast.walk(value)):
                continue
            if name in _names_in(value):
                return True         # aliased / stored for later close
    return False


def _literal_schedule_value(node):
    """Is this value expression a bare schedule literal — an int
    constant, possibly wrapped in arithmetic or a dtype-style ternary
    (``1024 if q.dtype == bf16 else 512``)? Name lookups, dict reads
    (``sched['block_h']``), and calls (``_pick_div(...)``) are how a
    TUNED schedule arrives, so any of those makes the value clean."""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, int) \
            and not isinstance(node.value, bool)
    if isinstance(node, ast.UnaryOp):
        return _literal_schedule_value(node.operand)
    if isinstance(node, ast.BinOp):
        return _literal_schedule_value(node.left) \
            and _literal_schedule_value(node.right)
    if isinstance(node, ast.IfExp):
        # the test may read anything (dtype checks); what matters is
        # that every value the name can take is a baked-in literal
        return _literal_schedule_value(node.body) \
            and _literal_schedule_value(node.orelse)
    return False


def lint_file(path, relpath):
    with open(path) as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=relpath)
    except SyntaxError as e:
        return [Violation('parse-error', relpath, e.lineno or 0,
                          str(e))], {}
    parents = _parents(tree)
    with_calls = _with_item_calls(tree)
    out = []
    metrics = {}
    for node in ast.walk(tree):
        if relpath != TELEMETRY_SANCTIONED:
            if isinstance(node, ast.Import) and any(
                    a.name == 'http.server' or
                    a.name.startswith('http.server.')
                    for a in node.names):
                out.append(Violation(
                    'http-outside-telemetry', relpath, node.lineno,
                    'import http.server: scrape endpoints live in '
                    'observability/telemetry.py only (serve_telemetry)'
                ))
            elif isinstance(node, ast.ImportFrom) \
                    and node.module == 'http.server':
                out.append(Violation(
                    'http-outside-telemetry', relpath, node.lineno,
                    'from http.server import %s: scrape endpoints '
                    'live in observability/telemetry.py only '
                    '(serve_telemetry)'
                    % ', '.join(a.name for a in node.names)))
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            out.append(Violation('bare-except', relpath, node.lineno,
                                 'bare except: catches SystemExit/'
                                 'KeyboardInterrupt; use except '
                                 'Exception'))
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute):
            recv = _src(node.func.value)
            if node.func.attr == 'acquire' \
                    and 'lock' in recv.lower() \
                    and id(node) not in with_calls:
                out.append(Violation(
                    'lock-outside-with', relpath, node.lineno,
                    '%s.acquire() outside a with item' % recv))
            if node.func.attr == 'emit' and 'journal' in recv.lower() \
                    and not _guarded(node, parents):
                out.append(Violation(
                    'unguarded-emit', relpath, node.lineno,
                    '%s.emit() with no journal_active()/None guard '
                    '(use observability.emit)' % recv))
            if node.func.attr == 'settimeout' and len(node.args) == 1 \
                    and isinstance(node.args[0], ast.Constant) \
                    and node.args[0].value is None:
                out.append(Violation(
                    'blocking-socket-recv', relpath, node.lineno,
                    '%s.settimeout(None) re-arms a blocking socket: '
                    'every fleet socket read keeps a deadline so a '
                    'partitioned peer times out typed instead of '
                    'hanging the thread' % recv))
            if node.func.attr == 'recv' and node.args \
                    and relpath != RECV_SANCTIONED:
                out.append(Violation(
                    'blocking-socket-recv', relpath, node.lineno,
                    '%s.recv(...) outside multihost/remote.py\'s '
                    'guarded reader: sized socket reads go through '
                    'the deadline-bounded RPC frame reader '
                    '(_recv_exact) or they can block forever on a '
                    'silent peer' % recv))
            if node.func.attr == 'cost_analysis' \
                    and relpath != os.path.join('paddle_tpu',
                                                'observability',
                                                'perf.py'):
                out.append(Violation(
                    'direct-cost-analysis', relpath, node.lineno,
                    '%s.cost_analysis()' % recv))
            if node.func.attr in METRIC_FACTORIES and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                metrics.setdefault(node.args[0].value, []).append(
                    (relpath, node.args[0].lineno))
        if isinstance(node, ast.Call):
            func = node.func
            callee = func.attr if isinstance(func, ast.Attribute) \
                else (func.id if isinstance(func, ast.Name) else None)
            if callee in ('jit', 'pjit') \
                    and _package_of(relpath) in JIT_FORBIDDEN_PACKAGES \
                    and relpath != JIT_SANCTIONED:
                out.append(Violation(
                    'jit-on-warmup-path', relpath, node.lineno,
                    '%s() compiles outside the Executor: the warmup '
                    'path must go through Executor.run so the '
                    'PTPU_AOT_CACHE store (fleet/coldstart.py) can '
                    'serve it' % _src(func)))
        if isinstance(node, ast.Assign) \
                and relpath.startswith(SCHEDULE_PACKAGE) \
                and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id.startswith(
                    SCHEDULE_NAME_PREFIXES) \
                and _literal_schedule_value(node.value):
            out.append(Violation(
                'hardcoded-schedule', relpath, node.lineno,
                '%s = %s' % (node.targets[0].id, _src(node.value))))
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Call) \
                and isinstance(node.value.func, ast.Attribute) \
                and node.value.func.attr in KV_ALLOC_FNS \
                and isinstance(node.value.func.value, ast.Name) \
                and node.value.func.value.id in ('np', 'numpy') \
                and _package_of(relpath) in KV_FORBIDDEN_PACKAGES:
            for target in node.targets:
                if 'kv' in _src(target).lower():
                    out.append(Violation(
                        'kv-alloc-outside-pool', relpath, node.lineno,
                        '%s = np.%s(...): KV buffers come from '
                        'kvcache.PagePool.alloc() so kv_bytes '
                        'budgeting and the pool gauges account them'
                        % (_src(target), node.value.func.attr)))
                    break
        if isinstance(node, ast.Call):
            func = node.func
            callee = func.attr if isinstance(func, ast.Attribute) \
                else (func.id if isinstance(func, ast.Name) else None)
            if callee == 'start_span' \
                    and relpath != os.path.join('paddle_tpu',
                                                'observability',
                                                'tracing.py'):
                disp = _span_disposition(node, parents)
                problem = None
                if disp == 'dropped':
                    problem = ('start_span() result dropped — the '
                               'span can never be end()ed; use '
                               'with span(...) or bind and close it')
                elif isinstance(disp, tuple):
                    scope = _enclosing_scope(node, parents)
                    if not _span_name_consumed(scope, disp[1], node):
                        problem = ('span %r is started but never '
                                   'end()ed, returned, or handed '
                                   'off in this scope' % disp[1])
                if problem:
                    out.append(Violation('span-not-ended', relpath,
                                         node.lineno, problem))
    return out, metrics


def _package_of(relpath):
    parts = relpath.split(os.sep)
    if len(parts) >= 2 and parts[0] == 'paddle_tpu' \
            and parts[1] in METRIC_PACKAGES:
        return parts[1]
    return None


def lint_tree(root=REPO):
    violations = []
    metric_sites = {}        # literal -> {package: [(path, line)]}
    for top in SCOPE:
        for dirpath, dirnames, filenames in os.walk(
                os.path.join(root, top)):
            dirnames[:] = [d for d in dirnames
                           if d != '__pycache__']
            for fn in sorted(filenames):
                if not fn.endswith('.py'):
                    continue
                path = os.path.join(dirpath, fn)
                relpath = os.path.relpath(path, root)
                found, metrics = lint_file(path, relpath)
                violations.extend(found)
                pkg = _package_of(relpath)
                if pkg:
                    for name, sites in metrics.items():
                        metric_sites.setdefault(
                            name, {}).setdefault(pkg, []).extend(sites)
    for name, by_pkg in sorted(metric_sites.items()):
        if len(by_pkg) < 2:
            continue
        for pkg, sites in sorted(by_pkg.items()):
            path, line = sites[0]
            violations.append(Violation(
                'dup-metric-name', path, line,
                'metric literal %r defined in %d packages (%s); hoist '
                'the name to one shared module'
                % (name, len(by_pkg), ', '.join(sorted(by_pkg)))))
    return violations


def main(argv=None):
    ap = argparse.ArgumentParser(description='paddle_tpu repo lint')
    ap.add_argument('--json', nargs='?', const='-', default=None,
                    help='write report as JSON (path or - for stdout)')
    ap.add_argument('--list', action='store_true',
                    help='print the rules and scope, then exit')
    args = ap.parse_args(argv)
    if args.list:
        print('scope: %s' % ', '.join(SCOPE))
        print('rules: bare-except, lock-outside-with, unguarded-emit, '
              'span-not-ended, direct-cost-analysis, '
              'jit-on-warmup-path, kv-alloc-outside-pool, '
              'http-outside-telemetry, blocking-socket-recv, '
              'hardcoded-schedule (in paddle_tpu/ops/), '
              'dup-metric-name (across %s)'
              % '/'.join(METRIC_PACKAGES))
        return 0
    violations = lint_tree()
    new = [v for v in violations if v.key() not in ALLOWLIST]
    seen = {v.key() for v in violations}
    stale = sorted(ALLOWLIST - seen)
    report = {'violations': [v.as_dict() for v in new],
              'allowlisted': len(violations) - len(new),
              'stale_allowlist': stale}
    if args.json:
        text = json.dumps(report, indent=2, sort_keys=True)
        if args.json == '-':
            print(text)
        else:
            with open(args.json, 'w') as f:
                f.write(text + '\n')
    else:
        for v in new:
            print(v.render())
        if stale:
            print('stale allowlist entries (remove them):')
            for k in stale:
                print('  ' + k)
        print('%d violation(s), %d allowlisted, %d stale pin(s)'
              % (len(new), len(violations) - len(new), len(stale)))
    return 1 if new else 0


if __name__ == '__main__':
    sys.exit(main())
