"""Convert saved paddle_tpu profiles into a chrome://tracing timeline.

Parity: /root/reference/tools/timeline.py — same CLI shape
(--profile_path accepts either one file or 'name1=file1,name2=file2'
for multi-trainer runs; --timeline_path is the output). The input here
is the JSON event stream written by
``paddle_tpu.profiler.save_profile(path)`` (op name, start, duration in
seconds) instead of the reference's profiler protobuf; the output is
the same catapult trace-event format, loadable in chrome://tracing or
https://ui.perfetto.dev.

``--journal_path`` additionally merges an observability run journal
(``paddle_tpu.observability.RunJournal`` JSONL) into the same trace on
its own process track: records carrying ``dur_s`` (steps, XLA
compiles, serving batches, executor runs, tracing spans) become
duration slices grouped into one named row per event type — tracing
``span_end`` records row by their span name — and instantaneous
records (checkpoints, anomalies, shed requests) become instant events,
so ONE artifact shows op kernels, compiles, and serving batches
together.

The flag REPEATS: every ``--journal_path`` becomes its own process
track (one per fleet replica / remote cell / launcher rank), and
tracks are clock-aligned through each journal's ``run_begin`` wall
anchor — the earliest anchor is the shared origin, so a request that
hops processes reads left-to-right across tracks. Profile timestamps
are rebased to their first event and only loosely aligned with journal
tracks (different clocks).
"""
import argparse
import json


class ChromeTraceFormatter(object):
    def __init__(self):
        self._events = []
        self._metadata = []

    def emit_pid(self, name, pid):
        self._metadata.append({
            'ph': 'M', 'pid': pid, 'tid': 0,
            'name': 'process_name', 'args': {'name': name}})

    def emit_tid(self, name, pid, tid):
        self._metadata.append({
            'ph': 'M', 'pid': pid, 'tid': tid,
            'name': 'thread_name', 'args': {'name': name}})

    def emit_region(self, timestamp_us, duration_us, pid, tid, category,
                    name, args):
        self._events.append({
            'ph': 'X', 'cat': category, 'name': name, 'pid': pid,
            'tid': tid, 'ts': int(timestamp_us),
            'dur': int(duration_us), 'args': args})

    def emit_instant(self, timestamp_us, pid, tid, category, name,
                     args):
        self._events.append({
            'ph': 'i', 's': 't', 'cat': category, 'name': name,
            'pid': pid, 'tid': tid, 'ts': int(timestamp_us),
            'args': args})

    def format_to_string(self, pretty=False):
        trace = {'traceEvents': self._metadata + self._events}
        return json.dumps(trace, indent=4 if pretty else None,
                          separators=None if pretty else (',', ':'))


def _load_profiles(profile_path):
    """{name: [(op, start_s, dur_s), ...]} from the CLI spec."""
    out = {}
    if '=' in profile_path:
        for pair in profile_path.split(','):
            name, _, path = pair.partition('=')
            with open(path) as f:
                out[name] = json.load(f)['events']
    else:
        with open(profile_path) as f:
            out['trainer'] = json.load(f)['events']
    return out


def _load_journal(journal_path):
    """Parsed journal records (malformed lines skipped — the smoke gate
    in tools/obs_report.py is where malformedness fails a run)."""
    records = []
    with open(journal_path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and 'ev' in rec:
                records.append(rec)
    return records


def _wall_anchor(journal):
    """The journal's ``run_begin`` wall-clock anchor (rotation repeats
    it with the ORIGINAL value, so any run_begin works); None when the
    journal predates wall anchoring."""
    for rec in journal:
        if rec.get('ev') == 'run_begin' and 'wall' in rec:
            return float(rec['wall'])
    return None


def build_timeline(profiles, journals=None):
    tracer = ChromeTraceFormatter()
    pid = 0
    for pid, (name, events) in enumerate(sorted(profiles.items())):
        tracer.emit_pid('%s(op kernels)' % name, pid)
        if not events:
            continue
        base = min(ev[1] for ev in events)
        for op, start, dur in events:
            tracer.emit_region((start - base) * 1e6, dur * 1e6, pid, 0,
                               'Op', op, {'name': op})
    journals = journals or []
    # shared origin: the earliest wall anchor across every journal;
    # per-journal offsets realign each file's monotonic 't' to it
    anchors = [_wall_anchor(j) for j in journals]
    known = [a for a in anchors if a is not None]
    wall0 = min(known) if known else 0.0
    for idx, (journal, anchor) in enumerate(zip(journals, anchors)):
        jpid = len(profiles) + idx
        offset = (anchor - wall0) if anchor is not None else 0.0
        run_id = next((r.get('run') for r in journal if r.get('run')),
                      '?')
        ospid = next((r.get('pid') for r in journal
                      if r.get('ev') == 'run_begin' and 'pid' in r),
                     None)
        label = 'journal(run %s)' % run_id if ospid is None else \
            'journal(run %s, pid %s)' % (run_id, ospid)
        tracer.emit_pid(label, jpid)
        tids = {}
        for rec in journal:
            ev = rec['ev']
            if ev == 'run_begin':
                continue
            if ev in ('span_begin', 'span_link'):
                continue   # tree structure is trace_report's job
            # tracing span_ends row by SPAN name, everything else by
            # event type
            row = rec.get('name', ev) if ev == 'span_end' else ev
            tid = tids.get(row)
            if tid is None:
                tid = tids[row] = len(tids)
                tracer.emit_tid(row, jpid, tid)
            args = {k: v for k, v in rec.items()
                    if k not in ('ev', 'run')}
            ts_us = (offset + rec.get('t', 0.0)) * 1e6
            if 'dur_s' in rec:
                dur_us = rec['dur_s'] * 1e6
                # 't' is the END of a span (records are written when
                # the block closes); slice back to its start
                tracer.emit_region(max(ts_us - dur_us, 0.0), dur_us,
                                   jpid, tid, 'journal', row, args)
            else:
                tracer.emit_instant(ts_us, jpid, tid, 'journal', row,
                                    args)
    return tracer


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        '--profile_path', type=str, default='',
        help='Input profile file name. If there are multiple files, the '
             'format should be trainer1=file1,trainer2=file2,ps=file3')
    parser.add_argument(
        '--journal_path', type=str, action='append', default=[],
        help='Observability run journal (.jsonl) merged into the trace '
             'on its own track. Repeat for multi-process runs (one per '
             'replica / remote cell / launcher rank); tracks are '
             'clock-aligned via each journal\'s run_begin wall anchor.')
    parser.add_argument('--timeline_path', type=str, default='',
                        help='Output timeline file name.')
    args = parser.parse_args()
    profiles = _load_profiles(args.profile_path) if args.profile_path \
        else {}
    journals = [_load_journal(p) for p in args.journal_path]
    if not profiles and not journals:
        parser.error('need --profile_path and/or --journal_path')
    tracer = build_timeline(profiles, journals=journals)
    with open(args.timeline_path, 'w') as f:
        f.write(tracer.format_to_string())
    print('timeline written to %s' % args.timeline_path)


if __name__ == '__main__':
    main()
