"""Convert saved paddle_tpu profiles into a chrome://tracing timeline.

Parity: /root/reference/tools/timeline.py — same CLI shape
(--profile_path accepts either one file or 'name1=file1,name2=file2'
for multi-trainer runs; --timeline_path is the output). The input here
is the JSON event stream written by
``paddle_tpu.profiler.save_profile(path)`` (op name, start, duration in
seconds) instead of the reference's profiler protobuf; the output is
the same catapult trace-event format, loadable in chrome://tracing or
https://ui.perfetto.dev.
"""
import argparse
import json


class ChromeTraceFormatter(object):
    def __init__(self):
        self._events = []
        self._metadata = []

    def emit_pid(self, name, pid):
        self._metadata.append({
            'ph': 'M', 'pid': pid, 'tid': 0,
            'name': 'process_name', 'args': {'name': name}})

    def emit_region(self, timestamp_us, duration_us, pid, tid, category,
                    name, args):
        self._events.append({
            'ph': 'X', 'cat': category, 'name': name, 'pid': pid,
            'tid': tid, 'ts': int(timestamp_us),
            'dur': int(duration_us), 'args': args})

    def format_to_string(self, pretty=False):
        trace = {'traceEvents': self._metadata + self._events}
        return json.dumps(trace, indent=4 if pretty else None,
                          separators=None if pretty else (',', ':'))


def _load_profiles(profile_path):
    """{name: [(op, start_s, dur_s), ...]} from the CLI spec."""
    out = {}
    if '=' in profile_path:
        for pair in profile_path.split(','):
            name, _, path = pair.partition('=')
            with open(path) as f:
                out[name] = json.load(f)['events']
    else:
        with open(profile_path) as f:
            out['trainer'] = json.load(f)['events']
    return out


def build_timeline(profiles):
    tracer = ChromeTraceFormatter()
    for pid, (name, events) in enumerate(sorted(profiles.items())):
        tracer.emit_pid('%s(op kernels)' % name, pid)
        if not events:
            continue
        base = min(ev[1] for ev in events)
        for op, start, dur in events:
            tracer.emit_region((start - base) * 1e6, dur * 1e6, pid, 0,
                               'Op', op, {'name': op})
    return tracer


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        '--profile_path', type=str, default='',
        help='Input profile file name. If there are multiple files, the '
             'format should be trainer1=file1,trainer2=file2,ps=file3')
    parser.add_argument('--timeline_path', type=str, default='',
                        help='Output timeline file name.')
    args = parser.parse_args()
    tracer = build_timeline(_load_profiles(args.profile_path))
    with open(args.timeline_path, 'w') as f:
        f.write(tracer.format_to_string())
    print('timeline written to %s' % args.timeline_path)


if __name__ == '__main__':
    main()
