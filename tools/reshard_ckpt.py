#!/usr/bin/env python
"""Offline checkpoint resharding: convert a checkpoint between mesh
topologies without a live device mesh.

    python tools/reshard_ckpt.py CKPT_DIR --out OUT --mesh 2
    python tools/reshard_ckpt.py CKPT_DIR --out OUT --mesh dp=2,mp=2
    python tools/reshard_ckpt.py CKPT_DIR --out OUT --mesh 1 --serial 3

CKPT_DIR is a checkpoint root (``checkpoint_<N>`` serials) or a single
serial directory; the newest healthy serial converts unless ``--serial``
picks one. The payload is reassembled host-side (sharded / npz / orbax
backends all readable), re-split per the TARGET mesh through the same
spec resolution the live restore path uses
(``resilience.sharded.resolve_spec`` — unknown axes and non-divisible
dims degrade to replicated), and committed with the atomic manifest
protocol (tmp dir -> fsync -> manifest -> rename). ``trainer_state``
and axis rules carry over, so auto-resume works from the converted
checkpoint exactly as from the original.

``--verify`` (default) reassembles the converted payload and checks it
bit-identical to the source. Exit codes: 0 converted (and verified),
1 conversion/verification failed, 2 nothing checkpoint-shaped found.

RESILIENCE.md "Sharded checkpoints & topology portability".
"""
import argparse
import json
import os
import re
import shutil
import sys
import time

os.environ.setdefault('JAX_PLATFORMS', 'cpu')
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from paddle_tpu.resilience import checkpoint as _ckpt  # noqa: E402
from paddle_tpu.resilience import sharded as _sharded  # noqa: E402

_SERIAL_RE = re.compile(r'^checkpoint_(\d+)$')


def parse_mesh(spec):
    """'4' -> dp=4; 'dp=2,mp=2' -> ordered axes. Returns (axes,
    extents dict, shape list)."""
    spec = (spec or '').strip()
    if re.match(r'^\d+$', spec):
        n = int(spec)
        return ('dp',), {'dp': n}, [n]
    axes, extents, shape = [], {}, []
    for part in spec.split(','):
        if '=' not in part:
            raise ValueError('bad mesh spec %r (want N or a=N,b=M)'
                             % spec)
        a, n = part.split('=', 1)
        a = a.strip()
        axes.append(a)
        extents[a] = int(n)
        shape.append(int(n))
    return tuple(axes), extents, shape


def _pick_serial(root, serial=None):
    """(serial, serial_dir) — the newest HEALTHY serial (or the
    requested one), mirroring load_checkpoint's preference."""
    if os.path.isfile(os.path.join(root, _ckpt.MANIFEST_FILENAME)):
        return None, root
    if not os.path.isdir(root):
        return None, None
    found = []
    for name in os.listdir(root):
        m = _SERIAL_RE.match(name)
        if m and os.path.isdir(os.path.join(root, name)):
            found.append(int(m.group(1)))
    if serial is not None:
        return (serial, os.path.join(root, 'checkpoint_%d' % serial)) \
            if serial in found else (None, None)
    for s in sorted(found, reverse=True):
        d = os.path.join(root, 'checkpoint_%d' % s)
        if not _ckpt.verify_checkpoint(d):
            return s, d
    return None, None


def load_source_state(serial_dir, manifest):
    """name -> host array for any backend (sharded / npz / orbax)."""
    backend = manifest.get('backend')
    if backend == 'sharded':
        return _sharded.load_state(serial_dir, manifest)
    orbax_dir = os.path.join(serial_dir, '__orbax__')
    if os.path.isdir(orbax_dir):
        import orbax.checkpoint as ocp
        restored = ocp.PyTreeCheckpointer().restore(orbax_dir)
        return {n: np.asarray(v) for n, v in restored.items()}
    npz = os.path.join(serial_dir, '__params__.npz')
    with np.load(npz, allow_pickle=False) as data:
        return {n: data[n] for n in data.files}


def reshard(serial_dir, out_root, mesh_spec, serial=None, verify=True):
    """Convert one serial dir into ``out_root/checkpoint_<serial>``
    laid out for ``mesh_spec``. Returns a result dict (problems empty
    == success)."""
    result = {'source': serial_dir, 'problems': []}
    manifest = _ckpt.read_manifest(serial_dir)
    if manifest is None:
        result['problems'].append(
            '%s has no manifest (legacy checkpoints cannot reshard '
            'offline)' % serial_dir)
        return result
    errors = _ckpt.verify_checkpoint(serial_dir)
    if errors:
        result['problems'].append('source corrupt: %s' % '; '.join(
            errors[:3]))
        return result
    axes, extents, shape = parse_mesh(mesh_spec)
    state = load_source_state(serial_dir, manifest)
    specs = {n: (meta.get('spec') or [])
             for n, meta in (manifest.get('tensors') or {}).items()}
    rules = manifest.get('rules')
    out_serial = serial if serial is not None else \
        manifest.get('serial') or 0
    os.makedirs(out_root, exist_ok=True)
    tmp = os.path.join(out_root, '%scheckpoint_%d.%d'
                       % (_ckpt.TMP_PREFIX, out_serial, os.getpid()))
    final = os.path.join(out_root, 'checkpoint_%d' % out_serial)
    t0 = time.monotonic()
    try:
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        tensors = _sharded.write_resharded(tmp, state, specs, axes,
                                           extents, rules=rules)
        _ckpt.write_manifest(
            tmp, tensors=tensors,
            trainer_state=manifest.get('trainer_state'),
            backend='sharded', serial=out_serial,
            mesh={'axes': list(axes), 'shape': shape,
                  'devices': int(np.prod(shape))},
            rules=rules)
        open(os.path.join(tmp, '_SUCCESS'), 'w').close()
        _ckpt.fsync_tree(tmp)
        if os.path.isdir(final):
            shutil.rmtree(final)
        os.rename(tmp, final)
    finally:
        if os.path.isdir(tmp):
            shutil.rmtree(tmp, ignore_errors=True)
    result.update({
        'out': final,
        'serial': out_serial,
        'from_mesh': manifest.get('mesh'),
        'to_mesh': {'axes': list(axes), 'shape': shape},
        'tensors': len(tensors),
        'shards': sum(len(m['shards']) for m in tensors.values()),
        'sharded_tensors': sum(1 for m in tensors.values()
                               if len(m['shards']) > 1),
        'dur_s': round(time.monotonic() - t0, 6),
    })
    if verify:
        errors = _ckpt.verify_checkpoint(final)
        if errors:
            result['problems'].append('converted checkpoint corrupt: '
                                      '%s' % '; '.join(errors[:3]))
        out_manifest = _ckpt.read_manifest(final)
        back = _sharded.load_state(final, out_manifest)
        for name, arr in state.items():
            got = back.get(name)
            if got is None:
                result['problems'].append(
                    'tensor %s missing after reshard' % name)
            elif not np.array_equal(np.asarray(arr), got):
                result['problems'].append(
                    'tensor %s not bit-identical after reshard' % name)
    return result


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split('\n')[0])
    ap.add_argument('ckpt_dir')
    ap.add_argument('--out', required=True,
                    help='output checkpoint root')
    ap.add_argument('--mesh', required=True,
                    help="target mesh: '4' (dp=4) or 'dp=2,mp=2'")
    ap.add_argument('--serial', type=int, default=None)
    ap.add_argument('--no-verify', action='store_true',
                    help='skip the bit-exact reassembly check')
    ap.add_argument('--json', default=None,
                    help='write the result dict to this path')
    args = ap.parse_args(argv)

    serial, serial_dir = _pick_serial(args.ckpt_dir, args.serial)
    if serial_dir is None:
        print('error: no healthy checkpoint serial under %s'
              % args.ckpt_dir, file=sys.stderr)
        return 2
    result = reshard(serial_dir, args.out, args.mesh, serial=serial,
                     verify=not args.no_verify)
    if args.json:
        with open(args.json, 'w') as f:
            json.dump(result, f, indent=2, sort_keys=True, default=repr)
    if result['problems']:
        print('RESHARD FAILED:', file=sys.stderr)
        for p in result['problems']:
            print('  - %s' % p, file=sys.stderr)
        return 1
    src = result.get('from_mesh') or {}
    print('resharded %s -> %s' % (result['source'], result['out']))
    print('mesh %s -> %s | %d tensors, %d shards (%d sharded) in %.3fs'
          % ('x'.join(map(str, src.get('shape', ['?']))),
             'x'.join(map(str, result['to_mesh']['shape'])),
             result['tensors'], result['shards'],
             result['sharded_tensors'], result['dur_s']))
    return 0


if __name__ == '__main__':
    sys.exit(main())
