#!/usr/bin/env python
"""Serving load generator + smoke regression gate for
``paddle_tpu.serving.ModelServer``.

Builds small MLP inference artifacts in a temp dir, serves them through
a ModelServer, and fires N client threads with mixed batch sizes.
Reports throughput, latency percentiles, batch occupancy, and
compile-cache behavior as JSON.

``--smoke`` runs a short deterministic workload and compares the
*functional* counters against the recorded baseline
(``tools/serve_baseline.json``), exiting nonzero on regression. The
gate is deliberately wall-clock-light — CI boxes vary wildly — and
anchors on the invariants instead: compiles bounded by the bucket
count, zero shed/expired/failed under capacity, exact outputs, plus a
very conservative throughput floor.

    python tools/serve_bench.py                 # full load run
    python tools/serve_bench.py --smoke         # CI regression gate
    python tools/serve_bench.py --smoke --update-baseline
"""
import argparse
import json
import os
import sys
import tempfile
import threading
import time

# Force CPU before jax initializes (the TPU plugin, when present, is
# configured by sitecustomize; jax.config below wins over the env var).
os.environ.setdefault('JAX_PLATFORMS', 'cpu')
sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

import numpy as np  # noqa: E402

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__),
                                'serve_baseline.json')
IN_DIM, OUT_DIM = 16, 4


def _force_cpu():
    import jax
    try:
        jax.config.update('jax_platforms', 'cpu')
    except Exception:
        pass


def _build_artifacts(workdir, n_models, seed0=7):
    import paddle_tpu.fluid as fluid
    dirs = {}
    exe = fluid.Executor(fluid.CPUPlace())
    for i in range(n_models):
        main, startup = fluid.Program(), fluid.Program()
        startup.random_seed = seed0 + i
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                x = fluid.layers.data(name='x', shape=[IN_DIM],
                                      dtype='float32')
                h = fluid.layers.fc(input=x, size=32, act='relu')
                y = fluid.layers.fc(input=h, size=OUT_DIM, act=None)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            d = os.path.join(workdir, 'model_%d' % i)
            fluid.io.save_inference_model(d, ['x'], [y], exe,
                                          main_program=main)
        dirs['model_%d' % i] = d
    return dirs


def _reference_runners(dirs):
    """Serial exact-output oracles, one per model, shared-lock
    serialized (the oracle must stay literally serial)."""
    import paddle_tpu.fluid as fluid
    lock = threading.Lock()
    runners = {}
    for name, d in dirs.items():
        scope = fluid.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        prog, _, fetch_vars = fluid.io.load_inference_model(
            d, exe, scope=scope)

        def run(x, _exe=exe, _prog=prog, _fv=fetch_vars, _scope=scope):
            with lock:
                out, = _exe.run(_prog, feed={'x': x}, fetch_list=_fv,
                                scope=_scope)
            return out
        runners[name] = run
    return runners


def run_load(n_models=1, n_threads=8, requests_per_thread=25,
             max_batch=16, batch_timeout=0.002, verify=False, seed=0,
             journal_path=None):
    """Returns the result dict (throughput, latency, serving stats).
    ``journal_path`` installs an observability RunJournal over the
    serving section, so the run leaves a JSONL artifact that
    ``tools/obs_report.py`` can render/validate."""
    import contextlib
    import paddle_tpu.fluid as fluid
    from paddle_tpu import observability
    from paddle_tpu.serving import ModelServer
    results = {}
    with tempfile.TemporaryDirectory(prefix='serve_bench_') as workdir:
        dirs = _build_artifacts(workdir, n_models)
        oracles = _reference_runners(dirs) if verify else None
        jctx = observability.journal(journal_path) if journal_path \
            else contextlib.nullcontext()
        with jctx, \
             ModelServer(place=fluid.CPUPlace(), max_batch_size=max_batch,
                         max_queue_depth=n_threads * requests_per_thread,
                         batch_timeout=batch_timeout) as srv:
            for name, d in dirs.items():
                srv.load_model(name, d)
            t_w0 = time.monotonic()
            warmed = srv.warmup()
            warmup_s = time.monotonic() - t_w0
            errors, lock = [], threading.Lock()

            def client(tid):
                rng = np.random.RandomState(seed * 1000 + tid)
                name = 'model_%d' % (tid % n_models)
                try:
                    for _ in range(requests_per_thread):
                        n = int(rng.randint(1, max_batch + 1))
                        x = rng.randn(n, IN_DIM).astype('float32')
                        out, = srv.infer(name, {'x': x}, timeout=120.0)
                        if out.shape != (n, OUT_DIM):
                            raise AssertionError('bad shape %r'
                                                 % (out.shape,))
                        if oracles is not None and not np.array_equal(
                                np.asarray(out),
                                np.asarray(oracles[name](x))):
                            raise AssertionError(
                                'output mismatch vs serial run')
                except Exception as e:   # noqa: BLE001 — reported below
                    with lock:
                        errors.append('%s: %r' % (name, e))

            threads = [threading.Thread(target=client, args=(t,))
                       for t in range(n_threads)]
            t0 = time.monotonic()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.monotonic() - t0
            stats = srv.stats_dict()
            report = srv.report()
        total = n_threads * requests_per_thread
        results = {
            'config': {'models': n_models, 'threads': n_threads,
                       'requests_per_thread': requests_per_thread,
                       'max_batch': max_batch,
                       'batch_timeout': batch_timeout,
                       'verified': bool(verify)},
            'warmup': {'seconds': warmup_s,
                       'buckets': {k: v for k, v in warmed.items()}},
            'wall_seconds': wall,
            'throughput_rps': total / wall if wall > 0 else 0.0,
            'errors': errors,
            'stats': stats,
            'report': report,
        }
    return results


def check_smoke(results, baseline):
    """Compare a smoke run against the recorded baseline; returns a
    list of regression messages (empty = pass)."""
    problems = []
    st = results['stats']
    req = st['requests']
    if results['errors']:
        problems.append('client errors: %s' % results['errors'][:3])
    for key in ('shed', 'expired', 'failed'):
        if req[key] > baseline.get('max_%s' % key, 0):
            problems.append('%s=%d exceeds baseline max_%s=%d'
                            % (key, req[key], key,
                               baseline.get('max_%s' % key, 0)))
    expected_total = results['config']['threads'] * \
        results['config']['requests_per_thread']
    if req['completed'] < expected_total:
        problems.append('dropped requests: completed %d < submitted %d'
                        % (req['completed'], expected_total))
    cc = st['compile_cache']
    if cc['misses'] > baseline['max_compiles']:
        problems.append(
            'compile-cache misses %d exceed max_compiles=%d — shape '
            'bucketing regressed' % (cc['misses'],
                                     baseline['max_compiles']))
    if results['throughput_rps'] < baseline['min_throughput_rps']:
        problems.append('throughput %.1f rps below floor %.1f rps'
                        % (results['throughput_rps'],
                           baseline['min_throughput_rps']))
    occ = st['batches']['occupancy']
    if occ < baseline.get('min_occupancy', 0.0):
        problems.append('batch occupancy %.2f below floor %.2f'
                        % (occ, baseline['min_occupancy']))
    return problems


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split('\n')[0])
    ap.add_argument('--models', type=int, default=1)
    ap.add_argument('--threads', type=int, default=8)
    ap.add_argument('--requests', type=int, default=25,
                    help='requests per thread')
    ap.add_argument('--max-batch', type=int, default=16)
    ap.add_argument('--batch-timeout', type=float, default=0.002)
    ap.add_argument('--verify', action='store_true',
                    help='check every output against a serial run')
    ap.add_argument('--smoke', action='store_true',
                    help='short deterministic run gated on the baseline')
    ap.add_argument('--baseline', default=DEFAULT_BASELINE)
    ap.add_argument('--update-baseline', action='store_true')
    ap.add_argument('--json', default=None,
                    help='write the full result dict to this path')
    ap.add_argument('--journal', default=None, metavar='PATH',
                    help='write an observability run journal (JSONL) '
                         'covering the serving run; --smoke validates '
                         'it via tools/obs_report.py')
    args = ap.parse_args(argv)
    _force_cpu()

    journal_path = args.journal
    if args.smoke and journal_path is None:
        # the smoke gate always exercises the journal path end to end
        fd, journal_path = tempfile.mkstemp(prefix='serve_bench_',
                                            suffix='.jsonl')
        os.close(fd)

    if args.smoke:
        results = run_load(n_models=2, n_threads=4,
                           requests_per_thread=6, max_batch=8,
                           verify=True, seed=1,
                           journal_path=journal_path)
    else:
        results = run_load(n_models=args.models, n_threads=args.threads,
                           requests_per_thread=args.requests,
                           max_batch=args.max_batch,
                           batch_timeout=args.batch_timeout,
                           verify=args.verify,
                           journal_path=journal_path)
    if journal_path:
        print('journal written to %s' % journal_path)

    if args.json:
        payload = dict(results)
        payload.pop('report', None)
        with open(args.json, 'w') as f:
            json.dump(payload, f, indent=2, sort_keys=True)
    print(results['report'])
    print('throughput: %.1f req/s over %.2fs (warmup %.2fs)'
          % (results['throughput_rps'], results['wall_seconds'],
             results['warmup']['seconds']))

    if not args.smoke:
        return 0
    if args.update_baseline:
        # floors at ~1/4 of the observed run so normal CI jitter passes
        baseline = {
            'max_compiles': results['stats']['compile_cache']['misses'],
            'min_throughput_rps': round(
                results['throughput_rps'] / 4.0, 1),
            'min_occupancy': 0.0,
            'max_shed': 0, 'max_expired': 0, 'max_failed': 0,
        }
        with open(args.baseline, 'w') as f:
            json.dump(baseline, f, indent=2, sort_keys=True)
        print('baseline updated: %s' % args.baseline)
        return 0
    with open(args.baseline) as f:
        baseline = json.load(f)
    problems = check_smoke(results, baseline)
    if journal_path:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from obs_report import check_journal
        problems += check_journal(journal_path, require='serving')
        # every smoke request is traced end to end; an empty span set
        # means the serving pipeline lost its tracing wiring
        problems += check_journal(journal_path, require='tracing')
        # warmup ledgers every per-bucket compile when a journal is
        # active (OBSERVABILITY.md "Performance observatory"); zero
        # perf_ledger records means the capture path regressed
        problems += check_journal(journal_path, require='perf')
    if problems:
        print('SMOKE REGRESSION:', file=sys.stderr)
        for p in problems:
            print('  - %s' % p, file=sys.stderr)
        return 1
    print('smoke OK (baseline: %s)' % os.path.basename(args.baseline))
    return 0


if __name__ == '__main__':
    sys.exit(main())
