#!/usr/bin/env python
"""Deterministic chaos harness + smoke gate for the serving SLO
guardrails (SERVING.md "Failure domains & SLO guardrails").

Drives a ModelServer through a seeded ``FaultPlan`` that kills a
schedule of batches at the ``serving/run_batch`` injection site, then
checks the guardrail invariants:

- no worker thread dies (the server keeps serving after the faults);
- the circuit breaker opens on the consecutive failures, sheds with
  typed CircuitOpen at admission, half-opens after the cooldown, and
  re-closes on probe successes — the exact open -> half_open -> closed
  transition schedule is asserted;
- no request is silently dropped: every submitted future resolves with
  a result or a typed error, and every admission rejection is typed;
- post-recovery outputs are bit-identical to a fault-free reference
  run over the same inputs;
- a second phase wedges a worker with an injected hang and checks the
  watchdog fails the batch within its stage deadline and
  ``close(timeout=)`` returns instead of hanging.

``--smoke`` runs the seeded schedule and exits nonzero if any
invariant breaks — the CI gate alongside ``serve_bench.py --smoke``
and ``check_checkpoint.py --json``.

    python tools/chaos_bench.py            # full run, prints report
    python tools/chaos_bench.py --smoke    # CI gate
"""
import argparse
import json
import os
import sys
import tempfile
import time

# Force CPU before jax initializes (the TPU plugin, when present, is
# configured by sitecustomize; jax.config below wins over the env var).
os.environ.setdefault('JAX_PLATFORMS', 'cpu')
sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

import numpy as np  # noqa: E402

IN_DIM, OUT_DIM = 16, 4


def _force_cpu():
    import jax
    try:
        jax.config.update('jax_platforms', 'cpu')
    except Exception:
        pass


def _build_artifact(workdir, seed=7):
    import paddle_tpu.fluid as fluid
    exe = fluid.Executor(fluid.CPUPlace())
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = seed
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data(name='x', shape=[IN_DIM],
                                  dtype='float32')
            h = fluid.layers.fc(input=x, size=32, act='relu')
            y = fluid.layers.fc(input=h, size=OUT_DIM, act=None)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        d = os.path.join(workdir, 'model')
        fluid.io.save_inference_model(d, ['x'], [y], exe,
                                      main_program=main)
    return d


def _reference_fn(model_dir):
    import paddle_tpu.fluid as fluid
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    prog, _, fetch_vars = fluid.io.load_inference_model(
        model_dir, exe, scope=scope)

    def run(x):
        out, = exe.run(prog, feed={'x': x}, fetch_list=fetch_vars,
                       scope=scope)
        return np.asarray(out)
    return run


def _mesh_partitioner(mesh):
    """A dp-mesh Partitioner over the first ``mesh`` local devices, or
    None for the classic single-device run."""
    if not mesh or mesh <= 1:
        return None
    import jax
    from jax.sharding import Mesh
    from paddle_tpu.partition import Partitioner
    devs = jax.devices()
    if len(devs) < mesh:
        raise RuntimeError(
            'mesh=%d requested but only %d device(s) visible — set '
            'XLA_FLAGS=--xla_force_host_platform_device_count=%d (the '
            'CLI does this automatically)' % (mesh, len(devs), mesh))
    return Partitioner(mesh=Mesh(np.asarray(devs[:mesh]), ('dp',)))


def _sharded_reference_fn(fluid, artifact, mesh, max_batch):
    """Fault-free reference for mesh mode: a CLEAN ModelServer with the
    same partitioner/bucketing config, so 'bit-identical recovery'
    compares the faulted sharded pipeline against the identical sharded
    computation (a raw single-device executor run is a different XLA
    program; cross-mesh float reductions need not match bitwise)."""
    from paddle_tpu.serving import ModelServer
    srv = ModelServer(place=fluid.CPUPlace(), max_batch_size=max_batch,
                      partitioner=_mesh_partitioner(mesh))
    srv.load_model('ref', artifact)
    srv.warmup('ref')

    def run(x):
        out, = srv.infer('ref', {'x': x}, timeout=60.0)
        return np.asarray(out)
    run.close = srv.close
    return run


def run_chaos(n_requests=24, fault_times=3, extra_fault_at=None,
              max_batch=8, seed=1, failure_threshold=3, cooldown=0.25,
              probe_successes=2, hang_phase=True, mesh=1):
    """Returns a result dict with ``problems`` (empty = all invariants
    held). Faults and inputs are fully seeded — two runs with the same
    arguments exercise the identical schedule. ``mesh=N`` runs the
    whole plan against a SHARDED ModelServer (models distributed over
    an N-device dp mesh via the Partitioner); the guardrail invariants
    — no worker death, typed resolution, bit-identical recovery — must
    hold unchanged."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.resilience import (FaultPlan, fault_plan,
                                       SITE_SERVING_RUN)
    from paddle_tpu.serving import (CircuitOpen, ModelServer,
                                    ServingError)
    from paddle_tpu.serving.breaker import CLOSED, HALF_OPEN, OPEN

    problems = []
    rng = np.random.RandomState(seed)
    inputs = [rng.randn(int(rng.randint(1, max_batch + 1)),
                        IN_DIM).astype('float32')
              for _ in range(n_requests)]
    with tempfile.TemporaryDirectory(prefix='chaos_bench_') as workdir:
        artifact = _build_artifact(workdir)
        if mesh and mesh > 1:
            reference = _sharded_reference_fn(fluid, artifact, mesh,
                                              max_batch)
        else:
            reference = _reference_fn(artifact)
        expected = [reference(x) for x in inputs]
        if hasattr(reference, 'close'):
            reference.close()

        # ---- phase 1: batch-kill schedule vs the breaker -----------------
        plan = FaultPlan().inject(SITE_SERVING_RUN, times=fault_times)
        if extra_fault_at:
            plan.inject(SITE_SERVING_RUN, at=list(extra_fault_at))
        srv = ModelServer(
            place=fluid.CPUPlace(), max_batch_size=max_batch,
            retry_attempts=1, retry_backoff=0.0,
            partitioner=_mesh_partitioner(mesh),
            breaker_config=dict(failure_threshold=failure_threshold,
                                cooldown=cooldown,
                                probe_successes=probe_successes,
                                window=256))
        outcomes, sheds = [], 0
        with srv:
            srv.load_model('m', artifact)
            srv.warmup('m')
            with fault_plan(plan):
                for i, x in enumerate(inputs):
                    # serial client: submit (backing off while the
                    # breaker sheds), then wait — every batch is one
                    # request, so the fault schedule is deterministic
                    give_up = time.monotonic() + 30.0
                    req = None
                    while req is None:
                        try:
                            req = srv.submit('m', {'x': x})
                        except CircuitOpen as e:
                            sheds += 1
                            if time.monotonic() > give_up:
                                problems.append(
                                    'request %d: breaker never '
                                    're-admitted: %r' % (i, e))
                                break
                            time.sleep(max(0.01, min(
                                0.05, e.retry_after or 0.02)))
                    if req is None:
                        outcomes.append(('stuck', None))
                        continue
                    try:
                        out, = req.result(timeout=60.0)
                        outcomes.append(('ok', np.asarray(out)))
                    except ServingError as e:
                        outcomes.append(('typed_error', e))
                    except Exception as e:  # noqa: BLE001 — judged below
                        if type(e).__name__ in ('RetryError',
                                                'FaultInjected'):
                            outcomes.append(('typed_error', e))
                        else:
                            outcomes.append(('untyped_error', e))
            health = srv.health()
            worker_alive = health['models']['m']['worker_alive']
            final_state = health['models']['m']['state']
            transitions = [to for to, _ in srv.breaker('m').transitions]
            # recovery proof: rerun every faulted input fault-free
            recovered = 0
            for i, (kind, _payload) in enumerate(outcomes):
                if kind != 'ok':
                    continue
                if not np.array_equal(_payload, expected[i]):
                    problems.append(
                        'request %d: output differs from the '
                        'fault-free reference' % i)
                else:
                    recovered += 1
            for i, (kind, _payload) in enumerate(outcomes):
                if kind in ('typed_error',):
                    out, = srv.infer('m', {'x': inputs[i]},
                                     timeout=60.0)
                    if not np.array_equal(np.asarray(out), expected[i]):
                        problems.append(
                            'request %d: post-recovery rerun differs '
                            'from the fault-free reference' % i)
            stats = srv.stats_dict()

        # invariants
        failed = [k for k, _ in outcomes if k == 'typed_error']
        untyped = [repr(p) for k, p in outcomes if k == 'untyped_error']
        if untyped:
            problems.append('untyped client errors: %s' % untyped[:3])
        if any(k == 'stuck' for k, _ in outcomes):
            problems.append('requests permanently shed: breaker stuck')
        if not worker_alive:
            problems.append('worker thread died under the fault plan')
        expected_faults = fault_times + len(extra_fault_at or ())
        if len(failed) != expected_faults:
            problems.append(
                'expected exactly %d typed failures (the injected '
                'schedule), saw %d' % (expected_faults, len(failed)))
        # the exact schedule depends on how many kills land on probes,
        # but every run must open, pass through half-open probing, and
        # re-close via a legal path
        legal = {OPEN: (HALF_OPEN,), HALF_OPEN: (OPEN, CLOSED),
                 CLOSED: (OPEN,)}
        if (not transitions or transitions[0] != OPEN or
                transitions[-1] != CLOSED or
                any(b not in legal[a]
                    for a, b in zip(transitions, transitions[1:]))):
            problems.append(
                'breaker transitions %r are not a legal open -> '
                'half_open(-> open)* -> closed schedule'
                % (transitions,))
        if final_state != 'ready':
            problems.append('final health state %r != ready'
                            % final_state)
        if sheds < 1:
            problems.append(
                'breaker never shed at admission while open')
        if plan.faults[SITE_SERVING_RUN] != expected_faults:
            problems.append(
                'fault plan fired %d times, expected %d'
                % (plan.faults[SITE_SERVING_RUN], expected_faults))

        # ---- phase 2: wedged worker vs watchdog + close(timeout) ---------
        wedge = None
        if hang_phase:
            wedge = _run_wedge_phase(fluid, artifact, problems,
                                     mesh=mesh)

    return {
        'config': {'n_requests': n_requests, 'fault_times': fault_times,
                   'extra_fault_at': sorted(extra_fault_at or ()),
                   'max_batch': max_batch, 'seed': seed,
                   'failure_threshold': failure_threshold,
                   'cooldown': cooldown,
                   'probe_successes': probe_successes,
                   'mesh': mesh or 1},
        'outcomes': {'ok': sum(1 for k, _ in outcomes if k == 'ok'),
                     'typed_errors': len(failed),
                     'breaker_sheds': sheds,
                     'recovered_bit_identical': recovered},
        'breaker_transitions': transitions,
        'stats': stats,
        'wedge_phase': wedge,
        'problems': problems,
    }


def _run_wedge_phase(fluid, artifact, problems, mesh=1):
    """Inject a pure hang, assert the watchdog fails it on deadline and
    close(timeout=) returns instead of hanging on the wedged worker."""
    from paddle_tpu.resilience import (FaultPlan, fault_plan,
                                       SITE_SERVING_RUN)
    from paddle_tpu.serving import ModelServer, WatchdogTimeout

    srv = ModelServer(place=fluid.CPUPlace(), max_batch_size=4,
                      retry_attempts=1, retry_backoff=0.0,
                      partitioner=_mesh_partitioner(mesh),
                      watchdog_poll=0.02)
    srv.load_model('m', artifact)
    srv.warmup('m')
    srv.stage_timeouts[SITE_SERVING_RUN] = 0.2
    plan = FaultPlan().inject(SITE_SERVING_RUN, error=None, delay=1.0,
                              at=[0])
    x = np.ones((2, IN_DIM), 'float32')
    result = {'watchdog_tripped': False, 'close_seconds': None}
    with fault_plan(plan):
        req = srv.submit('m', {'x': x})
        t0 = time.monotonic()
        try:
            req.result(timeout=10.0)
            problems.append('hung batch completed instead of tripping '
                            'the watchdog')
        except WatchdogTimeout:
            result['watchdog_tripped'] = True
            if time.monotonic() - t0 > 0.8:
                problems.append('watchdog trip took longer than the '
                                'hang itself')
        except Exception as e:  # noqa: BLE001 — reported below
            problems.append('hung batch failed with %r, expected '
                            'WatchdogTimeout' % e)
        t0 = time.monotonic()
        srv.close(timeout=0.5)
        result['close_seconds'] = time.monotonic() - t0
        if result['close_seconds'] > 1.5:
            problems.append(
                'close(timeout=0.5) took %.2fs against a wedged worker'
                % result['close_seconds'])
        time.sleep(1.0)     # let the abandoned worker's hang expire
    return result


def run_kill_host(n_requests=12, seed=3, replicas=2,
                  detect_window=5.0, poll_interval=0.1):
    """Whole-host-loss chaos for the fleet tier (RESILIENCE.md
    "Surviving host loss"): every replica is a ModelServer living in
    its OWN process (``multihost.remote.spawn_cell``). Mid-stream one
    cell process is killed with SIGKILL — the remote analogue of losing
    a host and every replica on it at once. Invariants:

    - every in-flight request resolves ok or with a typed error; the
      requeue path re-runs them on the surviving cell and every
      delivered output is bit-identical to the fault-free reference;
    - the fleet detects the dead host within ``detect_window`` seconds
      (supervisor poll or a client requeue, whichever is first);
    - the supervisor rebuilds the replica through the factory — a NEW
      process — and the rebuilt cell serves bit-identical outputs.
    """
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fleet import Router
    from paddle_tpu.fleet.router import ACTIVE
    from paddle_tpu.multihost.remote import spawn_cell
    from paddle_tpu.serving import ServingError

    problems = []
    rng = np.random.RandomState(seed)
    inputs = [rng.randn(int(rng.randint(1, 5)),
                        IN_DIM).astype('float32')
              for _ in range(n_requests)]
    with tempfile.TemporaryDirectory(prefix='chaos_kill_') as workdir:
        artifact = _build_artifact(workdir)
        reference = _reference_fn(artifact)
        expected = [reference(x) for x in inputs]

        router = Router(lambda rid: spawn_cell('cell-%d' % rid),
                        replicas=replicas, supervise=True,
                        poll_interval=poll_interval, requeue_wait=60.0)
        result = {'killed_replica': None, 'killed_pid': None,
                  'detect_seconds': None, 'restart_seconds': None,
                  'restarted_pid': None, 'requeues': 0,
                  'outputs_bit_identical': 0, 'typed_errors': 0}
        try:
            router.load_model('m', artifact)
            victim = router.placement('m')[0]
            result['killed_replica'] = victim
            result['killed_pid'] = router.replica(victim).server.pid

            pending = []
            for i, x in enumerate(inputs):
                pending.append((i, router.submit('m', {'x': x},
                                                 deadline=120.0)))
            # the kill must land on live work: top up until the victim
            # holds an unresolved request
            for extra in range(64):
                if any(r.replica_id == victim and not r.done()
                       for _i, r in pending):
                    break
                j = extra % len(inputs)
                pending.append((j, router.submit('m',
                                                 {'x': inputs[j]},
                                                 deadline=120.0)))
            else:
                problems.append('could not land an in-flight request '
                                'on the victim replica')
            # SIGKILL the whole cell process: host loss takes down the
            # replica AND every batch in flight on it
            t_kill = time.monotonic()
            router.replica(victim).server.kill()
            for i, req in pending:
                try:
                    out, = req.result(timeout=120.0)
                except ServingError as e:
                    result['typed_errors'] += 1
                    problems.append('request %d resolved with typed '
                                    'error %r (expected requeue to '
                                    'deliver it)' % (i, e))
                    continue
                except Exception as e:  # noqa: BLE001 — judged here
                    problems.append('request %d failed UNTYPED: %r'
                                    % (i, e))
                    continue
                if np.array_equal(np.asarray(out), expected[i]):
                    result['outputs_bit_identical'] += 1
                else:
                    problems.append('request %d: output differs from '
                                    'the fault-free reference' % i)
            result['requeues'] = sum(
                1 for _i, req in pending if req.requeues)

            # detection: the victim must leave ACTIVE within the window
            give_up = t_kill + detect_window
            rep = router.replica(victim)
            while time.monotonic() < give_up:
                if rep.state != ACTIVE or rep.restarts > 0:
                    result['detect_seconds'] = \
                        time.monotonic() - t_kill
                    break
                time.sleep(0.01)
            if result['detect_seconds'] is None:
                problems.append(
                    'dead host never detected within %.1fs'
                    % detect_window)

            # recovery: the supervisor rebuilds the cell (new process)
            give_up = time.monotonic() + 180.0
            while time.monotonic() < give_up:
                if rep.restarts > 0 and rep.state == ACTIVE:
                    result['restart_seconds'] = \
                        time.monotonic() - t_kill
                    break
                time.sleep(0.05)
            if result['restart_seconds'] is None:
                problems.append('replica never rebuilt within 180s')
            else:
                result['restarted_pid'] = rep.server.pid
                if result['restarted_pid'] == result['killed_pid']:
                    problems.append('rebuilt replica reuses the dead '
                                    'pid %s' % result['killed_pid'])
                for i in (0, len(inputs) - 1):
                    out, = rep.server.infer('m', {'x': inputs[i]},
                                            timeout=120.0)
                    if not np.array_equal(np.asarray(out),
                                          expected[i]):
                        problems.append(
                            'rebuilt replica output %d differs from '
                            'the fault-free reference' % i)
            if result['requeues'] < 1:
                problems.append('no request was requeued — the kill '
                                'landed on an idle stream?')
        finally:
            router.close(timeout=10.0)
    result['problems'] = problems
    return result


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split('\n')[0])
    ap.add_argument('--requests', type=int, default=48)
    ap.add_argument('--fault-times', type=int, default=5,
                    help='consecutive batch kills at the head')
    ap.add_argument('--max-batch', type=int, default=8)
    ap.add_argument('--seed', type=int, default=1)
    ap.add_argument('--mesh', type=int, default=1,
                    help='run the plan against a ModelServer sharded '
                         'over an N-device dp mesh (host CPU devices '
                         'are provisioned automatically)')
    ap.add_argument('--smoke', action='store_true',
                    help='seeded short schedule; exit nonzero if any '
                         'guardrail invariant breaks')
    ap.add_argument('--no-hang-phase', action='store_true',
                    help='skip the wedged-worker/close(timeout) phase')
    ap.add_argument('--kill-host', action='store_true',
                    help='whole-host-loss phase: replicas in separate '
                         'processes, one SIGKILLed mid-stream; the '
                         'fleet must requeue, rebuild and recover '
                         'bit-identically')
    ap.add_argument('--detect-window', type=float, default=5.0,
                    help='--kill-host: max seconds to detect the dead '
                         'host')
    ap.add_argument('--json', default=None,
                    help='write the full result dict to this path')
    args = ap.parse_args(argv)
    if args.kill_host:
        _force_cpu()
        results = run_kill_host(
            n_requests=12 if args.smoke else args.requests,
            seed=args.seed, detect_window=args.detect_window)
        if args.json:
            with open(args.json, 'w') as f:
                json.dump(results, f, indent=2, sort_keys=True,
                          default=repr)
        print('kill-host: replica %s (pid %s) SIGKILLed | detected in '
              '%s | rebuilt as pid %s in %s | %d requeued, '
              '%d bit-identical outputs'
              % (results['killed_replica'], results['killed_pid'],
                 '%.3fs' % results['detect_seconds']
                 if results['detect_seconds'] is not None else 'NEVER',
                 results['restarted_pid'],
                 '%.1fs' % results['restart_seconds']
                 if results['restart_seconds'] is not None else 'NEVER',
                 results['requeues'],
                 results['outputs_bit_identical']))
        if results['problems']:
            print('KILL-HOST INVARIANTS BROKEN:', file=sys.stderr)
            for p in results['problems']:
                print('  - %s' % p, file=sys.stderr)
            return 1
        print('kill-host OK (whole-host loss detected, requeued, '
              'rebuilt bit-identically)')
        return 0
    if args.mesh > 1 and 'xla_force_host_platform_device_count' not in \
            os.environ.get('XLA_FLAGS', ''):
        # must land before jax initializes (first import below)
        os.environ['XLA_FLAGS'] = (
            os.environ.get('XLA_FLAGS', '') +
            ' --xla_force_host_platform_device_count=%d'
            % args.mesh).strip()
    _force_cpu()

    if args.smoke:
        # ~17% of batches killed: 3 consecutive (opens the breaker)
        # plus one isolated mid-stream failure after recovery
        results = run_chaos(n_requests=24, fault_times=3,
                            extra_fault_at=(12,), max_batch=8, seed=1,
                            failure_threshold=3, cooldown=0.25,
                            probe_successes=2,
                            hang_phase=not args.no_hang_phase,
                            mesh=args.mesh)
    else:
        results = run_chaos(n_requests=args.requests,
                            fault_times=args.fault_times,
                            extra_fault_at=(args.requests // 2,),
                            max_batch=args.max_batch, seed=args.seed,
                            hang_phase=not args.no_hang_phase,
                            mesh=args.mesh)

    if args.json:
        payload = dict(results)
        payload['problems'] = list(payload['problems'])
        with open(args.json, 'w') as f:
            json.dump(payload, f, indent=2, sort_keys=True, default=repr)

    o = results['outcomes']
    print('chaos%s: %d ok, %d typed errors, %d breaker sheds, '
          '%d bit-identical post-recovery'
          % (' (mesh=%d)' % args.mesh if args.mesh > 1 else '',
             o['ok'], o['typed_errors'], o['breaker_sheds'],
             o['recovered_bit_identical']))
    print('breaker transitions: %s'
          % ' -> '.join(results['breaker_transitions']))
    if results['wedge_phase']:
        w = results['wedge_phase']
        print('wedge phase: watchdog_tripped=%s close_seconds=%s'
              % (w['watchdog_tripped'],
                 None if w['close_seconds'] is None
                 else '%.2f' % w['close_seconds']))
    if results['problems']:
        print('CHAOS INVARIANTS BROKEN:', file=sys.stderr)
        for p in results['problems']:
            print('  - %s' % p, file=sys.stderr)
        return 1
    print('chaos OK (seeded fault schedule held every invariant)')
    return 0


if __name__ == '__main__':
    sys.exit(main())
