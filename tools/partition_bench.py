#!/usr/bin/env python
"""Partition bench worker (PARTITIONING.md / PERF.md "ZeRO-2 and
collective overlap").

``--mode partition`` (default): the SAME pipelined
``Trainer.train(prefetch=2, steps_per_dispatch=4)`` loop through the
ParallelExecutor at mesh=1 (the Partitioner's plain-jit CPU fallback)
vs mesh=N (sharded pjit over N host CPU devices), reporting steps/s
and loss parity as JSON on stdout.

``--mode zero``: replicated all-reduce (zero_stage=0) vs ZeRO-2
(bucketed reduce-scatter tail + sharded update) on the SAME dp mesh —
steps/s, per-device optimizer-state bytes (model + compile-time
argument-byte accounting), bit-exact loss parity, the lowered-HLO
collective census, standalone collective walls
(``collective_seconds{op=}``) and the overlap fraction, journaled for
the ``obs_report --require zero`` gate.

Runs as a SUBPROCESS of ``bench.py bench_partition`` /
``bench.py bench_zero`` because the host CPU device count (XLA_FLAGS)
must be fixed before jax initializes — the parent process has usually
already brought a backend up. Feeds the MULTICHIP_r0*.json trajectory
alongside the in-process multichip dryruns.

    python tools/partition_bench.py --devices 2 --steps 12
    python tools/partition_bench.py --mode zero --devices 2 --steps 20
"""
import argparse
import json
import os
import sys
import time

# runnable from anywhere: the repo root (tools/..) hosts paddle_tpu
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)


def _bench_zero(args):
    """Replicated vs ZeRO-2 on one dp mesh (PERF.md).

    The model is a transformer encoder block stack scaled to what a
    host-CPU dp mesh can train in bench budget (the flagship-geometry
    d_ff = 4 x d_model blocks with attention + layer_norm; real-chip
    runs raise --d-model to the flagship 1024)."""
    import re

    import numpy as np
    from jax.sharding import Mesh
    import jax

    import paddle_tpu.fluid as fluid
    from paddle_tpu import nets, unique_name
    from paddle_tpu import observability as obs
    from paddle_tpu.compiler import zero as zmod
    from paddle_tpu.partition import Partitioner
    from paddle_tpu.parallel.collective import observe_collective

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import obs_report

    dp, steps, batch = args.devices, args.steps, args.batch
    d_model, seq = args.d_model, args.seq
    rng = np.random.RandomState(0)
    feeds = [{'x': rng.randn(batch, seq, d_model).astype('float32'),
              'y': rng.randn(batch, 1).astype('float32')}
             for _ in range(steps)]

    def build():
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 11
        with fluid.program_guard(main, startup), unique_name.guard():
            x = fluid.layers.data(name='x', shape=[seq, d_model],
                                  dtype='float32')
            y = fluid.layers.data(name='y', shape=[1],
                                  dtype='float32')
            h = x
            for _ in range(args.blocks):
                att = nets.scaled_dot_product_attention(
                    h, h, h, num_heads=args.heads)
                h = fluid.layers.layer_norm(h + att,
                                            begin_norm_axis=2)
                ff = fluid.layers.fc(h, size=4 * d_model, act='relu',
                                     num_flatten_dims=2)
                ff = fluid.layers.fc(ff, size=d_model,
                                     num_flatten_dims=2)
                h = fluid.layers.layer_norm(h + ff,
                                            begin_norm_axis=2)
            pooled = fluid.layers.reduce_mean(h, dim=1)
            pred = fluid.layers.fc(pooled, size=1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.Adam(learning_rate=1e-4).minimize(loss)
        return main, startup, loss

    def state_bytes(main, dp_extent):
        """Per-device optimizer-state bytes from the program's own
        annotations — the exact model of what XLA keeps resident
        (cross-checked against compile_stats argument bytes below)."""
        block = main.global_block()
        repl = dev = 0
        seen = set()
        for op in block.ops:
            slots = zmod.OPTIMIZER_STATE_SLOTS.get(op.type)
            for slot in (slots or ()):
                for name in op.inputs.get(slot, []):
                    if name in seen:
                        continue
                    seen.add(name)
                    var = block._find_var_recursive(name)
                    n = int(np.prod([int(s) for s in var.shape])) * 4
                    repl += n
                    spec = var.sharding or ()
                    dev += n // dp_extent if 'dp' in spec else n
        return repl, dev

    def run_leg(stage):
        main, startup, loss = build()
        scope = fluid.Scope()
        part = Partitioner(mesh=Mesh(
            np.asarray(jax.devices()[:dp]), ('dp',)))
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            pe = fluid.ParallelExecutor(
                use_cuda=False, loss_name=loss.name, main_program=main,
                partitioner=part, zero_stage=stage)
            losses, walls = [], []
            for i, f in enumerate(feeds):
                t0 = time.perf_counter()
                out = pe.run([loss.name], feed=f)
                losses.append(float(np.asarray(out[0]).ravel()[0]))
                walls.append(time.perf_counter() - t0)
            stats = pe.compile_stats([loss.name], dict(feeds[0]))
            # lowered-HLO collective census of the real step
            from paddle_tpu.core.lowering import lower_block
            fetch, pf, s_in, s_out, senv = exe._prep_lowering(
                main, dict(feeds[0]), [loss.name], scope)
            fn = lower_block(main, main.global_block(),
                             sorted(pf.keys()), fetch, s_in, s_out,
                             static_env=senv)
            jitted = part.partition(
                part.trace_wrap(fn),
                in_shardings=(part.feed_shardings(pf),
                              part.state_shardings(main, s_in)),
                out_shardings=(part.replicated,
                               part.state_shardings(main, s_out)))
            state = {n: scope.raw(n) for n in s_in}
            with part.run_context():
                hlo = jitted.lower(pf, state).compile().as_text()
        census = {p.replace('-', '_'): len(re.findall(p, hlo))
                  for p in ('all-reduce', 'reduce-scatter',
                            'all-gather', 'partition-id')}
        # steady-state wall: drop the compiling first step
        steady = walls[1:] or walls
        repl_b, dev_b = state_bytes(main, dp)
        return {
            'losses': losses,
            'steps_per_sec': round(len(steady) / sum(steady), 2),
            'mean_step_ms': round(1e3 * sum(steady) / len(steady), 2),
            'argument_bytes_per_device': stats['argument_bytes'],
            'optimizer_state_bytes_replicated': repl_b,
            'optimizer_state_bytes_per_device': dev_b,
            'hlo_collectives': census,
            'zero': {k: v for k, v in (getattr(pe, '_zero', {}) or
                                       {}).items()
                     if not k.endswith('_names')},
            '_main': main, '_part': part,
        }

    jpath = args.journal or os.path.join(
        os.environ.get('TMPDIR', '/tmp'), 'zero_bench.jsonl')
    with obs.journal(jpath):
        rep = run_leg(0)
        zro = run_leg(None)       # dp-mesh default = ZeRO-2

        # standalone collective walls: jit JUST the bucket collectives
        # + the parameter all-gather shapes of the ZeRO program, time
        # them on the mesh -> collective_seconds{op=} and the overlap
        # denominator (obs_report's zero section).
        main, part = zro.pop('_main'), zro.pop('_part')
        rep.pop('_main'), rep.pop('_part')
        block = main.global_block()
        standalone = {'reduce_scatter': 0.0, 'all_gather': 0.0}
        payload = 0
        with part.run_context():
            for op in block.ops:
                if op.type != 'zero_reduce_scatter':
                    continue
                shapes = [tuple(block._find_var_recursive(n).shape)
                          for n in op.inputs['X']]
                dims = list(op.attrs['shard_dims'])
                vals = [jax.device_put(np.zeros(s, 'float32'),
                                       part.replicated)
                        for s in shapes]

                def coll(vs, _d=tuple(dims)):
                    return zmod.bucket_reduce_scatter(
                        vs, list(_d), dp, manual=False)

                jc = jax.jit(coll)
                jax.block_until_ready(jc(vals))    # compile
                t0 = time.perf_counter()
                jax.block_until_ready(jc(vals))
                standalone['reduce_scatter'] += \
                    time.perf_counter() - t0
                payload += sum(int(np.prod(s)) * 4 for s in shapes)
                # the matching parameter re-gather (shard -> replicated)
                spec_vals = [jax.device_put(
                    np.zeros(s, 'float32'),
                    part.named_sharding(part.grad_shard_spec(s) or ()))
                    for s in shapes]

                def gath(vs):
                    return [jax.device_put(v, part.replicated)
                            for v in vs]
                t0 = time.perf_counter()
                jax.block_until_ready(gath(spec_vals))
                standalone['all_gather'] += time.perf_counter() - t0
        for op_name, wall in standalone.items():
            observe_collective(op_name, wall, payload)
        total_standalone = sum(standalone.values())
        visible = max(0.0, (1.0 / max(zro['steps_per_sec'], 1e-9)) -
                      (1.0 / max(rep['steps_per_sec'], 1e-9)))
        obs.emit('collective', op='zero_tail',
                 standalone_s=round(total_standalone, 6),
                 visible_s=round(min(visible, total_standalone), 6))
        overlap = None
        if total_standalone > 0:
            overlap = max(0.0, min(1.0, 1.0 - min(
                visible, total_standalone) / total_standalone))

    gate_ok = obs_report.check_journal(jpath, require='zero') == []
    out = {
        'mode': 'zero',
        'devices': dp, 'batch_size': batch, 'steps': steps,
        'model': ('transformer_block x%d (d_model=%d, heads=%d, '
                  'seq=%d, d_ff=%d)' % (args.blocks, d_model,
                                        args.heads, seq, 4 * d_model)),
        'replicated': rep,
        'zero2': zro,
        'losses_bitwise_equal': rep['losses'] == zro['losses'],
        'steps_per_sec_ratio': round(
            zro['steps_per_sec'] / max(rep['steps_per_sec'], 1e-9), 3),
        'optimizer_state_bytes_ratio': round(
            zro['optimizer_state_bytes_per_device'] /
            max(rep['optimizer_state_bytes_per_device'], 1), 4),
        'argument_bytes_saved_per_device':
            rep['argument_bytes_per_device'] -
            zro['argument_bytes_per_device'],
        'collective_standalone_s': {k: round(v, 6)
                                    for k, v in standalone.items()},
        'overlap_fraction': overlap,
        'journal_gate_ok': gate_ok,
        'journal': jpath if args.journal else None,
    }
    for leg in ('replicated', 'zero2'):
        out[leg] = {k: v for k, v in out[leg].items()
                    if k not in ('losses', 'zero')}
    json.dump(out, sys.stdout)
    print()
    return 0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--mode', choices=('partition', 'zero'),
                    default='partition')
    ap.add_argument('--devices', type=int, default=2)
    ap.add_argument('--steps', type=int, default=12)
    ap.add_argument('--batch', type=int, default=64)
    ap.add_argument('--d-model', type=int, default=128)
    ap.add_argument('--seq', type=int, default=32)
    ap.add_argument('--heads', type=int, default=4)
    ap.add_argument('--blocks', type=int, default=2)
    ap.add_argument('--journal', default=None)
    args = ap.parse_args()

    os.environ['JAX_PLATFORMS'] = 'cpu'
    if 'xla_force_host_platform_device_count' not in \
            os.environ.get('XLA_FLAGS', ''):
        os.environ['XLA_FLAGS'] = (
            os.environ.get('XLA_FLAGS', '') +
            ' --xla_force_host_platform_device_count=%d'
            % args.devices).strip()
    import jax
    jax.config.update('jax_platforms', 'cpu')
    if args.mode == 'zero':
        return _bench_zero(args)

    import numpy as np
    from jax.sharding import Mesh

    import paddle_tpu.fluid as fluid
    from paddle_tpu.parallel.mesh import set_mesh

    batch, steps = args.batch, args.steps
    rng = np.random.RandomState(0)
    xs = rng.randn(steps * batch, 64).astype('float32')
    ys = (xs[:, :1] * 0.5 + 0.1).astype('float32')

    def reader():
        for i in range(0, len(xs), batch):
            yield [(xs[j], ys[j]) for j in range(i, i + batch)]

    def train_func():
        x = fluid.layers.data(name='x', shape=[64], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        h = fluid.layers.fc(input=x, size=256, act='relu')
        h = fluid.layers.fc(input=h, size=256, act='relu')
        pred = fluid.layers.fc(input=h, size=1)
        return fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))

    def one(mesh_n):
        devs = jax.devices()
        set_mesh(Mesh(np.asarray(devs[:mesh_n]), ('dp',)))
        marks, losses = {}, []

        def handler(ev):
            if isinstance(ev, fluid.BeginEpochEvent) and ev.epoch == 1:
                marks['t0'] = time.perf_counter()
            elif isinstance(ev, fluid.EndEpochEvent) and ev.epoch == 1:
                marks['t1'] = time.perf_counter()
            elif isinstance(ev, fluid.EndStepEvent) and ev.metrics \
                    and ev.epoch == 1:
                losses.append(float(np.asarray(
                    ev.metrics[0]).ravel()[0]))
        try:
            trainer = fluid.Trainer(
                train_func=train_func,
                optimizer=fluid.optimizer.Adam(learning_rate=1e-3),
                place=fluid.CPUPlace(), parallel=True)
            # epoch 0 absorbs compiles; epoch 1 is the timed steady
            # state, with the full pipelined loop engaged (no clamps:
            # K-step sharded chaining + mesh-staged prefetch)
            trainer.train(num_epochs=2, event_handler=handler,
                          reader=reader, feed_order=['x', 'y'],
                          prefetch=2, steps_per_dispatch=4,
                          sync_interval=4)
        finally:
            set_mesh(None)
        wall = marks['t1'] - marks['t0']
        return {'steps_per_sec': round(steps / wall, 2),
                'examples_per_sec': round(steps * batch / wall, 1),
                'losses': [round(v, 6) for v in losses]}

    r1 = one(1)
    rn = one(args.devices)
    out = {
        'devices': args.devices,
        'batch_size': batch,
        'steps_per_epoch': steps,
        'mesh1': r1,
        'meshN': rn,
        'speedup_meshN_vs_mesh1': round(
            rn['steps_per_sec'] / max(r1['steps_per_sec'], 1e-9), 3),
        'losses_allclose': bool(np.allclose(
            r1['losses'], rn['losses'], rtol=1e-3, atol=1e-4)),
    }
    json.dump(out, sys.stdout)
    print()
    return 0


if __name__ == '__main__':
    sys.exit(main())
