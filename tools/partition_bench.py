#!/usr/bin/env python
"""Partition bench worker (PARTITIONING.md): the SAME pipelined
``Trainer.train(prefetch=2, steps_per_dispatch=4)`` loop through the
ParallelExecutor at mesh=1 (the Partitioner's plain-jit CPU fallback)
vs mesh=N (sharded pjit over N host CPU devices), reporting steps/s
and loss parity as JSON on stdout.

Runs as a SUBPROCESS of ``bench.py bench_partition`` because the host
CPU device count (XLA_FLAGS) must be fixed before jax initializes —
the parent process has usually already brought a backend up. Feeds the
MULTICHIP_r0*.json trajectory alongside the in-process multichip
dryruns.

    python tools/partition_bench.py --devices 2 --steps 12
"""
import argparse
import json
import os
import sys

# runnable from anywhere: the repo root (tools/..) hosts paddle_tpu
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--devices', type=int, default=2)
    ap.add_argument('--steps', type=int, default=12)
    ap.add_argument('--batch', type=int, default=64)
    args = ap.parse_args()

    os.environ['JAX_PLATFORMS'] = 'cpu'
    if 'xla_force_host_platform_device_count' not in \
            os.environ.get('XLA_FLAGS', ''):
        os.environ['XLA_FLAGS'] = (
            os.environ.get('XLA_FLAGS', '') +
            ' --xla_force_host_platform_device_count=%d'
            % args.devices).strip()
    import jax
    jax.config.update('jax_platforms', 'cpu')
    import time

    import numpy as np
    from jax.sharding import Mesh

    import paddle_tpu.fluid as fluid
    from paddle_tpu.parallel.mesh import set_mesh

    batch, steps = args.batch, args.steps
    rng = np.random.RandomState(0)
    xs = rng.randn(steps * batch, 64).astype('float32')
    ys = (xs[:, :1] * 0.5 + 0.1).astype('float32')

    def reader():
        for i in range(0, len(xs), batch):
            yield [(xs[j], ys[j]) for j in range(i, i + batch)]

    def train_func():
        x = fluid.layers.data(name='x', shape=[64], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        h = fluid.layers.fc(input=x, size=256, act='relu')
        h = fluid.layers.fc(input=h, size=256, act='relu')
        pred = fluid.layers.fc(input=h, size=1)
        return fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))

    def one(mesh_n):
        devs = jax.devices()
        set_mesh(Mesh(np.asarray(devs[:mesh_n]), ('dp',)))
        marks, losses = {}, []

        def handler(ev):
            if isinstance(ev, fluid.BeginEpochEvent) and ev.epoch == 1:
                marks['t0'] = time.perf_counter()
            elif isinstance(ev, fluid.EndEpochEvent) and ev.epoch == 1:
                marks['t1'] = time.perf_counter()
            elif isinstance(ev, fluid.EndStepEvent) and ev.metrics \
                    and ev.epoch == 1:
                losses.append(float(np.asarray(
                    ev.metrics[0]).ravel()[0]))
        try:
            trainer = fluid.Trainer(
                train_func=train_func,
                optimizer=fluid.optimizer.Adam(learning_rate=1e-3),
                place=fluid.CPUPlace(), parallel=True)
            # epoch 0 absorbs compiles; epoch 1 is the timed steady
            # state, with the full pipelined loop engaged (no clamps:
            # K-step sharded chaining + mesh-staged prefetch)
            trainer.train(num_epochs=2, event_handler=handler,
                          reader=reader, feed_order=['x', 'y'],
                          prefetch=2, steps_per_dispatch=4,
                          sync_interval=4)
        finally:
            set_mesh(None)
        wall = marks['t1'] - marks['t0']
        return {'steps_per_sec': round(steps / wall, 2),
                'examples_per_sec': round(steps * batch / wall, 1),
                'losses': [round(v, 6) for v in losses]}

    r1 = one(1)
    rn = one(args.devices)
    out = {
        'devices': args.devices,
        'batch_size': batch,
        'steps_per_epoch': steps,
        'mesh1': r1,
        'meshN': rn,
        'speedup_meshN_vs_mesh1': round(
            rn['steps_per_sec'] / max(r1['steps_per_sec'], 1e-9), 3),
        'losses_allclose': bool(np.allclose(
            r1['losses'], rn['losses'], rtol=1e-3, atol=1e-4)),
    }
    json.dump(out, sys.stdout)
    print()
    return 0


if __name__ == '__main__':
    sys.exit(main())
