#!/usr/bin/env python
"""Render a flight-recorder postmortem bundle into a human report.

The input is the atomic JSON bundle the crash flight recorder
(``paddle_tpu.observability.flight``) dumps when something trips — a
watchdog, a breaker opening, an anomaly guard, a replica kill, or
SIGTERM. The report answers the incident question the run journal
cannot: *what was this process doing right before it died* — the tail
of the event ring, the spans still open at dump time, the last health
and metrics snapshot.

    python tools/postmortem.py /tmp/flight/postmortem-*.json
    python tools/postmortem.py --latest /tmp/flight   # newest bundle
    python tools/postmortem.py bundle.json --ring 50  # longer tail

Exits nonzero when the bundle is missing, unparsable, or not a
schema-matched flight bundle — so ``fleet_bench``'s kill gate can use
a successful render as proof the dump path works end to end.
"""
import argparse
import glob
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from paddle_tpu.observability import flight  # noqa: E402


def find_latest(directory):
    """Newest ``postmortem-*.json`` under ``directory``, or None."""
    paths = glob.glob(os.path.join(directory, 'postmortem-*.json'))
    return max(paths, key=os.path.getmtime) if paths else None


def render(bundle, ring_tail=20):
    lines = [
        '----------------->   Postmortem Bundle   <-----------------',
        'reason:   %s  (pid %s, %s)'
        % (bundle['reason'], bundle.get('pid'),
           time.strftime('%Y-%m-%d %H:%M:%S',
                         time.localtime(bundle.get('wall', 0)))),
    ]
    ctx = bundle.get('context') or {}
    if ctx:
        lines.append('context:  %s' % ' '.join(
            '%s=%s' % kv for kv in sorted(ctx.items())))

    spans = bundle.get('live_spans') or []
    if spans:
        wall = bundle.get('wall', 0.0)
        lines.append('unclosed spans (%d — work that died in flight):'
                     % len(spans))
        for s in spans:
            age = max(0.0, wall - s.get('since_wall', wall))
            lines.append('  %-28s open %8.3fs  trace=%s span=%s'
                         % (s.get('name', '?'), age,
                            (s.get('trace') or '?')[:16],
                            (s.get('span') or '?')[:16]))
    else:
        lines.append('unclosed spans: none')

    health = bundle.get('health')
    if health:
        lines.append('health:   %s (%d provider(s))'
                     % (health.get('status'),
                        len(health.get('providers') or {})))
        for name, doc in sorted((health.get('providers')
                                 or {}).items()):
            if isinstance(doc, dict):
                detail = ' '.join(
                    '%s=%s' % (k, doc[k]) for k in sorted(doc)
                    if k != 'status' and not isinstance(
                        doc[k], (dict, list)))[:100]
                lines.append('  %-22s %-10s %s'
                             % (name, doc.get('status', '?'), detail))

    ring = bundle.get('ring') or []
    tail = ring[-ring_tail:]
    lines.append('event ring: %d event(s) captured, showing last %d:'
                 % (len(ring), len(tail)))
    for ev in tail:
        detail = ' '.join(
            '%s=%s' % (k, ev[k]) for k in sorted(ev)
            if k not in ('ev', 'wall', 'run', 't'))[:120]
        lines.append('  %s %-14s %s'
                     % (time.strftime(
                         '%H:%M:%S', time.localtime(ev.get('wall', 0))),
                        ev.get('ev', '?'), detail))

    ledgers = bundle.get('ledgers') or []
    if ledgers:
        lines.append('perf ledgers: %d program(s), top by bytes:'
                     % len(ledgers))
        for d in ledgers[:5]:
            lines.append('  %-20s %12s bytes  %10s flops'
                         % ((d.get('program') or
                             str(d.get('fp'))[:12]),
                            d.get('bytes_accessed', '-'),
                            d.get('flops', '-')))

    metrics = bundle.get('metrics')
    if metrics:
        lines.append('metrics snapshot: %d metric(s) (use --json for '
                     'the full dump)' % len(metrics))
    return lines


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split('\n')[0])
    ap.add_argument('bundle', nargs='?', default=None,
                    help='path to a postmortem-*.json bundle')
    ap.add_argument('--latest', default=None, metavar='DIR',
                    help='render the newest bundle under DIR instead')
    ap.add_argument('--ring', type=int, default=20,
                    help='ring-tail events to show (default 20)')
    ap.add_argument('--json', action='store_true',
                    help='dump the raw bundle as JSON instead')
    args = ap.parse_args(argv)

    path = args.bundle
    if args.latest:
        path = find_latest(args.latest)
        if path is None:
            print('no postmortem-*.json bundle under %s'
                  % args.latest, file=sys.stderr)
            return 1
    if path is None:
        ap.error('bundle path required (or --latest DIR)')
    try:
        bundle = flight.read_bundle(path)
    except (OSError, ValueError) as e:
        print('cannot read bundle %s: %s' % (path, e), file=sys.stderr)
        return 1
    if args.json:
        json.dump(bundle, sys.stdout, indent=2, sort_keys=True)
        print()
        return 0
    print('\n'.join(render(bundle, ring_tail=args.ring)))
    return 0


if __name__ == '__main__':
    sys.exit(main())
