#!/usr/bin/env python
"""Reconstruct distributed span trees from observability run journals.

The input is one or more JSONL journals written by
``paddle_tpu.observability.RunJournal`` — typically one per process
(router, fleet replicas, remote cells, launcher ranks). Spans carry
propagated trace ids (``paddle_tpu.observability.tracing``), so this
tool merges every file and reassembles each request's / step's tree no
matter how many processes it crossed. Standalone on purpose — stdlib
only, so it runs anywhere the journal files landed.

    python tools/trace_report.py j1.jsonl j2.jsonl        # overview
    python tools/trace_report.py *.jsonl --trace ab12...  # one tree
    python tools/trace_report.py *.jsonl --kind serving/request
    python tools/trace_report.py *.jsonl --json -

Overview mode prints, per span kind: count, p50/p95/p99/max latency,
and the EXEMPLAR trace id behind each percentile — the concrete trace
to pull up with ``--trace`` when a p99 looks wrong (the same ids ride
`MetricsRegistry` histogram buckets in-process). ``--kind`` adds
per-stage critical-path attribution: the percentile exemplars' trees
are decomposed into self-time per stage, so "p99 is 40ms" becomes
"32ms queue wait, 6ms run, 2ms pad".

A ``span_begin`` with no matching ``span_end`` is UNCLOSED: work that
died with its process (killed replica, lost host). Unclosed spans are
listed, marked in trees, and are NOT an error — they are the forensic
record fault injection leaves behind.

``span_link`` records (a coalesced batch span serving N request spans)
graft the linking span's subtree under every request it served, so a
request tree reaches through the batch into executor spans.
"""
import argparse
import json
import sys


def load_journal(path):
    """(records, malformed_count) without importing paddle_tpu."""
    records, malformed = [], 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                malformed += 1
                continue
            if not isinstance(rec, dict) or 'ev' not in rec:
                malformed += 1
                continue
            records.append(rec)
    return records, malformed


class SpanStore(object):
    """Merged span records from N journals, indexed for tree walks."""

    def __init__(self):
        self.spans = {}       # span_id -> span dict
        self.children = {}    # parent span_id -> [span_id]
        self.links = {}       # linked (request) span_id -> [batch ids]
        self.traces = {}      # trace_id -> [root span_id]
        self.malformed = 0
        self.journals = []    # (path, wall_anchor)

    def add_journal(self, path):
        records, bad = load_journal(path)
        self.malformed += bad
        wall = next((r.get('wall') for r in records
                     if r.get('ev') == 'run_begin' and 'wall' in r),
                    None)
        jidx = len(self.journals)
        self.journals.append((path, wall))
        for rec in records:
            ev = rec.get('ev')
            if ev == 'span_begin':
                self._touch(rec, jidx)
            elif ev == 'span_end':
                sp = self._touch(rec, jidx)
                sp['dur_s'] = rec.get('dur_s', 0.0)
                sp['t'] = rec.get('t')
                sp['closed'] = True
                # begin fields (who/why) merge with end fields (how it
                # went); end wins on collision
                sp['fields'].update(
                    (k, v) for k, v in rec.items()
                    if k not in ('ev', 'run', 't', 'name', 'trace',
                                 'span', 'parent', 'dur_s'))
            elif ev == 'span_link':
                self.links.setdefault(
                    rec.get('linked_span'), []).append(rec.get('span'))

    def _touch(self, rec, jidx):
        sid = rec.get('span')
        sp = self.spans.get(sid)
        if sp is None:
            sp = self.spans[sid] = {
                'span': sid, 'name': rec.get('name'),
                'trace': rec.get('trace'),
                'parent': rec.get('parent'), 'dur_s': None,
                't': rec.get('t'), 'closed': False,
                'fields': {k: v for k, v in rec.items()
                           if k not in ('ev', 'run', 't', 'name',
                                        'trace', 'span', 'parent',
                                        'dur_s')},
                'journal': jidx}
            self.children.setdefault(rec.get('parent'), []).append(sid)
        return sp

    def finalize(self):
        for sid, sp in self.spans.items():
            parent = sp['parent']
            # a root is parentless OR its parent lives in a journal we
            # were not given (cross-process orphan: still show it)
            if parent is None or parent not in self.spans:
                self.traces.setdefault(sp['trace'], []).append(sid)
        for roots in self.traces.values():
            roots.sort(key=lambda s: self.spans[s].get('t') or 0.0)

    # ---- queries ---------------------------------------------------------
    def by_kind(self, kind=None):
        """{name: [span dict, ...]} over CLOSED spans."""
        out = {}
        for sp in self.spans.values():
            if not sp['closed']:
                continue
            if kind is not None and sp['name'] != kind:
                continue
            out.setdefault(sp['name'], []).append(sp)
        for spans in out.values():
            spans.sort(key=lambda s: s['dur_s'])
        return out

    def unclosed(self):
        return sorted((sp for sp in self.spans.values()
                       if not sp['closed']),
                      key=lambda s: (s['trace'] or '', s['span'] or ''))

    def subtree_ids(self, sid, follow_links=True, _seen=None):
        """All span ids reachable from ``sid`` via children and (once
        each) link grafts."""
        seen = _seen if _seen is not None else set()
        if sid in seen:
            return seen
        seen.add(sid)
        for c in self.children.get(sid, ()):
            self.subtree_ids(c, follow_links, seen)
        if follow_links:
            for b in self.links.get(sid, ()):
                if b in self.spans:
                    self.subtree_ids(b, follow_links, seen)
        return seen

    def self_times(self, root_sid):
        """Per-stage attribution of one tree: {name: self_seconds},
        where a span's self time is its duration minus its direct
        children's (clamped at 0 — children measured on another clock
        can slightly overhang). Unclosed spans contribute 0."""
        out = {}
        for sid in self.subtree_ids(root_sid):
            sp = self.spans[sid]
            if not sp['closed']:
                continue
            dur = sp['dur_s'] or 0.0
            kids = [self.spans[c] for c in self.children.get(sid, ())
                    if self.spans[c]['closed']]
            child_dur = sum(k['dur_s'] or 0.0 for k in kids)
            self_s = max(0.0, dur - child_dur)
            out[sp['name']] = out.get(sp['name'], 0.0) + self_s
        return out

    def critical_path(self, root_sid, depth=8):
        """The chain of largest closed children under ``root_sid``."""
        path, sid = [], root_sid
        for _ in range(depth):
            sp = self.spans[sid]
            path.append(sp)
            kids = [self.spans[c] for c in self.children.get(sid, ())
                    if self.spans[c]['closed']]
            if not kids:
                break
            best = max(kids, key=lambda k: k['dur_s'] or 0.0)
            sid = best['span']
        return path


def _quantile(sorted_spans, q):
    """The actual span sitting at quantile ``q`` (nearest rank)."""
    if not sorted_spans:
        return None
    idx = min(len(sorted_spans) - 1,
              max(0, int(q * len(sorted_spans) + 0.5) - 1))
    return sorted_spans[idx]


def render_tree(store, trace_id, out_lines, max_depth=12):
    roots = store.traces.get(trace_id)
    if not roots:
        out_lines.append('trace %s: no spans found' % trace_id)
        return
    out_lines.append('trace %s (%d span(s)):' % (
        trace_id, sum(1 for s in store.spans.values()
                      if s['trace'] == trace_id)))
    seen = set()

    def walk(sid, depth, via_link=False):
        if sid in seen or depth > max_depth:
            return
        seen.add(sid)
        sp = store.spans[sid]
        dur = ('%.3fms' % (sp['dur_s'] * 1e3)) if sp['closed'] \
            else 'UNCLOSED'
        extra = ' '.join('%s=%s' % kv
                         for kv in sorted(sp['fields'].items()))
        mark = ' (via link)' if via_link else ''
        jpath = store.journals[sp['journal']][0]
        out_lines.append('%s%-26s %10s  [%s]%s%s' % (
            '  ' * depth, sp['name'], dur, jpath,
            (' ' + extra) if extra else '', mark))
        for c in sorted(store.children.get(sid, ()),
                        key=lambda s: store.spans[s].get('t') or 0.0):
            walk(c, depth + 1)
        for b in store.links.get(sid, ()):
            if b in store.spans:
                walk(b, depth + 1, via_link=True)

    for r in roots:
        walk(r, 1)


def summarize(store, kind=None, top=10):
    kinds = store.by_kind()
    table = {}
    for name, spans in sorted(kinds.items()):
        row = {'count': len(spans)}
        for label, q in (('p50', 0.50), ('p95', 0.95), ('p99', 0.99)):
            sp = _quantile(spans, q)
            row[label] = {'dur_s': sp['dur_s'], 'trace': sp['trace']}
        row['max_s'] = spans[-1]['dur_s']
        row['total_s'] = sum(s['dur_s'] for s in spans)
        table[name] = row
    unclosed = store.unclosed()
    summary = {
        'journals': [p for p, _ in store.journals],
        'malformed_lines': store.malformed,
        'spans': sum(1 for s in store.spans.values() if s['closed']),
        'unclosed': [
            {'name': s['name'], 'trace': s['trace'], 'span': s['span'],
             'journal': store.journals[s['journal']][0]}
            for s in unclosed],
        'traces': len(store.traces),
        'kinds': table,
    }
    if kind is not None:
        spans = kinds.get(kind, [])
        attribution = {}
        for label, q in (('p50', 0.50), ('p95', 0.95), ('p99', 0.99)):
            sp = _quantile(spans, q)
            if sp is None:
                continue
            attribution[label] = {
                'trace': sp['trace'], 'dur_s': sp['dur_s'],
                'stages': store.self_times(sp['span']),
                'critical_path': [
                    {'name': p['name'], 'dur_s': p['dur_s']}
                    for p in store.critical_path(sp['span'])],
            }
        summary['attribution'] = {'kind': kind, 'count': len(spans),
                                  'percentiles': attribution}
    return summary


def render(summary, top=10):
    s = summary
    lines = [
        '----------------->     Trace Report     <-----------------',
        '%d journal(s), %d closed span(s), %d trace(s), %d unclosed'
        % (len(s['journals']), s['spans'], s['traces'],
           len(s['unclosed'])),
    ]
    if s['malformed_lines']:
        lines.append('!! %d malformed line(s)' % s['malformed_lines'])
    if s['kinds']:
        lines.append('%-26s %6s %10s %10s %10s %10s' % (
            'span kind', 'count', 'p50', 'p95', 'p99', 'max'))
        for name, row in sorted(s['kinds'].items()):
            lines.append('%-26s %6d %9.2fms %9.2fms %9.2fms %9.2fms' % (
                name, row['count'], row['p50']['dur_s'] * 1e3,
                row['p95']['dur_s'] * 1e3, row['p99']['dur_s'] * 1e3,
                row['max_s'] * 1e3))
            lines.append('  %-24s        p50=%s p99=%s' % (
                'exemplar traces:', row['p50']['trace'],
                row['p99']['trace']))
    at = s.get('attribution')
    if at:
        lines.append('attribution for %r (%d spans):'
                     % (at['kind'], at['count']))
        for label in ('p50', 'p95', 'p99'):
            pct = at['percentiles'].get(label)
            if pct is None:
                continue
            lines.append('  %s %.3fms  trace %s'
                         % (label, pct['dur_s'] * 1e3, pct['trace']))
            stages = sorted(pct['stages'].items(),
                            key=lambda kv: -kv[1])
            for stage, self_s in stages[:top]:
                share = self_s / pct['dur_s'] if pct['dur_s'] else 0.0
                lines.append('    %-24s %9.3fms  (%4.1f%% self)'
                             % (stage, self_s * 1e3, 100.0 * share))
            lines.append('    critical path: %s' % ' > '.join(
                p['name'] for p in pct['critical_path']))
    if s['unclosed']:
        lines.append('unclosed spans (work that died in flight):')
        for u in s['unclosed'][:top]:
            lines.append('  %-26s trace=%s  [%s]'
                         % (u['name'], u['trace'], u['journal']))
        if len(s['unclosed']) > top:
            lines.append('  ... and %d more'
                         % (len(s['unclosed']) - top))
    return '\n'.join(lines)


def build_store(paths):
    store = SpanStore()
    for p in paths:
        store.add_journal(p)
    store.finalize()
    return store


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split('\n')[0])
    ap.add_argument('journals', nargs='+',
                    help='RunJournal .jsonl files (one per process)')
    ap.add_argument('--trace', default=None, metavar='TRACE_ID',
                    help='print the full span tree of one trace')
    ap.add_argument('--kind', default=None, metavar='SPAN_NAME',
                    help='per-stage attribution of the p50/p95/p99 '
                         'exemplars of this span kind')
    ap.add_argument('--top', type=int, default=10,
                    help='stages / unclosed spans to list')
    ap.add_argument('--json', default=None, metavar='PATH',
                    help="write the summary as JSON ('-' = stdout)")
    args = ap.parse_args(argv)
    store = build_store(args.journals)
    if args.trace:
        lines = []
        render_tree(store, args.trace, lines)
        print('\n'.join(lines))
        return 0
    summary = summarize(store, kind=args.kind, top=args.top)
    if args.json == '-':
        json.dump(summary, sys.stdout, indent=2, sort_keys=True)
        print()
        return 0
    if args.json:
        with open(args.json, 'w') as f:
            json.dump(summary, f, indent=2, sort_keys=True)
    print(render(summary, top=args.top))
    return 0


if __name__ == '__main__':
    sys.exit(main())
