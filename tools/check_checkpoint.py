#!/usr/bin/env python
"""Validate checkpoint directories: manifest presence + CRC32 integrity.

Usage:
    python tools/check_checkpoint.py CKPT_DIR [--serial N] [--quiet]

CKPT_DIR is either a checkpoint root (holding checkpoint_<N> serials)
or a single serial directory. Exit code 0 = every checked serial is
healthy, 1 = at least one is corrupt/incomplete, 2 = nothing
checkpoint-shaped found. Meant for CI gates and pre-restore sanity
checks; uses the exact validator ``io.load_checkpoint`` trusts
(paddle_tpu/resilience/checkpoint.py).
"""
import argparse
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from paddle_tpu.resilience.checkpoint import (  # noqa: E402
    MANIFEST_FILENAME, read_manifest, verify_checkpoint)

_SERIAL_RE = re.compile(r'^checkpoint_(\d+)$')


def _find_serial_dirs(root, serial=None):
    if os.path.isfile(os.path.join(root, MANIFEST_FILENAME)) or \
            os.path.isfile(os.path.join(root, '_SUCCESS')):
        return [(None, root)]  # root IS a serial dir
    found = []
    for name in sorted(os.listdir(root)):
        m = _SERIAL_RE.match(name)
        path = os.path.join(root, name)
        if m and os.path.isdir(path):
            s = int(m.group(1))
            if serial is None or s == serial:
                found.append((s, path))
    return found


def check_dir(root, serial=None, quiet=False):
    """Returns process exit code (0 healthy / 1 corrupt / 2 empty)."""
    def say(msg):
        if not quiet:
            print(msg)

    if not os.path.isdir(root):
        say('error: %s is not a directory' % root)
        return 2
    dirs = _find_serial_dirs(root, serial)
    if not dirs:
        say('error: no checkpoint serials under %s' % root)
        return 2
    bad = 0
    for s, path in dirs:
        label = path if s is None else 'serial %d (%s)' % (s, path)
        errors = verify_checkpoint(path)
        manifest = read_manifest(path)
        if errors:
            bad += 1
            say('CORRUPT  %s' % label)
            for e in errors:
                say('         - %s' % e)
            continue
        ntensors = len((manifest or {}).get('tensors', {}))
        nfiles = len((manifest or {}).get('files', {}))
        extra = ' [legacy: no manifest]' if manifest is None else \
            ' (%d tensors, %d files, backend=%s)' % (
                ntensors, nfiles, (manifest or {}).get('backend'))
        say('OK       %s%s' % (label, extra))
    say('%d/%d serial(s) healthy' % (len(dirs) - bad, len(dirs)))
    return 1 if bad else 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('ckpt_dir')
    ap.add_argument('--serial', type=int, default=None,
                    help='check only this serial')
    ap.add_argument('--quiet', action='store_true')
    args = ap.parse_args(argv)
    return check_dir(args.ckpt_dir, serial=args.serial, quiet=args.quiet)


if __name__ == '__main__':
    sys.exit(main())
