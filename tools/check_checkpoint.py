#!/usr/bin/env python
"""Validate checkpoint directories: manifest presence + CRC32 integrity.

Usage:
    python tools/check_checkpoint.py CKPT_DIR [--serial N] [--quiet]
                                     [--json]

CKPT_DIR is either a checkpoint root (holding checkpoint_<N> serials)
or a single serial directory. Exit code 0 = every checked serial is
healthy, 1 = at least one is corrupt/incomplete, 2 = nothing
checkpoint-shaped found. Meant for CI gates and pre-restore sanity
checks; uses the exact validator ``io.load_checkpoint`` trusts
(paddle_tpu/resilience/checkpoint.py).

``--json`` replaces the human lines with one machine-readable JSON
document on stdout (per-serial health + errors + manifest summary), so
automation can gate on it alongside ``serve_bench.py --smoke`` and
``chaos_bench.py --smoke``; the exit codes are unchanged.
"""
import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from paddle_tpu.resilience.checkpoint import (  # noqa: E402
    MANIFEST_FILENAME, read_manifest, verify_checkpoint)

_SERIAL_RE = re.compile(r'^checkpoint_(\d+)$')


def _find_serial_dirs(root, serial=None):
    if os.path.isfile(os.path.join(root, MANIFEST_FILENAME)) or \
            os.path.isfile(os.path.join(root, '_SUCCESS')):
        return [(None, root)]  # root IS a serial dir
    found = []
    for name in sorted(os.listdir(root)):
        m = _SERIAL_RE.match(name)
        path = os.path.join(root, name)
        if m and os.path.isdir(path):
            s = int(m.group(1))
            if serial is None or s == serial:
                found.append((s, path))
    return found


def scan_dir(root, serial=None):
    """Validate every matching serial. Returns ``(exit_code,
    result_dict)`` — the dict is what ``--json`` prints."""
    result = {'root': root, 'serials': [], 'healthy': 0, 'corrupt': 0}
    if not os.path.isdir(root):
        result['error'] = '%s is not a directory' % root
        return 2, result
    dirs = _find_serial_dirs(root, serial)
    if not dirs:
        result['error'] = 'no checkpoint serials under %s' % root
        return 2, result
    for s, path in dirs:
        errors = verify_checkpoint(path)
        manifest = read_manifest(path)
        tensors = (manifest or {}).get('tensors', {})
        entry = {
            'serial': s,
            'path': path,
            'healthy': not errors,
            'errors': list(errors),
            'legacy_no_manifest': manifest is None,
            'tensors': len(tensors),
            'files': len((manifest or {}).get('files', {})),
            'backend': (manifest or {}).get('backend'),
            # sharded-manifest surface (RESILIENCE.md "Sharded
            # checkpoints"): the recorded mesh topology + axis rules
            # and the shard-table totals — what a restore on a
            # different mesh (or tools/reshard_ckpt.py) keys off
            'mesh': (manifest or {}).get('mesh'),
            'rules': len((manifest or {}).get('rules') or []),
            'shards': sum(len(m.get('shards') or ())
                          for m in tensors.values()),
            'sharded_tensors': sum(
                1 for m in tensors.values()
                if len(m.get('shards') or ()) > 1),
        }
        result['serials'].append(entry)
        result['corrupt' if errors else 'healthy'] += 1
    return (1 if result['corrupt'] else 0), result


def check_dir(root, serial=None, quiet=False):
    """Returns process exit code (0 healthy / 1 corrupt / 2 empty)."""
    def say(msg):
        if not quiet:
            print(msg)

    code, result = scan_dir(root, serial=serial)
    if 'error' in result:
        say('error: %s' % result['error'])
        return code
    for entry in result['serials']:
        s = entry['serial']
        label = entry['path'] if s is None \
            else 'serial %d (%s)' % (s, entry['path'])
        if not entry['healthy']:
            say('CORRUPT  %s' % label)
            for e in entry['errors']:
                say('         - %s' % e)
            continue
        extra = ' [legacy: no manifest]' if entry['legacy_no_manifest'] \
            else ' (%d tensors, %d files, backend=%s)' % (
                entry['tensors'], entry['files'], entry['backend'])
        if entry.get('mesh'):
            extra += ' [mesh %s, %d shards, %d sharded tensors]' % (
                'x'.join('%s=%s' % (a, e) for a, e in
                         zip(entry['mesh'].get('axes', ()),
                             entry['mesh'].get('shape', ()))),
                entry['shards'], entry['sharded_tensors'])
        say('OK       %s%s' % (label, extra))
    say('%d/%d serial(s) healthy'
        % (result['healthy'], len(result['serials'])))
    return code


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('ckpt_dir')
    ap.add_argument('--serial', type=int, default=None,
                    help='check only this serial')
    ap.add_argument('--quiet', action='store_true')
    ap.add_argument('--json', action='store_true',
                    help='print one machine-readable JSON document '
                         'instead of the human lines')
    args = ap.parse_args(argv)
    if args.json:
        code, result = scan_dir(args.ckpt_dir, serial=args.serial)
        result['exit_code'] = code
        print(json.dumps(result, indent=2, sort_keys=True))
        return code
    return check_dir(args.ckpt_dir, serial=args.serial, quiet=args.quiet)


if __name__ == '__main__':
    sys.exit(main())
