#!/usr/bin/env python
"""Generate TRACEABILITY.md: reference unittest file -> repo test(s) or
an explicit N/A ruling (VERDICT r4 weak #2 / next #6).

Mapping precedence per reference file:
1. named mirror: tests/<same name>.py exists
2. N/A ruling from the curated table below (design-mapped subsystems:
   MKLDNN/cuDNN variants, protobuf plumbing, CUDA-only machinery)
3. op coverage: for test_<op>_op.py, repo test files that exercise the
   op by name (op-registry string or layers.<op> call)
4. keyword coverage: non-op files whose subject symbol appears in a
   repo test file
Anything left is UNMAPPED and fails tests/test_traceability.py.

Run: python tools/gen_traceability.py   (writes TRACEABILITY.md)
"""
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REF_UT = '/root/reference/python/paddle/fluid/tests/unittests'
OUT = os.path.join(REPO, 'TRACEABILITY.md')

# ---- curated N/A rulings (regex on basename -> reason) --------------------------
NA_RULES = [
    (r'_mkldnn_op\.py$|_mkldnn\.py$',
     'MKLDNN kernel variant: x86-library dispatch replaced by XLA '
     'fusion (SURVEY design ruling); the base op has parity tests'),
    (r'^test_cudnn_', 'cuDNN kernel variant: GPU-library dispatch '
     'replaced by XLA; base op covered'),
    (r'^test_nccl', 'NCCL plumbing: replaced by XLA collectives over '
     'Mesh (tests/test_parallel.py covers the replacement)'),
    (r'^test_nvprof', 'nvprof CUDA profiler hook: TPU path uses '
     'jax.profiler + tools/timeline.py (tests/test_profiler.py)'),
    (r'^test_protobuf', 'protobuf desc plumbing: the IR is native '
     'Python (framework.py), no proto layer exists by design'),
    (r'^test_op_support_gpu|^test_operator_desc|^test_operator\.py$|'
     r'^test_op_registry|^test_infer_shape',
     'C++ OpDesc/registry/InferShape machinery: replaced by the '
     'Python IR + kernel registry (tests/test_framework.py covers '
     'the replacement surface)'),
    (r'^test_program\.py$|^test_parallel_op\.py$',
     'covered under a different name: tests/test_framework.py '
     '(Program/Block semantics) and tests/test_parallel.py '
     '(ParallelDo -> mesh dp)'),
    (r'^test_data_feeder', 'covered: tests/test_executor.py + '
     'tests/test_sequence.py DataFeeder cases'),
    (r'^test_default_scope_funcs', 'C++ scope function bindings: '
     'Scope is Python (tests/test_executor.py)'),
    (r'^test_dyn_rnn\.py$', 'covered: tests/test_control_flow.py '
     'DynamicRNN cases + tests/test_dynrnn_gradient_check.py'),
    (r'^test_exception', 'pybind exception translation: native errors '
     'carry op provenance instead (tests/test_debug_memory.py)'),
    (r'^test_feed_fetch_method', 'C++ feed/fetch method bindings: '
     'covered by every Executor test'),
    (r'^test_fetch_var', 'covered: tests/test_executor.py fetch_var '
     'cases'),
    (r'^test_gaussian_random_batch_size_like_op',
     'covered: batch-size-like fill family in tests/test_ref_parity*'),
    (r'^test_memory_optimization_transpiler|^test_weight_normalization|'
     r'^test_calc_gradient|^test_dynrnn_gradient_check|'
     r'^test_math_op_patch|^test_normalization_wrapper|'
     r'^test_multihead_attention|^test_reorder_lod_tensor|'
     r'^test_lod_tensor_array_ops',
     None),  # named mirrors exist now; rule kept for ordering clarity
    (r'^test_mine_hard_examples_op|^test_target_assign_op',
     'SSD-specific detection helpers: covered via '
     'tests/test_detection.py end-to-end detection cases'),
    (r'^test_dist_train|^test_simple_dist_transpiler|^test_split_ids_op',
     'pserver gRPC machinery: replaced by SPMD collectives '
     '(tests/test_distributed_multiproc.py is the multi-process leg; '
     'transpiler surface in tests/test_parallel.py)'),
    (r'^test_debugger', 'covered: tests/test_debug_memory.py '
     '(debugger/graphviz draw)'),
    (r'^test_multi_file_reader|^test_multi_pass_reader|'
     r'^test_recv_op|^test_is_empty_op',
     'covered: tests/test_io.py reader decorators / '
     'tests/test_misc_ops.py'),
    (r'^test_registry', 'covered: kernel registry exercised by every '
     'op test; registration errors in tests/test_framework.py'),
]

# symbols to grep for non-op files: basename test_<subject>.py -> subject
SPECIAL_SUBJECT = {
    'test_lod_tensor': 'create_lod_tensor',
    'test_lod_rank_table': 'lod_rank_table',
    'test_selected_rows': 'SparseRows',
}

# curated different-name coverage: reference basename -> (repo tests,
# verified symbol that ties them). Kept explicit so the matrix is
# auditable file-by-file.
COVERED = {
    'test_array_read_write_op.py':
        ('tests/test_control_flow.py', 'array_write/array_read'),
    'test_compare_op.py':
        ('tests/test_ref_parity3.py, tests/test_math_op_patch.py',
         'less_than family + Variable comparisons'),
    'test_conditional_block.py':
        ('tests/test_control_flow.py', 'IfElse (conditional_block '
         'lowered as masked split/merge)'),
    'test_dist_transpiler.py':
        ('tests/test_parallel.py, tests/test_distributed_multiproc.py',
         'distribute_transpiler'),
    'test_dynrnn_static_input.py':
        ('tests/test_control_flow.py', 'DynamicRNN.static_input'),
    'test_elementwise_gradient_op.py':
        ('tests/test_ref_parity3.py', 'elementwise grad cases '
         '(_op_grad_check)'),
    'test_executor_and_mul.py':
        ('tests/test_executor.py', 'Executor + mul'),
    'test_framework_debug_str.py':
        ('tests/test_framework.py', 'Program.to_string'),
    'test_image_classification_layer.py':
        ('tests/test_layers.py', 'conv/bn composite layers'),
    'test_inference_model_io.py':
        ('tests/test_io.py, tests/test_fit_a_line.py',
         'save/load_inference_model'),
    'test_learning_rate_scheduler.py':
        ('tests/test_backward_optimizers.py', 'lr decay schedules'),
    'test_lod_array_length_op.py':
        ('tests/test_control_flow.py', 'array_length'),
    'test_lod_tensor_array.py':
        ('tests/test_control_flow.py, tests/test_lod_tensor_array_ops'
         '.py', 'tensor-array round trips'),
    'test_logical_op.py':
        ('tests/test_ref_parity3.py', 'logical_and/or/not/xor'),
    'test_lookup_sparse_table_op.py':
        ('tests/test_sparse_embedding.py', 'sparse lookup_table'),
    'test_network_with_dtype.py':
        ('tests/test_executor.py', 'f64 canonicalizes to f32 by design '
         '(TPU has no fast f64; runtime_dtype)'),
    'test_parallel_executor_crf.py':
        ('tests/test_parallel.py, tests/test_crf_ctc_search.py',
         'ParallelExecutor + CRF'),
    'test_parallel_executor_fetch_feed.py':
        ('tests/test_parallel.py', 'PE fetch/feed'),
    'test_parallel_executor_mnist.py':
        ('tests/test_parallel.py', 'PE mnist dp'),
    'test_parallel_executor_seresnext.py':
        ('tests/test_parallel.py, tests/test_books.py',
         'PE se_resnext'),
    'test_parallel_executor_test_while_train.py':
        ('tests/test_parallel.py', 'PE train/test alternation'),
    'test_parallel_executor_transformer.py':
        ('tests/test_parallel.py, tests/test_transformer.py',
         'PE transformer'),
    'test_pool_max_op.py':
        ('tests/test_ref_parity.py', 'pool2d max + grad'),
    'test_print_op.py':
        ('tests/test_control_flow.py', 'layers.Print forward + grad'),
    'test_recordio_reader.py':
        ('tests/test_recordio_compat.py, tests/test_io.py',
         'recordio read path incl. reference binary layout'),
    'test_recurrent_op.py':
        ('tests/test_control_flow.py, tests/test_ref_parity3.py',
         'StaticRNN'),
    'test_reduce_op.py':
        ('tests/test_ref_parity.py, tests/test_framework.py',
         'reduce_* dim/keep_dim grids'),
    'test_rnn_memory_helper_op.py':
        ('tests/test_control_flow.py', 'StaticRNN memory (helper op '
         'subsumed by the fused backward)'),
    'test_seq_concat_op.py':
        ('tests/test_sequence.py, tests/test_ref_parity2.py',
         'sequence_concat'),
    'test_seq_conv.py':
        ('tests/test_sequence.py, tests/test_book_sentiment.py',
         'sequence_conv'),
    'test_seq_pool.py':
        ('tests/test_sequence.py, tests/test_book_sentiment.py',
         'sequence_pool all pool_types'),
    'test_split_and_merge_lod_tensor_op.py':
        ('tests/test_control_flow.py, tests/test_ref_parity3.py',
         'split/merge_lod_tensor via IfElse'),
    'test_split_selected_rows_op.py':
        ('tests/test_sparse_embedding.py', 'SparseRows carriers '
         '(pserver row split replaced by SPMD sharding)'),
    'test_split_var.py':
        ('tests/test_parallel.py', 'transpiler var slicing (ZeRO '
         'byte accounting)'),
    'test_while_op.py':
        ('tests/test_control_flow.py', 'While -> lax.while_loop'),
    'test_const_value.py':
        ('tests/test_framework.py', 'framework constants '
         '(grad suffix etc.)'),
    'test_create_op_doc_string.py':
        ('tests/test_framework.py', 'N/A in substance: C++ OpProto '
         'doc strings have no analog; op registry introspection '
         'covered'),
}


def list_repo_tests():
    tdir = os.path.join(REPO, 'tests')
    out = {}
    for fn in sorted(os.listdir(tdir)):
        if fn.startswith('test_') and fn.endswith('.py'):
            with open(os.path.join(tdir, fn)) as f:
                out[fn] = f.read()
    return out


def op_names_from_file(base):
    """test_<op>_op.py -> candidate op-name strings."""
    stem = base[len('test_'):-len('.py')]
    if stem.endswith('_op'):
        stem = stem[:-3]
    names = {stem}
    # common family aliases
    if stem.startswith('elementwise_'):
        names.add(stem)
    if stem.startswith('sequence_'):
        names.add(stem)
    return names


def find_op_coverage(names, repo_tests):
    hits = []
    pats = [re.compile(r"['\"]%s['\"]|layers\.%s\b|\b%s\(" %
                       (re.escape(n), re.escape(n), re.escape(n)))
            for n in names]
    for fn, text in repo_tests.items():
        if any(p.search(text) for p in pats):
            hits.append(fn)
    return hits


# ---- reference tests OUTSIDE unittests/ (fluid/tests/*.py, demo/,
# book_memory_optimization/) — curated kind + mapping per file --------------------
TOPLEVEL = [
    ('test_concurrency.py', 'covered',
     'tests/test_highlevel_api.py — channels/select host-side scope'),
    ('notest_concurrency.py', 'covered',
     'tests/test_highlevel_api.py — channels/select host-side scope'),
    ('test_cpp_reader.py', 'N/A',
     'C++ reader-op machinery: the native prefetch loader + program '
     'readers are covered by tests/test_native.py and tests/test_io.py'),
    ('test_data_feeder.py', 'mirror', 'tests/test_data_feeder.py'),
    ('test_detection.py', 'mirror', 'tests/test_detection.py'),
    ('test_error_clip.py', 'mirror', 'tests/test_error_clip.py'),
    ('test_gradient_clip.py', 'mirror', 'tests/test_gradient_clip.py'),
    ('test_lod_tensor.py', 'mirror', 'tests/test_lod_tensor.py'),
    ('test_mnist_if_else_op.py', 'mirror',
     'tests/test_mnist_if_else_op.py (reference file is disabled '
     'upstream; mirror fixes its limit shape and passes)'),
    ('test_python_operator_overriding.py', 'covered',
     'tests/test_math_op_patch.py — Variable operator overloads'),
    ('book_memory_optimization/test_memopt_fit_a_line.py', 'covered',
     'tests/test_memory_optimization_transpiler.py + BENCH memory '
     'artifact (remat -55% temp on the transformer)'),
    ('book_memory_optimization/test_memopt_image_classification_train'
     '.py', 'covered',
     'tests/test_memory_optimization_transpiler.py (losses identical '
     'under memory_optimize)'),
    ('book_memory_optimization/test_memopt_machine_translation.py',
     'covered',
     'tests/test_memory_optimization_transpiler.py + '
     'tests/test_books.py NMT'),
    ('demo/fc_gan.py', 'mirror', 'tests/test_fc_gan.py'),
    ('demo/text_classification/train.py', 'mirror',
     'tests/test_demo_text_classification.py — the script\'s OWN '
     'network_cfg runs unchanged: recordio -> open_files -> shuffle -> '
     'double_buffer -> read_file -> ParallelExecutor train + '
     'share_vars_from eval + reader reset'),
]


def main():
    repo_tests = list_repo_tests()
    ref_files = sorted(
        f for f in os.listdir(REF_UT)
        if f.startswith('test_') and f.endswith('.py'))
    rows = []
    unmapped = []
    counts = {'mirror': 0, 'na': 0, 'op-coverage': 0,
              'keyword': 0, 'unmapped': 0}
    for base in ref_files:
        # 1. named mirror
        if base in repo_tests:
            rows.append((base, 'mirror', 'tests/' + base))
            counts['mirror'] += 1
            continue
        # 2. curated different-name coverage
        if base in COVERED:
            tests, why = COVERED[base]
            rows.append((base, 'covered', '%s — %s' % (tests, why)))
            counts['covered'] = counts.get('covered', 0) + 1
            continue
        # 2b. curated N/A
        reason = None
        for pat, r in NA_RULES:
            if r is not None and re.search(pat, base):
                reason = r
                break
        if reason:
            rows.append((base, 'N/A', reason))
            counts['na'] += 1
            continue
        # 3. op-name coverage
        if base.endswith('_op.py'):
            hits = find_op_coverage(op_names_from_file(base), repo_tests)
            if hits:
                rows.append((base, 'op-coverage', ', '.join(
                    'tests/' + h for h in hits[:4]) +
                    (' (+%d more)' % (len(hits) - 4)
                     if len(hits) > 4 else '')))
                counts['op-coverage'] += 1
                continue
        # 4. keyword coverage for non-op files
        stem = base[len('test_'):-len('.py')]
        subject = SPECIAL_SUBJECT.get(base[:-3], stem)
        hits = [fn for fn, text in repo_tests.items()
                if re.search(r'\b%s\b' % re.escape(subject), text)]
        if hits:
            rows.append((base, 'keyword', ', '.join(
                'tests/' + h for h in hits[:4])))
            counts['keyword'] += 1
            continue
        rows.append((base, 'UNMAPPED', ''))
        unmapped.append(base)
        counts['unmapped'] += 1

    for base, kind, detail in TOPLEVEL:
        counts[kind if kind != 'N/A' else 'na'] = \
            counts.get(kind if kind != 'N/A' else 'na', 0) + 1
        if kind == 'mirror':
            target = detail.split()[0].replace('tests/', '').rstrip(',')
            target = target.split('\u2014')[0].strip()
            assert os.path.exists(os.path.join(REPO, 'tests', target)), \
                'TOPLEVEL mirror target missing: %s' % detail

    with open(OUT, 'w') as f:
        f.write('# Reference unittest traceability matrix\n\n')
        f.write('Generated by `python tools/gen_traceability.py` — do '
                'not edit by hand.\nMaps every '
                '`python/paddle/fluid/tests/unittests/test_*.py` in '
                'the reference — PLUS the\ncurated '
                '`fluid/tests/*.py`, `demo/`, and '
                '`book_memory_optimization/` files in the\nsecond '
                'table — to the repo test(s) that carry its '
                'semantics, or to an explicit\ndesign ruling. The '
                'count table spans BOTH tables.\n\n')
        f.write('| kind | count |\n|---|---|\n')
        for k in ('mirror', 'covered', 'op-coverage', 'keyword', 'na',
                  'unmapped'):
            f.write('| %s | %d |\n' % (k, counts.get(k, 0)))
        f.write('\n| reference file | kind | repo test(s) / ruling |\n')
        f.write('|---|---|---|\n')
        for base, kind, detail in rows:
            f.write('| %s | %s | %s |\n' % (base, kind, detail))
        f.write('\n## fluid/tests (outside unittests/), demo, '
                'book_memory_optimization\n\n')
        f.write('| reference file | kind | repo test(s) / ruling |\n')
        f.write('|---|---|---|\n')
        for base, kind, detail in TOPLEVEL:
            f.write('| %s | %s | %s |\n' % (base, kind, detail))
    print('wrote %s: %s' % (OUT, counts))
    if unmapped:
        print('UNMAPPED (%d):' % len(unmapped))
        for u in unmapped:
            print('  ', u)
    return 1 if unmapped else 0


if __name__ == '__main__':
    sys.exit(main())
