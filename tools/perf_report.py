#!/usr/bin/env python
"""Per-program performance report + regression sentinel
(OBSERVABILITY.md "Performance observatory").

Three modes:

- ``--journal run.jsonl`` — render the perf section of a recorded run:
  per-program flops, bytes accessed, MFU, roofline classification, HBM
  live bytes and compile wall, straight from the ``perf_ledger``
  events the Executor journals on every compile miss (stdlib parse; no
  framework import).
- ``--smoke`` — run the deterministic CPU perf workload (the tier-1
  bench programs: an MLP train step and an FC inference step, built
  under ``unique_name.guard()`` so fingerprints are stable across
  processes), capture their ledgers through the live Executor path,
  and print the report. With ``--baseline PERF_BASELINE.json`` the run
  is DIFFED against the committed baseline and the process exits
  nonzero on any regression, naming the program: deterministic fields
  (flops, bytes) must match within 2%; timing fields (``step_ms``,
  ``mfu``), when the baseline carries them, gate at ``--tol``.
- ``--smoke --update-baseline PATH [--with-timings]`` — (re)write the
  baseline from the current run. The committed repo baseline holds
  deterministic fields only; ``--with-timings`` adds step_ms/MFU for
  same-box comparisons (never commit timings from a CI box).

    python tools/perf_report.py --journal run.jsonl
    python tools/perf_report.py --smoke --baseline PERF_BASELINE.json
    python tools/perf_report.py --smoke --update-baseline PERF_BASELINE.json
"""
import argparse
import json
import os
import sys
import time

os.environ.setdefault('JAX_PLATFORMS', 'cpu')
sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), '..',
                                'PERF_BASELINE.json')


def _force_cpu():
    import jax
    try:
        jax.config.update('jax_platforms', 'cpu')
    except Exception:
        pass


# ---- journal mode (stdlib-only) -------------------------------------------
def journal_ledgers(path):
    """Merge the ``perf_ledger`` events of a journal into one dict per
    program fingerprint (seal row first, measured updates folded in)."""
    progs = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get('ev') != 'perf_ledger':
                continue
            fp = rec.get('fp')
            cur = progs.setdefault(fp, {})
            cur.update({k: v for k, v in rec.items()
                        if k not in ('ev', 'run', 't', 'phase')
                        and v is not None})
    return progs


def render(progs, out=sys.stdout):
    if not progs:
        print('no perf_ledger events (is capture enabled? '
              'PTPU_PERF=1 / observability.perf.enable_capture)',
              file=out)
        return
    print('perf observatory: %d program(s)' % len(progs), file=out)
    hdr = ('  %-12s %-10s %12s %12s %8s %10s %10s %9s'
           % ('program', 'mesh', 'MFLOP', 'MB accessed', 'MFU',
              'roofline', 'live MB', 'compile'))
    print(hdr, file=out)
    watermark = 0
    for fp, d in sorted(progs.items(), key=lambda kv: -(
            kv[1].get('flops') or 0)):
        name = d.get('program') or (fp or '?')[:12]
        mfu = d.get('mfu')
        live = d.get('live_bytes') or 0
        watermark += live
        print('  %-12s %-10s %12.3f %12.2f %8s %10s %10.2f %8ss'
              % (name[:12], d.get('mesh', '-'),
                 (d.get('flops') or 0) / 1e6,
                 (d.get('bytes_accessed') or 0) / 1e6,
                 '%.4f' % mfu if mfu is not None else '-',
                 d.get('roofline', '-'), live / 1e6,
                 '%.2f' % d.get('compile_wall_s', 0.0)), file=out)
    print('  HBM watermark (sum of live bytes): %.2f MB'
          % (watermark / 1e6), file=out)


# ---- smoke workload --------------------------------------------------------
def _smoke_programs():
    """The deterministic tier-1 bench programs. Built under
    ``unique_name.guard()`` so variable names — and therefore program
    fingerprints, the baseline key — are stable across processes."""
    import numpy as np
    import paddle_tpu.fluid as fluid

    specs = []

    # 1) MLP train step: fc-relu-fc-softmax + Adam, batch 16
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 11
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            img = fluid.layers.data(name='img', shape=[64],
                                    dtype='float32')
            label = fluid.layers.data(name='label', shape=[1],
                                      dtype='int64')
            h = fluid.layers.fc(input=img, size=32, act='relu')
            pred = fluid.layers.fc(input=h, size=10, act='softmax')
            loss = fluid.layers.mean(fluid.layers.cross_entropy(
                input=pred, label=label))
            fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    rng = np.random.RandomState(0)
    feed = {'img': rng.randn(16, 64).astype('float32'),
            'label': rng.randint(0, 10, (16, 1)).astype('int64')}
    specs.append(('mlp_train', main, startup, feed, [loss]))

    # 2) FC inference step, batch 32
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 12
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data(name='x', shape=[64],
                                  dtype='float32')
            h = fluid.layers.fc(input=x, size=48, act='relu')
            y = fluid.layers.fc(input=h, size=8, act=None)
    feed = {'x': rng.randn(32, 64).astype('float32')}
    specs.append(('fc_infer', main, startup, feed, [y]))

    # 3) conv+BN+ReLU inference step: the conv_epilogue_fuse path —
    # the ledger rows the fused-conv bandwidth gate diffs (bytes
    # accessed must stay put on CPU where the fused op replays exactly)
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 13
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            im = fluid.layers.data(name='im', shape=[8, 16, 16],
                                   dtype='float32')
            c = fluid.layers.conv2d(input=im, num_filters=16,
                                    filter_size=3, padding=1)
            b = fluid.layers.batch_norm(input=c, is_test=True)
            r = fluid.layers.relu(b)
    feed = {'im': rng.randn(4, 8, 16, 16).astype('float32')}
    specs.append(('conv_fuse_infer', main, startup, feed, [r]))
    return specs


def run_smoke(steps=8, with_timings=False):
    """Compile + run the smoke programs through the live Executor
    capture path. Returns ({baseline_key: entry}, [ProgramLedger])."""
    _force_cpu()
    import paddle_tpu.fluid as fluid
    from paddle_tpu.observability import perf

    current, captured = {}, []
    with perf.capture_scope(True):
        for name, main, startup, feed, fetches in _smoke_programs():
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                exe.run(main, feed=feed, fetch_list=fetches)  # compile
                walls = []
                for _ in range(steps):
                    t0 = time.perf_counter()
                    exe.run(main, feed=feed, fetch_list=fetches)
                    walls.append(time.perf_counter() - t0)
            fp = main.fingerprint()
            ledger = perf.get_ledger(fp)
            if ledger is None:
                continue
            ledger.label = name
            walls.sort()
            perf.publish_step(fp, walls[len(walls) // 2])
            key = perf.PerfBaseline.key(ledger.fingerprint,
                                        ledger.shape_sig,
                                        ledger.backend, ledger.mesh)
            current[key] = perf.PerfBaseline.entry_from_ledger(
                ledger, with_timings=with_timings)
            captured.append(ledger)
    return current, captured


def main(argv=None):
    ap = argparse.ArgumentParser(
        description='per-program perf report + regression sentinel')
    ap.add_argument('--journal', help='render a recorded journal')
    ap.add_argument('--smoke', action='store_true',
                    help='run the deterministic CPU perf workload')
    ap.add_argument('--baseline', nargs='?', const=DEFAULT_BASELINE,
                    help='diff the smoke run against this baseline '
                         '(default: repo PERF_BASELINE.json); exits '
                         'nonzero on regression')
    ap.add_argument('--update-baseline', metavar='PATH',
                    help='write the smoke run as the new baseline')
    ap.add_argument('--with-timings', action='store_true',
                    help='include step_ms/mfu in baseline entries '
                         '(same-box comparisons only)')
    ap.add_argument('--tol', type=float, default=0.25,
                    help='relative tolerance for step-time/MFU '
                         'regressions (default 0.25)')
    ap.add_argument('--steps', type=int, default=8,
                    help='timed steps per smoke program')
    ap.add_argument('--json', action='store_true',
                    help='emit machine-readable JSON instead of text')
    args = ap.parse_args(argv)

    if args.journal:
        progs = journal_ledgers(args.journal)
        if args.json:
            print(json.dumps(progs, indent=1, sort_keys=True))
        else:
            render(progs)
        return 0

    if not args.smoke:
        ap.error('one of --journal or --smoke is required')

    from paddle_tpu.observability import perf
    timings = args.with_timings or bool(args.baseline)
    current, captured = run_smoke(steps=args.steps,
                                  with_timings=timings)
    if not captured:
        print('FAIL: smoke workload captured no ledgers',
              file=sys.stderr)
        return 1
    progs = {l.fingerprint: l.as_dict() for l in captured}
    if args.json:
        print(json.dumps({'programs': progs, 'entries': current},
                         indent=1, sort_keys=True))
    else:
        render(progs)

    if args.update_baseline:
        base = perf.PerfBaseline(args.update_baseline)
        for key, entry in current.items():
            if not args.with_timings:
                entry = {k: v for k, v in entry.items()
                         if k not in ('step_ms', 'mfu')}
            base.put(key, entry)
        base.save()
        print('baseline written: %s (%d entries)'
              % (args.update_baseline, len(base.entries)))
        return 0

    if args.baseline:
        base = perf.PerfBaseline(args.baseline).load()
        if not base.entries:
            print('FAIL: baseline %s missing or empty' % args.baseline,
                  file=sys.stderr)
            return 1
        problems = base.diff(current, tol=args.tol)
        if problems:
            print('PERF REGRESSION (%d problem(s) vs %s):'
                  % (len(problems), args.baseline), file=sys.stderr)
            for p in problems:
                print('  - %s' % p, file=sys.stderr)
            return 1
        print('perf baseline OK (%d program(s) vs %s)'
              % (len(base.entries), args.baseline))
    return 0


if __name__ == '__main__':
    sys.exit(main())
