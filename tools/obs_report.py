#!/usr/bin/env python
"""Render a paddle_tpu observability run journal into a human report.

The input is the JSONL file written by
``paddle_tpu.observability.RunJournal`` (schema: OBSERVABILITY.md).
Standalone on purpose — only stdlib imports, so it runs anywhere the
journal file landed, with no jax/paddle_tpu install.

    python tools/obs_report.py run.jsonl            # human report
    python tools/obs_report.py run.jsonl --top 20   # more slow spans
    python tools/obs_report.py run.jsonl --json -   # summary as JSON
    python tools/obs_report.py run.jsonl --smoke    # CI gate

``--smoke`` exits nonzero when the journal is empty, contains malformed
lines, or lacks the required records (``--require step`` by default —
a training journal must hold step records; ``--require serving`` for a
serving soak; ``--require pipeline`` for a pipelined-trainer run —
step records must carry the ``feed_wait`` host-wait field; ``--require
compiler`` for a run that must have gone through the compiler pass
pipeline (``compile_pass`` records); ``--require partition`` for a run
that must have placed work through the Partitioner (``partition``
records, PARTITIONING.md); ``--require resilience`` for a run that
must have exercised preemption saves or topology resharding
(``preempt_save`` / ``reshard`` records, RESILIENCE.md); ``--require
fleet`` for a run through the replica router / continuous-batching
decode engine (``fleet`` / ``decode`` records, SERVING.md);
``--require analysis`` for a run that must have exercised the static
program verifier (``analysis`` records, ANALYSIS.md); ``--require
tracing`` for a run that must hold completed distributed-tracing spans
(``span_end`` records, OBSERVABILITY.md — unclosed spans never fail
the gate; fault injection legitimately leaves them); ``--require
perf`` for a run that must have captured per-program performance
ledgers (``perf_ledger`` records, OBSERVABILITY.md "Performance
observatory"); ``--require autoscale`` for a self-driving fleet run —
``autoscale`` records must include at least one acted scale_up /
scale_down decision (SERVING.md "Self-driving fleet"); ``--require
coldstart`` for an AOT-warmed run — ``coldstart`` records must show
both a store save and a warm hit; ``--require kvcache`` for a
paged-KV / disaggregated-prefill run — ``kvcache`` records must show
both page-pool allocs and at least one prefilled prompt (SERVING.md
"Paged KV-cache & disaggregated prefill"); ``--require slo`` for a
run under declared service-level objectives — ``slo`` records must
show a burn-rate breach AND a recovery (OBSERVABILITY.md "SLO burn
rates"); ``--require telemetry`` for a run scraped through the live
telemetry plane — ``telemetry`` records must show an aggregator
scrape (OBSERVABILITY.md "Telemetry plane"); ``--require
remote_elastic`` for a cross-host elastic run — ``fleet`` records
must cover the whole remote replica lifecycle: a ``spawn_remote``,
a ``host_lost`` detected inside its heartbeat window, an in-flight
``requeue`` and a scale-in ``retire`` (RESILIENCE.md "Cross-host
elasticity"); ``--require autotune`` for a schedule-search run —
``autotune`` records must include a completed (``phase='end'``)
measured sweep (COMPILER.md "Schedule search"); ``--require any`` for
presence only). Run ``--list-requires`` for the full machine-derived
catalog — the argparse choices come straight from ``REQUIRED_EV``, so
the list above can lag but the tool cannot.
``tools/serve_bench.py --smoke`` runs this gate over the journal its
load run writes.
"""
import argparse
import json
import sys

REQUIRED_EV = {'step': 'step_end', 'serving': 'serving_batch',
               'pipeline': 'step_end', 'compiler': 'compile_pass',
               'partition': 'partition',
               # a resilience run must show at least one preemption
               # save OR one topology reshard (RESILIENCE.md)
               'resilience': ('preempt_save', 'reshard'),
               # a fleet run must show router/replica lifecycle events
               # OR continuous-batching decode steps (SERVING.md
               # "Fleet tier & continuous batching")
               'fleet': ('fleet', 'decode'),
               # a ZeRO-2 run must show the mode being applied
               # (bucketed grad tail / sliced state — PERF.md "ZeRO-2
               # and collective overlap") or a measured collective
               'zero': ('zero', 'collective'),
               # a multi-host pod must show bootstrap/barrier/host_lost
               # /relaunch lifecycle events (RESILIENCE.md "Surviving
               # host loss"); the gate also checks every host_lost was
               # detected inside its heartbeat window
               'multihost': 'multihost',
               # a run that must have gone through the static program
               # verifier (Executor miss-path verify / feed checks /
               # pass sanitizer — ANALYSIS.md) shows 'analysis' records
               'analysis': 'analysis',
               # a traced run must hold completed spans (span_end —
               # OBSERVABILITY.md "Distributed tracing"). Unclosed
               # spans are NOT gated: fault injection legitimately
               # leaves them (a killed replica's in-flight work)
               'tracing': 'span_end',
               # a perf-observed run must have ledgered at least one
               # compiled program (cost/memory accounting captured on
               # the Executor's compile-miss path — OBSERVABILITY.md
               # "Performance observatory")
               'perf': 'perf_ledger',
               # a self-driving fleet run must show autoscale decisions
               # (SERVING.md "Self-driving fleet"); the gate further
               # insists at least one decision actually resized the
               # fleet (scale_up / scale_down), not just holds
               'autoscale': 'autoscale',
               # an AOT-warmed run must show cold-start store traffic
               # (save on the compiling replica, hit on the warmed one)
               'coldstart': 'coldstart',
               # a paged-KV / disaggregated-prefill run must show
               # page-pool lifecycle events (SERVING.md "Paged
               # KV-cache & disaggregated prefill"); the gate further
               # insists at least one prompt was actually prefilled
               # (action='prefill'), not just pages cycled
               'kvcache': 'kvcache',
               # a run under declared SLOs must show the burn-rate
               # engine both breaching and recovering (the gate checks
               # the state transitions, not mere presence)
               'slo': 'slo',
               # a run on the live telemetry plane must show endpoint
               # lifecycle + at least one aggregator scrape that saw a
               # live endpoint
               'telemetry': 'telemetry',
               # a cross-host elastic run must show the full remote
               # replica lifecycle (RESILIENCE.md "Cross-host
               # elasticity"): a remote spawn, a heartbeat-detected
               # host loss inside its window, the in-flight requeue,
               # and the scale-in retire back to the floor
               'remote_elastic': 'fleet',
               # a schedule-search run must show completed autotune
               # sweeps (COMPILER.md "Schedule search"); the gate
               # further insists at least one search finished
               # (phase='end') and measured a real candidate
               'autotune': 'autotune',
               'any': None}

# one-line purpose per family, keyed like REQUIRED_EV — rendered by
# --list-requires so the CLI self-documents without re-reading this file
REQUIRE_DOC = {
    'step': 'training journal holds step_end records',
    'serving': 'serving soak holds serving_batch records',
    'pipeline': 'step_end records carry feed_wait (pipelined trainer)',
    'compiler': 'compile_pass records (compiler pass pipeline ran)',
    'partition': 'partition records (Partitioner placed work)',
    'resilience': 'preempt_save / reshard records',
    'fleet': 'fleet / decode records (router or decode engine ran)',
    'zero': 'zero / collective records (ZeRO-2 applied or measured)',
    'multihost': 'multihost lifecycle; host losses inside the window',
    'analysis': 'analysis records (static verifier ran)',
    'tracing': 'completed span_end records',
    'perf': 'perf_ledger records (cost/memory capture ran)',
    'autoscale': 'autoscale records incl. an acted scale decision',
    'coldstart': 'coldstart records incl. a store save and a warm hit',
    'kvcache': 'kvcache records incl. page allocs and a prefill',
    'slo': 'slo records incl. a burn-rate breach and a recovery',
    'telemetry': 'telemetry records incl. an aggregator scrape',
    'remote_elastic': 'fleet spawn_remote + in-window host_lost + '
                      'requeue + retire',
    'autotune': 'autotune records incl. a completed measured search',
    'any': 'presence only (any well-formed journal passes)',
}


def load_journal(path):
    """(records, malformed_line_count) — same contract as
    ``observability.read_journal`` without importing paddle_tpu."""
    records, malformed = [], 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                malformed += 1
                continue
            if not isinstance(rec, dict) or 'ev' not in rec:
                malformed += 1
                continue
            records.append(rec)
    return records, malformed


def _mean(xs):
    return sum(xs) / len(xs) if xs else 0.0


def _pipeline_summary(steps, duration):
    """Input-pipeline SLI (PERF.md "Dispatch pipelining"): how much of
    the run the trainer spent BLOCKED on host feed work (feed_wait) vs
    dispatching compute, and how well chaining amortized dispatches."""
    waits = [r['feed_wait'] for r in steps if 'feed_wait' in r]
    dispatches = [r['dispatch_s'] for r in steps if 'dispatch_s' in r]
    chained = [r for r in steps if r.get('chain', 0) > 1]
    return {
        'steps_with_feed_wait': len(waits),
        'host_wait_total_s': sum(waits),
        'host_wait_mean_s': _mean(waits),
        'host_wait_fraction': (sum(waits) / duration) if duration
        else 0.0,
        'dispatch_total_s': sum(dispatches),
        'chained_steps': len(chained),
        'mean_chain': _mean([r['chain'] for r in chained]),
    }


def _compiler_summary(by_ev):
    """Compiler SLI (COMPILER.md): per-pass wall + rewrite counts from
    ``compile_pass`` events, tuning-cache behavior from
    ``tuning_lookup``/``tuning_preload``/``tuning_put``."""
    passes = {}
    for r in by_ev.get('compile_pass', ()):
        p = passes.setdefault(r.get('pass', '?'), {
            'runs': 0, 'total_s': 0.0, 'removed': 0, 'fused': 0,
            'released': 0})
        p['runs'] += 1
        p['total_s'] += r.get('dur_s', 0.0)
        p['removed'] += r.get('removed', 0)
        p['fused'] += r.get('fused', 0)
        p['released'] += r.get('released', 0)
    lookups = by_ev.get('tuning_lookup', ())
    hits = sum(1 for r in lookups if r.get('hit'))
    return {
        'passes': passes,
        'pass_wall_s': sum(p['total_s'] for p in passes.values()),
        'ops_eliminated': sum(p['removed'] for p in passes.values()),
        'ops_fused': sum(p['fused'] for p in passes.values()),
        'tuning': {
            'lookups': len(lookups),
            'hits': hits,
            'misses': len(lookups) - hits,
            'hit_rate': hits / len(lookups) if lookups else 0.0,
            'preloads': len(by_ev.get('tuning_preload', ())),
            'entries_preloaded': sum(
                r.get('entries', 0)
                for r in by_ev.get('tuning_preload', ())),
            'puts': len(by_ev.get('tuning_put', ())),
        },
    }


def _resilience_summary(by_ev):
    """Resilience SLI (RESILIENCE.md "Sharded checkpoints & topology
    portability"): preemption saves (SIGTERM/SIGINT chunk-boundary
    commits) and restore-time topology reshards (from-mesh -> to-mesh,
    vars placed, wall)."""
    preempts = by_ev.get('preempt_save', ())
    reshards = by_ev.get('reshard', ())
    topologies = {}
    for r in reshards:
        key = '%s -> %s' % (r.get('from_mesh') or '?',
                            r.get('to_mesh') or '?')
        t = topologies.setdefault(key, {'count': 0, 'vars': 0,
                                        'wall_s': 0.0})
        t['count'] += 1
        t['vars'] += r.get('vars', 0)
        t['wall_s'] += r.get('dur_s', 0.0)
    return {
        'preempt_saves': len(preempts),
        'preempt_signals': sorted({r.get('signal') for r in preempts
                                   if r.get('signal') is not None}),
        'reshards': len(reshards),
        'reshard_vars': sum(r.get('vars', 0) for r in reshards),
        'reshard_wall_s': sum(r.get('dur_s', 0.0) for r in reshards),
        'topologies': topologies,
    }


def _partition_summary(by_ev):
    """Partition SLI (PARTITIONING.md): what mesh(es) the run placed
    work on and how much wall went into resharding-class work
    (shard_scope journal events carry dur_s; per-batch staging is
    metric-only by design)."""
    events = by_ev.get('partition', ())
    meshes = {}
    for r in events:
        m = meshes.setdefault(r.get('mesh', '?'), {
            'devices': r.get('devices'), 'creates': 0,
            'scopes_sharded': 0, 'vars_placed': 0, 'reshard_s': 0.0})
        if r.get('devices'):
            m['devices'] = r['devices']
        if r.get('action') == 'create':
            m['creates'] += 1
        elif r.get('action') == 'shard_scope':
            m['scopes_sharded'] += 1
            m['vars_placed'] += r.get('vars', 0)
        m['reshard_s'] += r.get('dur_s', 0.0)
    return {
        'events': len(events),
        'meshes': meshes,
        'scopes_sharded': sum(m['scopes_sharded']
                              for m in meshes.values()),
        'vars_placed': sum(m['vars_placed'] for m in meshes.values()),
        'reshard_wall_s': sum(m['reshard_s'] for m in meshes.values()),
    }


def _zero_summary(by_ev):
    """ZeRO-2 SLI (PERF.md "ZeRO-2 and collective overlap"): mode
    applications from ``zero`` events (buckets, sliced/replicated state
    tensors, per-device grad-shard bytes) and measured collective walls
    from ``collective`` events — ``overlap_fraction`` is the share of
    the standalone collective wall HIDDEN under compute (1.0 = the
    sharded step pays nothing visible over the replicated step)."""
    events = by_ev.get('zero', ())
    applies = [r for r in events if r.get('action') == 'apply']
    colls = by_ev.get('collective', ())
    total_coll_s = sum(r.get('standalone_s', 0.0) for r in colls)
    visible_s = sum(r.get('visible_s', 0.0) for r in colls)
    overlap = None
    if total_coll_s > 0:
        overlap = max(0.0, min(1.0, 1.0 - visible_s / total_coll_s))
    return {
        'events': len(events),
        'applied': len(applies),
        'buckets': sum(r.get('buckets', 0) for r in applies),
        'grads': sum(r.get('grads', 0) for r in applies),
        'sliced_state': sum(r.get('sliced', 0) for r in applies),
        'replicated_state': sum(r.get('replicated', 0)
                                for r in applies),
        'shard_bytes': max((r.get('shard_bytes', 0) for r in applies),
                           default=0),
        'collectives': {
            'measured': len(colls),
            'standalone_wall_s': total_coll_s,
            'visible_wall_s': visible_s,
            'overlap_fraction': overlap,
            'by_op': {
                op: sum(r.get('standalone_s', 0.0) for r in colls
                        if r.get('op') == op)
                for op in sorted({r.get('op', '?') for r in colls})},
        },
    }


def _analysis_summary(by_ev):
    """Static-verifier SLI (ANALYSIS.md): applications of the program
    verifier / feed checks / pass sanitizer from ``analysis`` events —
    diagnostics found per phase, verify wall, and which compiler
    passes ran under the sanitizer."""
    events = by_ev.get('analysis', ())
    phases = {}
    for r in events:
        p = phases.setdefault(r.get('phase', '?'), {
            'runs': 0, 'errors': 0, 'warnings': 0, 'wall_s': 0.0})
        p['runs'] += 1
        p['errors'] += r.get('errors', 0)
        p['warnings'] += r.get('warnings', 0)
        p['wall_s'] += r.get('dur_s', 0.0)
    return {
        'events': len(events),
        'errors': sum(p['errors'] for p in phases.values()),
        'warnings': sum(p['warnings'] for p in phases.values()),
        'wall_s': sum(p['wall_s'] for p in phases.values()),
        'phases': phases,
        'sanitized_passes': sorted({
            r['pass'] for r in events
            if r.get('phase') == 'sanitize' and r.get('pass')}),
    }


def _multihost_summary(by_ev):
    """Multi-host SLI (RESILIENCE.md "Surviving host loss"): pod
    lifecycle from ``multihost`` events — bootstraps per host,
    barriers/agreement checks, whole-host losses with their detection
    latency against the heartbeat window, degraded relaunches."""
    events = by_ev.get('multihost', ())
    actions = {}
    for r in events:
        actions[r.get('action', '?')] = \
            actions.get(r.get('action', '?'), 0) + 1
    losses = [r for r in events if r.get('action') == 'host_lost']
    detects = [r['detect_s'] for r in losses if 'detect_s' in r]
    relaunches = [r for r in events if r.get('action') == 'relaunch']
    boots = [r for r in events if r.get('action') == 'bootstrap']
    return {
        'events': len(events),
        'actions': actions,
        'bootstraps': len(boots),
        'world': max((r.get('world', 0) for r in boots), default=0),
        'barriers': actions.get('barrier', 0),
        'agreement_failures': actions.get('agreement_fail', 0),
        'hosts_lost': len(losses),
        'loss_reasons': sorted({str(r.get('reason', '?'))
                                for r in losses}),
        'detect_max_s': max(detects) if detects else None,
        'detect_mean_s': _mean(detects) if detects else None,
        'losses_outside_window': sum(
            1 for r in losses
            if 'detect_s' in r and 'window_s' in r
            and r['detect_s'] > r['window_s']),
        'relaunches': len(relaunches),
        'final_world': relaunches[-1].get('world') if relaunches
        else (max((r.get('world', 0) for r in boots), default=None)),
    }


def _fleet_summary(by_ev):
    """Fleet SLI (SERVING.md "Fleet tier & continuous batching"):
    replica lifecycle (quarantines, kills, restarts, swaps) from
    ``fleet`` events, continuous-batching decode behavior (steps,
    occupancy, admissions/retirements) from ``decode`` events."""
    events = by_ev.get('fleet', ())
    actions = {}
    for r in events:
        actions[r.get('action', '?')] = \
            actions.get(r.get('action', '?'), 0) + 1
    decode = by_ev.get('decode', ())
    occ = [r['occupancy'] for r in decode if 'occupancy' in r]
    return {
        'events': len(events),
        'actions': actions,
        'requeues': actions.get('requeue', 0),
        'restarts': actions.get('restart', 0),
        'swaps': actions.get('swap', 0),
        'decode': {
            'steps': len(decode),
            'mean_occupancy': _mean(occ),
            'min_occupancy': min(occ) if occ else 0.0,
            'admitted': sum(r.get('admitted', 0) for r in decode),
            'retired': sum(r.get('retired', 0) for r in decode),
            'slot_steps': sum(r.get('live', 0) for r in decode),
        },
    }


def _tracing_summary(by_ev):
    """Tracing SLI (OBSERVABILITY.md "Distributed tracing"): span
    counts per kind, distinct traces, link records, UNCLOSED spans
    (span_begin with no span_end in THIS journal — work that died with
    the process, or continued in another journal: tools/trace_report.py
    merges files before judging), and the top critical paths (largest
    roots with their dominant child chains)."""
    begins = by_ev.get('span_begin', ())
    ends = by_ev.get('span_end', ())
    ended = {r.get('span') for r in ends}
    unclosed = [r for r in begins if r.get('span') not in ended]
    kinds = {}
    children = {}
    for r in ends:
        k = kinds.setdefault(r.get('name', '?'), {
            'count': 0, 'total_s': 0.0, 'max_s': 0.0})
        k['count'] += 1
        k['total_s'] += r.get('dur_s', 0.0)
        k['max_s'] = max(k['max_s'], r.get('dur_s', 0.0))
        children.setdefault(r.get('parent'), []).append(r)
    ends_by_id = {r.get('span'): r for r in ends}
    roots = [r for r in ends
             if r.get('parent') is None
             or r.get('parent') not in ends_by_id]
    roots.sort(key=lambda r: -r.get('dur_s', 0.0))
    paths = []
    for root in roots[:5]:
        path, rec = [], root
        for _ in range(8):
            path.append('%s(%.1fms)' % (rec.get('name', '?'),
                                        rec.get('dur_s', 0.0) * 1e3))
            kids = children.get(rec.get('span'))
            if not kids:
                break
            rec = max(kids, key=lambda r: r.get('dur_s', 0.0))
        paths.append(' > '.join(path))
    return {
        'spans': len(ends),
        'traces': len({r.get('trace') for r in ends
                       if r.get('trace')}),
        'links': len(by_ev.get('span_link', ())),
        'unclosed': len(unclosed),
        'unclosed_names': sorted({r.get('name', '?')
                                  for r in unclosed}),
        'kinds': kinds,
        'critical_paths': paths,
    }


def _perf_summary(by_ev):
    """Perf SLI (OBSERVABILITY.md "Performance observatory"):
    per-program cost/memory ledgers from ``perf_ledger`` events. Seal
    rows (compile-miss capture) and measured rows (phase=measured,
    folded in once a step time lands) are merged per fingerprint."""
    progs = {}
    for r in by_ev.get('perf_ledger', ()):
        cur = progs.setdefault(r.get('fp'), {})
        cur.update({k: v for k, v in r.items()
                    if k not in ('ev', 'run', 't', 'phase')
                    and v is not None})
    bounds = {}
    for d in progs.values():
        b = d.get('roofline')
        if b:
            bounds[b] = bounds.get(b, 0) + 1
    return {
        'programs': len(progs),
        'live_bytes_total': sum(d.get('live_bytes') or 0
                                for d in progs.values()),
        'compile_wall_s': sum(d.get('compile_wall_s') or 0.0
                              for d in progs.values()),
        'roofline_bounds': bounds,
        'by_program': {
            (d.get('program') or (fp or '?')[:12]): {
                'flops': d.get('flops'),
                'bytes_accessed': d.get('bytes_accessed'),
                'live_bytes': d.get('live_bytes'),
                'mfu': d.get('mfu'),
                'roofline': d.get('roofline'),
                'measured_ms': d.get('measured_ms'),
                'compile_wall_s': d.get('compile_wall_s'),
                'mesh': d.get('mesh'),
            } for fp, d in progs.items()},
    }


def _autotune_summary(by_ev):
    """Schedule-search SLI (COMPILER.md "Schedule search"): completed
    autotune sweeps per program (candidates measured, ledger-pruned,
    poisoned, winner + best ms, search wall), plus the fused-conv
    fallback ledger — every op the compiler fused but the lowering
    replayed unfused, with the rejection reason."""
    events = by_ev.get('autotune', ())
    ends = [r for r in events if r.get('phase') == 'end']
    searches = {}
    for r in ends:
        s = searches.setdefault(r.get('program', '?'), {
            'searches': 0, 'candidates': 0, 'poisoned': 0,
            'pruned': 0, 'seconds': 0.0, 'winner': None,
            'best_ms': None})
        s['searches'] += 1
        s['candidates'] += r.get('candidates', 0)
        s['poisoned'] += r.get('poisoned', 0)
        s['pruned'] += r.get('pruned', 0)
        s['seconds'] += r.get('seconds', 0.0)
        s['winner'] = r.get('winner') or s['winner']
        if r.get('best_ms') is not None:
            s['best_ms'] = r['best_ms']
    fallbacks = by_ev.get('conv_fuse_fallback', ())
    reasons = {}
    for r in fallbacks:
        reasons[r.get('reason', '?')] = \
            reasons.get(r.get('reason', '?'), 0) + 1
    return {
        'events': len(events),
        'searches': len(ends),
        'candidates': sum(r.get('candidates', 0) for r in ends),
        'poisoned': sum(r.get('poisoned', 0) for r in ends),
        'pruned': sum(r.get('pruned', 0) for r in ends),
        'search_wall_s': sum(r.get('seconds', 0.0) for r in ends),
        'by_program': searches,
        'conv_fuse_fallbacks': len(fallbacks),
        'conv_fuse_fallback_reasons': reasons,
    }


def summarize(records, malformed=0):
    """Aggregate a record list into a JSON-ready summary dict."""
    by_ev = {}
    for r in records:
        by_ev.setdefault(r['ev'], []).append(r)
    header = (by_ev.get('run_begin') or [{}])[0]
    steps = [r for r in by_ev.get('step_end', ())
             if 'skipped' not in r]
    step_walls = [r['dur_s'] for r in steps if 'dur_s' in r]
    losses = [r['loss'] for r in steps if 'loss' in r]
    compiles = by_ev.get('compile_end', [])
    exe_runs = by_ev.get('exe_run', [])
    batches = by_ev.get('serving_batch', [])
    spans = sorted((r for r in records if 'dur_s' in r),
                   key=lambda r: -r['dur_s'])
    duration = max((r.get('t', 0.0) for r in records), default=0.0)
    summary = {
        'run_id': header.get('run') or (records[0].get('run')
                                        if records else None),
        'started_wall': header.get('wall'),
        'schema': header.get('schema'),
        'duration_s': duration,
        'malformed_lines': malformed,
        'event_counts': {ev: len(rs) for ev, rs in sorted(by_ev.items())},
        'steps': {
            'count': len(steps),
            'skipped': len(by_ev.get('step_end', ())) - len(steps),
            'examples': sum(r.get('examples', 0) for r in steps),
            'mean_step_s': _mean(step_walls),
            'max_step_s': max(step_walls) if step_walls else 0.0,
            'steps_per_s': len(steps) / duration if duration else 0.0,
            'examples_per_s': (sum(r.get('examples', 0) for r in steps)
                               / duration if duration else 0.0),
            'first_loss': losses[0] if losses else None,
            'last_loss': losses[-1] if losses else None,
        },
        'compiles': {
            'count': len(compiles),
            'total_s': sum(r.get('dur_s', 0.0) for r in compiles),
            'max_s': max((r.get('dur_s', 0.0) for r in compiles),
                         default=0.0),
        },
        'executor': {
            'runs': len(exe_runs),
            'cache_hits': sum(1 for r in exe_runs
                              if r.get('cache') == 'hit'),
            'cache_misses': sum(1 for r in exe_runs
                                if r.get('cache') == 'miss'),
        },
        'serving': {
            'batches': len(batches),
            'rows': sum(r.get('rows', 0) for r in batches),
            'padded_rows': sum(r.get('bucket', 0) - r.get('rows', 0)
                               for r in batches),
            'admitted': sum(r.get('n', 1)
                            for r in by_ev.get('serving_admit', ())),
            'shed': sum(r.get('n', 1)
                        for r in by_ev.get('serving_shed', ())),
            'retries': sum(r.get('n', 1)
                           for r in by_ev.get('serving_retry', ())),
        },
        'checkpoints': {
            'saves': len(by_ev.get('checkpoint_save', ())),
            'loads': len(by_ev.get('checkpoint_load', ())),
            'fallbacks': len(by_ev.get('checkpoint_fallback', ())),
        },
        'anomalies': len(by_ev.get('anomaly', ())),
        'pipeline': _pipeline_summary(steps, duration),
        'compiler': _compiler_summary(by_ev),
        'partition': _partition_summary(by_ev),
        'resilience': _resilience_summary(by_ev),
        'fleet': _fleet_summary(by_ev),
        'multihost': _multihost_summary(by_ev),
        'zero': _zero_summary(by_ev),
        'analysis': _analysis_summary(by_ev),
        'tracing': _tracing_summary(by_ev),
        'perf': _perf_summary(by_ev),
        'autotune': _autotune_summary(by_ev),
        'slowest_spans': [
            {'ev': r['ev'], 't': r.get('t'), 'dur_s': r['dur_s'],
             'detail': {k: v for k, v in r.items()
                        if k not in ('ev', 'run', 't', 'dur_s')}}
            for r in spans],
    }
    return summary


def render(summary, top=10):
    s = summary
    lines = [
        '----------------->   Run Journal Report   <-----------------',
        'run %s  (%.2fs journalled, schema %s)'
        % (s['run_id'], s['duration_s'], s['schema']),
    ]
    if s['malformed_lines']:
        lines.append('!! %d malformed line(s)' % s['malformed_lines'])
    st = s['steps']
    if st['count']:
        lines.append(
            'training: %d steps (%d skipped), %d examples | %.1f '
            'steps/s, %.1f examples/s | step mean %.1fms max %.1fms'
            % (st['count'], st['skipped'], st['examples'],
               st['steps_per_s'], st['examples_per_s'],
               st['mean_step_s'] * 1e3, st['max_step_s'] * 1e3))
        if st['first_loss'] is not None:
            lines.append('loss:     %.6g -> %.6g'
                         % (st['first_loss'], st['last_loss']))
    pl = s.get('pipeline') or {}
    if pl.get('steps_with_feed_wait'):
        line = ('pipeline: host wait %.3fs total (%.1f%% of wall, '
                'mean %.2fms/step)'
                % (pl['host_wait_total_s'],
                   100.0 * pl['host_wait_fraction'],
                   pl['host_wait_mean_s'] * 1e3))
        if pl['chained_steps']:
            line += (' | %d steps chained (avg %.1f steps/dispatch)'
                     % (pl['chained_steps'], pl['mean_chain']))
        lines.append(line)
    co = s.get('compiler') or {}
    if co.get('passes'):
        lines.append(
            'compiler: %d pass runs, %.3fs total | %d ops eliminated, '
            '%d fused' % (
                sum(p['runs'] for p in co['passes'].values()),
                co['pass_wall_s'], co['ops_eliminated'],
                co['ops_fused']))
        for name, p in sorted(co['passes'].items(),
                              key=lambda kv: -kv[1]['total_s']):
            lines.append(
                '  %-18s %3d runs  %8.3fms  removed=%d fused=%d '
                'released=%d' % (name, p['runs'], p['total_s'] * 1e3,
                                 p['removed'], p['fused'],
                                 p['released']))
        tu = co['tuning']
        if tu['lookups'] or tu['preloads'] or tu['puts']:
            lines.append(
                'tuning:   %d lookups (%d hits, %.0f%% hit rate) | '
                '%d preloads (%d entries), %d puts'
                % (tu['lookups'], tu['hits'], 100.0 * tu['hit_rate'],
                   tu['preloads'], tu['entries_preloaded'],
                   tu['puts']))
    pa = s.get('partition') or {}
    if pa.get('events'):
        lines.append(
            'partition: %d events | %d scope(s) sharded (%d vars), '
            '%.3fs resharding wall'
            % (pa['events'], pa['scopes_sharded'], pa['vars_placed'],
               pa['reshard_wall_s']))
        for mesh, m in sorted(pa['meshes'].items()):
            lines.append('  mesh %-14s devices=%s creates=%d '
                         'shard_scope=%d' % (mesh, m['devices'],
                                             m['creates'],
                                             m['scopes_sharded']))
    ex = s['executor']
    if ex['runs']:
        lookups = ex['cache_hits'] + ex['cache_misses']
        lines.append(
            'executor: %d runs | cache %d hits / %d misses (%.1f%% hit '
            'rate)' % (ex['runs'], ex['cache_hits'], ex['cache_misses'],
                       100.0 * ex['cache_hits'] / lookups
                       if lookups else 0.0))
    c = s['compiles']
    if c['count']:
        lines.append('compiles: %d, %.2fs total (max %.2fs)'
                     % (c['count'], c['total_s'], c['max_s']))
    sv = s['serving']
    if sv['batches'] or sv['admitted'] or sv['shed']:
        lines.append(
            'serving:  %d admitted, %d shed, %d retries | %d batches, '
            '%d rows (+%d pad)'
            % (sv['admitted'], sv['shed'], sv['retries'], sv['batches'],
               sv['rows'], sv['padded_rows']))
    ck = s['checkpoints']
    if ck['saves'] or ck['loads'] or ck['fallbacks']:
        lines.append('ckpts:    %d saves, %d loads, %d corruption '
                     'fallbacks' % (ck['saves'], ck['loads'],
                                    ck['fallbacks']))
    rz = s.get('resilience') or {}
    if rz.get('preempt_saves') or rz.get('reshards'):
        lines.append(
            'resilience: %d preemption save(s), %d reshard(s) '
            '(%d vars, %.3fs wall)'
            % (rz['preempt_saves'], rz['reshards'],
               rz['reshard_vars'], rz['reshard_wall_s']))
        for topo, t in sorted(rz.get('topologies', {}).items()):
            lines.append('  reshard %-22s x%d  vars=%d  %.3fs'
                         % (topo, t['count'], t['vars'], t['wall_s']))
    zr = s.get('zero') or {}
    if zr.get('applied') or zr.get('collectives', {}).get('measured'):
        lines.append(
            'zero:     %d application(s) | %d grads -> %d bucket(s) | '
            'state sliced=%d replicated=%d | shard bytes/device %d'
            % (zr['applied'], zr['grads'], zr['buckets'],
               zr['sliced_state'], zr['replicated_state'],
               zr['shard_bytes']))
        zc = zr['collectives']
        if zc['measured']:
            line = ('collective: %d measured, %.3fs standalone wall'
                    % (zc['measured'], zc['standalone_wall_s']))
            if zc['overlap_fraction'] is not None:
                line += (' | %.0f%% hidden under compute'
                         % (100.0 * zc['overlap_fraction']))
            lines.append(line)
            for op, wall in sorted(zc['by_op'].items()):
                lines.append('  %-16s %8.3fms' % (op, wall * 1e3))
    fl = s.get('fleet') or {}
    if fl.get('events') or fl.get('decode', {}).get('steps'):
        if fl.get('events'):
            lines.append(
                'fleet:    %d events | %d requeues, %d restarts, '
                '%d swaps | %s'
                % (fl['events'], fl['requeues'], fl['restarts'],
                   fl['swaps'],
                   ', '.join('%s=%d' % kv for kv in sorted(
                       fl['actions'].items())) or '-'))
        dc = fl.get('decode') or {}
        if dc.get('steps'):
            lines.append(
                'decode:   %d steps, %d slot-steps | occupancy mean '
                '%.1f%% min %.1f%% | %d admitted, %d retired'
                % (dc['steps'], dc['slot_steps'],
                   100.0 * dc['mean_occupancy'],
                   100.0 * dc['min_occupancy'], dc['admitted'],
                   dc['retired']))
    mh = s.get('multihost') or {}
    if mh.get('events'):
        line = ('multihost: %d hosts bootstrapped | %d barriers, '
                '%d agreement failure(s) | %d host(s) lost, '
                '%d relaunch(es)'
                % (mh['bootstraps'], mh['barriers'],
                   mh['agreement_failures'], mh['hosts_lost'],
                   mh['relaunches']))
        lines.append(line)
        if mh['hosts_lost']:
            lines.append(
                '  loss detection: mean %.3fs max %.3fs (%d outside '
                'the heartbeat window) | reasons: %s'
                % (mh['detect_mean_s'] or 0.0, mh['detect_max_s']
                   or 0.0, mh['losses_outside_window'],
                   ', '.join(mh['loss_reasons']) or '-'))
        if mh['relaunches']:
            lines.append('  degraded to world=%s after relaunch'
                         % mh['final_world'])
    an = s.get('analysis') or {}
    if an.get('events'):
        line = ('analysis: %d verifier run(s), %.3fs wall | %d '
                'error(s), %d warning(s)'
                % (an['events'], an['wall_s'], an['errors'],
                   an['warnings']))
        if an['sanitized_passes']:
            line += (' | sanitized passes: %s'
                     % ', '.join(an['sanitized_passes']))
        lines.append(line)
        for ph, p in sorted(an['phases'].items()):
            lines.append('  %-10s %3d runs  %8.3fms  errors=%d '
                         'warnings=%d' % (ph, p['runs'],
                                          p['wall_s'] * 1e3,
                                          p['errors'], p['warnings']))
    tr = s.get('tracing') or {}
    if tr.get('spans') or tr.get('unclosed'):
        line = ('tracing:  %d span(s) over %d trace(s), %d link(s)'
                % (tr['spans'], tr['traces'], tr['links']))
        if tr['unclosed']:
            line += (' | %d UNCLOSED (%s)'
                     % (tr['unclosed'],
                        ', '.join(tr['unclosed_names']) or '-'))
        lines.append(line)
        for name, k in sorted(tr.get('kinds', {}).items(),
                              key=lambda kv: -kv[1]['total_s'])[:top]:
            lines.append('  %-24s %5d spans  %9.3fms total  max '
                         '%8.3fms' % (name, k['count'],
                                      k['total_s'] * 1e3,
                                      k['max_s'] * 1e3))
        for p in tr.get('critical_paths', ())[:3]:
            lines.append('  path: %s' % p)
    pf = s.get('perf') or {}
    if pf.get('programs'):
        bounds = ', '.join('%d %s-bound' % (n, b) for b, n in
                           sorted(pf['roofline_bounds'].items()))
        lines.append(
            'perf:     %d program ledger(s) | live %.2f MB | compile '
            '%.2fs%s' % (pf['programs'],
                         pf['live_bytes_total'] / 1e6,
                         pf['compile_wall_s'],
                         (' | %s' % bounds) if bounds else ''))
        for name, d in sorted(pf['by_program'].items(),
                              key=lambda kv: -(kv[1]['flops'] or 0)):
            mfu = d.get('mfu')
            lines.append(
                '  %-20s %10.3f MFLOP %8.2f MB  mfu=%s  %s'
                % (name[:20], (d['flops'] or 0) / 1e6,
                   (d['bytes_accessed'] or 0) / 1e6,
                   '%.4f' % mfu if mfu is not None else '-',
                   d.get('roofline') or '-'))
    at = s.get('autotune') or {}
    if at.get('searches') or at.get('conv_fuse_fallbacks'):
        if at.get('searches'):
            lines.append(
                'autotune: %d search(es), %.3fs wall | %d candidates '
                'measured (%d poisoned), %d ledger-pruned'
                % (at['searches'], at['search_wall_s'],
                   at['candidates'], at['poisoned'], at['pruned']))
            for name, a in sorted(at['by_program'].items()):
                win = ', '.join('%s=%s' % kv for kv in sorted(
                    (a['winner'] or {}).items())) or 'baseline'
                lines.append(
                    '  %-20s %d search(es)  best %sms  winner: %s'
                    % (name[:20], a['searches'],
                       a['best_ms'] if a['best_ms'] is not None
                       else '-', win))
        if at.get('conv_fuse_fallbacks'):
            lines.append(
                'conv fallbacks: %d fused op(s) replayed unfused (%s)'
                % (at['conv_fuse_fallbacks'],
                   ', '.join('%s=%d' % kv for kv in sorted(
                       at['conv_fuse_fallback_reasons'].items()))))
    if s['anomalies']:
        lines.append('anomaly:  %d guard trips' % s['anomalies'])
    lines.append('events:   %s' % ', '.join(
        '%s=%d' % kv for kv in sorted(s['event_counts'].items())))
    if s['slowest_spans']:
        lines.append('top %d slowest spans:' % min(
            top, len(s['slowest_spans'])))
        for r in s['slowest_spans'][:top]:
            detail = ' '.join('%s=%s' % kv
                              for kv in sorted(r['detail'].items()))
            lines.append('  %10.3fms  t=%-10.3f %-16s %s'
                         % (r['dur_s'] * 1e3, r.get('t') or 0.0,
                            r['ev'], detail))
    return '\n'.join(lines)


def check_journal(path, require='step'):
    """Smoke validation -> list of problems (empty == healthy)."""
    if require not in REQUIRED_EV:
        raise ValueError('require must be one of %s'
                         % sorted(REQUIRED_EV))
    try:
        records, malformed = load_journal(path)
    except OSError as e:
        return ['journal unreadable: %r' % (e,)]
    problems = []
    if malformed:
        problems.append('%d malformed journal line(s)' % malformed)
    if not records:
        problems.append('journal contains no records')
        return problems
    if records[0].get('ev') != 'run_begin':
        problems.append('journal does not start with run_begin')
    need = REQUIRED_EV[require]
    if need is not None:
        wanted = need if isinstance(need, tuple) else (need,)
        n = sum(1 for r in records
                if r['ev'] in wanted and 'skipped' not in r)
        if n == 0:
            problems.append('journal contains zero %s records'
                            % ' / '.join(wanted))
        elif require == 'pipeline':
            n = sum(1 for r in records if r['ev'] == need
                    and 'skipped' not in r and 'feed_wait' in r)
            if n == 0:
                problems.append(
                    'journal contains zero step_end records with '
                    'pipeline fields (feed_wait) — was the run made '
                    'with a pre-pipelining trainer?')
    if require == 'autoscale':
        acted = sum(1 for r in records if r['ev'] == 'autoscale'
                    and r.get('action') in ('scale_up', 'scale_down'))
        if not acted:
            problems.append(
                'journal holds autoscale records but no scale_up / '
                'scale_down decision — the control loop never acted')
    if require == 'coldstart':
        actions = {r.get('action') for r in records
                   if r['ev'] == 'coldstart'}
        if 'save' not in actions:
            problems.append('coldstart journal shows no AOT save — '
                            'nothing was ever sealed to the store')
        if 'hit' not in actions:
            problems.append('coldstart journal shows no AOT hit — '
                            'no warmup ever deserialized')
    if require == 'kvcache':
        actions = {r.get('action') for r in records
                   if r['ev'] == 'kvcache'}
        if 'prefill' not in actions:
            problems.append(
                'kvcache journal shows page traffic but no prefill — '
                'no prompt was ever disaggregated')
        if 'alloc' not in actions:
            problems.append(
                'kvcache journal shows no page alloc — the pool was '
                'never exercised')
    if require == 'slo':
        states = {r.get('state') for r in records if r['ev'] == 'slo'}
        if 'breach' not in states:
            problems.append(
                'slo journal shows no burn-rate breach — the error '
                'budget was never pressured')
        if 'recovered' not in states:
            problems.append(
                'slo journal shows no recovery — every breached '
                'objective stayed breached to the end of the run')
    if require == 'telemetry':
        actions = {r.get('action') for r in records
                   if r['ev'] == 'telemetry'}
        if 'scrape' not in actions:
            problems.append(
                'telemetry journal shows no aggregator scrape — '
                'endpoints may have served but nothing merged them')
    if require == 'remote_elastic':
        actions = {r.get('action') for r in records
                   if r['ev'] == 'fleet'}
        for action, why in (
                ('spawn_remote', 'no remote replica was ever '
                                 'provisioned'),
                ('host_lost', 'no heartbeat-detected host loss — the '
                              'chaos kill never registered'),
                ('requeue', 'no in-flight request was requeued off '
                            'the lost host'),
                ('retire', 'the fleet never scaled back in')):
            if action not in actions:
                problems.append(
                    'remote_elastic journal shows no fleet %s '
                    'record — %s' % (action, why))
        # detection must come from the heartbeat monitor, not from an
        # eventual RPC failure: the journalled detect_s is the file
        # age at detection, which lags a silent death by at most one
        # beat interval + one supervisor poll — 2x window + 1s is the
        # generous ceiling that still catches RPC-deadline detection
        for r in records:
            if (r['ev'] == 'fleet' and r.get('action') == 'host_lost'
                    and 'detect_s' in r and 'window_s' in r
                    and float(r['detect_s'])
                    > 2.0 * float(r['window_s']) + 1.0):
                problems.append(
                    'remote host %s loss detected after %.2fs — '
                    'outside its %.2fs heartbeat window (+slack); '
                    'detection leaned on an RPC failure, not the '
                    'monitor' % (r.get('host'), float(r['detect_s']),
                                 float(r['window_s'])))
    if require == 'autotune':
        ends = [r for r in records if r['ev'] == 'autotune'
                and r.get('phase') == 'end']
        if not ends:
            problems.append(
                'autotune journal shows no completed search '
                '(phase=end) — a sweep began but never finished, or '
                'only cache hits were journalled')
        elif not any(r.get('candidates', 0) > 0 for r in ends):
            problems.append(
                'autotune journal shows completed searches but zero '
                'measured candidates — the schedule space was empty')
    if require == 'multihost':
        # a host loss the monitor only noticed after its own heartbeat
        # window means detection is broken even if recovery worked
        for r in records:
            if (r['ev'] == 'multihost'
                    and r.get('action') == 'host_lost'
                    and 'detect_s' in r and 'window_s' in r
                    and float(r['detect_s']) > float(r['window_s'])):
                problems.append(
                    'host %s loss detected after %.2fs — outside its '
                    '%.2fs heartbeat window'
                    % (r.get('host'), float(r['detect_s']),
                       float(r['window_s'])))
    return problems


def list_requires():
    """The --list-requires catalog: every --require family with the
    journal events it insists on, straight from REQUIRED_EV."""
    lines = []
    for fam in sorted(REQUIRED_EV):
        need = REQUIRED_EV[fam]
        evs = ('-' if need is None else
               ' | '.join(need if isinstance(need, tuple) else (need,)))
        lines.append('%-11s %-24s %s'
                     % (fam, evs, REQUIRE_DOC.get(fam, '')))
    return '\n'.join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split('\n')[0])
    ap.add_argument('journal', nargs='?', default=None,
                    help='path to a RunJournal .jsonl file')
    ap.add_argument('--top', type=int, default=10,
                    help='slowest spans to list')
    ap.add_argument('--json', default=None, metavar='PATH',
                    help="write the summary dict as JSON ('-' = stdout)")
    ap.add_argument('--smoke', action='store_true',
                    help='validate instead of report; nonzero exit on '
                         'an empty/malformed/step-less journal')
    ap.add_argument('--require', default='step',
                    choices=sorted(REQUIRED_EV),
                    help='record family --smoke insists on (default: '
                         'step; see --list-requires for the catalog)')
    ap.add_argument('--list-requires', action='store_true',
                    help='print every --require family with the '
                         'journal events it gates on, then exit')
    args = ap.parse_args(argv)

    if args.list_requires:
        print(list_requires())
        return 0
    if args.journal is None:
        ap.error('journal path required (or use --list-requires)')

    if args.smoke:
        problems = check_journal(args.journal, require=args.require)
        if problems:
            print('JOURNAL SMOKE FAILED (%s):' % args.journal,
                  file=sys.stderr)
            for p in problems:
                print('  - %s' % p, file=sys.stderr)
            return 1
        print('journal smoke OK (%s)' % args.journal)
        return 0

    records, malformed = load_journal(args.journal)
    summary = summarize(records, malformed)
    if args.json == '-':
        json.dump(summary, sys.stdout, indent=2, sort_keys=True)
        print()
    else:
        if args.json:
            with open(args.json, 'w') as f:
                json.dump(summary, f, indent=2, sort_keys=True)
        print(render(summary, top=args.top))
    return 0


if __name__ == '__main__':
    sys.exit(main())
