#!/usr/bin/env python
"""Live fleet status from the telemetry plane — ``top`` for replicas.

Feeds a :class:`paddle_tpu.observability.TelemetryAggregator` from
explicit endpoints and/or a ``PTPU_TELEMETRY_DIR`` port-file directory,
scrapes twice (rates are scrape-to-scrape deltas), and renders one
table: per-endpoint liveness, request counters, shed and latency, plus
the fleet rollup line (``fleet_qps`` / ``fleet_shed_rate`` /
``fleet_worst_p99_seconds``).

    python tools/fleet_top.py r0=18321 r1=18322        # one-shot
    python tools/fleet_top.py --dir /tmp/hb/telemetry  # discovered
    python tools/fleet_top.py --dir ... --watch        # refresh loop

Endpoints are ``name=url`` or ``name=port`` pairs; ``--watch`` redraws
every ``--interval`` seconds until interrupted. Exit is nonzero when
no endpoint answered the final scrape — so a CI step can use a
one-shot invocation as a liveness gate.
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from paddle_tpu.observability.telemetry import TelemetryAggregator  # noqa: E402


def build_aggregator(args):
    agg = TelemetryAggregator()
    for spec in args.endpoints:
        name, sep, target = spec.partition('=')
        if not sep or not name or not target:
            raise SystemExit('endpoint must be name=url or name=port, '
                             'got %r' % spec)
        agg.add_endpoint(name, int(target) if target.isdigit()
                         else target)
    if args.dir:
        agg.add_dir(args.dir)
    if not agg.endpoints():
        raise SystemExit('no endpoints: pass name=url pairs or --dir')
    return agg


def _series_value(snapshot, metric, want_labels, default=None):
    """The value of ``metric`` whose labels are a superset of
    ``want_labels``, summed across matching series (one endpoint can
    republish several label sets, e.g. per-model counters)."""
    entry = snapshot.get(metric)
    if not entry:
        return default
    total, hit = 0.0, False
    for s in entry['series']:
        if all(s['labels'].get(k) == v for k, v in want_labels.items()):
            total += s.get('value', 0.0)
            hit = True
    return total if hit else default


def render(agg, health):
    """The status table as a list of lines."""
    snapshot = agg.registry.snapshot()
    endpoints = agg.endpoints()
    lines = ['%-14s %-3s %-9s %10s %10s %8s %9s'
             % ('ENDPOINT', 'UP', 'STATUS', 'SUBMITTED', 'COMPLETED',
                'SHED', 'QUEUE')]
    for name, ep in sorted(endpoints.items()):
        want = ep['labels']
        doc = health.get(name)
        status = (doc or {}).get('status', '-') if doc else 'down'
        sub = _series_value(snapshot,
                            'serving_requests_submitted_total', want)
        done = _series_value(snapshot,
                             'serving_requests_completed_total', want)
        shed = _series_value(snapshot,
                             'serving_requests_shed_total', want)
        queue = _series_value(snapshot, 'serving_queue_depth', want)
        lines.append(
            '%-14s %-3s %-9s %10s %10s %8s %9s'
            % (name[:14], {1: 'yes', 0: 'NO'}.get(ep['up'], '?'),
               status[:9],
               '-' if sub is None else '%d' % sub,
               '-' if done is None else '%d' % done,
               '-' if shed is None else '%d' % shed,
               '-' if queue is None else '%g' % queue))

    def roll(metric):
        entry = snapshot.get(metric)
        return entry['series'][0]['value'] if entry else 0.0

    lines.append('')
    lines.append(
        'fleet: %.1f req/s | shed %.2f%% | worst p99 %.1fms%s | '
        '%d/%d endpoints up'
        % (roll('fleet_qps'), 100.0 * roll('fleet_shed_rate'),
           1e3 * roll('fleet_worst_p99_seconds'),
           (' (%s)' % agg.worst_endpoint) if agg.worst_endpoint
           else '', int(roll('fleet_endpoints_up')), len(endpoints)))
    return lines


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split('\n')[0])
    ap.add_argument('endpoints', nargs='*', metavar='NAME=URL',
                    help='scrape targets (URL or localhost port)')
    ap.add_argument('--dir', default=None,
                    help='PTPU_TELEMETRY_DIR port-file directory to '
                         'discover endpoints from')
    ap.add_argument('--watch', action='store_true',
                    help='redraw until interrupted')
    ap.add_argument('--interval', type=float, default=2.0,
                    help='seconds between scrapes (default 2)')
    ap.add_argument('--timeout', type=float, default=5.0,
                    help='per-endpoint scrape timeout')
    args = ap.parse_args(argv)

    agg = build_aggregator(args)
    summary = agg.scrape_once(timeout=args.timeout)
    try:
        while True:
            time.sleep(max(0.1, args.interval))
            if args.dir:
                agg.add_dir(args.dir)   # late-published ports join in
            summary = agg.scrape_once(timeout=args.timeout)
            health = agg.scrape_health(timeout=args.timeout)
            out = '\n'.join(render(agg, health))
            if args.watch:
                # clear + home, then the table: a cheap top(1) redraw
                sys.stdout.write('\x1b[2J\x1b[H' + out + '\n')
                sys.stdout.flush()
            else:
                print(out)
                break
    except KeyboardInterrupt:
        pass
    return 0 if summary['scraped'] else 1


if __name__ == '__main__':
    sys.exit(main())
