#!/usr/bin/env python
"""Run the paddle_tpu static program verifier from the command line
(ANALYSIS.md).

    python tools/analyze_program.py MODEL_DIR             # saved model
    python tools/analyze_program.py build_net.py          # builder file
    python tools/analyze_program.py MODEL_DIR --json      # machine output
    python tools/analyze_program.py build_net.py --passes # + sanitizer

The target is either a ``save_inference_model`` directory (holding
``__model__.json`` with program + feed/fetch names) or a Python file
that BUILDS a program: the file is executed and must either define
``build()`` returning ``(program, feed_names, fetch_names)`` (names may
be empty) or leave a ``fluid.Program`` bound to one of ``program`` /
``main`` / ``main_program`` (optional ``FEEDS`` / ``FETCHES`` name
lists alongside).

Checks: dataflow (use-before-def, fetch reachability), shape/dtype
inference (rank / broadcast / dtype mismatches named per op), sharding
consistency (specs vs the partition rules). ``--passes`` additionally
runs the default compiler pipeline under the sanitizer
(``PassPipeline(verify=True)``) and reports any invariant violation
with the pass named.

Exit status: 0 when no error-severity diagnostics, 1 on errors (or a
sanitizer violation), 2 on usage/load problems — so CI can gate on it.
"""
import argparse
import json
import os
import runpy
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _load_saved_model(dirname):
    from paddle_tpu.io import MODEL_FILE, program_from_json
    with open(os.path.join(dirname, MODEL_FILE)) as f:
        meta = json.load(f)
    return (program_from_json(meta['program']),
            list(meta.get('feed_names') or ()),
            list(meta.get('fetch_names') or ()))


def _load_builder(path):
    from paddle_tpu.framework import Program
    ns = runpy.run_path(path)
    if callable(ns.get('build')):
        prog, feeds, fetches = ns['build']()
        return prog, list(feeds or ()), list(fetches or ())
    for name in ('program', 'main', 'main_program'):
        if isinstance(ns.get(name), Program):
            return (ns[name], list(ns.get('FEEDS') or ()),
                    list(ns.get('FETCHES') or ()))
    raise SystemExit('%s defines neither build() nor a Program bound '
                     'to program/main/main_program' % path)


def _sanitize(program, fetches):
    """Default pipeline under the sanitizer; returns violation
    diagnostics instead of raising so they join the report."""
    from paddle_tpu import compiler
    from paddle_tpu.compiler.pass_base import PassPipeline
    from paddle_tpu.analysis import PassVerificationError
    pipe = PassPipeline(compiler.default_pipeline().passes,
                        name='analyze', verify=True)
    try:
        pipe.run(program, protected=tuple(fetches))
    except PassVerificationError as e:
        return list(e.diagnostics)
    return []


def main(argv=None):
    ap = argparse.ArgumentParser(
        description='statically verify a paddle_tpu program')
    ap.add_argument('target', help='saved-model dir or builder .py')
    ap.add_argument('--json', action='store_true',
                    help='print diagnostics as JSON')
    ap.add_argument('--passes', action='store_true',
                    help='also run the default compiler pipeline under '
                         'the sanitizer')
    ap.add_argument('--feeds', default='',
                    help='comma-separated feed names (override/extend)')
    ap.add_argument('--fetches', default='',
                    help='comma-separated fetch names (override/extend)')
    args = ap.parse_args(argv)

    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    from paddle_tpu.io import MODEL_FILE
    from paddle_tpu.analysis import verify_program, errors_of

    if os.path.isdir(args.target):
        if not os.path.exists(os.path.join(args.target, MODEL_FILE)):
            print('error: %s has no %s' % (args.target, MODEL_FILE),
                  file=sys.stderr)
            return 2
        program, feeds, fetches = _load_saved_model(args.target)
    elif os.path.isfile(args.target):
        program, feeds, fetches = _load_builder(args.target)
    else:
        print('error: no such file or directory: %s' % args.target,
              file=sys.stderr)
        return 2
    feeds += [n for n in args.feeds.split(',') if n]
    fetches += [n for n in args.fetches.split(',') if n]

    diags = verify_program(program, feeds=tuple(feeds),
                           fetch_names=tuple(fetches))
    if args.passes:
        diags = diags + _sanitize(program, fetches)
    errors = errors_of(diags)

    if args.json:
        print(json.dumps({
            'target': args.target,
            'ops': sum(len(b.ops) for b in program.blocks),
            'feeds': feeds, 'fetches': fetches,
            'errors': len(errors),
            'warnings': len([d for d in diags
                             if d.severity == 'warning']),
            'diagnostics': [d.as_dict() for d in diags],
        }, indent=2, sort_keys=True))
    else:
        print('analyzed %s: %d op(s), %d feed(s), %d fetch(es)'
              % (args.target, sum(len(b.ops) for b in program.blocks),
                 len(feeds), len(fetches)))
        if not diags:
            print('clean: no diagnostics')
        for d in diags:
            print('  ' + d.render())
        print('%d error(s), %d diagnostic(s) total'
              % (len(errors), len(diags)))
    return 1 if errors else 0


if __name__ == '__main__':
    sys.exit(main())
