#!/usr/bin/env python
"""Multi-host pod launcher (RESILIENCE.md "Surviving host loss",
PARTITIONING.md "Multi-host meshes").

Spawns one worker process per "host" on host CPU devices, wires the
coordinator/rank/heartbeat env contract, and supervises: a host that
exits nonzero, dies to a signal, or goes heartbeat-stale within the
bounded window is declared lost; surviving processes are killed out of
their hung collectives; with ``--elastic N`` the pod relaunches up to
N degraded generations that resume from the newest sharded checkpoint
(workers see ``PTPU_RESUME=1``).

Quickstart (2-host data-parallel training of train.py)::

    python tools/launch.py --nproc 2 -- python train.py --epochs 3

Worker env contract (generation g, rank r of w): PTPU_NPROC=w,
PTPU_PROC_ID=r, PTPU_COORD=host:port, PTPU_HB_DIR, PTPU_HB_INTERVAL,
PTPU_GENERATION=g, PADDLE_TPU_DISTRIBUTED=1, and PTPU_RESUME=1 when
g > 0. A worker bootstraps by calling
``DistributeTranspiler().transpile(trainer_id=int(os.environ[
'PTPU_PROC_ID']), trainers=int(os.environ['PTPU_NPROC']),
pservers=os.environ['PTPU_COORD'])`` — the reference-compatible
surface — or ``paddle_tpu.multihost.initialize`` directly.

Exit code: 0 when a generation completes with every worker at rc 0;
1 when the pod failed and no relaunch budget (or no survivor) remains.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None):
    parser = argparse.ArgumentParser(
        description='launch + supervise an N-host paddle_tpu pod',
        epilog='everything after -- (or the first positional) is the '
               'worker command, run once per host')
    parser.add_argument('--nproc', type=int, required=True,
                        help='host (process) count of generation 0')
    parser.add_argument('--devices-per-host', type=int, default=1,
                        help='virtual CPU devices per host process '
                             '(xla_force_host_platform_device_count)')
    parser.add_argument('--heartbeat-window', type=float, default=10.0,
                        help='seconds without a heartbeat before a '
                             'live process counts as stalled')
    parser.add_argument('--heartbeat-interval', type=float,
                        default=0.5)
    parser.add_argument('--poll-interval', type=float, default=0.2)
    parser.add_argument('--elastic', type=int, default=0,
                        metavar='RELAUNCHES',
                        help='max degraded relaunches after host '
                             'losses (0 = fail on first loss)')
    parser.add_argument('--startup-grace', type=float, default=180.0,
                        help='seconds a worker may run before its '
                             'first heartbeat')
    parser.add_argument('--workdir', default=None,
                        help='scratch dir for heartbeat files '
                             '(default: --log-dir or .)')
    parser.add_argument('--log-dir', default=None,
                        help='per-worker stdout/stderr log files '
                             '(worker_g<gen>_r<rank>.log)')
    parser.add_argument('--journal', default=None,
                        help='shared multihost JSONL journal '
                             '(launcher + all workers append; feed to '
                             'tools/obs_report.py --require multihost)')
    parser.add_argument('--json', action='store_true',
                        help='print the launch record as JSON')
    parser.add_argument('cmd', nargs=argparse.REMAINDER,
                        help='worker command (prefix with --)')
    args = parser.parse_args(argv)
    cmd = [c for c in args.cmd if c != '--'] or None
    if not cmd:
        parser.error('no worker command given')
    if args.nproc < 1:
        parser.error('--nproc must be >= 1')
    if args.journal:
        import time
        import uuid

        from paddle_tpu.multihost import JOURNAL_ENV
        from paddle_tpu.observability.journal import SCHEMA_VERSION
        path = os.path.abspath(args.journal)
        # fresh journal per launch, opened with the same run_begin
        # header every RunJournal carries so obs_report --smoke accepts
        # the launcher+worker-appended stream as a well-formed journal
        with open(path, 'w') as f:
            f.write(json.dumps(
                {'ev': 'run_begin', 'run': uuid.uuid4().hex[:12],
                 't': 0.0, 'wall': time.time(), 'pid': os.getpid(),
                 'schema': SCHEMA_VERSION, 'launcher': 'multihost'},
                separators=(',', ':')) + '\n')
        os.environ[JOURNAL_ENV] = path
    from paddle_tpu.multihost import launch
    result = launch(
        cmd, args.nproc, devices_per_host=args.devices_per_host,
        heartbeat_window=args.heartbeat_window,
        heartbeat_interval=args.heartbeat_interval,
        poll_interval=args.poll_interval,
        max_relaunches=args.elastic,
        startup_grace=args.startup_grace,
        workdir=args.workdir, log_dir=args.log_dir)
    record = {'returncode': result.returncode,
              'generations': result.generations}
    if args.json:
        print(json.dumps(record, indent=2, sort_keys=True))
    else:
        for g in result.generations:
            state = 'completed' if not g['failed'] else \
                'lost host(s) %s' % sorted(g['failed'])
            print('[launch] generation %d (world=%d): %s'
                  % (g['generation'], g['world'], state))
        print('[launch] exit %d' % result.returncode)
    return result.returncode


if __name__ == '__main__':
    sys.exit(main())
