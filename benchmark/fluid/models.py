"""Benchmark model builders (parity: benchmark/fluid/{mnist,vgg,resnet,
se_resnext,stacked_dynamic_lstm,machine_translation}.py).

Each builder returns (avg_loss, feed_fn(batch_size) -> feed dict, unit).
Data is synthetic with fixed seed — the loop measures the training step,
not the input pipeline (which is benchmarked by the native loader tests).
"""
import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.lod import create_lod_tensor
from paddle_tpu.models import resnet as resnet_m
from paddle_tpu.models import vgg as vgg_m


def _img_feed(shape, classes):
    def feed_fn(bs):
        rng = np.random.RandomState(0)
        return {'data': rng.randn(bs, *shape).astype('float32'),
                'label': rng.randint(0, classes, (bs, 1)).astype('int64')}
    return feed_fn


def mnist(args):
    img = fluid.layers.data(name='data', shape=[1, 28, 28],
                            dtype='float32')
    label = fluid.layers.data(name='label', shape=[1], dtype='int64')
    conv1 = fluid.nets.simple_img_conv_pool(input=img, filter_size=5,
                                            num_filters=20, pool_size=2,
                                            pool_stride=2, act='relu')
    conv2 = fluid.nets.simple_img_conv_pool(input=conv1, filter_size=5,
                                            num_filters=50, pool_size=2,
                                            pool_stride=2, act='relu')
    predict = fluid.layers.fc(input=conv2, size=10, act='softmax')
    cost = fluid.layers.cross_entropy(input=predict, label=label)
    return (fluid.layers.mean(x=cost), _img_feed((1, 28, 28), 10),
            'images/sec')


def vgg(args):
    img = fluid.layers.data(name='data', shape=[3, 32, 32],
                            dtype='float32')
    label = fluid.layers.data(name='label', shape=[1], dtype='int64')
    predict = vgg_m.vgg16(img, class_dim=10)
    cost = fluid.layers.cross_entropy(input=predict, label=label)
    return (fluid.layers.mean(x=cost), _img_feed((3, 32, 32), 10),
            'images/sec')


def resnet(args):
    img = fluid.layers.data(name='data', shape=[3, 224, 224],
                            dtype='float32')
    label = fluid.layers.data(name='label', shape=[1], dtype='int64')
    predict = resnet_m.resnet_imagenet(img, class_dim=1000, depth=50)
    cost = fluid.layers.cross_entropy(input=predict, label=label)
    return (fluid.layers.mean(x=cost), _img_feed((3, 224, 224), 1000),
            'images/sec')


def se_resnext(args):
    img = fluid.layers.data(name='data', shape=[3, 224, 224],
                            dtype='float32')
    label = fluid.layers.data(name='label', shape=[1], dtype='int64')
    predict = resnet_m.se_resnext(img, class_dim=1000, depth=50)
    cost = fluid.layers.cross_entropy(input=predict, label=label)
    return (fluid.layers.mean(x=cost), _img_feed((3, 224, 224), 1000),
            'images/sec')


def stacked_dynamic_lstm(args):
    """Stacked LSTM sentiment net on synthetic word sequences
    (parity: benchmark/fluid/stacked_dynamic_lstm.py)."""
    dict_size = 10000
    emb_dim = 512
    hid_dim = 512
    stacked_num = 3
    seq_len = 80

    data = fluid.layers.data(name='data', shape=[1], dtype='int64',
                             lod_level=1)
    label = fluid.layers.data(name='label', shape=[1], dtype='int64')
    emb = fluid.layers.embedding(input=data, size=[dict_size, emb_dim])
    fc1 = fluid.layers.fc(input=emb, size=hid_dim * 4)
    lstm1, _ = fluid.layers.dynamic_lstm(input=fc1, size=hid_dim * 4)
    inputs = [fc1, lstm1]
    for _ in range(2, stacked_num + 1):
        fc = fluid.layers.fc(input=inputs, size=hid_dim * 4)
        lstm, _ = fluid.layers.dynamic_lstm(input=fc, size=hid_dim * 4)
        inputs = [fc, lstm]
    fc_last = fluid.layers.sequence_pool(input=inputs[0], pool_type='max')
    lstm_last = fluid.layers.sequence_pool(input=inputs[1], pool_type='max')
    prediction = fluid.layers.fc(input=[fc_last, lstm_last], size=2,
                                 act='softmax')
    cost = fluid.layers.cross_entropy(input=prediction, label=label)

    def feed_fn(bs):
        rng = np.random.RandomState(0)
        rows = rng.randint(0, dict_size, (bs * seq_len, 1)).astype('int64')
        st = create_lod_tensor(rows, [[seq_len] * bs])
        lab = rng.randint(0, 2, (bs, 1)).astype('int64')
        return {'data': st, 'label': lab}

    return fluid.layers.mean(x=cost), feed_fn, 'sequences/sec'


def machine_translation(args):
    """Seq2seq encoder-decoder with attention on synthetic parallel data
    (parity: benchmark/fluid/machine_translation.py)."""
    dict_size = 8000
    emb_dim = 256
    hid_dim = 512
    src_len, trg_len = 24, 24

    src = fluid.layers.data(name='data', shape=[1], dtype='int64',
                            lod_level=1)
    trg = fluid.layers.data(name='trg', shape=[1], dtype='int64',
                            lod_level=1)
    label = fluid.layers.data(name='label', shape=[1], dtype='int64',
                              lod_level=1)
    src_emb = fluid.layers.embedding(input=src, size=[dict_size, emb_dim])
    enc_fc = fluid.layers.fc(input=src_emb, size=hid_dim * 4)
    enc, _ = fluid.layers.dynamic_lstm(input=enc_fc, size=hid_dim * 4)
    enc_last = fluid.layers.sequence_pool(input=enc, pool_type='last')

    trg_emb = fluid.layers.embedding(input=trg, size=[dict_size, emb_dim])
    dec_fc = fluid.layers.fc(input=trg_emb, size=hid_dim * 4)
    dec, _ = fluid.layers.dynamic_lstm(input=dec_fc, size=hid_dim * 4)
    # context via last encoder state broadcast over decoder steps
    ctx = fluid.layers.sequence_expand(x=enc_last, y=dec)
    merged = fluid.layers.fc(input=[dec, ctx], size=hid_dim, act='tanh')
    predict = fluid.layers.fc(input=merged, size=dict_size, act='softmax')
    cost = fluid.layers.cross_entropy(input=predict, label=label)

    def feed_fn(bs):
        rng = np.random.RandomState(0)
        s_rows = rng.randint(0, dict_size,
                             (bs * src_len, 1)).astype('int64')
        t_rows = rng.randint(0, dict_size,
                             (bs * trg_len, 1)).astype('int64')
        l_rows = rng.randint(0, dict_size,
                             (bs * trg_len, 1)).astype('int64')
        return {'data': create_lod_tensor(s_rows, [[src_len] * bs]),
                'trg': create_lod_tensor(t_rows, [[trg_len] * bs]),
                'label': create_lod_tensor(l_rows, [[trg_len] * bs])}

    return fluid.layers.mean(x=cost), feed_fn, 'sentence_pairs/sec'


def transformer(args, vocab=8192, d_model=1024, n_heads=16, n_layers=6,
                d_ff=4096, seq=2048):
    """Decoder-only transformer LM through the FLUID surface: the
    flagship long-context path (layers.flash_attention -> Pallas kernel
    on TPU) built as a Program and run by the Executor, so the
    framework's lowering/executor is in the measured loop. Keyword dims
    exist for small-shape CPU tests."""
    tok = fluid.layers.data(name='data', shape=[seq], dtype='int64')
    label = fluid.layers.data(name='label', shape=[seq, 1], dtype='int64')
    pos = fluid.layers.data(name='pos', shape=[seq], dtype='int64')
    x = fluid.layers.embedding(input=tok, size=[vocab, d_model])
    p = fluid.layers.embedding(input=pos, size=[seq, d_model],
                               param_attr='pos_table')
    x = x + p
    for i in range(n_layers):
        ln = fluid.layers.layer_norm(x, begin_norm_axis=2)
        q = fluid.layers.fc(input=ln, size=d_model, num_flatten_dims=2,
                            bias_attr=False)
        k = fluid.layers.fc(input=ln, size=d_model, num_flatten_dims=2,
                            bias_attr=False)
        v = fluid.layers.fc(input=ln, size=d_model, num_flatten_dims=2,
                            bias_attr=False)
        att = fluid.layers.flash_attention(q, k, v, num_heads=n_heads,
                                           causal=True)
        proj = fluid.layers.fc(input=att, size=d_model,
                               num_flatten_dims=2, bias_attr=False)
        x = x + proj
        ln2 = fluid.layers.layer_norm(x, begin_norm_axis=2)
        ff = fluid.layers.fc(input=ln2, size=d_ff, num_flatten_dims=2,
                             act='relu')
        ff2 = fluid.layers.fc(input=ff, size=d_model, num_flatten_dims=2)
        x = x + ff2
    x = fluid.layers.layer_norm(x, begin_norm_axis=2)
    logits = fluid.layers.fc(input=x, size=vocab, num_flatten_dims=2)
    loss = fluid.layers.softmax_with_cross_entropy(logits=logits,
                                                   label=label)

    def feed_fn(bs):
        rng = np.random.RandomState(0)
        return {'data': rng.randint(0, vocab, (bs, seq)).astype('int64'),
                'label': rng.randint(0, vocab,
                                     (bs, seq, 1)).astype('int64'),
                'pos': np.tile(np.arange(seq, dtype='int64'), (bs, 1))}

    return fluid.layers.mean(x=loss), feed_fn, 'tokens/sec'


MODELS = {
    'mnist': mnist,
    'vgg': vgg,
    'resnet': resnet,
    'se_resnext': se_resnext,
    'stacked_dynamic_lstm': stacked_dynamic_lstm,
    'machine_translation': machine_translation,
    'transformer': transformer,
}
