"""Benchmark harness (parity: benchmark/fluid/fluid_benchmark.py CLI).

Runs one model's training loop on synthetic data and reports throughput:

    python benchmark/fluid/fluid_benchmark.py --model resnet \
        --batch_size 64 --iterations 20 [--device TPU|CPU] [--pass_num N]

Models: mnist, vgg, resnet, se_resnext, stacked_dynamic_lstm,
machine_translation (same set the reference benchmarks).
"""
import argparse
import json
import sys
import time

import numpy as np

import paddle_tpu.fluid as fluid
from models import MODELS


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument('--model', default='resnet', choices=sorted(MODELS))
    p.add_argument('--batch_size', type=int, default=32)
    p.add_argument('--iterations', type=int, default=20)
    p.add_argument('--skip_batch_num', type=int, default=3,
                   help='warmup steps excluded from timing')
    p.add_argument('--device', default='TPU', choices=['TPU', 'CPU'])
    p.add_argument('--learning_rate', type=float, default=0.01)
    p.add_argument('--pass_num', type=int, default=1,
                   help='repeat the timed loop this many times')
    p.add_argument('--no_random', action='store_true')
    return p.parse_args()


def main():
    args = parse_args()
    build = MODELS[args.model]

    main_prog, startup = fluid.Program(), fluid.Program()
    if args.no_random:
        main_prog.random_seed = startup.random_seed = 42
    with fluid.program_guard(main_prog, startup):
        loss, feed_fn, unit = build(args)
        opt = fluid.optimizer.Momentum(learning_rate=args.learning_rate,
                                       momentum=0.9)
        opt.minimize(loss)

    place = fluid.TPUPlace(0) if args.device == 'TPU' else fluid.CPUPlace()
    exe = fluid.Executor(place)
    exe.run(startup)

    feed = feed_fn(args.batch_size)
    for _ in range(args.skip_batch_num):
        exe.run(main_prog, feed=feed, fetch_list=[loss])
    t0 = time.perf_counter()
    last = None
    for _ in range(args.pass_num):
        for _ in range(args.iterations):
            last, = exe.run(main_prog, feed=feed, fetch_list=[loss])
    dt = time.perf_counter() - t0
    per_sec = args.pass_num * args.iterations * args.batch_size / dt
    print(json.dumps({
        'model': args.model,
        'batch_size': args.batch_size,
        'iterations': args.iterations,
        'last_loss': float(np.ravel(last)[0]),
        'throughput': round(per_sec, 2),
        'unit': unit,
    }))


if __name__ == '__main__':
    sys.exit(main())
