"""``paddle`` — top-level import shim over :mod:`paddle_tpu`.

The north star (BASELINE.json) requires existing reference scripts to run
unchanged except for the ``place =`` line: they do ``import paddle``,
``import paddle.fluid as fluid``, ``import paddle.v2 as paddle`` and then
use ``paddle.batch`` / ``paddle.reader`` / ``paddle.dataset``
(ref: python/paddle/fluid/tests/book/test_fit_a_line.py:15-16).

This package aliases the whole ``paddle_tpu`` tree under the ``paddle``
name with a meta-path finder, so ``paddle.fluid`` *is*
``paddle_tpu.fluid`` (same module object) and submodule imports like
``import paddle.fluid.profiler`` or ``import paddle.dataset.mnist``
resolve without enumerating anything here.
"""
import importlib
import importlib.abc
import importlib.util
import sys

__version__ = '0.12.0+tpu'


def _real_name(fullname):
    """Map a ``paddle[...]`` module path to its paddle_tpu home.

    paddle.fluid        -> paddle_tpu.fluid   (fluid.py facade module)
    paddle.fluid.<sub>  -> paddle_tpu.<sub>   (framework, layers, io, ...)
    paddle.v2           -> paddle_tpu.v2
    paddle.v2.<sub>     -> paddle_tpu.<sub>   (dataset, reader)
    paddle.<sub>        -> paddle_tpu.<sub>   (dataset, reader, ...)
    """
    rest = fullname[len('paddle.'):]
    if rest == 'fluid':
        return 'paddle_tpu.fluid'
    if rest.startswith('fluid.'):
        return 'paddle_tpu.' + rest[len('fluid.'):]
    if rest == 'v2':
        return 'paddle_tpu.v2'
    if rest.startswith('v2.'):
        return 'paddle_tpu.' + rest[len('v2.'):]
    return 'paddle_tpu.' + rest


class _AliasLoader(importlib.abc.Loader):
    def __init__(self, real):
        self._real = real

    def create_module(self, spec):
        # Return the real module itself: ``paddle.fluid is
        # paddle_tpu.fluid``, so state (default programs, scopes) is
        # shared no matter which name a script imported.
        return importlib.import_module(self._real)

    def exec_module(self, module):
        pass


class _AliasFinder(importlib.abc.MetaPathFinder):
    def find_spec(self, fullname, path=None, target=None):
        if not fullname.startswith('paddle.'):
            return None
        real = _real_name(fullname)
        try:
            found = importlib.util.find_spec(real) is not None
        except (ImportError, ValueError):
            found = False
        if not found:
            return None
        spec = importlib.util.spec_from_loader(fullname,
                                               _AliasLoader(real))
        real_spec = importlib.util.find_spec(real)
        # Mark alias packages as packages so ``import paddle.v2.dataset``
        # style chains keep resolving through this finder.
        if real_spec.submodule_search_locations is not None:
            spec.submodule_search_locations = list(
                real_spec.submodule_search_locations)
        return spec


if not any(isinstance(f, _AliasFinder) for f in sys.meta_path):
    sys.meta_path.insert(0, _AliasFinder())

# Eager conveniences used as plain attributes by reference scripts:
#   paddle.batch(reader, batch_size), paddle.reader.shuffle,
#   paddle.dataset.mnist.train
from paddle_tpu.reader import batch  # noqa: E402
from paddle_tpu import reader, dataset  # noqa: E402
