"""Pass-pipeline sanitizer: diff the verifier's facts across one
compiler pass and turn any regression into a named invariant violation
(ANALYSIS.md "Sanitizer invariants", COMPILER.md).

``snapshot`` captures a program's static facts; ``check_pass`` compares
them against the rewritten program and returns diagnostics whose
``pass_name``/``invariant`` fields name exactly what broke:

- ``def-use``: the rewrite introduced a use-before-def the original
  program did not have.
- ``protected-live``: a protected (fetch) name that was producible
  before the pass no longer is.
- ``side-effect-preserved``: the multiset of side-effecting / RNG /
  feed-fetch ops shrank (dead-op elim dropping a ``print``, or any
  pass eating an RNG consumer and shifting the stream).
- ``release-liveness``: a ``__release__`` annotation names a value a
  LATER op still reads, a protected fetch, persistable state, or the
  PRNG key (buffer_reuse starving a reader).
- ``read-order-hazard``: a surviving read now observes a different
  writer than before the pass (elementwise_fuse moving a member past
  an interloper write — the WAR/WAW hazard). Reads are attributed by
  (name, reader op type), with fused ops expanded through their
  ``sub_ops``; writers whose op type the pass itself introduced are
  exempt (a pass wiring its OWN ops in is the point of the pass).
- ``shape-stable``: a var fully shape-known on both sides changed
  shape.
- ``shard-spec``: a new sharding-consistency error appeared
  (zero_shard_grads emitting a spec that conflicts with
  ``Partitioner.resolve_spec`` / ``grad_shard_spec``).
"""
import time
from collections import Counter

from .diagnostics import Diagnostic, ERROR, PassVerificationError
from .dataflow import (analyze_dataflow, op_reads, op_writes,
                       hidden_reads, last_reads)
from .infer import infer_program
from .verifier import check_sharding, observe

__all__ = ['Snapshot', 'snapshot', 'check_pass', 'run_checked',
           'PassVerificationError']

_IN = '<live-in>'


def _effect_types():
    from ..core.registry import SIDE_EFFECT_OPS
    from ..compiler.passes import RNG_OPS, _ALWAYS_KEEP
    return frozenset(SIDE_EFFECT_OPS) | RNG_OPS | _ALWAYS_KEEP


def _events(program):
    """Per-name ordered access events over the global block, fused ops
    expanded to their members: (op_counts, read_map) where read_map is
    {(name, reader_type): Counter({reaching_writer_type: n})}."""
    from ..compiler.passes import FUSED_ELEMENTWISE_OP
    block = program.global_block()
    per_name = {}
    op_counts = Counter()
    for op in block.ops:
        op_counts[op.type] += 1
        if op.type == FUSED_ELEMENTWISE_OP:
            members = []
            for t, ins, outs, _attrs in op.attrs.get('sub_ops', ()):
                members.append(
                    (t, [n for ns in ins.values() for n in ns],
                     [n for ns in outs.values() for n in ns]))
        else:
            members = [(op.type, op_reads(op), op_writes(op))]
        for t, reads, writes in members:
            for nm in reads:
                per_name.setdefault(nm, []).append(('R', t))
            for nm in writes:
                per_name.setdefault(nm, []).append(('W', t))
    read_map = {}
    for nm, events in per_name.items():
        writer = _IN
        for kind, t in events:
            if kind == 'W':
                writer = t
            else:
                read_map.setdefault((nm, t), Counter())[writer] += 1
    return op_counts, read_map


class Snapshot(object):
    """Static facts about one program, cheap to diff."""

    __slots__ = ('op_counts', 'read_map', 'effects', 'producible',
                 'undef_keys', 'shapes', 'shard_keys', 'protected')

    def __init__(self, program, protected=()):
        self.protected = frozenset(protected or ())
        self.op_counts, self.read_map = _events(program)
        eff = _effect_types()
        self.effects = Counter({t: n for t, n in self.op_counts.items()
                                if t in eff})
        flow, flow_diags = analyze_dataflow(program,
                                            protected=self.protected)
        self.producible = frozenset(flow.defs) | flow.available
        self.undef_keys = frozenset(
            (d.op_type, d.var_names) for d in flow_diags
            if d.code == 'use-before-def')
        env, _diags, _stats = infer_program(program)
        self.shapes = {nm: info.shape for nm, info in env.items()
                       if info.shape is not None
                       and all(d is not None for d in info.shape)}
        self.shard_keys = frozenset(
            (d.code, d.var_names, d.message)
            for d in check_sharding(program) if d.is_error)


def snapshot(program, protected=()):
    return Snapshot(program, protected)


def _violation(pass_name, invariant, message, **kw):
    return Diagnostic('pass-invariant', ERROR, message,
                      pass_name=pass_name, invariant=invariant, **kw)


def check_pass(pass_name, pre, program, protected=None):
    """Diff ``program`` (post-pass) against the ``pre`` Snapshot;
    return violation diagnostics (empty when the pass held every
    invariant)."""
    from ..core.lowering import RNG_KEY
    protected = frozenset(protected if protected is not None
                          else pre.protected)
    diags = []
    post = Snapshot(program, protected)
    block = program.global_block()

    for t, n in pre.effects.items():
        have = post.effects.get(t, 0)
        if have < n:
            diags.append(_violation(
                pass_name, 'side-effect-preserved',
                "pass removed %d %r op(s) (%d -> %d): side-effecting/"
                "RNG/feed-fetch ops must survive every rewrite"
                % (n - have, t, n, have), op_type=t))

    for nm in protected:
        if nm in pre.producible and nm not in post.producible:
            diags.append(_violation(
                pass_name, 'protected-live',
                "protected fetch %r was producible before the pass "
                "and no longer is" % nm, var_names=[nm]))

    for key in post.undef_keys - pre.undef_keys:
        op_type, names = key
        diags.append(_violation(
            pass_name, 'def-use',
            "pass introduced a use-before-def: %s now reads %s with "
            "no earlier definition" % (op_type, ', '.join(names)),
            op_type=op_type, var_names=names))

    last = last_reads(block)
    for i, op in enumerate(block.ops):
        for nm in op.attrs.get('__release__', ()):
            why = None
            if last.get(nm, -1) > i:
                why = ("a later op (op #%d) still reads it"
                       % last[nm])
            elif nm in protected:
                why = "it is a protected fetch"
            elif nm == RNG_KEY:
                why = "it is the threaded PRNG key"
            else:
                var = block._find_var_recursive(nm)
                if var is not None and var.persistable:
                    why = "it is persistable state"
            if why:
                diags.append(_violation(
                    pass_name, 'release-liveness',
                    "op #%d (%s) releases %r but %s — the buffer "
                    "would be dropped while still needed"
                    % (i, op.type, nm, why),
                    op_index=i, op_type=op.type, var_names=[nm]))

    introduced = {t for t, n in post.op_counts.items()
                  if n > pre.op_counts.get(t, 0)}
    for key, writers in post.read_map.items():
        nm, reader = key
        if reader in introduced:
            continue
        pre_writers = pre.read_map.get(key)
        residue = Counter({w: n for w, n in writers.items()
                           if w not in introduced})
        if not residue:
            continue
        if pre_writers is None:
            continue   # renamed input of a surviving op type: benign
        extra = residue - pre_writers
        if extra:
            w = next(iter(extra))
            diags.append(_violation(
                pass_name, 'read-order-hazard',
                "%s now reads %r produced by %s, but before the pass "
                "the same read observed %s — the rewrite moved a read "
                "across a write (WAR/WAW hazard)"
                % (reader, nm, w,
                   '/'.join(sorted(pre_writers)) or _IN),
                op_type=reader, var_names=[nm]))

    for nm, shape in post.shapes.items():
        before = pre.shapes.get(nm)
        if before is not None and tuple(before) != tuple(shape):
            diags.append(_violation(
                pass_name, 'shape-stable',
                "var %r changed inferred shape across the pass: "
                "%s -> %s" % (nm, before, shape), var_names=[nm]))

    for key in post.shard_keys - pre.shard_keys:
        _code, names, message = key
        diags.append(_violation(
            pass_name, 'shard-spec', message, var_names=names))
    return diags


def run_checked(pass_obj, program, ctx):
    """Apply one pass under the sanitizer: snapshot, run, check, raise
    :class:`PassVerificationError` on violations. The building block
    ``PassPipeline(verify=True)`` loops over; exposed for tools and
    tests that drive a single pass."""
    pre = snapshot(program, ctx.protected)
    res = pass_obj.run(program, ctx)
    t0 = time.perf_counter()
    diags = check_pass(pass_obj.name, pre, program, ctx.protected)
    observe('sanitize', diags, time.perf_counter() - t0,
            **{'pass': pass_obj.name})
    if any(d.is_error for d in diags):
        raise PassVerificationError(diags)
    return res
