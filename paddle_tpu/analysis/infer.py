"""Forward shape/dtype inference over the Program IR (ANALYSIS.md
"Inference registry").

A per-op-type rule registry (``register_shape``) seeded for the op
families ``core/registry.py`` has kernels for — mul / conv2d /
elementwise_* / batch_norm / softmax / reduce_* / reshape / concat /
lookup_table — plus the ops the compiler itself emits
(``fused_elementwise``, ``assign_value``, ``zero_reduce_scatter``).
Rules propagate :class:`VarInfo` (per-dim sizes with ``None`` for
dynamic dims, canonical dtype string) forward through the program.

Severity policy (the golden book sweep pins zero errors, so this is
load-bearing):

- intra-op input incompatibility that the lowering could only surface
  as an XLA trace error (mul inner-dim mismatch, broadcast conflict,
  concat off-axis mismatch, conv channel/groups mismatch, float ids
  into lookup_table) -> **error**;
- inferred-vs-declared disagreement -> **warning**, and the DECLARED
  shape wins for further propagation (a wrong rule must never cascade
  into false errors downstream);
- ops without a rule propagate their declared metadata untouched;
- inside control-flow sub-blocks every finding is demoted to warning
  (loop-carried shapes legitimately vary across iterations).
"""
import numpy as np

from .diagnostics import Diagnostic, ERROR, WARNING

__all__ = ['VarInfo', 'register_shape', 'registered_shape_ops',
           'infer_program', 'declared_info']


class VarInfo(object):
    """Static metadata for one value: ``shape`` is a tuple with ``None``
    for unknown dims (or None when even the rank is unknown); ``dtype``
    a canonical numpy dtype string or None."""

    __slots__ = ('shape', 'dtype')

    def __init__(self, shape=None, dtype=None):
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype

    @property
    def rank(self):
        return None if self.shape is None else len(self.shape)

    def numel(self):
        if self.shape is None or any(d is None for d in self.shape):
            return None
        return int(np.prod([int(d) for d in self.shape])) \
            if self.shape else 1

    def __repr__(self):
        return 'VarInfo(shape=%s, dtype=%s)' % (self.shape, self.dtype)


def declared_info(var):
    """VarInfo from a declared Variable: -1 / 0-negative dims are
    dynamic (the batch dim ``layers.data`` prepends)."""
    shape = getattr(var, 'shape', None)
    if shape is None:
        return VarInfo(None, getattr(var, 'dtype', None))
    return VarInfo(tuple(None if int(d) < 0 else int(d) for d in shape),
                   getattr(var, 'dtype', None))


def _canon(dtype):
    if dtype is None:
        return None
    from ..core.lowering import runtime_dtype
    try:
        return runtime_dtype(dtype)
    except Exception:
        return str(dtype)


def _dims_agree(a, b):
    return a is None or b is None or int(a) == int(b)


def _merge_shapes(declared, inferred):
    """Meet of declared and inferred: known beats unknown; on a known
    conflict the DECLARED dim wins. Returns (shape, conflict?)."""
    if inferred is None:
        return declared, False
    if declared is None:
        return inferred, False
    if len(declared) != len(inferred):
        return declared, True
    out, conflict = [], False
    for d, i in zip(declared, inferred):
        if d is None:
            out.append(i)
        elif i is None or int(d) == int(i):
            out.append(d)
        else:
            out.append(d)
            conflict = True
    return tuple(out), conflict


# ---- rule registry ---------------------------------------------------------

_RULES = {}


def register_shape(*op_types):
    """Decorator: ``fn(op, env, emit) -> {out_name: VarInfo}`` where
    ``env(name)`` resolves current VarInfo and ``emit(code, severity,
    message, vars)`` files a diagnostic against the op. COMPILER.md's
    pass-authoring note: register a rule for any op type your pass
    emits, or the sanitizer's shape diff goes blind there."""
    def deco(fn):
        for t in op_types:
            _RULES[t] = fn
        return fn
    return deco


def registered_shape_ops():
    return sorted(_RULES)


def _first(op, slot):
    names = op.inputs.get(slot) or []
    return names[0] if names else None


def _out(op, slot='Out'):
    names = op.outputs.get(slot) or []
    return names[0] if names else None


# identity-shaped ops: first (X) input -> every output in the named slot
_IDENTITY_SLOTS = {
    'softmax': ('X', ('Out',)),
    'dropout': ('X', ('Out', 'Mask')),
    'batch_norm': ('X', ('Y',)),
    'layer_norm': ('X', ('Y',)),
    'assign': ('X', ('Out',)),
    'relu_grad': ('X', ('Out',)),
    'softmax_with_cross_entropy': ('Logits', ('Softmax',)),
    'zero_reduce_scatter': ('X', ('Out',)),
}


def _register_identity_ops():
    from ..compiler.passes import _ELEMENTWISE

    @register_shape(*sorted(_ELEMENTWISE - {
        'elementwise_add', 'elementwise_sub', 'elementwise_mul',
        'elementwise_div', 'elementwise_max', 'elementwise_min',
        'elementwise_pow'}))
    def _unary_elementwise(op, env, emit):
        x = env(_first(op, 'X'))
        out = _out(op)
        if out is None or x is None:
            return {}
        return {out: VarInfo(x.shape, x.dtype)}


@register_shape('cast')
def _cast(op, env, emit):
    x = env(_first(op, 'X'))
    out = _out(op)
    if out is None or x is None:
        return {}
    return {out: VarInfo(x.shape, op.attrs.get('out_dtype')
                         or op.attrs.get('dtype') or x.dtype)}


@register_shape('softmax', 'dropout', 'batch_norm', 'layer_norm',
                'assign', 'zero_reduce_scatter',
                'softmax_with_cross_entropy')
def _identity(op, env, emit):
    in_slot, out_slots = _IDENTITY_SLOTS[op.type]
    updates = {}
    if op.type == 'zero_reduce_scatter':
        # bucketed: Out[i] mirrors X[i], name for name
        for nm_in, nm_out in zip(op.inputs.get('X', ()),
                                 op.outputs.get('Out', ())):
            x = env(nm_in)
            if x is not None:
                updates[nm_out] = VarInfo(x.shape, x.dtype)
        return updates
    x = env(_first(op, in_slot))
    if x is None:
        return {}
    for slot in out_slots:
        nm = _out(op, slot)
        if nm is not None:
            updates[nm] = VarInfo(x.shape, x.dtype)
    if op.type == 'softmax_with_cross_entropy':
        loss = _out(op, 'Loss')
        if loss is not None and x.shape is not None and len(x.shape):
            updates[loss] = VarInfo(tuple(x.shape[:-1]) + (1,), x.dtype)
    return updates


def _broadcast_check(op, x, y, emit):
    """Paddle elementwise semantics: Y aligns to X's dims starting at
    ``axis`` (default: trailing). A known-unequal pair with neither side
    1 can only die in the XLA trace — error here instead."""
    if x.shape is None or y.shape is None:
        return
    if len(y.shape) > len(x.shape):
        return  # grad/unusual orientation: leave to the trace
    axis = op.attrs.get('axis', -1)
    if axis is None or int(axis) < 0:
        axis = len(x.shape) - len(y.shape)
    axis = int(axis)
    for j, yd in enumerate(y.shape):
        i = axis + j
        if i >= len(x.shape):
            break
        xd = x.shape[i]
        if xd is None or yd is None or int(yd) == 1 or int(xd) == 1:
            continue
        if int(xd) != int(yd):
            emit('broadcast-mismatch', ERROR,
                 "elementwise inputs cannot broadcast: X dim %d is %s "
                 "but Y dim %d is %s (axis=%s)"
                 % (i, xd, j, yd, op.attrs.get('axis', -1)),
                 [_first(op, 'X'), _first(op, 'Y')])
            return


@register_shape('elementwise_add', 'elementwise_sub', 'elementwise_mul',
                'elementwise_div', 'elementwise_max', 'elementwise_min',
                'elementwise_pow')
def _elementwise(op, env, emit):
    x, y = env(_first(op, 'X')), env(_first(op, 'Y'))
    out = _out(op)
    if out is None or x is None:
        return {}
    if y is not None:
        _broadcast_check(op, x, y, emit)
        if x.dtype and y.dtype and _canon(x.dtype) != _canon(y.dtype):
            emit('dtype-mismatch', WARNING,
                 "elementwise inputs disagree on dtype: %s vs %s"
                 % (x.dtype, y.dtype),
                 [_first(op, 'X'), _first(op, 'Y')])
    return {out: VarInfo(x.shape, x.dtype)}


def _flat2(shape, ncol):
    """Collapse to 2-D around ``ncol`` like mul does; dims with unknown
    members collapse to None."""
    a, b = shape[:ncol], shape[ncol:]

    def prod(dims):
        if any(d is None for d in dims):
            return None
        return int(np.prod([int(d) for d in dims])) if dims else 1
    return prod(a), prod(b)


@register_shape('mul')
def _mul(op, env, emit):
    x, y = env(_first(op, 'X')), env(_first(op, 'Y'))
    out = _out(op)
    if out is None or x is None or y is None \
            or x.shape is None or y.shape is None:
        return {}
    xn = int(op.attrs.get('x_num_col_dims', 1))
    yn = int(op.attrs.get('y_num_col_dims', 1))
    if len(x.shape) < xn + 1 or len(y.shape) < yn + 1:
        emit('rank-mismatch', ERROR,
             "mul needs X rank > x_num_col_dims (%d) and Y rank > "
             "y_num_col_dims (%d); got X%s Y%s"
             % (xn, yn, x.shape, y.shape),
             [_first(op, 'X'), _first(op, 'Y')])
        return {}
    _, xk = _flat2(x.shape, xn)
    yk, _ = _flat2(y.shape, yn)
    if xk is not None and yk is not None and xk != yk:
        emit('rank-mismatch', ERROR,
             "mul inner dims mismatch: X%s flattens to [*, %d] but Y%s "
             "flattens to [%d, *]" % (x.shape, xk, y.shape, yk),
             [_first(op, 'X'), _first(op, 'Y')])
        return {}
    return {out: VarInfo(tuple(x.shape[:xn]) + tuple(y.shape[yn:]),
                         x.dtype)}


@register_shape('matmul')
def _matmul(op, env, emit):
    x, y = env(_first(op, 'X')), env(_first(op, 'Y'))
    out = _out(op)
    if out is None or x is None or y is None \
            or x.shape is None or y.shape is None \
            or len(x.shape) < 2 or len(y.shape) < 2:
        return {}
    tx = bool(op.attrs.get('transpose_X', False))
    ty = bool(op.attrs.get('transpose_Y', False))
    xk = x.shape[-2] if tx else x.shape[-1]
    yk = y.shape[-1] if ty else y.shape[-2]
    if xk is not None and yk is not None and int(xk) != int(yk):
        emit('rank-mismatch', ERROR,
             "matmul contraction dims mismatch: %s vs %s "
             "(transpose_X=%s transpose_Y=%s)" % (xk, yk, tx, ty),
             [_first(op, 'X'), _first(op, 'Y')])
        return {}
    m = x.shape[-1] if tx else x.shape[-2]
    n = y.shape[-2] if ty else y.shape[-1]
    batch = x.shape[:-2] if len(x.shape) >= len(y.shape) else y.shape[:-2]
    return {out: VarInfo(tuple(batch) + (m, n), x.dtype)}


def _conv_out(size, k, pad, stride, dilation):
    if size is None or k is None:
        return None
    eff = dilation * (int(k) - 1) + 1
    return (int(size) + 2 * pad - eff) // stride + 1


@register_shape('conv2d', 'depthwise_conv2d')
def _conv2d(op, env, emit):
    x = env(_first(op, 'Input'))
    f = env(_first(op, 'Filter'))
    out = _out(op, 'Output')
    if out is None or x is None or f is None \
            or x.shape is None or f.shape is None \
            or len(x.shape) != 4 or len(f.shape) != 4:
        return {}
    groups = int(op.attrs.get('groups', 1) or 1)
    cin, fc = x.shape[1], f.shape[1]
    if cin is not None and fc is not None \
            and int(cin) != int(fc) * groups:
        emit('conv-channel-mismatch', ERROR,
             "conv2d input channels (%s) != filter channels (%s) * "
             "groups (%d)" % (cin, fc, groups),
             [_first(op, 'Input'), _first(op, 'Filter')])
        return {}
    strides = list(op.attrs.get('strides', [1, 1]) or [1, 1])
    pads = list(op.attrs.get('paddings', [0, 0]) or [0, 0])
    dil = list(op.attrs.get('dilations', [1, 1]) or [1, 1])
    ho = _conv_out(x.shape[2], f.shape[2], int(pads[0]),
                   int(strides[0]), int(dil[0]))
    wo = _conv_out(x.shape[3], f.shape[3], int(pads[1]),
                   int(strides[1]), int(dil[1]))
    return {out: VarInfo((x.shape[0], f.shape[0], ho, wo), x.dtype)}


@register_shape('pool2d')
def _pool2d(op, env, emit):
    x = env(_first(op, 'X'))
    out = _out(op)
    if out is None or x is None or x.shape is None \
            or len(x.shape) != 4:
        return {}
    if op.attrs.get('global_pooling', False):
        return {out: VarInfo((x.shape[0], x.shape[1], 1, 1), x.dtype)}
    ksize = list(op.attrs.get('ksize', [2, 2]) or [2, 2])
    strides = list(op.attrs.get('strides', [1, 1]) or [1, 1])
    pads = list(op.attrs.get('paddings', [0, 0]) or [0, 0])
    ceil = bool(op.attrs.get('ceil_mode', False))

    def _o(size, k, p, s):
        if size is None:
            return None
        num = int(size) + 2 * int(p) - int(k)
        return (num + int(s) - 1) // int(s) + 1 if ceil \
            else num // int(s) + 1
    return {out: VarInfo((x.shape[0], x.shape[1],
                          _o(x.shape[2], ksize[0], pads[0], strides[0]),
                          _o(x.shape[3], ksize[1], pads[1], strides[1])),
                         x.dtype)}


@register_shape('reduce_sum', 'reduce_mean', 'reduce_max', 'reduce_min',
                'reduce_prod')
def _reduce(op, env, emit):
    x = env(_first(op, 'X'))
    out = _out(op)
    if out is None or x is None or x.shape is None:
        return {}
    keep = bool(op.attrs.get('keep_dim', False))
    dims = op.attrs.get('dim', None)
    if op.attrs.get('reduce_all', False) or dims is None:
        shape = (1,) * len(x.shape) if keep else (1,)
        return {out: VarInfo(shape, x.dtype)}
    if not isinstance(dims, (list, tuple)):
        dims = [dims]
    dims = {int(d) % len(x.shape) for d in dims} if x.shape else set()
    shape = tuple(1 if i in dims else d
                  for i, d in enumerate(x.shape)) if keep else \
        tuple(d for i, d in enumerate(x.shape) if i not in dims)
    return {out: VarInfo(shape or (1,), x.dtype)}


@register_shape('mean')
def _mean(op, env, emit):
    out = _out(op)
    x = env(_first(op, 'X'))
    if out is None:
        return {}
    return {out: VarInfo((1,), x.dtype if x else None)}


@register_shape('reshape')
def _reshape(op, env, emit):
    x = env(_first(op, 'X'))
    out = _out(op)
    if out is None or x is None:
        return {}
    if op.inputs.get('Shape'):
        return {out: VarInfo(None, x.dtype)}   # runtime shape feed
    target = op.attrs.get('shape')
    if not target:
        return {out: VarInfo(None, x.dtype)}
    shape, infer_at = [], None
    for i, d in enumerate(target):
        d = int(d)
        if d == -1:
            infer_at = i
            shape.append(None)
        elif d == 0:
            shape.append(x.shape[i] if x.shape is not None
                         and i < len(x.shape) else None)
        else:
            shape.append(d)
    if infer_at is not None:
        total = x.numel()
        rest = [d for i, d in enumerate(shape) if i != infer_at]
        if total is not None and all(d is not None for d in rest):
            denom = int(np.prod([int(d) for d in rest])) if rest else 1
            if denom and total % denom == 0:
                shape[infer_at] = total // denom
            else:
                emit('reshape-numel', ERROR,
                     "reshape cannot infer -1: %d elements do not "
                     "divide by %s (target %s)" % (total, denom, target),
                     [_first(op, 'X')])
                return {}
    return {out: VarInfo(tuple(shape), x.dtype)}


@register_shape('concat')
def _concat(op, env, emit):
    names = op.inputs.get('X') or []
    out = _out(op)
    infos = [env(n) for n in names]
    if out is None or not infos or any(i is None for i in infos):
        return {}
    known = [i for i in infos if i.shape is not None]
    if not known:
        return {}
    rank = len(known[0].shape)
    axis = int(op.attrs.get('axis', 0))
    axis = axis % rank if rank else 0
    base = list(known[0].shape)
    axis_total, any_unknown = 0, False
    for idx, info in enumerate(infos):
        if info.shape is None:
            any_unknown = True
            continue
        if len(info.shape) != rank:
            emit('concat-rank', ERROR,
                 "concat inputs disagree on rank: %s vs %s"
                 % (known[0].shape, info.shape), names)
            return {}
        for d in range(rank):
            if d == axis:
                continue
            if not _dims_agree(base[d], info.shape[d]):
                emit('concat-mismatch', ERROR,
                     "concat off-axis dim %d mismatch: %s vs %s "
                     "(axis=%d)" % (d, base[d], info.shape[d], axis),
                     names)
                return {}
            if base[d] is None:
                base[d] = info.shape[d]
        if info.shape[axis] is None:
            any_unknown = True
        else:
            axis_total += int(info.shape[axis])
    dtypes = {_canon(i.dtype) for i in infos if i.dtype}
    if len(dtypes) > 1:
        emit('dtype-mismatch', WARNING,
             "concat inputs disagree on dtype: %s"
             % sorted(dtypes), names)
    base[axis] = None if any_unknown else axis_total
    return {out: VarInfo(tuple(base), known[0].dtype)}


@register_shape('lookup_table')
def _lookup_table(op, env, emit):
    w = env(_first(op, 'W'))
    ids = env(_first(op, 'Ids'))
    out = _out(op)
    if out is None or w is None or w.shape is None \
            or len(w.shape) != 2:
        return {}
    if ids is not None and ids.dtype is not None:
        kind = np.dtype(_canon(ids.dtype)).kind
        if kind not in ('i', 'u'):
            emit('dtype-mismatch', ERROR,
                 "lookup_table ids must be an integer dtype, got %s"
                 % ids.dtype, [_first(op, 'Ids')])
    if ids is None or ids.shape is None:
        return {out: VarInfo(None, w.dtype)}
    base = ids.shape[:-1] if (len(ids.shape) and
                              ids.shape[-1] == 1) else ids.shape
    return {out: VarInfo(tuple(base) + (w.shape[1],), w.dtype)}


@register_shape('cross_entropy')
def _cross_entropy(op, env, emit):
    x = env(_first(op, 'X'))
    out = _out(op, 'Y') or _out(op)
    if out is None or x is None or x.shape is None \
            or len(x.shape) < 1:
        return {}
    return {out: VarInfo(tuple(x.shape[:-1]) + (1,), x.dtype)}


@register_shape('sum')
def _sum(op, env, emit):
    names = op.inputs.get('X') or []
    out = _out(op)
    infos = [env(n) for n in names if env(n) is not None]
    known = [i for i in infos if i.shape is not None]
    if out is None or not known:
        return {}
    base = known[0].shape
    for i in known[1:]:
        if len(i.shape) != len(base) or not all(
                _dims_agree(a, b) for a, b in zip(base, i.shape)):
            emit('sum-mismatch', ERROR,
                 "sum inputs disagree on shape: %s vs %s"
                 % (base, i.shape), names)
            return {}
    return {out: VarInfo(base, known[0].dtype)}


@register_shape('transpose')
def _transpose(op, env, emit):
    x = env(_first(op, 'X'))
    out = _out(op)
    perm = op.attrs.get('axis')
    if out is None or x is None or x.shape is None or not perm:
        return {}
    if len(perm) != len(x.shape):
        emit('rank-mismatch', ERROR,
             "transpose perm %s does not match input rank %d"
             % (perm, len(x.shape)), [_first(op, 'X')])
        return {}
    return {out: VarInfo(tuple(x.shape[int(p)] for p in perm), x.dtype)}


@register_shape('top_k')
def _top_k(op, env, emit):
    x = env(_first(op, 'X'))
    k = op.attrs.get('k', 1)
    updates = {}
    if x is None or x.shape is None or not len(x.shape):
        return updates
    shape = tuple(x.shape[:-1]) + (int(k),)
    nm = _out(op)
    if nm is not None:
        updates[nm] = VarInfo(shape, x.dtype)
    ind = _out(op, 'Indices')
    if ind is not None:
        updates[ind] = VarInfo(shape, 'int64')
    return updates


@register_shape('fill_constant', 'uniform_random', 'gaussian_random',
                'assign_value')
def _filled(op, env, emit):
    out = _out(op)
    shape = op.attrs.get('shape')
    if out is None or shape is None:
        return {}
    return {out: VarInfo(tuple(None if int(d) < 0 else int(d)
                               for d in shape),
                         op.attrs.get('dtype') or 'float32')}


@register_shape('fill_constant_batch_size_like',
                'uniform_random_batch_size_like',
                'gaussian_random_batch_size_like')
def _filled_like(op, env, emit):
    out = _out(op)
    shape = op.attrs.get('shape')
    if out is None or shape is None:
        return {}
    shape = [None if int(d) < 0 else int(d) for d in shape]
    out_idx = int(op.attrs.get('output_dim_idx', 0))
    ref = env(_first(op, 'Input'))
    in_idx = int(op.attrs.get('input_dim_idx', 0))
    if 0 <= out_idx < len(shape):
        shape[out_idx] = (ref.shape[in_idx]
                          if ref is not None and ref.shape is not None
                          and in_idx < len(ref.shape) else None)
    return {out: VarInfo(tuple(shape),
                         op.attrs.get('dtype') or 'float32')}


@register_shape('fused_elementwise', 'fused_conv')
def _fused(op, env, emit):
    """Replay the captured sub-ops through their own rules so the fused
    kernel stays as transparent to inference as to execution."""
    local = {}

    def _env(name):
        return local.get(name) or env(name)
    updates = {}
    for t, ins, outs, attrs in op.attrs.get('sub_ops', ()):
        rule = _RULES.get(t)
        if rule is None:
            continue
        from ..framework import Operator
        sub = Operator.__new__(Operator)
        sub.block, sub.type = op.block, t
        sub.inputs = {s: list(v) for s, v in ins.items()}
        sub.outputs = {s: list(v) for s, v in outs.items()}
        sub.attrs = dict(attrs)
        try:
            got = rule(sub, _env, emit) or {}
        except Exception:
            got = {}
        local.update(got)
    for nm in op.output_arg_names:
        if nm in local:
            updates[nm] = local[nm]
    return updates


@register_shape('cos_sim')
def _cos_sim(op, env, emit):
    x = env(_first(op, 'X'))
    out = _out(op)
    if out is None or x is None or x.shape is None or not len(x.shape):
        return {}
    return {out: VarInfo((x.shape[0], 1), x.dtype)}


_register_identity_ops()


# ---- the forward walk ------------------------------------------------------

def infer_program(program, feeds=None):
    """Propagate VarInfo forward through ``program``.

    Returns ``(env, diagnostics, stats)`` — ``env`` maps every var name
    to its final VarInfo, ``stats`` carries rule-coverage counters for
    the CLI report.
    """
    env = {}
    diags = []
    stats = {'ops': 0, 'covered': 0}
    for b in program.blocks:
        for v in b.vars.values():
            env[v.name] = declared_info(v)

    def lookup(name):
        if name is None:
            return None
        info = env.get(name)
        if info is None:
            info = env[name] = VarInfo(None, None)
        return info

    def _walk(block, bidx, demote):
        from ..framework import Block as _B
        for i, op in enumerate(block.ops):
            stats['ops'] += 1

            def emit(code, severity, message, var_names=()):
                if demote and severity == ERROR:
                    severity = WARNING
                diags.append(Diagnostic(
                    code, severity, message, block_idx=bidx,
                    op_index=i, op_type=op.type,
                    var_names=[n for n in var_names if n]))
            rule = _RULES.get(op.type)
            if rule is not None:
                stats['covered'] += 1
                try:
                    updates = rule(op, lookup, emit) or {}
                except Exception:
                    updates = {}   # a rule bug must never fail a run
                for nm, info in updates.items():
                    cur = env.get(nm)
                    declared = cur.shape if cur is not None else None
                    merged, conflict = _merge_shapes(declared, info.shape)
                    if conflict:
                        emit('shape-mismatch-declared', WARNING,
                             "inferred shape %s conflicts with declared "
                             "%s for %r; declared wins"
                             % (info.shape, declared, nm), [nm])
                    env[nm] = VarInfo(
                        merged, info.dtype or
                        (cur.dtype if cur is not None else None))
            for v in op.attrs.values():
                if isinstance(v, _B):
                    _walk(v, v.idx, True)

    _walk(program.global_block(), 0, False)
    return env, diags, stats
