"""The program verifier: dataflow + shape/dtype inference + sharding
consistency, with telemetry (ANALYSIS.md).

``verify_program`` returns typed diagnostics; ``assert_valid`` turns
error severity into :class:`ProgramInvalid`. The executor-facing hooks
(``verify_for_executor`` / ``check_feeds_for_executor``) memoize per
program fingerprint so steady-state steps pay one dict lookup, and an
internal analyzer bug degrades to "no diagnostics" rather than taking a
training step down (only deliberate ``ProgramInvalid`` escapes).

Sharding checks reuse the partition layer as an ABSTRACT domain — the
same ``resolve_entry`` rules and ``first_divisible_dim`` divisibility
test the Partitioner applies at run time, evaluated with no mesh: an
annotation that would silently degrade (or disagree with
``Partitioner.grad_shard_spec``) is flagged before any device exists.
"""
import os
import time

import numpy as np

from .diagnostics import (Diagnostic, ProgramInvalid, FeedInvalid,
                          ERROR, WARNING, errors_of)
from .dataflow import analyze_dataflow
from .infer import infer_program, declared_info

__all__ = ['verify_program', 'assert_valid', 'check_sharding',
           'check_feeds', 'verify_for_executor',
           'check_feeds_for_executor', 'enabled', 'set_enabled',
           'verify_passes_enabled', 'observe']

_STATE = {'enabled': None}


def enabled():
    """Executor-path verification switch: default on; env
    ``PTPU_VERIFY=0`` or ``set_enabled(False)`` disables."""
    if _STATE['enabled'] is not None:
        return _STATE['enabled']
    return os.environ.get('PTPU_VERIFY', '1') not in ('0', 'off', '')


def set_enabled(on):
    """True/False force; None -> consult the PTPU_VERIFY env var."""
    _STATE['enabled'] = None if on is None else bool(on)


def verify_passes_enabled():
    """Default for ``PassPipeline(verify=None)``: the
    ``PTPU_VERIFY_PASSES=1`` sanitizer env switch (COMPILER.md)."""
    return os.environ.get('PTPU_VERIFY_PASSES', '') not in ('', '0')


def observe(phase, diagnostics, dur_s, **fields):
    """Publish one analysis application: per-severity
    ``analysis_diagnostics_total`` counters, the
    ``analysis_verify_seconds`` histogram, and an ``analysis`` journal
    event (OBSERVABILITY.md)."""
    from .. import observability as _obs
    reg = _obs.default_registry()
    reg.histogram('analysis_verify_seconds',
                  'wall seconds per static verifier application'
                  ).observe(dur_s)
    counts = {}
    for d in diagnostics:
        counts[d.severity] = counts.get(d.severity, 0) + 1
    for sev, n in counts.items():
        reg.counter('analysis_diagnostics_total',
                    'diagnostics produced by the static program '
                    'verifier', severity=sev).inc(n)
    _obs.emit('analysis', phase=phase, dur_s=round(dur_s, 6),
              errors=counts.get(ERROR, 0),
              warnings=counts.get(WARNING, 0), **fields)


# ---- sharding consistency (partition rules as abstract domain) -------------

def _abstract_axes():
    """Every mesh axis the standard rules may ever target plus the
    conventional names — the most permissive mesh, so the only way an
    entry resolves to None is a genuinely unknown axis."""
    from ..partition.rules import standard_logical_axis_rules
    axes = {'dp', 'mp', 'pp', 'sp'}
    for _logical, mesh_axis in standard_logical_axis_rules():
        if mesh_axis:
            axes.add(mesh_axis)
    return axes


def check_sharding(program):
    """Static sharding-spec validation with no mesh.

    - malformed spec entries (non-string, non-None) -> error;
    - specs longer than the var's known rank -> warning (resolve_spec
      truncates silently);
    - every ``zero_reduce_scatter`` bucket entry must agree with
      ``partition.first_divisible_dim`` — the ONE divisibility rule
      ``Partitioner.resolve_spec`` degrades by and
      ``grad_shard_spec`` chooses by; a mismatched dim or a
      non-dividing extent is an error (the annotation would silently
      degrade, or shard a different dim than the optimizer-state
      slicing assumes).
    """
    from ..partition.rules import (standard_logical_axis_rules,
                                   resolve_entry)
    from ..partition import first_divisible_dim
    diags = []
    axes = _abstract_axes()
    rules = standard_logical_axis_rules()
    for b in program.blocks:
        for v in b.vars.values():
            spec = v.sharding
            if spec is None:
                continue
            bad = [e for e in spec
                   if e is not None and not isinstance(e, str)
                   and not (isinstance(e, (tuple, list)) and all(
                       isinstance(a, str) for a in e))]
            if bad:
                diags.append(Diagnostic(
                    'shard-spec', ERROR,
                    "malformed sharding spec %r on %r: entries must be "
                    "axis names or None" % (spec, v.name),
                    block_idx=b.idx, var_names=[v.name]))
                continue
            info = declared_info(v)
            if info.shape is not None and len(spec) > len(info.shape):
                diags.append(Diagnostic(
                    'shard-rank', WARNING,
                    "sharding spec %r has %d entries but %r has rank "
                    "%d; resolve_spec will truncate"
                    % (spec, len(spec), v.name, len(info.shape)),
                    block_idx=b.idx, var_names=[v.name]))
            for e in spec:
                if e is not None and \
                        resolve_entry(e, axes, rules) is None:
                    diags.append(Diagnostic(
                        'shard-axis', WARNING,
                        "spec entry %r on %r names no mesh or logical "
                        "axis the partition rules know; it degrades to "
                        "replicated" % (e, v.name),
                        block_idx=b.idx, var_names=[v.name]))
    block = program.global_block()
    for i, op in enumerate(block.ops):
        if op.type != 'zero_reduce_scatter':
            continue
        dp = int(op.attrs.get('dp', 0) or 0)
        axis = op.attrs.get('axis_name', 'dp')
        names = op.inputs.get('X') or []
        dims = list(op.attrs.get('shard_dims') or [])
        for nm, d in zip(names, dims):
            var = block._find_var_recursive(nm)
            shape = declared_info(var).shape if var is not None else None
            if shape is None or any(s is None for s in shape):
                continue
            d = int(d)
            want = first_divisible_dim(shape, dp)
            if d >= len(shape) or dp <= 0 \
                    or int(shape[d]) % dp != 0:
                diags.append(Diagnostic(
                    'shard-spec', ERROR,
                    "grad shard for %r puts axis %r on dim %d of %s, "
                    "which %d-way sharding does not divide — "
                    "Partitioner.resolve_spec would silently degrade "
                    "it to replicated while the optimizer-state "
                    "slicing stays sharded" % (nm, axis, d, shape, dp),
                    op_index=i, op_type=op.type, var_names=[nm]))
            elif want != d:
                diags.append(Diagnostic(
                    'shard-spec', ERROR,
                    "grad shard for %r uses dim %d of %s but "
                    "Partitioner.grad_shard_spec (first_divisible_dim) "
                    "resolves the same tensor to dim %s — the "
                    "annotation conflicts with the partition rules"
                    % (nm, d, shape, want),
                    op_index=i, op_type=op.type, var_names=[nm]))
            if var is not None and var.sharding is not None:
                canon = (None,) * d + (axis,)
                if tuple(var.sharding) != canon:
                    diags.append(Diagnostic(
                        'shard-spec', ERROR,
                        "var %r is annotated %r but its "
                        "zero_reduce_scatter bucket shards dim %d "
                        "(expected %r)" % (nm, var.sharding, d, canon),
                        op_index=i, op_type=op.type, var_names=[nm]))
    return diags


# ---- the combined verify ---------------------------------------------------

def verify_program(program, feeds=(), fetch_names=(), observe_as=None):
    """Run every static check; return the full diagnostic list (never
    raises). ``feeds`` are run-time-available names beyond data vars
    and persistable state; ``fetch_names`` gate reachability."""
    t0 = time.perf_counter()
    flow, diags = analyze_dataflow(program, feeds=feeds,
                                   protected=fetch_names)
    for nm in fetch_names or ():
        if nm not in flow.defs and nm not in flow.available:
            diags.append(Diagnostic(
                'fetch-unreachable', ERROR,
                "fetch target %r is produced by no op and is neither "
                "persistable state nor a data/feed var" % nm,
                var_names=[nm]))
    _env, infer_diags, stats = infer_program(program, feeds=feeds)
    diags.extend(infer_diags)
    diags.extend(check_sharding(program))
    dur = time.perf_counter() - t0
    if observe_as:
        observe(observe_as, diags, dur, ops=flow.num_ops,
                covered=stats['covered'])
    return diags


def assert_valid(program, feeds=(), fetch_names=(), observe_as='verify'):
    """``verify_program`` + raise :class:`ProgramInvalid` on any
    error-severity diagnostic."""
    diags = verify_program(program, feeds=feeds, fetch_names=fetch_names,
                           observe_as=observe_as)
    if errors_of(diags):
        raise ProgramInvalid(diags)
    return diags


# ---- feed validation -------------------------------------------------------

def _is_sequence_feed(val):
    return getattr(val, 'lengths', None) is not None \
        or getattr(val, '_packed', None) is not None


def check_feeds(program, feed):
    """Typed early feed validation: shape rank / known dims / dtype
    kind against declared var metadata, per feed slot. Sequence feeds
    (ragged) and scalar feeds are skipped; unknown (-1) dims match
    anything — exactly what the lowering can absorb."""
    diags = []
    block = program.global_block()
    for name, val in (feed or {}).items():
        var = block._find_var_recursive(name)
        if var is None or _is_sequence_feed(val) \
                or getattr(var, 'lod_level', 0):
            continue
        declared = declared_info(var)
        if not declared.shape:
            continue
        try:
            got = tuple(int(d) for d in np.shape(val))
        except Exception:
            continue
        if not got:
            continue  # scalar feeds broadcast
        # Paddle idiom: a (N,) feed into a (None, 1) label var (and the
        # reverse) is routine — trailing size-1 dims are layout, not
        # content, so strip them only as far as needed to reconcile rank.
        decl = list(declared.shape)
        fed = list(got)
        while len(decl) > len(fed) and decl and decl[-1] == 1:
            decl.pop()
        while len(fed) > len(decl) and fed and fed[-1] == 1:
            fed.pop()
        if len(fed) != len(decl):
            diags.append(Diagnostic(
                'feed-rank', ERROR,
                "feed slot %r: fed rank-%d value of shape %s but the "
                "var declares rank %d (%s)"
                % (name, len(got), got, len(declared.shape),
                   declared.shape), var_names=[name]))
            continue
        for i, (fd, dd) in enumerate(zip(fed, decl)):
            # WARNING, not error: lowering traces with the FED shape,
            # and data-dependent kernels (detection) legitimately feed
            # a different extent than the declared hint.
            if dd is not None and int(fd) != int(dd):
                diags.append(Diagnostic(
                    'feed-shape', WARNING,
                    "feed slot %r: dim %d is %d but the var declares "
                    "%d (declared %s, fed %s) — ops whose parameter "
                    "shapes were sized from the declaration will fail"
                    % (name, i, fd, dd, declared.shape, got),
                    var_names=[name]))
                break
        fed_dt = getattr(val, 'dtype', None)
        if fed_dt is not None and declared.dtype:
            try:
                fk = np.dtype(str(fed_dt)).kind
                dk = np.dtype(str(declared.dtype)).kind
            except Exception:
                continue
            if fk == 'f' and dk in ('i', 'u'):
                diags.append(Diagnostic(
                    'feed-dtype', ERROR,
                    "feed slot %r: float data fed into %s var — the "
                    "boundary cast would silently truncate"
                    % (name, declared.dtype), var_names=[name]))
    return diags


# ---- executor hooks --------------------------------------------------------

def verify_for_executor(program, feed_names=(), fetch_names=()):
    """Cache-miss-path verify (Executor.run, before lowering): memoized
    per (fingerprint, feed names, fetch names); raises
    :class:`ProgramInvalid` on error diagnostics so the user sees a
    named op instead of an XLA traceback."""
    if not enabled():
        return
    memo = program.__dict__.setdefault('_analysis_memo', {})
    key = (program.fingerprint(), tuple(sorted(feed_names or ())),
           tuple(sorted(fetch_names or ())))
    diags = memo.get(key)
    if diags is None:
        try:
            diags = verify_program(program, feeds=feed_names,
                                   fetch_names=fetch_names,
                                   observe_as='verify')
        except Exception:
            diags = []   # analyzer bug: never block the step
        memo[key] = diags
    if errors_of(diags):
        raise ProgramInvalid(diags)


def check_feeds_for_executor(program, feed):
    """Raise :class:`FeedInvalid` on a statically bad feed; memoized on
    the raw feed signature so steady-state steps skip the walk."""
    if not feed or not enabled():
        return
    memo = program.__dict__.setdefault('_feed_check_memo', set())
    try:
        sig = (program.fingerprint(), tuple(sorted(
            (n, tuple(np.shape(v)) if not _is_sequence_feed(v) else 'seq',
             str(getattr(v, 'dtype', '')))
            for n, v in feed.items())))
    except Exception:
        return
    if sig in memo:
        return
    try:
        diags = check_feeds(program, feed)
    except Exception:
        diags = []
    if errors_of(diags):
        observe('feed', diags, 0.0)
        raise FeedInvalid(diags)
    memo.add(sig)
