"""paddle_tpu.analysis — static program verifier, shape/dtype/sharding
inference, and the pass-pipeline sanitizer (ANALYSIS.md).

Three entry points, matching the three choke points the rest of the
framework calls through:

- :func:`verify_program` / :func:`assert_valid` — whole-program static
  checks (dataflow + shape/dtype inference + sharding consistency),
  returning typed :class:`Diagnostic` records. ``Executor.run`` calls
  the memoized :func:`verify_for_executor` on every compile-cache miss
  BEFORE lowering, so a mis-wired program raises
  :class:`ProgramInvalid` naming the offending op instead of an XLA
  traceback.
- :func:`check_feeds` / :func:`check_feeds_for_executor` — early feed
  validation; a rank/shape/dtype-incompatible feed raises
  :class:`FeedInvalid` naming the feed slot.
- :mod:`~paddle_tpu.analysis.sanitizer` — ``PassPipeline(verify=True)``
  (env ``PTPU_VERIFY_PASSES=1``) snapshots the program before every
  compiler pass and diffs dataflow/shape/sharding facts after it,
  raising :class:`PassVerificationError` that names the pass and the
  violated invariant.

Pass authors registering new fused ops should also register shape
inference for them via :func:`register_shape` (COMPILER.md).
"""

from .diagnostics import (Diagnostic, ProgramInvalid, FeedInvalid,
                          PassVerificationError, SEVERITIES, ERROR,
                          WARNING, INFO, max_severity, errors_of,
                          format_diagnostics)
from .dataflow import (analyze_dataflow, DataflowResult, op_reads,
                       op_writes, hidden_reads, hidden_writes,
                       carrier_defs, reachable_ops, last_reads)
from .infer import (VarInfo, register_shape, infer_program,
                    declared_info)
from .verifier import (verify_program, assert_valid, check_feeds,
                       check_sharding, verify_for_executor,
                       check_feeds_for_executor, enabled, set_enabled,
                       verify_passes_enabled, observe)
from .sanitizer import Snapshot, snapshot, check_pass, run_checked

__all__ = [
    'Diagnostic', 'ProgramInvalid', 'FeedInvalid',
    'PassVerificationError', 'SEVERITIES', 'ERROR', 'WARNING', 'INFO',
    'max_severity', 'errors_of', 'format_diagnostics',
    'analyze_dataflow', 'DataflowResult', 'op_reads', 'op_writes',
    'hidden_reads', 'hidden_writes', 'carrier_defs', 'reachable_ops',
    'last_reads',
    'VarInfo', 'register_shape', 'infer_program', 'declared_info',
    'verify_program', 'assert_valid', 'check_feeds', 'check_sharding',
    'verify_for_executor', 'check_feeds_for_executor', 'enabled',
    'set_enabled', 'verify_passes_enabled', 'observe',
    'Snapshot', 'snapshot', 'check_pass', 'run_checked',
]
