"""Typed diagnostics for the static program verifier (ANALYSIS.md).

A :class:`Diagnostic` is one finding about a Program: where it is (block
index, op index, op type, var names), what it is (a stable ``code``
slug), how bad it is (``severity``), and — for sanitizer findings — the
compiler pass and invariant it violates. :class:`ProgramInvalid` carries
a batch of them as a typed exception, replacing the opaque XLA traceback
a mis-wired program used to die with at trace time.
"""

__all__ = ['Diagnostic', 'ProgramInvalid', 'FeedInvalid',
           'PassVerificationError', 'SEVERITIES', 'ERROR', 'WARNING',
           'INFO', 'max_severity', 'errors_of', 'format_diagnostics']

ERROR = 'error'
WARNING = 'warning'
INFO = 'info'
SEVERITIES = (INFO, WARNING, ERROR)
_RANK = {INFO: 0, WARNING: 1, ERROR: 2}


class Diagnostic(object):
    """One typed finding about a Program."""

    __slots__ = ('code', 'severity', 'message', 'block_idx', 'op_index',
                 'op_type', 'var_names', 'pass_name', 'invariant')

    def __init__(self, code, severity, message, block_idx=0,
                 op_index=None, op_type=None, var_names=(),
                 pass_name=None, invariant=None):
        if severity not in _RANK:
            raise ValueError('severity must be one of %s, got %r'
                             % (SEVERITIES, severity))
        self.code = code
        self.severity = severity
        self.message = message
        self.block_idx = block_idx
        self.op_index = op_index
        self.op_type = op_type
        self.var_names = tuple(var_names)
        self.pass_name = pass_name
        self.invariant = invariant

    @property
    def is_error(self):
        return self.severity == ERROR

    def as_dict(self):
        d = {'code': self.code, 'severity': self.severity,
             'message': self.message, 'block': self.block_idx,
             'op_index': self.op_index, 'op_type': self.op_type,
             'vars': list(self.var_names)}
        if self.pass_name is not None:
            d['pass'] = self.pass_name
        if self.invariant is not None:
            d['invariant'] = self.invariant
        return d

    def location(self):
        loc = 'block %d' % self.block_idx
        if self.op_index is not None:
            loc += ' op #%d' % self.op_index
        if self.op_type:
            loc += ' (%s)' % self.op_type
        return loc

    def render(self):
        head = '%s[%s] %s: %s' % (self.severity, self.code,
                                  self.location(), self.message)
        if self.pass_name:
            head += ' [pass=%s invariant=%s]' % (self.pass_name,
                                                 self.invariant)
        return head

    def __repr__(self):
        return 'Diagnostic(%s)' % self.render()


def max_severity(diagnostics):
    """Highest severity in a batch, or None when empty."""
    top = None
    for d in diagnostics:
        if top is None or _RANK[d.severity] > _RANK[top]:
            top = d.severity
    return top


def errors_of(diagnostics):
    return [d for d in diagnostics if d.severity == ERROR]


def format_diagnostics(diagnostics, limit=10):
    lines = [d.render() for d in diagnostics[:limit]]
    extra = len(diagnostics) - limit
    if extra > 0:
        lines.append('... and %d more' % extra)
    return '\n'.join(lines)


class ProgramInvalid(ValueError):
    """Static verification found error-severity diagnostics.

    Raised from ``Executor.run``'s cache-miss path BEFORE lowering
    (ANALYSIS.md), so a rank-mismatched program names its offending op
    instead of dying inside an XLA trace. ``diagnostics`` holds every
    finding of the failed verify, errors first.
    """

    def __init__(self, diagnostics, message=None):
        diagnostics = sorted(diagnostics, key=lambda d: -_RANK[d.severity])
        self.diagnostics = tuple(diagnostics)
        errs = errors_of(diagnostics)
        if message is None:
            message = ('program verification failed (%d error(s), '
                       '%d diagnostic(s) total):\n%s'
                       % (len(errs), len(diagnostics),
                          format_diagnostics(list(diagnostics))))
        super(ProgramInvalid, self).__init__(message)


class FeedInvalid(ProgramInvalid):
    """A feed value is statically incompatible with the var it feeds
    (rank/dim/dtype-kind mismatch); the diagnostic names the feed slot."""


class PassVerificationError(ProgramInvalid):
    """The pass-pipeline sanitizer caught an invariant violation.

    ``pass_name``/``invariant`` repeat the first error's fields so
    callers (and test asserts) can name the broken pass directly.
    """

    def __init__(self, diagnostics, message=None):
        super(PassVerificationError, self).__init__(diagnostics, message)
        first = next(iter(errors_of(list(diagnostics))), None)
        self.pass_name = getattr(first, 'pass_name', None)
        self.invariant = getattr(first, 'invariant', None)
