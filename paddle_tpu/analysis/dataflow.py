"""Dataflow over the Program IR: def-use chains, use-before-def,
liveness, reachability (ANALYSIS.md "Dataflow model").

The read/write model is shared with lowering and the compiler passes:
``core.lowering._op_reads`` / ``_op_writes`` (sub-block recursive) plus
the compiler's hidden reads (gradient markers' cotangent sources and
sparse-lookup ids) and one hidden WRITE set of our own —
``backward_marker`` defines every ``<param>@GRAD`` name through its
``grads`` attr, with no textual output slot. Any liveness-style
analysis that forgets either half calls live code dead.

Availability semantics mirror the executor environment: a name may be
read if it was written by an earlier op, is persistable (scope state),
is a data var / explicit feed (run-time feed dict), or is the threaded
PRNG key. Sub-blocks (While/IfElse/StaticRNN/DynamicRNN step blocks)
re-run against the enclosing environment, so a name written ANYWHERE in
the sub-block may be read before its textual write (loop-carried state);
the analysis is conservative there and only flags names with no writer
at all.
"""

from .diagnostics import Diagnostic, ERROR, WARNING

__all__ = ['op_reads', 'op_writes', 'hidden_reads', 'hidden_writes',
           'carrier_defs', 'DataflowResult', 'analyze_dataflow',
           'reachable_ops', 'last_reads']


def op_reads(op):
    from ..core.lowering import _op_reads
    return list(_op_reads(op)) + hidden_reads(op)


def op_writes(op):
    from ..core.lowering import _op_writes
    return list(_op_writes(op)) + hidden_writes(op)


def hidden_reads(op):
    from ..compiler.passes import _hidden_reads
    return _hidden_reads(op)


def hidden_writes(op):
    """Names an op defines through ATTRS, invisible to ``_op_writes``:
    ``backward_marker`` plants every ``<param>@GRAD`` via its ``grads``
    attr (backward.py) — downstream clip/regularizer/update ops read
    them with no textual producer."""
    if op.type == 'backward_marker':
        return [n for n in (op.attrs.get('grads') or ()) if n]
    return []


def _has_sub_block(op):
    from ..framework import Block
    return any(isinstance(v, Block) for v in op.attrs.values())


def carrier_defs(op):
    """Sub-block-local names a control-flow CARRIER op materializes at
    block entry, declared only through attrs (layers/control_flow.py):
    StaticRNN provides per-step input slices and pre-memories;
    DynamicRNN additionally threads static inputs. Ops inside the
    sub-block read these with no textual producer."""
    names = []
    if op.type in ('static_rnn', 'dynamic_rnn'):
        names.extend(op.attrs.get('step_inputs') or ())
    if op.type == 'static_rnn':
        names.extend(op.attrs.get('pre_mems') or ())
    elif op.type == 'dynamic_rnn':
        names.extend(op.attrs.get('static_inside') or ())
        names.extend(mi.get('pre') for mi in
                     (op.attrs.get('mem_info') or ()) if mi.get('pre'))
    return names


class DataflowResult(object):
    """Def-use facts for one Program (global block resolution).

    ``defs``/``uses``: name -> ordered list of (block_idx, op_index,
    op_type) sites. ``undefined_reads``: (name, site) pairs that no
    availability source covers. ``unused_defs``: names written but
    never read nor fetched (informational).
    """

    __slots__ = ('defs', 'uses', 'undefined_reads', 'unused_defs',
                 'num_ops', 'available')

    def __init__(self):
        self.defs = {}
        self.uses = {}
        self.undefined_reads = []
        self.unused_defs = []
        self.num_ops = 0
        self.available = frozenset()


def _initial_available(program, feeds=()):
    from ..core.lowering import RNG_KEY
    avail = {RNG_KEY}
    avail.update(feeds or ())
    for b in program.blocks:
        for v in b.vars.values():
            if v.persistable or v.is_data:
                avail.add(v.name)
    return avail


def analyze_dataflow(program, feeds=(), protected=()):
    """Walk the program once; return ``(DataflowResult, [Diagnostic])``.

    Use-before-def in the GLOBAL block is an error (the lowering would
    KeyError or trace garbage); inside sub-blocks the conservative
    loop-carried rule applies and any residue is still an error — a
    name with no writer anywhere cannot come from a previous
    iteration either.
    """
    res = DataflowResult()
    diags = []
    avail = set(_initial_available(program, feeds))
    res.available = frozenset(avail)
    block = program.global_block()
    read_ever = set(protected or ())

    def _site(bidx, i, op):
        return (bidx, i, op.type)

    def _record(table, name, site):
        table.setdefault(name, []).append(site)

    def _walk(blk, bidx, avail, depth):
        for i, op in enumerate(blk.ops):
            res.num_ops += 1
            direct_reads = list(op.input_arg_names) + hidden_reads(op)
            for nm in direct_reads:
                _record(res.uses, nm, _site(bidx, i, op))
                read_ever.add(nm)
            missing = [nm for nm in dict.fromkeys(direct_reads)
                       if nm not in avail]
            if missing:
                diags.append(Diagnostic(
                    'use-before-def', ERROR,
                    "op reads %s before any definition (no earlier "
                    "writer, not persistable state, not a data/feed "
                    "var)" % ', '.join(repr(n) for n in missing),
                    block_idx=bidx, op_index=i, op_type=op.type,
                    var_names=missing))
                res.undefined_reads.extend((nm, _site(bidx, i, op))
                                           for nm in missing)
            if _has_sub_block(op):
                from ..framework import Block as _B
                for sub in op.attrs.values():
                    if not isinstance(sub, _B):
                        continue
                    # loop-carried conservative availability: anything
                    # the sub-block (or this one, for nested) writes is
                    # available from iteration 2 onward — plus what the
                    # op itself will have read in (its inputs)
                    sub_avail = set(avail)
                    sub_avail.update(op_writes(op))
                    sub_avail.update(carrier_defs(op))
                    _walk(sub, sub.idx, sub_avail, depth + 1)
                    for sop in sub.ops:
                        for nm in sop.input_arg_names + hidden_reads(sop):
                            read_ever.add(nm)
            writes = list(op.output_arg_names) + hidden_writes(op)
            if _has_sub_block(op):
                writes = op_writes(op)
            for nm in writes:
                _record(res.defs, nm, _site(bidx, i, op))
            avail.update(writes)

    _walk(block, 0, avail, 0)

    from ..core.lowering import RNG_KEY
    for nm, sites in res.defs.items():
        if nm in read_ever or nm == RNG_KEY:
            continue
        var = block._find_var_recursive(nm)
        if var is not None and var.persistable:
            continue  # state writes are externally observable
        res.unused_defs.append(nm)
    return res, diags


def reachable_ops(block, targets):
    """Indices of global-block ops whose outputs (transitively) feed any
    of ``targets`` — backward reachability over names, the static twin
    of ``Program.prune``."""
    need = set(targets)
    keep = set()
    for i in reversed(range(len(block.ops))):
        op = block.ops[i]
        if any(nm in need for nm in op_writes(op)):
            keep.add(i)
            need.update(op_reads(op))
    return keep


def last_reads(block):
    """name -> index of its LAST reader in the block (hidden reads
    included) — the fact ``buffer_reuse`` annotations must agree with."""
    last = {}
    for i, op in enumerate(block.ops):
        for nm in list(op.input_arg_names) + hidden_reads(op):
            last[nm] = i
        if _has_sub_block(op):
            for nm in op_reads(op):
                last[nm] = i
    return last
