"""Disaggregated prefill/decode: prompt ingestion as a placement
problem.

A :class:`DisaggregatedDecoder` is the client-side join of the two
halves: it routes each prompt through the fleet
:class:`~paddle_tpu.fleet.router.Router` to whatever ``role='prefill'``
replica placement chose (in-process :class:`~paddle_tpu.kvcache.
prefill.PrefillServer` or a remote one behind ``spawn_cell(
kind='prefill')``), then admits the returned KV pages into its LOCAL
paged :class:`~paddle_tpu.fleet.decode.DecodeEngine` via
``submit(init_pages=..., pos0=..., first_id=...)``. The decode batch
never stalls on a long prompt, and the Router's requeue/failover
machinery covers the prefill leg for free (a killed prefill replica
surfaces ``ServerClosed`` — REQUEUEABLE — and the prompt re-runs
elsewhere; prefill is stateless between prompts so replay is safe).

Tracing (PR 13): each request opens a root ``kvcache/request`` span
whose context parents BOTH legs — the Router's ``fleet/request`` (and
under it the replica-side ``kvcache/prefill``, across the process
boundary) and the decode engine's ``decode/request`` — plus a
``kvcache/transfer`` span for the page handoff itself. One tree spans
the hop; ``obs_report --require kvcache`` checks it.
"""
import time

import numpy as np

from .. import observability as _obs
from ..serving.errors import DeadlineExceeded
from .prefill import make_paged_engine

__all__ = ['DisaggregatedDecoder', 'DisaggRequest']


class DisaggRequest(object):
    """Handle for one in-flight disaggregated request: the prefill leg
    is in the Router's hands, the decode leg starts when its pages
    land."""

    __slots__ = ('_decoder', '_routed', '_mnt', '_span', '_value',
                 '_error')

    def __init__(self, decoder, routed, max_new_tokens, span):
        self._decoder = decoder
        self._routed = routed
        self._mnt = max_new_tokens
        self._span = span
        self._value = None
        self._error = None

    def result(self, timeout=60.0):
        """Block for the full token sequence (prompt continuation,
        ``max_new_tokens`` long, first token from the prefill leg)."""
        if self._value is not None:
            return self._value
        if self._error is not None:
            raise self._error
        deadline = time.monotonic() + timeout
        try:
            payload = self._routed.result(timeout=timeout)
            t_hop = time.monotonic()
            tokens = [payload['next_id']]
            if self._mnt > 1:
                req = self._decoder.engine.submit(
                    init_states=payload['states'],
                    init_pages=payload['pages'],
                    pos0=payload['pos0'],
                    first_id=payload['next_id'],
                    max_new_tokens=self._mnt - 1,
                    trace=self._span.context)
                _obs.emit_span(
                    'kvcache/transfer', time.monotonic() - t_hop,
                    parent=self._span,
                    pages=sum(len(v) for v in payload['pages'].values()),
                    pos0=payload['pos0'])
                left = deadline - time.monotonic()
                if left <= 0:
                    raise DeadlineExceeded(
                        'prefill consumed the whole %.1fs budget'
                        % timeout)
                tokens.extend(int(t) for t in req.result(timeout=left))
        except Exception as e:
            self._error = e
            self._span.end(error=type(e).__name__)
            raise
        self._value = np.asarray(tokens, dtype=np.int64)
        self._span.end(ok=True, tokens=len(tokens),
                       prompt_len=payload['prompt_len'])
        return self._value


class DisaggregatedDecoder(object):
    """Routes prompts to ``role='prefill'`` replicas, decodes the
    returned pages locally.

    Parameters
    ----------
    router : :class:`~paddle_tpu.fleet.router.Router`
        Must already have the prefill model registered
        (``router.register_prefill(model, spec, ...)``) on replicas
        whose cells carry ``role='prefill'``.
    model : str
        The registered prefill model name.
    spec : dict
        The SAME declarative spec dict (:func:`~paddle_tpu.kvcache.
        prefill.stock_spec`) the prefill side was registered with —
        same spec + same seed means both sides build identical
        parameters, which is what makes the handoff exact.
    """

    def __init__(self, router, model, spec, slots=8, num_pages=None,
                 end_id=None, place=None, partitioner=None):
        self.router = router
        self.model = model
        self.spec = dict(spec)
        self.engine, self.pool = make_paged_engine(
            spec, slots=slots, num_pages=num_pages, end_id=end_id,
            place=place, partitioner=partitioner)

    def submit(self, prompt_ids, max_new_tokens, deadline=None):
        """Dispatch the prefill leg; returns a :class:`DisaggRequest`
        whose ``result()`` runs the decode leg once pages arrive."""
        prompt = np.asarray(prompt_ids, dtype=np.int64).reshape(-1)
        mnt = int(max_new_tokens)
        if mnt < 1:
            raise ValueError('max_new_tokens must be >= 1')
        if len(prompt) + mnt - 1 > self.spec['max_len']:
            raise ValueError(
                'prompt (%d) + max_new_tokens (%d) - 1 exceeds '
                'max_len %d' % (len(prompt), mnt,
                                self.spec['max_len']))
        span = _obs.start_span('kvcache/request', activate=False,
                               model=self.model,
                               prompt_len=len(prompt),
                               max_new_tokens=mnt)
        try:
            routed = self.router.submit(self.model,
                                        {'prompt_ids': prompt},
                                        deadline=deadline,
                                        trace=span.context)
        except Exception as e:
            span.end(error=type(e).__name__)
            raise
        return DisaggRequest(self, routed, mnt, span)

    def decode(self, prompt_ids, max_new_tokens, deadline=None,
               timeout=60.0):
        """Synchronous convenience: ``submit(...).result(...)``."""
        return self.submit(prompt_ids, max_new_tokens,
                           deadline=deadline).result(timeout=timeout)

    def close(self, drain=True, timeout=60.0):
        self.engine.close(drain=drain, timeout=timeout)
