"""paddle_tpu.kvcache — paged KV-cache + disaggregated prefill
(SERVING.md "Paged KV-cache & disaggregated prefill").

- :mod:`~paddle_tpu.kvcache.pool` — :class:`PagePool`: a fixed pool of
  ``[page_size, ...]`` KV blocks behind a free-list allocator (typed
  :class:`PoolExhausted`), plus per-sequence :class:`BlockTable`s.
  Admission becomes "allocate pages", so resident KV bytes track
  actual sequence lengths and sequences-resident decouples from the
  compiled batch dim.
- :mod:`~paddle_tpu.kvcache.paged` —
  :func:`paged_attention_cell`: the PR 9 slotted
  ``attention_history_cell`` re-expressed over pool pages (gather by
  block table + position mask), bit-identical outputs.
- :mod:`~paddle_tpu.kvcache.prefill` — :class:`PrefillEngine` (prompt
  ingestion producing KV pages + carry state) and
  :class:`PrefillServer` (the replica-cell surface, so the fleet
  Router places prompt ingestion on dedicated ``role='prefill'``
  replicas — in-process or behind ``multihost.remote.spawn_cell``).
- :mod:`~paddle_tpu.kvcache.disagg` — :class:`DisaggregatedDecoder`:
  routes prompts to prefill replicas through the Router, streams the
  finished pages into a local paged
  :class:`~paddle_tpu.fleet.decode.DecodeEngine`, one trace tree
  spanning the hop.
"""
from .pool import BlockTable, PagePool, PoolExhausted  # noqa
from .paged import paged_attention_cell  # noqa
from .prefill import (PrefillEngine, PrefillServer,  # noqa
                      build_cell, make_paged_engine, stock_spec)
from .disagg import DisaggregatedDecoder  # noqa

__all__ = [
    'PagePool', 'BlockTable', 'PoolExhausted',
    'paged_attention_cell',
    'PrefillEngine', 'PrefillServer', 'build_cell',
    'make_paged_engine', 'stock_spec',
    'DisaggregatedDecoder',
]
