"""Paged KV-cache storage: a fixed pool of pages + per-sequence block
tables.

The PR 9 :class:`~paddle_tpu.fleet.decode.DecodeEngine` gives every
slot a dense ``[max_len, ...]`` KV region: HBM is committed for the
WORST CASE length of every resident sequence, so short sequences
strand memory and the number of sequences resident is welded to the
compiled slot count. The :class:`PagePool` breaks that weld:

- KV state lives in ONE pool tensor per spec, shaped
  ``[num_pages, page_size, ...]`` — a fixed operand of the compiled
  step program (the compiled shape never changes as sequences come
  and go);
- a sequence owns an ordered list of page ids (its
  :class:`BlockTable`); admission is "allocate
  ``ceil(len / page_size)`` pages from the free list", retirement
  returns them — so resident KV bytes track ACTUAL lengths, not
  ``slots * max_len``;
- exhaustion is a typed :class:`PoolExhausted` the admission path
  turns into backpressure (the request waits for pages, it is never
  dropped untyped).

The pool is host-side numpy (the step program feeds and fetches the
pool tensors like any other decode state); pages are zeroed on
``alloc`` so the paged attention cell's additive writes see the same
all-zeros initial state a freshly admitted dense slot does — that is
what makes paged decode bit-identical to the slotted cell
(``tests/test_kvcache.py``).

Telemetry (OBSERVABILITY.md): ``kvcache_pool_used_pages`` /
``kvcache_pool_free_pages`` gauges and ``kvcache`` journal events for
every alloc/free/backpressure transition.
"""
import threading

import numpy as np

from .. import observability as _obs
from ..serving.errors import ServingError

__all__ = ['PagePool', 'BlockTable', 'PoolExhausted']


class PoolExhausted(ServingError):
    """The free list cannot satisfy an allocation. ``needed`` /
    ``free`` / ``num_pages`` let the admission path distinguish
    transient pressure (backpressure: wait for retirements) from a
    request that can NEVER fit (``needed > num_pages``: reject)."""

    def __init__(self, message, needed=None, free=None, num_pages=None):
        super(PoolExhausted, self).__init__(message)
        self.needed = needed
        self.free = free
        self.num_pages = num_pages


class BlockTable(object):
    """One sequence's ordered page list: logical position ``p`` lives
    in pool page ``pages[p // page_size]`` at offset
    ``p % page_size``."""

    __slots__ = ('pages', 'page_size')

    def __init__(self, pages, page_size):
        self.pages = list(pages)
        self.page_size = int(page_size)

    def __len__(self):
        return len(self.pages)

    def capacity(self):
        return len(self.pages) * self.page_size

    def page_for(self, pos):
        return self.pages[pos // self.page_size]

    def offset(self, pos):
        return pos % self.page_size

    def row(self, max_pages, pad=0):
        """The int64 feed row for the step program's gather: page ids
        padded to the compiled ``max_pages`` extent. Padding entries
        are gathered too, but the position mask zeroes their attention
        weight exactly (-1e9 before the softmax underflows to 0.0 in
        f32), so any valid page id works as padding."""
        if len(self.pages) > max_pages:
            raise ValueError('block table holds %d pages, program '
                             'compiled for %d' % (len(self.pages),
                                                  max_pages))
        out = np.full((max_pages,), pad, dtype=np.int64)
        out[:len(self.pages)] = self.pages
        return out


class PagePool(object):
    """Fixed pool of KV pages behind a free-list allocator.

    Parameters
    ----------
    specs : sequence of (name, feature_shape[, dtype]) tuples
        One pool tensor per spec, shaped
        ``[num_pages, page_size] + feature_shape`` — e.g.
        ``[('kv', [word_dim])]`` for an attention cell whose per-token
        KV entry is a ``word_dim`` vector.
    num_pages : int
        Pool extent — the compiled page axis. Total KV capacity is
        ``num_pages * page_size`` token positions.
    page_size : int
        Token positions per page (the allocation granule).
    """

    def __init__(self, specs, num_pages, page_size):
        if num_pages < 1 or page_size < 1:
            raise ValueError('num_pages and page_size must be >= 1')
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.specs = []
        for spec in specs:
            name, shape = spec[0], tuple(int(d) for d in spec[1])
            dtype = spec[2] if len(spec) > 2 else 'float32'
            self.specs.append((name, shape, dtype))
        if not self.specs:
            raise ValueError('a PagePool needs at least one spec')
        self.data = {
            name: np.zeros((self.num_pages, self.page_size) + shape,
                           dtype=dtype)
            for name, shape, dtype in self.specs}
        self._lock = threading.Lock()
        self._free = list(range(self.num_pages))   # FIFO: pop(0)
        self._allocs = 0
        self._frees = 0
        self._peak_used = 0
        reg = _obs.default_registry()
        self._g_used = reg.gauge(
            'kvcache_pool_used_pages',
            'KV pages currently allocated to resident sequences')
        self._g_free = reg.gauge(
            'kvcache_pool_free_pages',
            'KV pages on the pool free list')
        self._publish_locked()

    # ---- geometry --------------------------------------------------------
    @property
    def page_bytes(self):
        """Bytes one page occupies across every spec tensor."""
        return sum(self.data[name][0].nbytes
                   for name, _, _ in self.specs)

    @property
    def nbytes(self):
        """Total pool bytes — what :class:`~paddle_tpu.fleet.router.
        PlacementBudget` folds into the replica's hbm axis
        (``kv_bytes=pool.nbytes``)."""
        return sum(arr.nbytes for arr in self.data.values())

    def pages_for(self, length):
        """Pages a sequence of ``length`` token positions needs."""
        return -(-int(length) // self.page_size)

    # ---- allocator -------------------------------------------------------
    def alloc(self, n, zero=True):
        """Take ``n`` pages off the free list (FIFO — the oldest freed
        page is reused first, pinned by tests) and zero them; raises
        typed :class:`PoolExhausted` without taking any on shortfall
        (all-or-nothing, so backpressure never strands a partial
        grab)."""
        n = int(n)
        if n < 1:
            raise ValueError('alloc needs n >= 1')
        with self._lock:
            if n > len(self._free):
                free = len(self._free)
                raise PoolExhausted(
                    'pool exhausted: need %d page(s), %d free of %d'
                    % (n, free, self.num_pages), needed=n, free=free,
                    num_pages=self.num_pages)
            pages, self._free = self._free[:n], self._free[n:]
            self._allocs += 1
            used = self.num_pages - len(self._free)
            self._peak_used = max(self._peak_used, used)
            self._publish_locked()
        if zero:
            for name, _, _ in self.specs:
                self.data[name][pages] = 0
        _obs.emit('kvcache', action='alloc', pages=len(pages),
                  used=used, free=self.num_pages - used)
        return pages

    def free(self, pages):
        """Return pages to the free list (their contents are garbage
        until the next ``alloc`` zeroes them)."""
        pages = list(pages)
        if not pages:
            return
        with self._lock:
            live = set(self._free)
            for p in pages:
                if not 0 <= p < self.num_pages:
                    raise ValueError('page id %r outside pool [0, %d)'
                                     % (p, self.num_pages))
                if p in live:
                    raise ValueError('double free of page %d' % p)
            self._free.extend(pages)
            self._frees += 1
            used = self.num_pages - len(self._free)
            self._publish_locked()
        _obs.emit('kvcache', action='free', pages=len(pages),
                  used=used, free=self.num_pages - used)

    def reset(self):
        """Reclaim every page (the prefill engine recycles its private
        pool between prompts)."""
        with self._lock:
            self._free = list(range(self.num_pages))
            self._publish_locked()

    # ---- introspection ---------------------------------------------------
    def _publish_locked(self):
        used = self.num_pages - len(self._free)
        self._g_used.set(used)
        self._g_free.set(len(self._free))

    @property
    def free_pages(self):
        with self._lock:
            return len(self._free)

    @property
    def used_pages(self):
        with self._lock:
            return self.num_pages - len(self._free)

    def stats(self):
        with self._lock:
            used = self.num_pages - len(self._free)
            return {'num_pages': self.num_pages,
                    'page_size': self.page_size,
                    'used_pages': used,
                    'free_pages': len(self._free),
                    'peak_used_pages': self._peak_used,
                    'allocs': self._allocs,
                    'frees': self._frees,
                    'nbytes': self.nbytes}
