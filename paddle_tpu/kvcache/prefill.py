"""Prefill: prompt ingestion producing KV pages, as a fleet replica
role.

Long prompts are the continuous-batching engine's enemy: ingesting a
K-token prompt inline would hold a decode slot for K steps producing
nothing, stalling the running batch. Disaggregation moves that work to
DEDICATED replicas (SERVING.md "Paged KV-cache & disaggregated
prefill"): a :class:`PrefillEngine` teacher-forces the SAME paged step
program over the prompt tokens — writing KV pages exactly as the
decode engine would have — and hands back the pages, the carry state
(mask/h), the prefix length and the first generated token. A decode
replica admits that payload with
``DecodeEngine.submit(init_pages=..., pos0=..., first_id=...)`` and
continues mid-stream, bit-identically to having ingested the prompt
itself (``tests/test_kvcache.py``).

Cells are built from a **declarative spec dict** (``stock_spec``) —
plain picklable data, so the fleet Router can replay a
``register_prefill`` placement onto restarted replicas and ship it to
a remote prefill process (``multihost.remote.spawn_cell(
kind='prefill')``) over the cell protocol. Both sides of the hop build
their cell from the same spec, so the seeded parameter init is
identical and the handoff is exact.

:class:`PrefillServer` wraps the engine in the replica-cell surface
the Router already speaks (``submit``/``health``/``load_score``/
``drain``/``close``...), sets ``role='prefill'`` so role-aware
placement pins prompt ingestion to prefill replicas, and fails
in-flight work typed ``ServerClosed`` on death — the REQUEUEABLE
error fleet requeue fails over on.
"""
import collections
import threading
import time

import numpy as np

from .. import layers
from .. import observability as _obs
from .. import unique_name
from ..core import places as _places
from ..executor import Executor, Scope
from ..framework import Program, program_guard
from ..serving.errors import (DeadlineExceeded, ModelNotFound,
                              ServerClosed, ServingError)
from .paged import paged_attention_cell
from .pool import PagePool

__all__ = ['PrefillEngine', 'PrefillServer', 'build_cell',
           'make_paged_engine', 'stock_spec', 'CELLS']

# declarative cell registry: specs name a builder here instead of
# carrying a callable, so placements pickle across the remote-cell
# protocol and replay byte-identically on replica restart
CELLS = {'paged_attention': paged_attention_cell}

_CELL_KEYS = ('dict_size', 'word_dim', 'hidden', 'max_len',
              'page_size', 'num_pages')


def stock_spec(dict_size, word_dim=32, hidden=32, max_len=64,
               page_size=8, num_pages=32, seed=0):
    """The spec dict for the stock paged attention cell."""
    return {'cell': 'paged_attention', 'dict_size': int(dict_size),
            'word_dim': int(word_dim), 'hidden': int(hidden),
            'max_len': int(max_len), 'page_size': int(page_size),
            'num_pages': int(num_pages), 'seed': int(seed)}


def build_cell(spec, num_pages=None):
    """``(cell_fn, state_specs, pool_specs)`` from a spec dict.
    ``num_pages`` overrides the spec's pool extent (the prefill side
    sizes its private pool for one prompt; the decode side for the
    whole resident set — page CONTENT transfers, page ids are
    local)."""
    kind = spec.get('cell')
    if kind not in CELLS:
        raise ValueError('unknown cell %r (have: %s)'
                         % (kind, sorted(CELLS)))
    kwargs = {k: spec[k] for k in _CELL_KEYS if k in spec}
    if num_pages is not None:
        kwargs['num_pages'] = int(num_pages)
    return CELLS[kind](**kwargs)


def make_paged_engine(spec, slots=8, end_id=None, place=None,
                      partitioner=None, num_pages=None):
    """Build the decode side of the hop from the SAME spec the prefill
    replicas were registered with: ``(DecodeEngine, PagePool)``. Same
    spec + same seed -> identical parameters on both sides, which is
    what makes the prefill->decode handoff exact."""
    from ..fleet.decode import DecodeEngine
    n_pages = int(num_pages if num_pages is not None
                  else spec.get('num_pages', 32))
    cell, state_specs, pool_specs = build_cell(spec,
                                               num_pages=n_pages)
    pool = PagePool(pool_specs, num_pages=n_pages,
                    page_size=spec['page_size'])
    engine = DecodeEngine(cell, state_specs, slots=slots,
                          max_len=spec['max_len'], end_id=end_id,
                          place=place, partitioner=partitioner,
                          seed=spec.get('seed', 0), admission='paged',
                          page_pool=pool)
    return engine, pool


class PrefillEngine(object):
    """Single-lane teacher-forced runner of the paged step program.

    One prompt at a time: positions ``0..k-1`` are fed the prompt
    tokens (not the argmax), writing each token's KV into this
    engine's PRIVATE page pool (``max_len / page_size`` pages — one
    max-length prompt, recycled per call). The last step's argmax is
    the first generated token, returned so the decode side emits it
    without re-running the step.
    """

    def __init__(self, spec, place=None):
        self.spec = dict(spec)
        self.max_len = int(spec['max_len'])
        self.page_size = int(spec['page_size'])
        if self.max_len % self.page_size != 0:
            raise ValueError('max_len must be a multiple of page_size')
        self.max_pages = self.max_len // self.page_size
        cell, state_specs, pool_specs = build_cell(
            spec, num_pages=self.max_pages)
        self.pool = PagePool(pool_specs, num_pages=self.max_pages,
                             page_size=self.page_size)
        self.specs = []
        for s in state_specs:
            name, shape = s[0], tuple(int(d) for d in s[1])
            dtype = s[2] if len(s) > 2 else 'float32'
            self.specs.append((name, shape, dtype))
        self.place = place or _places.CPUPlace()
        self.executor = Executor(self.place)
        self.scope = Scope()
        self._build(cell, spec.get('seed', 0))

    def _build(self, cell_fn, seed):
        self._main, self._startup = Program(), Program()
        self._startup.random_seed = seed
        with program_guard(self._main, self._startup):
            with unique_name.guard():
                ids = layers.data(name='dec_ids', shape=[1],
                                  dtype='int64')
                pos = layers.data(name='dec_pos', shape=[1],
                                  dtype='int64')
                states = {name: layers.data(name='dec_state_%s' % name,
                                            shape=list(shape),
                                            dtype=dtype)
                          for name, shape, dtype in self.specs}
                pools = {name: layers.data(
                    name='kv_pool_%s' % name,
                    shape=[self.pool.num_pages,
                           self.pool.page_size] + list(shape),
                    dtype=dtype, append_batch_size=False)
                    for name, shape, dtype in self.pool.specs}
                table = layers.data(name='kv_table',
                                    shape=[self.max_pages],
                                    dtype='int64')
                page = layers.data(name='kv_page', shape=[1],
                                   dtype='int64')
                off = layers.data(name='kv_off', shape=[1],
                                  dtype='int64')
                probs, new_states, new_pools = cell_fn(
                    ids, states, pos, pools, table, page, off)
                _, next_ids = layers.topk(probs, k=1)
        self._fetch = [next_ids] + \
            [new_states[n] for n, _, _ in self.specs] + \
            [new_pools[n] for n, _, _ in self.pool.specs]
        self.executor.run(self._startup, scope=self.scope)

    def prefill(self, prompt_ids, trace=None):
        """Ingest one prompt; returns the handoff payload::

            {'pages':  {pool spec name: [page arrays]},
             'states': {state name: per-slot array},
             'pos0':   prompt length,
             'next_id': first generated token (last step's argmax),
             'prompt_len': prompt length}

        ``trace`` parents the ``kvcache/prefill`` span (the hop stays
        one tree across processes — the context pickles through the
        remote-cell protocol)."""
        prompt = np.asarray(prompt_ids, dtype=np.int64).reshape(-1)
        k = len(prompt)
        if not 1 <= k <= self.max_len:
            raise ValueError('prompt length must be in [1, %d], got %d'
                             % (self.max_len, k))
        span = _obs.start_span('kvcache/prefill', parent=trace,
                               activate=False, prompt_len=k)
        t0 = time.monotonic()
        try:
            self.pool.reset()
            pages = self.pool.alloc(self.pool.pages_for(k))
            table = np.zeros((1, self.max_pages), dtype=np.int64)
            table[0, :len(pages)] = pages
            states = {name: np.zeros((1,) + shape, dtype=dtype)
                      for name, shape, dtype in self.specs}
            ids = np.zeros((1, 1), dtype=np.int64)
            pos = np.zeros((1, 1), dtype=np.int64)
            page = np.zeros((1, 1), dtype=np.int64)
            off = np.zeros((1, 1), dtype=np.int64)
            next_id = None
            for t in range(k):
                ids[0, 0] = prompt[t]
                pos[0, 0] = t
                page[0, 0] = pages[t // self.page_size]
                off[0, 0] = t % self.page_size
                feed = {'dec_ids': ids, 'dec_pos': pos,
                        'kv_table': table, 'kv_page': page,
                        'kv_off': off}
                for name, _, _ in self.specs:
                    feed['dec_state_%s' % name] = states[name]
                for name, _, _ in self.pool.specs:
                    feed['kv_pool_%s' % name] = self.pool.data[name]
                outs = self.executor.run(self._main, feed=feed,
                                         fetch_list=self._fetch,
                                         scope=self.scope)
                next_id = int(np.asarray(outs[0]).reshape(-1)[0])
                for (name, _, _), out in zip(
                        self.specs, outs[1:1 + len(self.specs)]):
                    states[name] = np.array(out)
                for (name, _, _), out in zip(
                        self.pool.specs, outs[1 + len(self.specs):]):
                    self.pool.data[name] = np.array(out)
            payload = {
                'pages': {name: [self.pool.data[name][p].copy()
                                 for p in pages]
                          for name, _, _ in self.pool.specs},
                'states': {name: states[name][0].copy()
                           for name, _, _ in self.specs},
                'pos0': k, 'next_id': next_id, 'prompt_len': k,
            }
        except Exception as e:
            span.end(error=type(e).__name__)
            raise
        span.end(ok=True, pages=len(pages))
        _obs.emit('kvcache', action='prefill', prompt_len=k,
                  pages=len(pages),
                  dur_s=round(time.monotonic() - t0, 6))
        return payload


class _PrefillRequest(object):
    __slots__ = ('model', 'prompt', 'trace', 'deadline_abs', '_event',
                 '_value', '_error')

    def __init__(self, model, prompt, trace, deadline_abs):
        self.model = model
        self.prompt = prompt
        self.trace = trace
        self.deadline_abs = deadline_abs
        self._event = threading.Event()
        self._value = None
        self._error = None

    def done(self):
        return self._event.is_set()

    def _complete(self, ok, value):
        if ok:
            self._value = value
        else:
            self._error = value
        self._event.set()

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise DeadlineExceeded(
                'prefill result not ready within %ss' % timeout)
        if self._error is not None:
            raise self._error
        return self._value


class PrefillServer(object):
    """The replica-cell surface over :class:`PrefillEngine`\\ s.

    Looks to the :class:`~paddle_tpu.fleet.router.Router` exactly like
    a ModelServer (same ``submit``/``health``/``load_score``/... and
    error taxonomy) but ``role='prefill'``, so role-aware placement
    pins prompt-ingestion models here. Feeds are
    ``{'prompt_ids': <1-D int array>}``; the future resolves to the
    :meth:`PrefillEngine.prefill` payload, which the decode side
    admits via ``DecodeEngine.submit(init_pages=...)``.
    """

    role = 'prefill'

    def __init__(self, place=None):
        self.place = place
        self._engines = {}
        self._queue = collections.deque()
        self._draining = set()
        self._cond = threading.Condition()
        self._closed = False
        self._worker = threading.Thread(target=self._loop,
                                        name='prefill-server',
                                        daemon=True)
        self._worker.start()

    # ---- placement surface ----------------------------------------------
    def register_prefill(self, name, spec):
        """Build the engine for ``name`` from a declarative spec dict
        (:func:`stock_spec`) — data, not code, so the Router's restart
        replay and the remote-cell protocol both carry it."""
        engine = PrefillEngine(spec, place=self.place)
        with self._cond:
            if self._closed:
                raise ServerClosed('prefill server is shut down')
            self._engines[name] = engine
            self._draining.discard(name)

    def models(self):
        with self._cond:
            return sorted(self._engines)

    def warmup(self, model_name=None, upto=None, timeout=300.0):
        """Compile the step program ahead of traffic (one throwaway
        single-token prefill per engine)."""
        with self._cond:
            names = [model_name] if model_name is not None \
                else sorted(self._engines)
            engines = [self._engines[n] for n in names
                       if n in self._engines]
        for engine in engines:
            engine.prefill([1])
        return len(engines)

    # ---- request surface -------------------------------------------------
    def submit(self, name, feeds, deadline=None, trace=None, **kwargs):
        with self._cond:
            if self._closed:
                raise ServerClosed('prefill server is shut down')
            if name not in self._engines or name in self._draining:
                raise ModelNotFound(
                    'no prefill model registered as %r (have: %s)'
                    % (name, sorted(self._engines) or '-'))
            prompt = feeds.get('prompt_ids') if isinstance(feeds, dict) \
                else None
            if prompt is None:
                raise ServingError(
                    "prefill feeds must carry 'prompt_ids'")
            req = _PrefillRequest(
                name, np.asarray(prompt, dtype=np.int64), trace,
                None if deadline is None
                else time.monotonic() + deadline)
            self._queue.append(req)
            self._cond.notify_all()
        return req

    def infer(self, name, feeds, deadline=None, timeout=30.0):
        return self.submit(name, feeds,
                           deadline=deadline).result(timeout=timeout)

    def _loop(self):
        while True:
            with self._cond:
                while not self._closed and not self._queue:
                    self._cond.wait(0.05)
                if self._closed and not self._queue:
                    return
                req = self._queue.popleft()
                engine = self._engines.get(req.model)
            if engine is None:
                req._complete(False, ModelNotFound(
                    'prefill model %r was drained' % req.model))
                continue
            if req.deadline_abs is not None and \
                    time.monotonic() > req.deadline_abs:
                req._complete(False, DeadlineExceeded(
                    'prefill deadline passed before the prompt ran'))
                continue
            try:
                req._complete(True, engine.prefill(req.prompt,
                                                   trace=req.trace))
            except Exception as e:  # noqa: BLE001 — forwarded typed
                err = e if isinstance(e, ServingError) else \
                    ServingError('prefill failed: %r' % (e,))
                req._complete(False, err)

    # ---- health surface the Router/supervisor polls ----------------------
    def queue_depth(self, model_name):
        with self._cond:
            if model_name not in self._engines:
                raise ModelNotFound('no prefill model %r' % model_name)
            return sum(1 for r in self._queue
                       if r.model == model_name)

    def load_score(self, model_name=None):
        with self._cond:
            if self._closed:
                return float('inf')
            if not self._worker.is_alive():
                return float('inf')
            if model_name is not None and (
                    model_name not in self._engines or
                    model_name in self._draining):
                return float('inf')
            return float(len(self._queue))

    def health(self):
        with self._cond:
            closed = self._closed
            alive = self._worker.is_alive()
            models = {}
            for name in self._engines:
                depth = sum(1 for r in self._queue
                            if r.model == name)
                models[name] = {
                    'state': 'draining' if name in self._draining
                    else 'ready',
                    'breaker': 'closed',
                    'queue_depth': depth,
                    'worker_alive': alive,
                    'wedged': False,
                    'watchdog_trips': 0,
                }
        return {'status': 'closed' if closed else 'serving',
                'models': models}

    def pause(self, model_name=None):
        return None

    def resume(self, model_name=None):
        return None

    def drain(self, name, timeout=None):
        """Complete the model's queued prompts, then unregister it."""
        with self._cond:
            if name not in self._engines:
                raise ModelNotFound('no prefill model %r' % name)
            self._draining.add(name)
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        while True:
            with self._cond:
                left = sum(1 for r in self._queue if r.model == name)
                if left == 0:
                    self._engines.pop(name, None)
                    self._draining.discard(name)
                    return
            if deadline is not None and time.monotonic() > deadline:
                raise DeadlineExceeded(
                    'prefill drain of %r timed out with %d queued'
                    % (name, left))
            time.sleep(0.01)

    def unload_model(self, name, timeout=None):
        return self.drain(name, timeout=timeout)

    def close(self, timeout=30.0):
        with self._cond:
            if self._closed:
                return
            self._closed = True
            failed = list(self._queue)
            self._queue.clear()
            self._cond.notify_all()
        for req in failed:
            req._complete(False, ServerClosed(
                'prefill server closed before the prompt ran'))
        self._worker.join(timeout)
