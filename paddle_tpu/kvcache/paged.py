"""Paged attention cell: the PR 9 slotted ``attention_history_cell``
with its per-slot dense KV replaced by :class:`~paddle_tpu.kvcache.
pool.PagePool` pages — bit-identical outputs, pooled memory.

Layout. The slotted cell carries ``kv [max_len, word_dim]`` PER SLOT;
here the same rows live scattered across a shared pool tensor
``[num_pages, page_size, word_dim]`` and each slot's
:class:`~paddle_tpu.kvcache.pool.BlockTable` names which pages hold
its positions. The step program takes three extra per-slot feeds the
engine derives host-side from each slot's position and table —
``kv_table [max_pages] int64`` (the padded page list), ``kv_page
[1] int64`` (the pool page this step's write lands in) and ``kv_off
[1] int64`` (the offset inside it) — plus the pool tensors themselves,
which are fed and fetched like any other decode state.

Write path (all row-wise ops, exactly like the slotted cell's one-hot
outer product): ``one_hot(kv_page) ⊗ one_hot(kv_off)`` selects one
``(page, offset)`` cell per slot; its transpose matmul against the
token embeddings scatters each slot's embedding into its cell, and the
result adds onto the pool. A retired slot is fed ``kv_page =
num_pages`` — out of range, so its one-hot row is all zeros and it
writes nothing.

Read path: gather the slot's pages by table, reshape to the same
``[S, max_len, word_dim]`` the slotted cell attends over, and run the
IDENTICAL mask/softmax/context ops.

Bit-identity argument (gated by ``tests/test_kvcache.py``): every
``(page, offset)`` cell is owned by exactly one slot at one step, so
the scatter matmul's contraction sums one embedding against zeros —
exact in IEEE — and pages are zeroed on alloc, so a gathered row holds
precisely the embedding the slotted cell's dense row would. Identical
operand values into identical attention ops give bit-identical tokens.
"""
from .. import layers

__all__ = ['paged_attention_cell']


def paged_attention_cell(dict_size, word_dim=32, hidden=32, max_len=64,
                         page_size=8, num_pages=32):
    """Build the paged analogue of :func:`~paddle_tpu.fleet.decode.
    attention_history_cell`.

    Returns ``(cell_fn, state_specs, pool_specs)``:

    - ``cell_fn(pre_ids, states, pos, pools, table, page, offset) ->
      (probs, new_states, new_pools)`` — the signature
      ``DecodeEngine(admission='paged')`` drives;
    - ``state_specs`` — the per-slot state that STAYS slotted
      (``mask [max_len]``, ``h [hidden]``: tiny, so slots are cheap
      and the compiled batch dim can grow past what dense KV allowed);
    - ``pool_specs`` — what a :class:`~paddle_tpu.kvcache.pool.
      PagePool` must be built with (``[('kv', [word_dim])]``).

    The cell must agree with the pool geometry: construct the pool as
    ``PagePool(pool_specs, num_pages=num_pages, page_size=page_size)``.
    """
    if max_len % page_size != 0:
        raise ValueError('max_len (%d) must be a multiple of '
                         'page_size (%d)' % (max_len, page_size))
    max_pages = max_len // page_size

    def cell(pre_ids, states, pos, pools, table, page, offset):
        kvpool = pools['kv']                       # [NP, P, D]
        mask, h = states['mask'], states['h']
        emb = layers.embedding(input=pre_ids, size=[dict_size, word_dim])
        emb = layers.reshape(emb, shape=[-1, word_dim])       # [S, D]
        # scatter emb into pool[page, offset]: one_hot(page) (x)
        # one_hot(offset) selects one cell per slot (all-zero for a
        # retired slot fed page == num_pages), and the transposed
        # matmul against emb sums exactly one embedding into it
        page_oh = layers.one_hot(page, depth=num_pages)       # [S, NP]
        off_oh = layers.one_hot(offset, depth=page_size)      # [S, P]
        sel = layers.matmul(
            layers.reshape(page_oh, shape=[-1, num_pages, 1]),
            layers.reshape(off_oh, shape=[-1, 1, page_size]))
        sel = layers.reshape(sel, shape=[-1, num_pages * page_size])
        write = layers.matmul(sel, emb, transpose_x=True)   # [NP*P, D]
        kvpool = layers.elementwise_add(
            kvpool, layers.reshape(write,
                                   shape=[-1, page_size, word_dim]))
        # the position mask stays per-slot state, same as the slotted
        # cell: one_hot(pos) accumulates the valid-prefix indicator
        mask = layers.elementwise_add(
            mask, layers.one_hot(pos, depth=max_len))         # [S, L]
        # gather this slot's pages back into the dense [S, L, D] view
        # the slotted cell attends over (padding table entries gather a
        # live page, but the mask zeroes their weight exactly)
        flat = layers.reshape(kvpool,
                              shape=[-1, page_size * word_dim])
        kv = layers.reshape(layers.gather(flat, table),
                            shape=[-1, max_pages * page_size, word_dim])
        # identical attention ops to attention_history_cell from here
        query = layers.fc(input=layers.concat([h, emb], axis=-1),
                          size=word_dim, act='tanh')          # [S, D]
        scores = layers.reshape(
            layers.matmul(kv, layers.reshape(
                query, shape=[-1, word_dim, 1])),
            shape=[-1, max_len])                              # [S, L]
        scores = layers.elementwise_add(
            scores, layers.scale(mask, scale=1e9, bias=-1e9))
        attn = layers.softmax(scores)
        ctx = layers.reshape(
            layers.matmul(layers.reshape(attn, shape=[-1, 1, max_len]),
                          kv),
            shape=[-1, word_dim])                             # [S, D]
        h = layers.fc(input=layers.concat([h, ctx], axis=-1),
                      size=hidden, act='tanh')
        probs = layers.fc(input=h, size=dict_size, act='softmax')
        return probs, {'mask': mask, 'h': h}, {'kv': kvpool}

    return cell, [('mask', [max_len]), ('h', [hidden])], \
        [('kv', [word_dim])]
