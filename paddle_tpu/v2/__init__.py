"""``paddle_tpu.v2`` — thin compat veneer for the legacy v2 surface.

Parity scope (SURVEY.md §1.5 ruling): Fluid-era book/benchmark scripts
import only the data pieces of v2 (``import paddle.v2 as paddle`` then
``paddle.batch`` / ``paddle.reader`` / ``paddle.dataset``) plus a no-op
``init``. The v2 gserver/trainer stack itself is superseded by Fluid and
is out of the rebuild's surface (ref: python/paddle/v2/__init__.py).
"""
from ..reader import batch  # noqa
from .. import reader  # noqa
from .. import dataset  # noqa


def init(**kwargs):
    """No-op (ref v2.init configured the legacy C++ trainer; the XLA
    runtime needs no global init). Accepts and ignores use_gpu/
    trainer_count/... keywords."""
    return None
