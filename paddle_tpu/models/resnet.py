"""ResNet / SE-ResNeXt builders on the fluid layer API.

Parity: benchmark/fluid/resnet.py and benchmark/fluid/se_resnext.py in the
reference (ResNet-50/101/152 bottleneck nets for ImageNet; basicblock net
for cifar10). Built from paddle_tpu.layers conv2d/batch_norm/pool2d so the
whole model lowers into one XLA program per training step.
"""
from .. import layers

__all__ = ['resnet_imagenet', 'resnet_cifar10', 'se_resnext']


def conv_bn_layer(input, ch_out, filter_size, stride, padding, act='relu',
                  is_test=False):
    conv = layers.conv2d(input=input, filter_size=filter_size,
                         num_filters=ch_out, stride=stride, padding=padding,
                         act=None, bias_attr=False)
    return layers.batch_norm(input=conv, act=act, is_test=is_test)


def shortcut(input, ch_in, ch_out, stride, is_test=False):
    if ch_in != ch_out or stride != 1:
        return conv_bn_layer(input, ch_out, 1, stride, 0, None,
                             is_test=is_test)
    return input


def basicblock(input, ch_in, ch_out, stride, is_test=False):
    short = shortcut(input, ch_in, ch_out, stride, is_test)
    conv1 = conv_bn_layer(input, ch_out, 3, stride, 1, is_test=is_test)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, act=None, is_test=is_test)
    return layers.elementwise_add(x=short, y=conv2, act='relu')


def bottleneck(input, ch_in, ch_out, stride, is_test=False):
    short = shortcut(input, ch_in, ch_out * 4, stride, is_test)
    conv1 = conv_bn_layer(input, ch_out, 1, stride, 0, is_test=is_test)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, is_test=is_test)
    conv3 = conv_bn_layer(conv2, ch_out * 4, 1, 1, 0, act=None,
                          is_test=is_test)
    return layers.elementwise_add(x=short, y=conv3, act='relu')


def layer_warp(block_func, input, ch_in, ch_out, count, stride,
               is_test=False):
    res_out = block_func(input, ch_in, ch_out, stride, is_test)
    for _ in range(1, count):
        res_out = block_func(res_out, ch_out * (4 if block_func is bottleneck
                                                else 1),
                             ch_out, 1, is_test)
    return res_out


def resnet_imagenet(input, class_dim, depth=50, is_test=False):
    cfg = {18: ([2, 2, 2, 1], basicblock),
           34: ([3, 4, 6, 3], basicblock),
           50: ([3, 4, 6, 3], bottleneck),
           101: ([3, 4, 23, 3], bottleneck),
           152: ([3, 8, 36, 3], bottleneck)}
    stages, block_func = cfg[depth]
    conv1 = conv_bn_layer(input, ch_out=64, filter_size=7, stride=2,
                          padding=3, is_test=is_test)
    pool1 = layers.pool2d(input=conv1, pool_type='max', pool_size=3,
                          pool_stride=2, pool_padding=1)
    res1 = layer_warp(block_func, pool1, 64, 64, stages[0], 1, is_test)
    res2 = layer_warp(block_func, res1, 256, 128, stages[1], 2, is_test)
    res3 = layer_warp(block_func, res2, 512, 256, stages[2], 2, is_test)
    res4 = layer_warp(block_func, res3, 1024, 512, stages[3], 2, is_test)
    pool2 = layers.pool2d(input=res4, pool_size=7, pool_type='avg',
                          global_pooling=True)
    return layers.fc(input=pool2, size=class_dim, act='softmax')


def resnet_cifar10(input, class_dim, depth=32, is_test=False):
    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    conv1 = conv_bn_layer(input, ch_out=16, filter_size=3, stride=1,
                          padding=1, is_test=is_test)
    res1 = layer_warp(basicblock, conv1, 16, 16, n, 1, is_test)
    res2 = layer_warp(basicblock, res1, 16, 32, n, 2, is_test)
    res3 = layer_warp(basicblock, res2, 32, 64, n, 2, is_test)
    pool = layers.pool2d(input=res3, pool_size=8, pool_type='avg',
                         global_pooling=True)
    return layers.fc(input=pool, size=class_dim, act='softmax')


def _squeeze_excitation(input, num_channels, reduction_ratio):
    pool = layers.pool2d(input=input, pool_type='avg', global_pooling=True)
    squeeze = layers.fc(input=pool, size=num_channels // reduction_ratio,
                        act='relu')
    excitation = layers.fc(input=squeeze, size=num_channels, act='sigmoid')
    return layers.elementwise_mul(x=input, y=excitation, axis=0)


def _se_bottleneck(input, num_filters, stride, cardinality, reduction_ratio,
                   ch_in, is_test=False):
    conv0 = conv_bn_layer(input, num_filters, 1, 1, 0, is_test=is_test)
    conv1 = layers.conv2d(input=conv0, num_filters=num_filters,
                          filter_size=3, stride=stride, padding=1,
                          groups=cardinality, act=None, bias_attr=False)
    conv1 = layers.batch_norm(input=conv1, act='relu', is_test=is_test)
    conv2 = conv_bn_layer(conv1, num_filters * 2, 1, 1, 0, act=None,
                          is_test=is_test)
    scale = _squeeze_excitation(conv2, num_filters * 2, reduction_ratio)
    short = shortcut(input, ch_in, num_filters * 2, stride, is_test)
    return layers.elementwise_add(x=short, y=scale, act='relu')


def se_resnext(input, class_dim, depth=50, is_test=False):
    """SE-ResNeXt-50/101/152 (benchmark/fluid/se_resnext.py parity)."""
    cfg = {50: [3, 4, 6, 3], 101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}
    depth_cfg = cfg[depth]
    cardinality, reduction_ratio = 32, 16
    num_filters = [128, 256, 512, 1024]

    conv = conv_bn_layer(input, 64, 7, 2, 3, is_test=is_test)
    conv = layers.pool2d(input=conv, pool_size=3, pool_stride=2,
                         pool_padding=1, pool_type='max')
    ch_in = 64
    for block in range(len(depth_cfg)):
        for i in range(depth_cfg[block]):
            conv = _se_bottleneck(conv, num_filters[block],
                                  2 if i == 0 and block != 0 else 1,
                                  cardinality, reduction_ratio, ch_in,
                                  is_test)
            ch_in = num_filters[block] * 2
    pool = layers.pool2d(input=conv, pool_size=7, pool_type='avg',
                         global_pooling=True)
    return layers.fc(input=pool, size=class_dim, act='softmax')
