"""Model zoo: fluid-style builders for the reference's book/benchmark models
plus the TPU-native transformer flagship."""
from . import transformer  # noqa: F401
from . import resnet  # noqa: F401
from . import vgg  # noqa: F401
