"""TPU-native transformer LM — the paddle_tpu flagship.

This is the framework's headline long-context model: a decoder-only
transformer expressed directly in JAX with explicit mesh shardings, so one
jitted training step scales over a `jax.sharding.Mesh` with axes

    dp — data parallel (batch dim; gradients psum over ICI)
    tp — tensor parallel (hidden/head dim; Megatron-style column/row splits)
    sp — sequence parallel (sequence dim; ring attention over a ppermute ring)

Design notes (vs the reference, paddle/fluid has no transformer — this is the
capability ceiling of its machine_translation seq2seq+attention stack
re-imagined for TPU):
  * all matmuls run in bfloat16 on the MXU with f32 accumulation
    (preferred_element_type), params kept in f32.
  * attention: online-softmax blockwise attention; over the sp axis the KV
    blocks rotate around the ring via `jax.lax.ppermute` so no device ever
    materialises the full [T, T] score matrix (ring attention).
  * the whole step (fwd + bwd + adam) is ONE XLA program; param/opt state is
    donated.
"""
import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shard_map_compat(f=None, **kwargs):
    """``jax.shard_map`` across jax versions: jax < 0.5 ships it under
    ``jax.experimental.shard_map`` and spells ``check_vma`` as
    ``check_rep`` — normalize so the model code runs on both (this
    image's jax lacks ``jax.shard_map``; the tier-1 seed failed here)."""
    if hasattr(jax, 'shard_map'):
        fn = jax.shard_map
    else:
        from jax.experimental.shard_map import shard_map as fn
        if 'check_vma' in kwargs:
            kwargs['check_rep'] = kwargs.pop('check_vma')
    if f is None:
        return functools.partial(fn, **kwargs)
    return fn(f, **kwargs)

__all__ = ['TransformerConfig', 'init_params', 'forward', 'loss_fn',
           'make_train_step', 'param_specs', 'ring_attention',
           'stack_pipeline_params', 'unstack_pipeline_params',
           'make_pipeline_fn', 'forward_pipelined',
           'pipeline_param_specs', 'make_pipeline_train_step',
           'shard_params', 'init_adam_state']


class TransformerConfig(object):
    def __init__(self, vocab=32000, d_model=512, n_heads=8, n_layers=4,
                 d_ff=2048, max_len=2048, dtype=jnp.bfloat16,
                 remat=False):
        assert d_model % n_heads == 0
        self.vocab = vocab
        self.d_model = d_model
        self.n_heads = n_heads
        self.n_layers = n_layers
        self.d_ff = d_ff
        self.max_len = max_len
        self.dtype = dtype
        self.remat = remat
        self.d_head = d_model // n_heads


def _init(key, shape, scale):
    return jax.random.normal(key, shape, jnp.float32) * scale


def init_params(cfg, seed=0):
    """f32 master params as a flat dict pytree."""
    key = jax.random.PRNGKey(seed)
    ks = jax.random.split(key, 2 + cfg.n_layers)
    p = {
        'embed': _init(ks[0], (cfg.vocab, cfg.d_model), 0.02),
        'pos': _init(ks[1], (cfg.max_len, cfg.d_model), 0.02),
        'ln_f_g': jnp.ones((cfg.d_model,), jnp.float32),
        'ln_f_b': jnp.zeros((cfg.d_model,), jnp.float32),
    }
    for i in range(cfg.n_layers):
        kq, kk, kv, ko, k1, k2 = jax.random.split(ks[2 + i], 6)
        s = 0.02
        so = 0.02 / math.sqrt(2 * cfg.n_layers)
        p['l%d' % i] = {
            'ln1_g': jnp.ones((cfg.d_model,), jnp.float32),
            'ln1_b': jnp.zeros((cfg.d_model,), jnp.float32),
            'wq': _init(kq, (cfg.d_model, cfg.d_model), s),
            'wk': _init(kk, (cfg.d_model, cfg.d_model), s),
            'wv': _init(kv, (cfg.d_model, cfg.d_model), s),
            'wo': _init(ko, (cfg.d_model, cfg.d_model), so),
            'ln2_g': jnp.ones((cfg.d_model,), jnp.float32),
            'ln2_b': jnp.zeros((cfg.d_model,), jnp.float32),
            'w1': _init(k1, (cfg.d_model, cfg.d_ff), s),
            'b1': jnp.zeros((cfg.d_ff,), jnp.float32),
            'w2': _init(k2, (cfg.d_ff, cfg.d_model), so),
            'b2': jnp.zeros((cfg.d_model,), jnp.float32),
        }
    return p


def param_specs(cfg):
    """PartitionSpecs: Megatron column/row splits over 'tp'; vocab over 'tp'
    for the (large) embedding."""
    lp = {
        'ln1_g': P(), 'ln1_b': P(), 'ln2_g': P(), 'ln2_b': P(),
        'wq': P(None, 'tp'), 'wk': P(None, 'tp'), 'wv': P(None, 'tp'),
        'wo': P('tp', None),
        'w1': P(None, 'tp'), 'b1': P('tp'),
        'w2': P('tp', None), 'b2': P(),
    }
    specs = {'embed': P('tp', None), 'pos': P(), 'ln_f_g': P(),
             'ln_f_b': P()}
    for i in range(cfg.n_layers):
        specs['l%d' % i] = dict(lp)
    return specs


def _layer_norm(x, g, b, eps=1e-5):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
    out = (xf - mu) * jax.lax.rsqrt(var + eps) * g + b
    return out.astype(x.dtype)


def _causal_attention(q, k, v, q_off=0, k_off=0):
    """Plain blockwise causal attention (ring-attention building block).
    q,k,v: [B, T, H, Dh] (bf16); offsets give the global positions of the
    local blocks. Math lives in ops/pallas_kernels.attention_reference."""
    from ..ops.pallas_kernels import attention_reference
    return attention_reference(q, k, v, causal=True, q_off=q_off,
                               k_off=k_off)


def ring_attention(q, k, v, axis_name='sp'):
    """Causal ring attention inside shard_map: the sequence dim is sharded
    over `axis_name`; KV blocks rotate around the ring (ppermute over ICI)
    while each device merges per-block (out, lse) partials by exact
    logsumexp weighting. Memory per device: O(T_local) when the Pallas
    kernel engages (TPU, 128-aligned blocks >= _FLASH_MIN_T),
    O(T_local^2) on the XLA fallback — never O(T^2) either way.

    Per ring step the held KV block is globally either entirely in the
    PAST (full unmasked attention), the DIAGONAL (plain causal), or the
    FUTURE (contributes nothing) — so each partial is computed by the
    Pallas flash kernel (ops/pallas_kernels.flash_attention_with_lse;
    XLA reference off-TPU) with NO positional offsets, and lse gradients
    flow through the merge via the kernel's lse-aware backward.

    q,k,v: [B, T_local, H, Dh]. Returns [B, T_local, H, Dh].
    """
    from ..ops.pallas_kernels import flash_attention_with_lse
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, T, H, Dh = q.shape

    def partial_block(k_cur, v_cur, kind):
        # kind: 0 = past (full), 1 = diagonal (causal), 2 = future (skip)
        def past(_):
            return flash_attention_with_lse(q, k_cur, v_cur,
                                            causal=False)
        def diag(_):
            return flash_attention_with_lse(q, k_cur, v_cur,
                                            causal=True)
        def future(_):
            # finite "empty" sentinel: -inf would make 0 * nan gradients
            # through logaddexp; exp(-1e30 - real_lse) is exactly 0
            return (jnp.zeros_like(q),
                    jnp.full((B, H, T), -1e30, jnp.float32))
        return jax.lax.switch(kind, (past, diag, future), None)

    def step(carry, i):
        acc, lse_acc, k_cur, v_cur = carry
        src = (idx - i) % n            # whose KV block we hold this step
        kind = jnp.where(src == idx, 1, jnp.where(src < idx, 0, 2))
        out_b, lse_b = partial_block(k_cur, v_cur, kind)
        # exact merge of normalized partials by logsumexp weights
        lse_new = jnp.logaddexp(lse_acc, lse_b)
        w_acc = jnp.exp(lse_acc - lse_new)
        w_b = jnp.exp(lse_b - lse_new)
        # weights are [B, H, T]; outputs are [B, T, H, Dh]
        wt = lambda w: jnp.transpose(w, (0, 2, 1))[..., None]
        acc = acc * wt(w_acc) + out_b.astype(jnp.float32) * wt(w_b)
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (acc, lse_new, k_nxt, v_nxt), None

    acc0 = jnp.zeros((B, T, H, Dh), jnp.float32)
    lse0 = jnp.full((B, H, T), -1e30, jnp.float32)
    (acc, _, _, _), _ = jax.lax.scan(step, (acc0, lse0, k, v),
                                     jnp.arange(n))
    return acc.astype(q.dtype)


def _block(x, lp, cfg, attn_fn):
    h = _layer_norm(x, lp['ln1_g'], lp['ln1_b'])
    B, T, D = h.shape
    H, Dh = cfg.n_heads, cfg.d_head
    dt = cfg.dtype
    q = (h @ lp['wq'].astype(dt)).reshape(B, T, H, Dh)
    k = (h @ lp['wk'].astype(dt)).reshape(B, T, H, Dh)
    v = (h @ lp['wv'].astype(dt)).reshape(B, T, H, Dh)
    a = attn_fn(q, k, v).reshape(B, T, D)
    x = x + a @ lp['wo'].astype(dt)
    h = _layer_norm(x, lp['ln2_g'], lp['ln2_b'])
    h = jax.nn.gelu(h @ lp['w1'].astype(dt) + lp['b1'].astype(dt))
    return x + h @ lp['w2'].astype(dt) + lp['b2'].astype(dt)


def forward(params, tokens, cfg, attn_fn=None, pos_offset=0):
    """tokens [B, T] int32 -> logits [B, T, vocab] f32."""
    if attn_fn is None:
        # Pallas flash-attention on TPU (ops/pallas_kernels.py); identical
        # -math XLA fallback elsewhere / for non-block-aligned shapes.
        from ..ops.pallas_kernels import flash_attention
        attn_fn = lambda q, k, v: flash_attention(q, k, v, causal=True)
    dt = cfg.dtype
    x = params['embed'].astype(dt)[tokens]
    T = tokens.shape[1]
    x = x + jax.lax.dynamic_slice_in_dim(
        params['pos'].astype(dt), pos_offset, T, 0)[None]
    blk = _block
    if cfg.remat:
        blk = jax.checkpoint(_block, static_argnums=(2, 3))
    for i in range(cfg.n_layers):
        x = blk(x, params['l%d' % i], cfg, attn_fn)
    x = _layer_norm(x, params['ln_f_g'], params['ln_f_b'])
    return (x @ params['embed'].astype(dt).T).astype(jnp.float32)


def loss_fn(params, inputs, targets, cfg, attn_fn=None, pos_offset=0):
    """Next-token cross entropy. inputs/targets: [B, T] (targets = inputs
    shifted by one; split on the host so the sequence dim stays divisible
    by the sp axis)."""
    logits = forward(params, inputs, cfg, attn_fn, pos_offset)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


# ---------------------------------------------------------------------------
# sharded train step
# ---------------------------------------------------------------------------
def init_adam_state(params):
    z = lambda p: jnp.zeros_like(p)
    return {'m': jax.tree_util.tree_map(z, params),
            'v': jax.tree_util.tree_map(z, params),
            't': jnp.zeros((), jnp.int32)}


def _adam_update(params, grads, opt, lr=1e-3, b1=0.9, b2=0.999, eps=1e-8):
    t = opt['t'] + 1
    m = jax.tree_util.tree_map(lambda m, g: b1 * m + (1 - b1) * g,
                               opt['m'], grads)
    v = jax.tree_util.tree_map(lambda v, g: b2 * v + (1 - b2) * g * g,
                               opt['v'], grads)
    tc = t.astype(jnp.float32)
    corr = jnp.sqrt(1 - b2 ** tc) / (1 - b1 ** tc)
    new_p = jax.tree_util.tree_map(
        lambda p, m, v: p - lr * corr * m / (jnp.sqrt(v) + eps),
        params, m, v)
    return new_p, {'m': m, 'v': v, 't': t}


# ---------------------------------------------------------------------------
# pipeline parallelism (pp axis)
# ---------------------------------------------------------------------------
def stack_pipeline_params(params, cfg, n_stages):
    """Per-layer trees l0..l{L-1} -> one 'layers' tree whose leaves are
    [n_stages, L/n_stages, ...] (stage-major), ready to shard over the
    'pp' mesh axis on dim 0. Non-layer params pass through."""
    L = cfg.n_layers
    assert L % n_stages == 0, (L, n_stages)
    per = L // n_stages
    layer_trees = [params['l%d' % i] for i in range(L)]
    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs).reshape((n_stages, per) + xs[0].shape),
        *layer_trees)
    rest = {k: v for k, v in params.items() if not _is_layer_key(k)}
    rest['layers'] = stacked
    return rest


def unstack_pipeline_params(params, cfg):
    """Inverse of stack_pipeline_params."""
    stacked = params['layers']
    L = cfg.n_layers
    out = {k: v for k, v in params.items() if k != 'layers'}
    flat = jax.tree_util.tree_map(
        lambda x: x.reshape((L,) + x.shape[2:]), stacked)
    for i in range(L):
        out['l%d' % i] = jax.tree_util.tree_map(lambda x: x[i], flat)
    return out


def _is_layer_key(k):
    return k.startswith('l') and k[1:].isdigit()


def make_pipeline_fn(cfg, mesh, attn_fn, n_micro, axis_name='pp'):
    """The pipelined middle of the network: [B, T, D] -> [B, T, D]
    through all transformer blocks, GPipe fill/drain over the pp axis.

    shard_map covers ONLY the block stack — embedding/ln_f/unembed stay
    outside under the SPMD partitioner, so shard_map's replication rules
    insert the right gradient psums (activations enter replicated over
    pp; stage weights enter sharded over pp). Per tick every stage runs
    its local layers and ppermutes the activation to the next stage;
    stage 0 injects microbatch t, the last stage collects microbatch
    t-(S-1). Bubble fraction is (S-1)/(n_micro+S-1).
    """
    axes = dict(zip(mesh.axis_names, mesh.devices.shape))
    S = axes[axis_name]
    per = cfg.n_layers // S
    if attn_fn is None:
        from ..ops.pallas_kernels import flash_attention
        attn_fn = lambda q, k, v: flash_attention(q, k, v, causal=True)

    def run(layers, x):
        # layers leaves arrive [1, per, ...]; x arrives [B_local, T, D]
        layers = jax.tree_util.tree_map(lambda v: v[0], layers)
        stage = jax.lax.axis_index(axis_name)
        B, T, D = x.shape
        assert B % n_micro == 0, (B, n_micro)
        bm = B // n_micro
        x_micro = x.reshape(n_micro, bm, T, D)

        blk = _block
        if cfg.remat:
            blk = jax.checkpoint(_block, static_argnums=(2, 3))

        def apply_stage(h):
            for j in range(per):
                lp = jax.tree_util.tree_map(lambda v: v[j], layers)
                h = blk(h, lp, cfg, attn_fn)
            return h

        def tick(carry, t):
            state, outbuf = carry
            inj = x_micro[jnp.minimum(t, n_micro - 1)]
            x_in = jnp.where(stage == 0, inj, state)
            y = apply_stage(x_in)
            out_t = t - (S - 1)
            idx = jnp.clip(out_t, 0, n_micro - 1)
            is_out = (stage == S - 1) & (out_t >= 0)
            cur = jax.lax.dynamic_index_in_dim(outbuf, idx, 0,
                                               keepdims=False)
            outbuf = jax.lax.dynamic_update_index_in_dim(
                outbuf, jnp.where(is_out, y, cur), idx, 0)
            perm = [(i, (i + 1) % S) for i in range(S)]
            state = jax.lax.ppermute(y, axis_name, perm)
            return (state, outbuf), None

        state0 = jnp.zeros((bm, T, D), x.dtype)
        outbuf0 = jnp.zeros((n_micro, bm, T, D), x.dtype)
        (_, outbuf), _ = jax.lax.scan(
            tick, (state0, outbuf0), jnp.arange(n_micro + S - 1))
        # outputs live on the last stage; replicate them over pp
        outbuf = jax.lax.psum(
            jnp.where(stage == S - 1, outbuf, jnp.zeros_like(outbuf)),
            axis_name)
        return outbuf.reshape(B, T, D)

    layers_specs = _stacked_layer_specs(cfg, S, axis_name)
    batch_axis = 'dp' if axes.get('dp', 1) > 1 else None
    return shard_map_compat(
        mesh=mesh,
        in_specs=(layers_specs, P(batch_axis, None, None)),
        out_specs=P(batch_axis, None, None),
        check_vma=False)(run)


def forward_pipelined(params, tokens, cfg, pipe_fn, pos_offset=0):
    """Pipelined forward: embed -> pp block pipeline -> ln_f/unembed.
    params must be in stacked form (stack_pipeline_params)."""
    dt = cfg.dtype
    x = params['embed'].astype(dt)[tokens]
    T = tokens.shape[1]
    x = x + jax.lax.dynamic_slice_in_dim(
        params['pos'].astype(dt), pos_offset, T, 0)[None]
    x = pipe_fn(params['layers'], x)
    x = _layer_norm(x, params['ln_f_g'], params['ln_f_b'])
    return (x @ params['embed'].astype(dt).T).astype(jnp.float32)


def _stacked_layer_specs(cfg, n_stages, axis_name='pp'):
    """PartitionSpec tree for stack_pipeline_params' 'layers' entry:
    stage dim over `axis_name`, everything else replicated."""
    sample = jax.eval_shape(
        lambda: stack_pipeline_params(init_params(cfg, 0), cfg,
                                      n_stages))['layers']
    return jax.tree_util.tree_map(
        lambda x: P(*((axis_name,) + (None,) * (x.ndim - 1))), sample)


def pipeline_param_specs(cfg, n_stages, mesh=None, axis_name='pp'):
    """PartitionSpecs for the stacked form: stage dim over `axis_name`,
    everything else from param_specs' non-layer entries (axis names
    absent from `mesh` degrade to replicated)."""
    base = param_specs(cfg)
    specs = {k: v for k, v in base.items() if not _is_layer_key(k)}
    if mesh is not None:
        from ..parallel.mesh import clean_spec
        specs = jax.tree_util.tree_map(
            lambda s: P(*clean_spec(tuple(s), mesh)), specs,
            is_leaf=lambda x: isinstance(x, P))
    specs['layers'] = _stacked_layer_specs(cfg, n_stages, axis_name)
    return specs


def make_pipeline_train_step(cfg, mesh, lr=1e-3, n_micro=4,
                             axis_name='pp'):
    """(stacked_params, opt, inputs, targets) -> (loss, params', opt')
    with pipeline parallelism over the mesh's 'pp' axis (+ dp batch
    sharding). v1 scope: dp x pp meshes (tensor/sequence axes compose
    via make_train_step instead)."""
    axes = dict(zip(mesh.axis_names, mesh.devices.shape))
    assert axes.get(axis_name, 1) > 1, "mesh has no %s axis" % axis_name
    pipe_fn = make_pipeline_fn(cfg, mesh, None, n_micro, axis_name)

    pspecs = pipeline_param_specs(cfg, axes[axis_name], mesh, axis_name)
    param_sh = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), pspecs,
        is_leaf=lambda x: isinstance(x, P))
    opt_sh = {'m': param_sh, 'v': param_sh,
              't': NamedSharding(mesh, P())}
    tok_sh = NamedSharding(mesh, P('dp') if axes.get('dp', 1) > 1
                           else P())

    def loss_pp(params, inputs, targets):
        logits = forward_pipelined(params, inputs, cfg, pipe_fn)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None],
                                   axis=-1)[..., 0]
        return jnp.mean(nll)

    def step(params, opt, inputs, targets):
        loss, grads = jax.value_and_grad(loss_pp)(params, inputs,
                                                  targets)
        new_params, new_opt = _adam_update(params, grads, opt, lr)
        return loss, new_params, new_opt

    return jax.jit(
        step,
        in_shardings=(param_sh, opt_sh, tok_sh, tok_sh),
        out_shardings=(NamedSharding(mesh, P()), param_sh, opt_sh),
        donate_argnums=(0, 1))


def make_train_step(cfg, mesh, lr=1e-3, seq_parallel=None):
    """One jitted (params, opt, tokens) -> (loss, params', opt') step over
    `mesh`. Sequence parallelism (ring attention) activates when the mesh
    has an 'sp' axis of size > 1 (or when `seq_parallel` forces it).
    """
    axes = dict(zip(mesh.axis_names, mesh.devices.shape))
    use_sp = seq_parallel if seq_parallel is not None else \
        axes.get('sp', 1) > 1

    pspecs = param_specs(cfg)
    param_sh = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), pspecs,
        is_leaf=lambda x: isinstance(x, P))
    opt_sh = {'m': param_sh, 'v': param_sh,
              't': NamedSharding(mesh, P())}
    tok_spec = P('dp', 'sp') if use_sp else P('dp')
    tok_sh = NamedSharding(mesh, tok_spec)

    if use_sp:
        # ring attention runs under shard_map over the sp axis only;
        # dp/tp stay with the SPMD partitioner.
        @shard_map_compat(
            mesh=mesh,
            in_specs=(P(None, 'sp', None, None),) * 3,
            out_specs=P(None, 'sp', None, None),
            check_vma=False)
        def attn_fn(q, k, v):
            return ring_attention(q, k, v, 'sp')
    else:
        attn_fn = None

    def step(params, opt, inputs, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, inputs, targets,
                                                  cfg, attn_fn)
        new_params, new_opt = _adam_update(params, grads, opt, lr)
        return loss, new_params, new_opt

    return jax.jit(
        step,
        in_shardings=(param_sh, opt_sh, tok_sh, tok_sh),
        out_shardings=(NamedSharding(mesh, P()), param_sh, opt_sh),
        donate_argnums=(0, 1))


def shard_params(params, cfg, mesh):
    pspecs = param_specs(cfg)
    return jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
        params, pspecs)
