"""Evaluator classes (deprecated in the reference in favor of metrics; kept
for book-script parity). Parity: python/paddle/fluid/evaluator.py."""
import numpy as np

from . import layers
from .framework import Program, Variable, program_guard
from .initializer import Constant
from .layer_helper import LayerHelper
from . import unique_name

__all__ = ['ChunkEvaluator', 'EditDistance', 'DetectionMAP', 'Evaluator']


def _clone_var_(block, var):
    return block.create_var(name=var.name, shape=var.shape, dtype=var.dtype,
                            lod_level=var.lod_level, persistable=True)


class Evaluator(object):
    """Accumulates per-batch statistics into persistable state vars."""

    def __init__(self, name, **kwargs):
        self.states = []
        self.metrics = []
        self.helper = LayerHelper(name, **kwargs)

    def reset(self, executor, reset_program=None):
        import jax.numpy as jnp
        from .executor import global_scope
        for var in self.states:
            global_scope().set_var(
                var.name, jnp.zeros([int(s) for s in var.shape],
                                    dtype=var.dtype if var.dtype !=
                                    'int64' else 'int32'))

    def eval(self, executor, eval_program=None):
        raise NotImplementedError()

    def create_state(self, suffix, dtype, shape):
        state = self.helper.create_variable(
            name="_".join([unique_name.generate(self.helper.name), suffix]),
            persistable=True, dtype=dtype, shape=tuple(shape))
        self.states.append(state)
        return state


class ChunkEvaluator(Evaluator):
    def __init__(self, input, label, chunk_scheme, num_chunk_types,
                 excluded_chunk_types=None):
        super(ChunkEvaluator, self).__init__("chunk_eval")
        main_program = self.helper.main_program
        if main_program.current_block().idx != 0:
            raise ValueError("You can only invoke Evaluator in root block")

        self.num_infer_chunks = self.create_state(
            dtype='int64', shape=[1], suffix='num_infer_chunks')
        self.num_label_chunks = self.create_state(
            dtype='int64', shape=[1], suffix='num_label_chunks')
        self.num_correct_chunks = self.create_state(
            dtype='int64', shape=[1], suffix='num_correct_chunks')
        precision, recall, f1_score, num_infer_chunks, num_label_chunks, \
            num_correct_chunks = layers.chunk_eval(
                input=input, label=label, chunk_scheme=chunk_scheme,
                num_chunk_types=num_chunk_types,
                excluded_chunk_types=excluded_chunk_types)
        layers.sums(input=[self.num_infer_chunks, num_infer_chunks],
                    out=self.num_infer_chunks)
        layers.sums(input=[self.num_label_chunks, num_label_chunks],
                    out=self.num_label_chunks)
        layers.sums(input=[self.num_correct_chunks, num_correct_chunks],
                    out=self.num_correct_chunks)
        self.metrics.extend([precision, recall, f1_score])

    def eval(self, executor, eval_program=None):
        from .executor import global_scope, as_numpy
        num_infer_chunks = float(
            np.asarray(as_numpy(global_scope().find_var(
                self.num_infer_chunks.name))).sum())
        num_label_chunks = float(
            np.asarray(as_numpy(global_scope().find_var(
                self.num_label_chunks.name))).sum())
        num_correct_chunks = float(
            np.asarray(as_numpy(global_scope().find_var(
                self.num_correct_chunks.name))).sum())
        precision = num_correct_chunks / num_infer_chunks \
            if num_infer_chunks else 0
        recall = num_correct_chunks / num_label_chunks \
            if num_label_chunks else 0
        f1 = 2 * precision * recall / (precision + recall) \
            if num_correct_chunks else 0
        return np.array([precision]), np.array([recall]), np.array([f1])


class EditDistance(Evaluator):
    def __init__(self, input, label, ignored_tokens=None, **kwargs):
        super(EditDistance, self).__init__("edit_distance", **kwargs)
        self.total_distance = self.create_state(
            dtype='float32', shape=[1], suffix='total_distance')
        self.seq_num = self.create_state(dtype='int64', shape=[1],
                                         suffix='seq_num')
        distances, seq_num = layers.edit_distance(
            input=input, label=label, ignored_tokens=ignored_tokens)
        total = layers.reduce_sum(distances)
        layers.sums(input=[self.total_distance, total],
                    out=self.total_distance)
        layers.sums(input=[self.seq_num, seq_num], out=self.seq_num)
        self.metrics.append(distances)

    def eval(self, executor, eval_program=None):
        from .executor import global_scope, as_numpy
        total = float(np.asarray(as_numpy(global_scope().find_var(
            self.total_distance.name))).sum())
        n = float(np.asarray(as_numpy(global_scope().find_var(
            self.seq_num.name))).sum())
        return np.array([total / max(n, 1.0)])


class DetectionMAP(Evaluator):
    """mAP over the evaluation stream.

    Per-batch MAP comes from the in-XLA detection_map kernel; the
    cross-batch Accum* LoD state of the reference op
    (paddle/fluid/operators/detection_map_op.h GetInputPos/GetOutputPos)
    maps to a host-side DetectionMAPState (ops/detection_map_ref.py):
    call update_state(detections, labels) with per-image rows after each
    eval batch, then eval() for the exact accumulated mAP.
    """

    def __init__(self, input, gt_label, gt_box, gt_difficult=None,
                 class_num=None, background_label=0, overlap_threshold=0.5,
                 evaluate_difficult=True, ap_version='integral'):
        super(DetectionMAP, self).__init__("map_eval")
        from .ops.detection_map_ref import DetectionMAPState
        if class_num is None:
            raise ValueError(
                "DetectionMAP requires class_num; note gt_difficult "
                "precedes class_num in the signature (reference "
                "evaluator.py:314-323)")
        gt_label = layers.cast(x=gt_label, dtype=gt_box.dtype)
        if gt_difficult is not None:
            # 6-col [label, difficult, xmin..ymax] layout, matching the
            # reference evaluator (python/paddle/fluid/evaluator.py:326-331).
            gt_difficult = layers.cast(x=gt_difficult, dtype=gt_box.dtype)
            label = layers.concat([gt_label, gt_difficult, gt_box], axis=1)
        else:
            label = layers.concat([gt_label, gt_box], axis=1)
        map_out = layers.detection_map(
            input, label, class_num, background_label=background_label,
            overlap_threshold=overlap_threshold,
            evaluate_difficult=evaluate_difficult, ap_version=ap_version)
        self.cur_map = map_out
        self.accum_map = self.create_state(
            dtype='float32', shape=[1], suffix='accum_map')
        layers.sums(input=[self.accum_map, map_out], out=self.accum_map)
        self._state = DetectionMAPState(
            overlap_threshold, evaluate_difficult, ap_version,
            class_num, background_label)
        self._host_mode = False

    def get_map_var(self):
        return self.cur_map, self.accum_map

    def update_state(self, detections, labels):
        """Accumulate one evaluated batch (lists of per-image arrays:
        detections [D_i, 6], labels [G_i, 5|6])."""
        self._host_mode = True
        self._state.update(detections, labels)

    def reset(self, executor, reset_program=None):
        self._state.reset()
        self._host_mode = False
        return super(DetectionMAP, self).reset(executor, reset_program)

    def eval(self, executor, eval_program=None):
        if self._host_mode:
            return np.array([self._state.value()], np.float32)
        from .executor import global_scope, as_numpy
        return np.asarray(as_numpy(global_scope().find_var(
            self.accum_map.name)))
