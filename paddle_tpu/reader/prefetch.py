"""Bounded async host-prefetch pipeline (PERF.md "Dispatch pipelining").

The training hot loop's host work — pulling the next reader batch,
``DataFeeder`` conversion, the H2D ``jax.device_put`` — runs serially
with device compute unless something pulls it ahead.
:class:`PrefetchPipeline` is that something: a daemon worker thread
drains the source through an optional ``transform`` (batch -> feed
dict) and optional device staging into a bounded queue, so by the time
the consuming step asks for batch *i+1* its host cost has already been
paid while the device was busy with batch *i*.

Contract (mirrors the reference's create_double_buffer_reader /
create_threaded_reader semantics, reader_io.iterate_reader):

- **order-preserving** — one worker, one FIFO queue;
- **bounded** — at most ``depth`` converted batches are ever ahead
  (memory stays O(depth), and a slow consumer back-pressures the
  source);
- **exception propagation** — a source/transform error surfaces at the
  consumer exactly where the stream broke, with the original exception
  object (not an EOF);
- **clean shutdown** — ``close()`` (or abandoning the iterator: break,
  GC, ``with`` exit) stops the worker promptly; the worker never blocks
  forever on a full queue, and a worker that dies without signalling is
  detected instead of hanging the consumer.

``layers.io.double_buffer(place=...)`` and
``Trainer.train(prefetch=N)`` both route through this class.
"""
import queue
import threading

__all__ = ['PrefetchPipeline', 'stage_on_device', 'prefetch_feeds']

_END = object()


class _Err(object):
    __slots__ = ('exc',)

    def __init__(self, exc):
        self.exc = exc


def stage_on_device(value, place):
    """``jax.device_put`` a batch/feed (dict, tuple, SequenceTensor —
    any pytree) onto ``place``'s device. ``place`` may be a
    core.places.Place, a raw jax Device, a
    :class:`~paddle_tpu.partition.Partitioner` (staging then uses its
    sharded ``device_put`` — batch-dim sharded over the mesh), or None
    (no staging)."""
    if place is None:
        return value
    if hasattr(place, 'stage'):
        return place.stage(value)
    import jax
    device = place.jax_device() if hasattr(place, 'jax_device') else place
    return jax.device_put(value, device)


class PrefetchPipeline(object):
    """Iterate a reader ahead of its consumer through a bounded queue.

    ``source``: a reader callable (paddle convention: ``source()``
    yields batches) or a plain iterable. ``transform``: optional
    per-batch host conversion (e.g. ``feeder.feed``) executed on the
    WORKER thread — that is the whole point. ``place``: optional device
    place; transformed batches are ``jax.device_put`` onto it, still on
    the worker, so H2D transfer overlaps the consuming step too.
    """

    def __init__(self, source, transform=None, depth=2, place=None):
        if depth < 1:
            raise ValueError('prefetch depth must be >= 1, got %r'
                             % (depth,))
        self._source = source
        self._transform = transform
        self._place = place
        self._queue = queue.Queue(maxsize=int(depth))
        self._stop = threading.Event()
        self._thread = None
        self._consumed = False

    # ---- worker side ----------------------------------------------------
    def _offer(self, item):
        # never block forever on a full queue: an abandoned consumer
        # (close(), break, interpreter teardown) sets _stop
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _worker(self):
        try:
            it = self._source() if callable(self._source) \
                else iter(self._source)
            for batch in it:
                if self._stop.is_set():
                    return
                if self._transform is not None:
                    batch = self._transform(batch)
                if self._place is not None:
                    batch = stage_on_device(batch, self._place)
                if not self._offer(batch):
                    return
        except BaseException as e:  # surface at the consumer, not EOF
            self._offer(_Err(e))
            return
        self._offer(_END)

    # ---- consumer side --------------------------------------------------
    def _start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._worker, name='paddle_tpu-prefetch',
                daemon=True)
            self._thread.start()

    def __iter__(self):
        # plain method (not a generator) so the single-use check and
        # worker start happen AT iter() time, not first next()
        if self._consumed:
            raise RuntimeError(
                'PrefetchPipeline is single-use: build a fresh one per '
                'pass (Trainer does, once per epoch)')
        self._consumed = True
        self._start()
        return self._drain()

    def _drain(self):
        try:
            while True:
                try:
                    item = self._queue.get(timeout=5.0)
                except queue.Empty:
                    # liveness check: a worker killed without posting
                    # _END/_Err (daemon teardown mid-put) must raise,
                    # not hang the trainer forever
                    if not self._thread.is_alive():
                        raise RuntimeError(
                            'prefetch worker thread died without '
                            'signalling end-of-data')
                    continue
                if item is _END:
                    return
                if isinstance(item, _Err):
                    raise item.exc
                yield item
        finally:
            self.close()

    def close(self, timeout=5.0):
        """Stop the worker and release queue slots. Idempotent; safe
        from any thread."""
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive() and \
                t is not threading.current_thread():
            # unblock a worker parked in put(): drain whatever is queued
            while True:
                try:
                    self._queue.get_nowait()
                except queue.Empty:
                    break
            t.join(timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):  # pragma: no cover - GC timing dependent
        try:
            self._stop.set()
        except Exception:
            pass


def prefetch_feeds(reader, feeder, depth=2, place=None):
    """Convenience: iterate ``reader()`` batches as ``(batch_size,
    feed_dict)`` pairs with conversion (and optional device staging)
    running ``depth`` batches ahead on a worker thread."""

    def _convert(data):
        try:
            n = len(data)
        except TypeError:
            n = 0
        feed = feeder.feed(data)
        if place is not None:
            # stage only the feed dict — the count stays a host int
            feed = stage_on_device(feed, place)
        return n, feed

    pipe = PrefetchPipeline(reader, transform=_convert, depth=depth)
    return iter(pipe)
