"""Reader decorators.

Parity: python/paddle/reader/decorator.py + python/paddle/batch.py — pure
host-side composable iterators feeding DataFeeder.
"""
import itertools
import random
import threading
import queue as Queue

__all__ = ['map_readers', 'buffered', 'compose', 'chain', 'shuffle',
           'ComposeNotAligned', 'firstn', 'xmap_readers', 'batch',
           'retry_reader', 'PrefetchPipeline', 'prefetch_feeds',
           'stage_on_device']


def __getattr__(name):  # lazy: prefetch pulls jax only when staging
    if name in ('PrefetchPipeline', 'prefetch_feeds', 'stage_on_device'):
        from . import prefetch as _prefetch
        return getattr(_prefetch, name)
    raise AttributeError(name)


def retry_reader(reader, max_attempts=3, backoff=0.05, jitter=0.1,
                 retry_on=(IOError, OSError), sleep=None):
    """Absorb transient source errors: when the underlying reader
    raises a ``retry_on`` error mid-iteration, re-open it and fast
    forward past the items already delivered, so the consumer sees an
    uninterrupted stream (no duplicates, no holes). The attempt budget
    resets whenever progress is made since the last failure; a source
    that fails ``max_attempts`` times without yielding anything new
    propagates the error wrapped in
    :class:`~paddle_tpu.resilience.RetryError`.

    The trade-off is that of any re-openable stream: the source must be
    restartable and deterministic up to the failure point (recordio
    files, dataset generators are; an already-shuffled stream should be
    wrapped BEFORE ``shuffle``).
    """
    import time as _time
    from ..resilience.retry import RetryError, _jitter_rng, logger
    sleep = sleep or _time.sleep

    def robust_reader():
        delivered = 0
        failures_since_progress = 0
        while True:
            it = reader()
            to_skip = delivered  # fast-forward past items already out
            skipped = 0
            progressed = False
            try:
                for item in it:
                    if skipped < to_skip:
                        skipped += 1
                        continue
                    yield item
                    delivered += 1
                    progressed = True
                return
            except retry_on as e:  # noqa: B902 — tuple from caller
                if progressed:
                    failures_since_progress = 1
                else:
                    failures_since_progress += 1
                if failures_since_progress >= max_attempts:
                    raise RetryError('retry_reader',
                                     failures_since_progress, e) from e
                delay = backoff * (2 ** (failures_since_progress - 1))
                if jitter:
                    delay *= 1.0 + _jitter_rng.uniform(0.0, jitter)
                logger.warning(
                    'retry_reader: source failed at item %d (%r); '
                    'reopening (attempt %d/%d, sleeping %.3fs)',
                    delivered, e, failures_since_progress, max_attempts,
                    delay)
                if delay > 0:
                    sleep(delay)

    return robust_reader


def batch(reader, batch_size, drop_last=False):
    """Parity: python/paddle/batch.py — the ragged tail batch IS yielded
    (reference batch.py:34). r3: drop_last used to default True for
    shape stability, but scripts whose datasets are smaller than one
    batch (high-level-api cifar10_small_test_set) then see ZERO batches
    and silently train nothing. A ragged tail costs one extra XLA
    compile per program; pass drop_last=True to keep shapes constant."""

    def batch_reader():
        r = reader()
        b = []
        for instance in r:
            b.append(instance)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader


def map_readers(func, *readers):
    def reader():
        rs = []
        for r in readers:
            rs.append(r())
        for e in map(func, *rs):
            yield e

    return reader


def shuffle(reader, buf_size):
    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if len(buf) > 0:
            random.shuffle(buf)
            for b in buf:
                yield b

    return data_reader


def chain(*readers):
    def reader():
        rs = []
        for r in readers:
            rs.append(r())
        for e in itertools.chain(*rs):
            yield e

    return reader


class ComposeNotAligned(ValueError):
    pass


def compose(*readers, **kwargs):
    check_alignment = kwargs.pop('check_alignment', True)

    def make_tuple(x):
        if isinstance(x, tuple):
            return x
        else:
            return (x, )

    def reader():
        rs = []
        for r in readers:
            rs.append(r())
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum(list(map(make_tuple, outputs)), ())
        else:
            for outputs in zip(*rs):
                for o in outputs:
                    if o is None:
                        raise ComposeNotAligned(
                            "outputs of readers are not aligned.")
                yield sum(list(map(make_tuple, outputs)), ())

    return reader


def buffered(reader, size):
    """Background-thread prefetch buffer (parity: decorator.py::buffered)."""

    class EndSignal():
        pass

    end = EndSignal()

    def read_worker(r, q):
        for d in r:
            q.put(d)
        q.put(end)

    def data_reader():
        r = reader()
        q = Queue.Queue(maxsize=size)
        t = threading.Thread(target=read_worker, args=(r, q))
        t.daemon = True
        t.start()
        e = q.get()
        while e != end:
            yield e
            e = q.get()

    return data_reader


def firstn(reader, n):
    def firstn_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item

    return firstn_reader


class XmapEndSignal():
    pass


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over a reader with worker threads.
    Parity: decorator.py::xmap_readers."""
    end = XmapEndSignal()

    def read_worker(reader, in_queue):
        for i in reader():
            in_queue.put(i)
        in_queue.put(end)

    def order_read_worker(reader, in_queue):
        in_order = 0
        for i in reader():
            in_queue.put((in_order, i))
            in_order += 1
        in_queue.put(end)

    def handle_worker(in_queue, out_queue, mapper):
        sample = in_queue.get()
        while not isinstance(sample, XmapEndSignal):
            r = mapper(sample)
            out_queue.put(r)
            sample = in_queue.get()
        in_queue.put(end)
        out_queue.put(end)

    def order_handle_worker(in_queue, out_queue, mapper, out_order):
        ins = in_queue.get()
        while not isinstance(ins, XmapEndSignal):
            order, sample = ins
            r = mapper(sample)
            while order != out_order[0]:
                pass
            out_queue.put(r)
            out_order[0] += 1
            ins = in_queue.get()
        in_queue.put(end)
        out_queue.put(end)

    def xreader():
        in_queue = Queue.Queue(buffer_size)
        out_queue = Queue.Queue(buffer_size)
        out_order = [0]
        target = order_read_worker if order else read_worker
        t = threading.Thread(target=target, args=(reader, in_queue))
        t.daemon = True
        t.start()
        target = order_handle_worker if order else handle_worker
        args = (in_queue, out_queue, mapper, out_order) if order else (
            in_queue, out_queue, mapper)
        workers = []
        for i in range(process_num):
            worker = threading.Thread(target=target, args=args)
            worker.daemon = True
            workers.append(worker)
        for w in workers:
            w.start()

        finish = 0
        while finish < process_num:
            sample = out_queue.get()
            if isinstance(sample, XmapEndSignal):
                finish += 1
            else:
                yield sample

    return xreader
