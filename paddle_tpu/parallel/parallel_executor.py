"""ParallelExecutor — data-parallel training over a device mesh.

Parity: python/paddle/fluid/parallel_executor.py + the C++ SSA-graph
executor (paddle/fluid/framework/details/*). The reference clones the
program per GPU, schedules ops over threads, and allreduces gradients with
NCCL. TPU design: ONE program, batch dimension sharded over mesh axis 'dp',
parameters replicated; XLA's SPMD partitioner inserts the gradient psum
(over ICI) automatically. Multi-host: call jax.distributed.initialize first
(see paddle_tpu.parallel.transpiler).

Since the partition subsystem landed (PARTITIONING.md) this class is a
thin facade: it builds a :class:`~paddle_tpu.partition.Partitioner` for
its mesh and hands every run to the SAME ``Executor.run`` /
``Executor.run_chained`` code path the single-device executor uses —
one dispatch engine, one compiled-program cache (keys carry the
partitioner's (mesh, sharding) token), K-step chaining and async fetch
included.
"""
import numpy as np
import jax

from ..executor import Executor, global_scope
from ..framework import default_main_program
from ..partition import Partitioner

__all__ = ['ParallelExecutor', 'ExecutionStrategy', 'BuildStrategy']


class ExecutionStrategy(object):
    """Parity: core.ParallelExecutor.ExecutionStrategy. Scheduling
    knobs for the reference's threaded SSA-graph executor
    (num_threads, allow_op_delay, num_iteration_per_drop_scope). The
    whole-block XLA design has no per-op scheduler to tune — the
    compiler owns the schedule — so these are carried as attributes for
    script compatibility and the executor reads none of them."""

    def __init__(self):
        self.num_threads = 0
        self.allow_op_delay = False
        self.num_iteration_per_drop_scope = 100
        self.use_event = True


class BuildStrategy(object):
    """Parity: core.ParallelExecutor.BuildStrategy (reduce/broadcast
    strategy, debug graphviz path). Gradient aggregation strategy is
    XLA SPMD's choice on this path; debug_graphviz_path is honored by
    paddle_tpu.graphviz.draw callers."""

    class ReduceStrategy(object):
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy(object):
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = \
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.debug_graphviz_path = ""


class ParallelExecutor(object):
    def __init__(self, use_cuda=True, loss_name=None, main_program=None,
                 share_vars_from=None, num_threads=None,
                 allow_op_delay=False, use_tpu=True, num_devices=None,
                 mesh=None, partitioner=None, exec_strategy=None,
                 build_strategy=None, zero_stage=None,
                 zero_bucket_bytes=None):
        self._program = main_program or default_main_program()
        if partitioner is None:
            partitioner = Partitioner(mesh=mesh, num_devices=num_devices)
        self._partitioner = partitioner
        self._mesh = partitioner.mesh
        self._loss_name = loss_name
        self._exec_strategy = exec_strategy or ExecutionStrategy()
        self._build_strategy = build_strategy or BuildStrategy()
        self._exe = Executor(partitioner=partitioner)
        if share_vars_from is not None:
            # parity: share scope with the training ParallelExecutor
            self._scope = share_vars_from._scope
        else:
            self._scope = global_scope()
        # ZeRO-2 by default on a dp mesh (PERF.md "ZeRO-2 and
        # collective overlap"): a TRAINING ParallelExecutor
        # (loss_name given, real dp extent) shards the optimizer state
        # and reduce-scatters the bucketed gradient tail. The rewrite
        # is the exact identity on every fetched value — the replicated
        # path stays available with zero_stage=0.
        self._zero = {'stage': 0, 'dp': 1}
        dp = partitioner.axis_extent('dp')
        if loss_name is not None and dp > 1:
            from ..compiler import zero as _zero
            self._zero = _zero.apply_zero(
                self._program, dp, stage=zero_stage,
                bucket_bytes=zero_bucket_bytes)

    @property
    def partitioner(self):
        return self._partitioner

    @property
    def device_count(self):
        return self._partitioner.device_count

    def cache_info(self):
        return self._exe.cache_info()

    def reset_cache_info(self):
        return self._exe.reset_cache_info()

    def _var_sharding(self, name):
        """Facade kept for callers of the pre-partitioner API."""
        return self._partitioner.var_sharding(self._program, name)

    def _shardings(self, feed, state_names):
        part = self._partitioner
        return (part.feed_shardings(feed),
                part.state_shardings(self._program, state_names),
                part.replicated)

    def run(self, fetch_list=None, feed=None, feed_dict=None,
            return_numpy=True, async_fetch=False):
        feed = feed if feed is not None else feed_dict or {}
        return self._exe.run(program=self._program, feed=feed,
                             fetch_list=fetch_list or [],
                             scope=self._scope,
                             return_numpy=return_numpy,
                             async_fetch=async_fetch)

    def run_chained(self, feed_list=None, fetch_list=None,
                    return_numpy=True, async_fetch=False, program=None):
        """K steps in ONE sharded dispatch — the same
        ``Executor.run_chained`` the single-device trainer uses, with
        the scan carry sharded per the partitioner (PERF.md "Dispatch
        pipelining"). Falls back to sequential sharded runs under the
        same conditions as the plain executor."""
        return self._exe.run_chained(program or self._program,
                                     feed_list=feed_list,
                                     fetch_list=fetch_list,
                                     scope=self._scope,
                                     return_numpy=return_numpy,
                                     async_fetch=async_fetch)

    def bcast_params(self):
        """Parity: ParallelExecutor.bcast_params (NCCL bcast). Params are
        replicated by sharding; nothing to do."""
        pass

    def compile_stats(self, fetch_list, feed):
        """Compile-time PER-DEVICE buffer accounting for the sharded
        step (no execution): XLA's memory_analysis on the AOT-lowered
        program. Used to prove ZeRO accumulator slicing at real scale
        (VERDICT r3 #4) — sliced optimizer state shows up as smaller
        per-device argument bytes.

        Returns dict(argument_bytes, temp_bytes, output_bytes) for ONE
        device of the mesh."""
        from ..core.lowering import lower_block
        program = self._program
        scope = self._scope
        part = self._partitioner
        fetch_names, feed, state_in, state_out, static_env = \
            self._exe._prep_lowering(program, feed, fetch_list, scope,
                                     consume_readers=False)
        # NB: lowers the FULL program (no pruning), so the accounting
        # covers every declared buffer; Executor.run models the pruned
        # path instead.
        fn = lower_block(program, program.global_block(),
                         sorted(feed.keys()), fetch_names, state_in,
                         state_out, static_env=static_env)
        feeds_s = part.feed_shardings(feed)
        state_s = part.state_shardings(program, state_in)
        out_state_s = part.state_shardings(program, state_out)
        jitted = part.partition(part.trace_wrap(fn),
                                in_shardings=(feeds_s, state_s),
                                out_shardings=(None, out_state_s))
        state = {n: scope.raw(n) for n in state_in}
        abstract = jax.tree_util.tree_map(
            lambda v: jax.ShapeDtypeStruct(np.shape(v),
                                           np.asarray(v).dtype),
            (feed, state))
        with part.run_context():
            comp = jitted.lower(*abstract).compile()
        # shared memory_analysis reader (observability.perf) — same
        # dict the perf ledger's byte fields come from
        from ..observability import perf as _perf
        return _perf.memory_dict(comp)
