"""ParallelExecutor — data-parallel training over a device mesh.

Parity: python/paddle/fluid/parallel_executor.py + the C++ SSA-graph
executor (paddle/fluid/framework/details/*). The reference clones the
program per GPU, schedules ops over threads, and allreduces gradients with
NCCL. TPU design: ONE program, batch dimension sharded over mesh axis 'dp',
parameters replicated; XLA's SPMD partitioner inserts the gradient psum
(over ICI) automatically. Multi-host: call jax.distributed.initialize first
(see paddle_tpu.parallel.transpiler).
"""
import numpy as np
import jax

from ..executor import Executor, global_scope, as_numpy
from ..framework import default_main_program, Program, Variable
from ..core.lowering import lower_block, RNG_KEY
from ..lod import SequenceTensor
from .mesh import get_mesh

__all__ = ['ParallelExecutor', 'ExecutionStrategy', 'BuildStrategy']


class ExecutionStrategy(object):
    """Parity: core.ParallelExecutor.ExecutionStrategy. Scheduling
    knobs for the reference's threaded SSA-graph executor
    (num_threads, allow_op_delay, num_iteration_per_drop_scope). The
    whole-block XLA design has no per-op scheduler to tune — the
    compiler owns the schedule — so these are carried as attributes for
    script compatibility and the executor reads none of them."""

    def __init__(self):
        self.num_threads = 0
        self.allow_op_delay = False
        self.num_iteration_per_drop_scope = 100
        self.use_event = True


class BuildStrategy(object):
    """Parity: core.ParallelExecutor.BuildStrategy (reduce/broadcast
    strategy, debug graphviz path). Gradient aggregation strategy is
    XLA SPMD's choice on this path; debug_graphviz_path is honored by
    paddle_tpu.graphviz.draw callers."""

    class ReduceStrategy(object):
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy(object):
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = \
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.debug_graphviz_path = ""


class ParallelExecutor(object):
    def __init__(self, use_cuda=True, loss_name=None, main_program=None,
                 share_vars_from=None, num_threads=None,
                 allow_op_delay=False, use_tpu=True, num_devices=None,
                 mesh=None, exec_strategy=None, build_strategy=None):
        self._program = main_program or default_main_program()
        self._mesh = mesh or get_mesh(num_devices)
        self._loss_name = loss_name
        self._exec_strategy = exec_strategy or ExecutionStrategy()
        self._build_strategy = build_strategy or BuildStrategy()
        self._exe = Executor()
        if share_vars_from is not None:
            # parity: share scope with the training ParallelExecutor
            self._scope = share_vars_from._scope
        else:
            self._scope = global_scope()
        self._cache = {}

    @property
    def device_count(self):
        return int(np.prod(list(self._mesh.shape.values())))

    def _var_sharding(self, name):
        """NamedSharding for a state var: Variable.sharding (set via
        ParamAttr(sharding=...) / set_sharding / the ZeRO transpiler) is
        honored; axis names absent from this mesh degrade to replicated
        on that dim. Default: replicated (reference semantics)."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        from .mesh import clean_spec
        mesh = self._mesh
        var = self._program.global_block()._find_var_recursive(name)
        spec = getattr(var, 'sharding', None) if var is not None else None
        if not spec:
            return NamedSharding(mesh, P())
        spec = clean_spec(spec, mesh)
        # a sharding decided against a different world size (e.g. ZeRO
        # slicing at transpile time before the mesh existed) may not
        # divide this mesh's extent — degrade that dim to replicated
        # rather than failing the whole step
        extents = dict(zip(mesh.axis_names, mesh.devices.shape))
        shape = getattr(var, 'shape', None) or ()
        for d, entry in enumerate(spec):
            if entry is None or d >= len(shape):
                continue
            names = entry if isinstance(entry, (tuple, list)) else (entry,)
            e = int(np.prod([extents.get(a, 1) for a in names]))
            if e and int(shape[d]) % e != 0:
                spec[d] = None
        return NamedSharding(mesh, P(*spec))

    def _shardings(self, feed, state_names):
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh = self._mesh
        repl = NamedSharding(mesh, P())

        def feed_shard(v):
            if isinstance(v, SequenceTensor):
                return SequenceTensor(
                    NamedSharding(mesh, P('dp')), NamedSharding(mesh,
                                                                P('dp')),
                    None if v.sub_lengths is None else
                    NamedSharding(mesh, P('dp')))
            return NamedSharding(mesh, P('dp'))

        feeds_s = {k: feed_shard(v) for k, v in feed.items()}
        state_s = {n: self._var_sharding(n) for n in state_names}
        return feeds_s, state_s, repl

    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True):
        feed = feed if feed is not None else feed_dict or {}
        program = self._program
        scope = self._scope
        fetch_names, feed, state_in, state_out, static_env = \
            self._exe._prep_lowering(program, feed, fetch_list, scope)

        from ..executor import program_cache_key
        from ..debugging import nan_checks_enabled
        guard = nan_checks_enabled()
        key = program_cache_key(program, feed, static_env, fetch_names,
                                state_in, state_out, guard)
        multiproc = jax.process_count() > 1
        jitted = self._cache.get(key)
        if jitted is None or multiproc:
            # only the cache-miss path and the multi-process globalize
            # path consume the shardings; skip the per-step block walk
            # on the single-process hot path
            feeds_s, state_s, repl = self._shardings(feed, state_in)
        if jitted is None:
            from ..core import lowering as _lowering
            fn = lower_block(program, program.global_block(),
                             sorted(feed.keys()), fetch_names, state_in,
                             state_out, static_env=static_env)

            def fn_with_mesh(feeds, state, _fn=fn):
                # activations with Variable.sharding get a
                # with_sharding_constraint during tracing
                with _lowering.sharding_mesh(self._mesh):
                    return _fn(feeds, state)

            out_state_s = {n: self._var_sharding(n) for n in state_out}
            # multi-process: fetches must come back fully replicated so
            # every process can materialize them as numpy
            fetch_s = repl if multiproc else None
            if guard:
                # debug mode: functionalize per-op NaN/Inf checks; no
                # donation so state survives a thrown error
                from jax.experimental import checkify
                jitted = jax.jit(
                    checkify.checkify(fn_with_mesh),
                    in_shardings=(feeds_s, state_s),
                    out_shardings=(None, (fetch_s, out_state_s)))
            else:
                jitted = jax.jit(
                    fn_with_mesh, in_shardings=(feeds_s, state_s),
                    out_shardings=(fetch_s, out_state_s),
                    donate_argnums=(1,))
            self._cache[key] = jitted

        state = {n: scope.raw(n) for n in state_in}
        if multiproc:
            # Each process feeds its LOCAL batch shard (the reference's
            # per-trainer reader semantics); host-local values become
            # global arrays over the multi-process mesh. Replicated
            # state (params, RNG key) passes the full local value.
            def _globalize(v, s, full_value):
                if isinstance(v, jax.Array) and not v.is_fully_addressable:
                    return v          # already a global array (prev step)
                arr = np.asarray(v)
                # full_value: every process holds the WHOLE tensor
                # (startup-initialized state) — pass global_shape so a
                # dp-sharded var (ZeRO slice) extracts this process's
                # shards instead of inferring a nprocs-times-larger
                # global. Feeds are per-process chunks: infer global.
                return jax.make_array_from_process_local_data(
                    s, arr, global_shape=arr.shape if full_value
                    else None)
            feed = jax.tree_util.tree_map(
                lambda v, s: _globalize(v, s, False), feed, feeds_s)
            # state shardings are per-var NamedShardings; broadcast over
            # the (possibly pytree) state value's leaves
            state = {n: jax.tree_util.tree_map(
                lambda v, s=state_s[n]: _globalize(v, s, True), state[n])
                for n in state}
        with self._mesh:
            if guard:
                err, (fetches, new_state) = jitted(feed, state)
                err.throw()
            else:
                fetches, new_state = jitted(feed, state)
        for n, v in new_state.items():
            scope.set_var(n, v)
        if getattr(program, '_half_inference', None):
            # Float16Transpiler boundary contract, same as Executor.run
            from ..executor import _to_f32_fetch
            fetches = [_to_f32_fetch(f) for f in fetches]
        if return_numpy:
            fetches = [as_numpy(f) for f in fetches]
        return fetches

    def bcast_params(self):
        """Parity: ParallelExecutor.bcast_params (NCCL bcast). Params are
        replicated by sharding; nothing to do."""
        pass

    def compile_stats(self, fetch_list, feed):
        """Compile-time PER-DEVICE buffer accounting for the sharded
        step (no execution): XLA's memory_analysis on the AOT-lowered
        program. Used to prove ZeRO accumulator slicing at real scale
        (VERDICT r3 #4) — sliced optimizer state shows up as smaller
        per-device argument bytes.

        Returns dict(argument_bytes, temp_bytes, output_bytes) for ONE
        device of the mesh."""
        program = self._program
        scope = self._scope
        fetch_names, feed, state_in, state_out, static_env = \
            self._exe._prep_lowering(program, feed, fetch_list, scope,
                                     consume_readers=False)
        # NB: lowers the FULL program (no pruning), mirroring
        # ParallelExecutor.run — Executor.cost_analysis models the
        # pruning Executor.run path instead.
        from ..core import lowering as _lowering
        fn = lower_block(program, program.global_block(),
                         sorted(feed.keys()), fetch_names, state_in,
                         state_out, static_env=static_env)

        def fn_with_mesh(feeds, state, _fn=fn):
            with _lowering.sharding_mesh(self._mesh):
                return _fn(feeds, state)

        feeds_s, state_s, repl = self._shardings(feed, state_in)
        out_state_s = {n: self._var_sharding(n) for n in state_out}
        jitted = jax.jit(fn_with_mesh, in_shardings=(feeds_s, state_s),
                         out_shardings=(None, out_state_s))
        state = {n: scope.raw(n) for n in state_in}
        abstract = jax.tree_util.tree_map(
            lambda v: jax.ShapeDtypeStruct(np.shape(v),
                                           np.asarray(v).dtype),
            (feed, state))
        with self._mesh:
            comp = jitted.lower(*abstract).compile()
        ma = comp.memory_analysis()
        return {
            'argument_bytes': int(ma.argument_size_in_bytes),
            'temp_bytes': int(ma.temp_size_in_bytes),
            'output_bytes': int(ma.output_size_in_bytes),
        }
