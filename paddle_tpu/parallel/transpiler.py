"""Program transpilers.

Parity: python/paddle/fluid/transpiler/* —
- DistributeTranspiler (distribute_transpiler.py): the reference splits
  parameters into blocks spread round-robin over parameter servers and
  rewrites the trainer program with send/recv ops over gRPC. TPU design:
  the pserver role is absorbed into the collective path — every trainer
  holds a replica (or ZeRO shard) of the parameters, gradients are psum'd
  over ICI/DCN by XLA SPMD, and multi-host process groups bootstrap via
  jax.distributed.initialize. The transpile() API is kept so reference
  scripts run unchanged; get_pserver_program returns a no-op heartbeat
  program and documents the mapping.
- memory_optimization_transpiler: XLA already does liveness-based buffer
  reuse; the shim keeps the API and records remat hints.
- inference_transpiler: folds batch_norm into the preceding conv/fc at the
  IR level (same rewrite as the reference's fuse pass).
"""
import os

from ..framework import Program, default_main_program

__all__ = ['DistributeTranspiler', 'DistributeTranspilerSimple',
           'InferenceTranspiler', 'memory_optimize', 'release_memory']


class DistributeTranspiler(object):
    def __init__(self):
        self.trainer_id = 0
        self.trainers = 1
        self.pserver_endpoints = []
        self.sync_mode = True
        self._program = None

    def transpile(self, trainer_id, program=None, pservers="127.0.0.1:6174",
                  trainers=1, sync_mode=True, split_method=None,
                  slice_var_up=True):
        self.trainer_id = trainer_id
        self.trainers = trainers
        self.pserver_endpoints = [e for e in pservers.split(",") if e]
        self.sync_mode = sync_mode
        self._program = program or default_main_program()
        # Multi-host bootstrap: one process per trainer. The coordinator is
        # the first pserver endpoint (reused as the JAX coordination
        # service address); single-process setups skip initialization.
        if trainers > 1 and os.environ.get('PADDLE_TPU_DISTRIBUTED', '0') \
                == '1':
            import jax
            jax.distributed.initialize(
                coordinator_address=self.pserver_endpoints[0],
                num_processes=trainers, process_id=trainer_id)
        return self

    def get_trainer_program(self):
        """The trainer program is the original program: gradient exchange
        is implicit in the sharded step (XLA psum over ICI/DCN), matching
        the send/recv semantics of the reference in sync mode."""
        return self._program

    def get_pserver_program(self, endpoint):
        """No parameter server exists on the TPU stack; optimizer state is
        replicated (or ZeRO-sharded via sharding attrs). Returns an empty
        heartbeat program so pserver launcher scripts stay functional."""
        return Program()

    def get_startup_program(self, endpoint, pserver_program=None):
        return Program()


class DistributeTranspilerSimple(DistributeTranspiler):
    """Parity: distribute_transpiler_simple.py — same collective mapping."""
    pass


def memory_optimize(input_program, skip_opt_set=None, print_log=False,
                    level=0):
    """Parity: memory_optimization_transpiler.memory_optimize. Buffer
    liveness/reuse is handled by XLA; donation of persistable state is
    already performed by the Executor. No-op that keeps the API."""
    if print_log:
        print("[paddle_tpu] memory_optimize: buffer reuse delegated to "
              "XLA; persistable state donated by the executor.")
    return input_program


def release_memory(input_program, skip_opt_set=None):
    return input_program


class InferenceTranspiler(object):
    """Parity: inference_transpiler.py (conv+bn fold, relu fuse)."""

    def transpile(self, program, place=None, scope=None):
        self._fold_batch_norm(program)
        return program

    def _fold_batch_norm(self, program):
        """Mark BN ops as test-mode; actual folding of scale into conv
        weights happens numerically at load time when weights are static.
        XLA fuses the remaining scale/shift into the conv epilogue, which
        achieves the same runtime effect as the reference's weight
        rewrite."""
        for block in program.blocks:
            for op in block.ops:
                if op.type == 'batch_norm':
                    op.attrs['is_test'] = True
                if op.type == 'dropout':
                    op.attrs['is_test'] = True
        program._bump_version()
