"""Program transpilers.

Parity: python/paddle/fluid/transpiler/* —
- DistributeTranspiler (distribute_transpiler.py): the reference splits
  parameters into blocks spread round-robin over parameter servers and
  rewrites the trainer program with send/recv ops over gRPC. TPU design:
  the pserver role is absorbed into the collective path — every trainer
  holds a replica (or ZeRO shard) of the parameters, gradients are psum'd
  over ICI/DCN by XLA SPMD, and multi-host process groups bootstrap via
  jax.distributed.initialize. The transpile() API is kept so reference
  scripts run unchanged; get_pserver_program returns a no-op heartbeat
  program and documents the mapping.
- memory_optimization_transpiler: facade over the compiler's
  ``buffer_reuse`` liveness pass plus the remat hint (COMPILER.md).
- inference_transpiler: facade over the compiler's ``bn_fold`` pass —
  folds batch_norm into the preceding conv/fc at the IR level (same
  rewrite as the reference's fuse pass).
"""
import os

from ..framework import Program, default_main_program

__all__ = ['DistributeTranspiler', 'DistributeTranspilerSimple',
           'InferenceTranspiler', 'memory_optimize', 'release_memory']

# The optimizer update-op -> accumulator-slot table moved to
# compiler.zero.OPTIMIZER_STATE_SLOTS (the ZeRO engine owns it).
class DistributeTranspiler(object):
    def __init__(self):
        self.trainer_id = 0
        self.trainers = 1
        self.pserver_endpoints = []
        self.sync_mode = True
        self._program = None
        self.sliced_vars = []
        self.replicated_vars = []

    def transpile(self, trainer_id, program=None, pservers="127.0.0.1:6174",
                  trainers=1, sync_mode=True, split_method=None,
                  slice_var_up=True, zero_stage=None, bucket_bytes=None):
        if trainers < 1:
            raise ValueError('trainers must be >= 1, got %d' % trainers)
        if not 0 <= trainer_id < trainers:
            raise ValueError(
                'trainer_id must be in [0, %d) but is %d — every '
                'launched trainer process needs a distinct id below '
                'the trainer count' % (trainers, trainer_id))
        self.trainer_id = trainer_id
        self.trainers = trainers
        self.pserver_endpoints = [e for e in pservers.split(",") if e]
        self.sync_mode = sync_mode
        if not sync_mode:
            # ref distribute_transpiler.py:196-204: async SGD applies
            # each trainer's grads without barriers. XLA SPMD collectives
            # are inherently synchronous; silently running async scripts
            # as sync would change convergence behavior without signal.
            import warnings
            warnings.warn(
                "DistributeTranspiler(sync_mode=False): async parameter-"
                "server SGD has no TPU mapping — XLA collectives are "
                "synchronous. This job will run in SYNC mode (gradients "
                "psum'd every step). Set sync_mode=True to silence.",
                UserWarning, stacklevel=2)
        self._program = program or default_main_program()
        # Multi-host bootstrap: one process per trainer. The coordinator is
        # the first pserver endpoint (reused as the JAX coordination
        # service address); single-process setups skip initialization.
        # multihost.initialize bounds the handshake: an unreachable
        # coordinator raises a typed BootstrapTimeout after a few
        # retried attempts instead of hanging this trainer forever.
        if trainers > 1 and os.environ.get('PADDLE_TPU_DISTRIBUTED', '0') \
                == '1':
            from ..multihost import initialize as _mh_initialize
            _mh_initialize(self.pserver_endpoints[0],
                           num_processes=trainers,
                           process_id=trainer_id)
        if slice_var_up:
            self._slice_optimizer_state(zero_stage=zero_stage,
                                        bucket_bytes=bucket_bytes)
        return self

    def _dp_size(self):
        """Shard count for ZeRO slicing: the dp extent of the active mesh
        (single- or multi-process), falling back to the trainer count.
        Routed through the partition subsystem so the transpiler and
        the Partitioner can never disagree about an axis extent."""
        from ..partition import mesh_axis_extent
        from .mesh import _current_mesh
        if _current_mesh is not None:
            return mesh_axis_extent(_current_mesh, 'dp')
        return max(self.trainers, 1)

    def _slice_optimizer_state(self, zero_stage=None, bucket_bytes=None):
        """ZeRO sharding — the TPU mapping of the reference's
        param-slice-per-pserver layout.

        The reference slices each parameter round-robin over pservers and
        runs the optimizer remotely on the slice, so each host holds
        1/N of the optimizer state (ref: python/paddle/fluid/transpiler/
        distribute_transpiler.py::transpile, slice_var_up). Here the
        whole mode lives in ``compiler.zero.apply_zero`` (PERF.md
        "ZeRO-2 and collective overlap"): stage >= 1 marks each
        accumulator Variable sharded over the 'dp' mesh axis on its
        first divisible dim — per TENSOR, falling back to replicated
        only for tensors no dim of which divides — and stage >= 2
        (the default) additionally rewrites the gradient tail so every
        eligible gradient rides a bucketed reduce-scatter and the
        update runs on local shards before the parameter all-gather.
        ``self.sliced_vars`` / ``self.replicated_vars`` record the
        per-tensor outcome."""
        from ..compiler import zero as _zero
        dp = self._dp_size()
        self.sliced_vars = []
        self.replicated_vars = []
        if dp <= 1:
            return
        summary = _zero.apply_zero(self._program, dp, stage=zero_stage,
                                   bucket_bytes=bucket_bytes)
        self.sliced_vars = summary.get('sliced_names', [])
        self.replicated_vars = summary.get('replicated_names', [])

    def get_trainer_program(self):
        """The trainer program is the original program: gradient exchange
        is implicit in the sharded step (XLA psum over ICI/DCN), matching
        the send/recv semantics of the reference in sync mode."""
        return self._program

    def get_pserver_program(self, endpoint):
        """No parameter server exists on the TPU stack; optimizer state is
        ZeRO-sharded across the dp axis instead (see
        _slice_optimizer_state). Returns an empty heartbeat program so
        pserver launcher scripts stay functional — and WARNS, because a
        cluster script that expected remote optimization would otherwise
        idle silently (r2 weak #6)."""
        import warnings
        warnings.warn(
            "get_pserver_program(%r): the TPU stack has no parameter "
            "server — optimizer state is ZeRO-sharded over the dp mesh "
            "axis on the trainers and gradients ride XLA collectives. "
            "Returning an empty heartbeat program; this process performs "
            "NO optimization work." % (endpoint,),
            UserWarning, stacklevel=2)
        return Program()

    def get_startup_program(self, endpoint, pserver_program=None):
        return Program()


class DistributeTranspilerSimple(DistributeTranspiler):
    """Parity: distribute_transpiler_simple.py — same collective mapping."""
    pass


# the reference exports it under this name (transpiler/__init__.py)
SimpleDistributeTranspiler = DistributeTranspilerSimple


def memory_optimize(input_program, skip_opt_set=None, print_log=False,
                    level=0):
    """Parity: memory_optimization_transpiler.memory_optimize — now a
    facade over the compiler's ``buffer_reuse`` pass (COMPILER.md).

    Two layers: (1) the liveness pass annotates every op with the names
    whose last reader it is (``__release__``), and lowering drops those
    environment references as the block executes — the reference's
    in-place variable reuse, with fetch/state names guarded at lowering
    time; (2) the program is marked for rematerialization: the forward
    segment of a training step runs under ``jax.checkpoint`` in sqrt-N
    segments, trading recompute for the activation memory that actually
    dominates on TPU."""
    from ..compiler.pass_base import PassContext
    from ..compiler.passes import BufferReuse
    input_program._remat = True
    res = BufferReuse(skip=skip_opt_set).run(
        input_program, PassContext(protected=frozenset(skip_opt_set
                                                       or ())))
    input_program._bump_version()
    if print_log:
        print("[paddle_tpu] memory_optimize: %d buffer-release "
              "annotations (compiler buffer_reuse pass) + forward "
              "segment marked for rematerialization (jax.checkpoint)."
              % res.vars_released)
    return input_program


def release_memory(input_program, skip_opt_set=None):
    return input_program


class InferenceTranspiler(object):
    """Parity: inference_transpiler.py (conv+bn fold) — now a facade
    over the compiler's ``bn_fold`` pass (COMPILER.md).

    For every conv2d/depthwise_conv2d/mul whose single consumer is a
    batch_norm and whose weights are resident in the scope,

        w' = w * scale / sqrt(var + eps)        (per output channel)
        b' = bias - mean * scale / sqrt(var + eps)

    the BN op is REMOVED and an elementwise_add(axis=1) with the new
    bias takes over BN's output name; remaining BN/dropout ops flip to
    test mode. Same in-place contract and signature as the reference;
    the rewrite itself lives in ``compiler.passes.BatchNormFolding``.
    """

    def transpile(self, program, place=None, scope=None):
        from ..compiler.pass_base import PassContext
        from ..compiler.passes import BatchNormFolding
        from ..executor import global_scope
        BatchNormFolding().run(program,
                               PassContext(scope=scope or global_scope()))
        return program
