"""Program transpilers.

Parity: python/paddle/fluid/transpiler/* —
- DistributeTranspiler (distribute_transpiler.py): the reference splits
  parameters into blocks spread round-robin over parameter servers and
  rewrites the trainer program with send/recv ops over gRPC. TPU design:
  the pserver role is absorbed into the collective path — every trainer
  holds a replica (or ZeRO shard) of the parameters, gradients are psum'd
  over ICI/DCN by XLA SPMD, and multi-host process groups bootstrap via
  jax.distributed.initialize. The transpile() API is kept so reference
  scripts run unchanged; get_pserver_program returns a no-op heartbeat
  program and documents the mapping.
- memory_optimization_transpiler: XLA already does liveness-based buffer
  reuse; the shim keeps the API and records remat hints.
- inference_transpiler: folds batch_norm into the preceding conv/fc at the
  IR level (same rewrite as the reference's fuse pass).
"""
import os

from ..framework import Program, default_main_program

__all__ = ['DistributeTranspiler', 'DistributeTranspilerSimple',
           'InferenceTranspiler', 'memory_optimize', 'release_memory']

# Optimizer update ops -> their accumulator-state input slots.
# (ref: the pserver held exactly these vars — its optimize blocks ran on
# param slices, distribute_transpiler.py::_create_table_optimize_block)
_OPTIM_STATE_SLOTS = {
    'momentum': ('Velocity',),
    'adam': ('Moment1', 'Moment2'),
    'adamax': ('Moment', 'InfNorm'),
    'adagrad': ('Moment',),
    'decayed_adagrad': ('Moment',),
    'adadelta': ('AvgSquaredGrad', 'AvgSquaredUpdate'),
    'rmsprop': ('MeanSquare', 'Moment'),
    'ftrl': ('SquaredAccumulator', 'LinearAccumulator'),
}


class DistributeTranspiler(object):
    def __init__(self):
        self.trainer_id = 0
        self.trainers = 1
        self.pserver_endpoints = []
        self.sync_mode = True
        self._program = None
        self.sliced_vars = []

    def transpile(self, trainer_id, program=None, pservers="127.0.0.1:6174",
                  trainers=1, sync_mode=True, split_method=None,
                  slice_var_up=True):
        self.trainer_id = trainer_id
        self.trainers = trainers
        self.pserver_endpoints = [e for e in pservers.split(",") if e]
        self.sync_mode = sync_mode
        if not sync_mode:
            # ref distribute_transpiler.py:196-204: async SGD applies
            # each trainer's grads without barriers. XLA SPMD collectives
            # are inherently synchronous; silently running async scripts
            # as sync would change convergence behavior without signal.
            import warnings
            warnings.warn(
                "DistributeTranspiler(sync_mode=False): async parameter-"
                "server SGD has no TPU mapping — XLA collectives are "
                "synchronous. This job will run in SYNC mode (gradients "
                "psum'd every step). Set sync_mode=True to silence.",
                UserWarning, stacklevel=2)
        self._program = program or default_main_program()
        # Multi-host bootstrap: one process per trainer. The coordinator is
        # the first pserver endpoint (reused as the JAX coordination
        # service address); single-process setups skip initialization.
        if trainers > 1 and os.environ.get('PADDLE_TPU_DISTRIBUTED', '0') \
                == '1':
            import jax
            jax.distributed.initialize(
                coordinator_address=self.pserver_endpoints[0],
                num_processes=trainers, process_id=trainer_id)
        if slice_var_up:
            self._slice_optimizer_state()
        return self

    def _dp_size(self):
        """Shard count for ZeRO slicing: the dp extent of the active mesh
        (single- or multi-process), falling back to the trainer count."""
        from .mesh import _current_mesh
        if _current_mesh is not None:
            return int(dict(zip(_current_mesh.axis_names,
                                _current_mesh.devices.shape)).get('dp', 1))
        return max(self.trainers, 1)

    def _slice_optimizer_state(self):
        """ZeRO-style optimizer-state sharding — the TPU mapping of the
        reference's param-slice-per-pserver layout.

        The reference slices each parameter round-robin over pservers and
        runs the optimizer remotely on the slice, so each host holds
        1/N of the optimizer state (ref: python/paddle/fluid/transpiler/
        distribute_transpiler.py::transpile, slice_var_up). Here the same
        memory win comes from marking each accumulator Variable sharded
        over the 'dp' mesh axis on dim 0: XLA SPMD keeps the moment
        buffers resident as [N/dp, ...] shards, partitions the elementwise
        update, and gathers only the param output (params stay replicated,
        matching trainer semantics). Consumed by
        ParallelExecutor._var_sharding.
        """
        dp = self._dp_size()
        self.sliced_vars = []
        if dp <= 1:
            return
        block = self._program.global_block()
        for op in block.ops:
            slots = _OPTIM_STATE_SLOTS.get(op.type)
            if not slots:
                continue
            for slot in slots:
                for name in op.inputs.get(slot, []):
                    var = block._find_var_recursive(name)
                    if var is None or var.sharding is not None:
                        continue  # keep explicit (e.g. tp) shardings
                    # slice over the FIRST dp-divisible dim (r3: was
                    # dim-0-only, which left odd-leading-dim
                    # accumulators — biases, embeddings with ragged
                    # vocab — fully replicated)
                    for d, extent in enumerate(var.shape):
                        if extent % dp == 0 and extent >= dp:
                            var.sharding = (None,) * d + ('dp',)
                            self.sliced_vars.append(name)
                            break
        self._program._bump_version()

    def get_trainer_program(self):
        """The trainer program is the original program: gradient exchange
        is implicit in the sharded step (XLA psum over ICI/DCN), matching
        the send/recv semantics of the reference in sync mode."""
        return self._program

    def get_pserver_program(self, endpoint):
        """No parameter server exists on the TPU stack; optimizer state is
        ZeRO-sharded across the dp axis instead (see
        _slice_optimizer_state). Returns an empty heartbeat program so
        pserver launcher scripts stay functional — and WARNS, because a
        cluster script that expected remote optimization would otherwise
        idle silently (r2 weak #6)."""
        import warnings
        warnings.warn(
            "get_pserver_program(%r): the TPU stack has no parameter "
            "server — optimizer state is ZeRO-sharded over the dp mesh "
            "axis on the trainers and gradients ride XLA collectives. "
            "Returning an empty heartbeat program; this process performs "
            "NO optimization work." % (endpoint,),
            UserWarning, stacklevel=2)
        return Program()

    def get_startup_program(self, endpoint, pserver_program=None):
        return Program()


class DistributeTranspilerSimple(DistributeTranspiler):
    """Parity: distribute_transpiler_simple.py — same collective mapping."""
    pass


# the reference exports it under this name (transpiler/__init__.py)
SimpleDistributeTranspiler = DistributeTranspilerSimple


def memory_optimize(input_program, skip_opt_set=None, print_log=False,
                    level=0):
    """Parity: memory_optimization_transpiler.memory_optimize.

    Buffer liveness/reuse is XLA's job and persistable state is already
    donated by the Executor; what the TPU stack CAN still trade is
    activation memory for recompute. This marks the program for
    rematerialization: the lowering wraps the forward segment of a
    training step in ``jax.checkpoint``, so the backward pass
    recomputes activations instead of keeping them live — the moral
    equivalent of the reference's in-place variable reuse, aimed at the
    memory that actually dominates on TPU."""
    input_program._remat = True
    input_program._bump_version()
    if print_log:
        print("[paddle_tpu] memory_optimize: forward segment marked for "
              "rematerialization (jax.checkpoint); buffer reuse is "
              "XLA's, persistable state donated by the executor.")
    return input_program


def release_memory(input_program, skip_opt_set=None):
    return input_program


class InferenceTranspiler(object):
    """Parity: inference_transpiler.py (conv+bn fold).

    The reference rewrites conv weights in place so inference programs
    drop their batch_norm ops entirely
    (python/paddle/fluid/transpiler/inference_transpiler.py::
    _fuse_conv_bn / _fuse_param). Same rewrite here, at the Program IR
    level: for every conv2d whose single consumer is a batch_norm,

        w' = w * scale / sqrt(var + eps)        (per output channel)
        b' = bias - mean * scale / sqrt(var + eps)

    the BN op is REMOVED and an elementwise_add(axis=1) with the new
    bias takes over BN's output name. Remaining BN/dropout ops are
    flipped to test mode.
    """

    def transpile(self, program, place=None, scope=None):
        from ..executor import global_scope
        scope = scope or global_scope()
        self._fuse_conv_bn(program, scope)
        self._mark_test_mode(program)
        return program

    def _consumers(self, program, name):
        return [op for b in program.blocks for op in b.ops
                if name in op.input_arg_names]

    def _fuse_conv_bn(self, program, scope):
        import numpy as np
        block = program.global_block()
        # a filter with ANY other consumer (another conv, a sub-block op,
        # a fetch helper) cannot be rewritten in place: each use would
        # need its own scaled copy
        filter_uses = {}
        for b in program.blocks:
            for op in b.ops:
                for name in op.input_arg_names:
                    filter_uses[name] = filter_uses.get(name, 0) + 1
        i = 0
        while i < len(block.ops):
            op = block.ops[i]
            if op.type not in ('conv2d', 'depthwise_conv2d'):
                i += 1
                continue
            out_name = op.outputs['Output'][0]
            consumers = self._consumers(program, out_name)
            if len(consumers) != 1 or consumers[0].type != 'batch_norm':
                i += 1
                continue
            bn = consumers[0]
            w_name = op.inputs['Filter'][0]
            if filter_uses.get(w_name, 0) > 1:
                i += 1
                continue
            vals = {}
            ok = True
            for slot in ('Scale', 'Bias', 'Mean', 'Variance'):
                v = scope.raw(bn.inputs[slot][0])
                if v is None:
                    ok = False
                    break
                vals[slot] = np.asarray(v, np.float32)
            w_val = scope.raw(w_name)
            if not ok or w_val is None:
                i += 1
                continue
            w_val = np.asarray(w_val, np.float32)
            eps = float(bn.attrs.get('epsilon', 1e-5))
            alpha = vals['Scale'] / np.sqrt(vals['Variance'] + eps)
            new_w = w_val * alpha[:, None, None, None]
            new_b = vals['Bias'] - vals['Mean'] * alpha

            bias_var = block.create_var(
                name=w_name + '.bn_fold_bias', shape=list(new_b.shape),
                dtype='float32', persistable=True)
            scope.set_var(w_name, new_w.astype(w_val.dtype))
            scope.set_var(bias_var.name, new_b.astype(np.float32))

            bn_idx = block.ops.index(bn)
            bn_out = bn.outputs['Y'][0]
            block.remove_op(bn_idx)
            block.insert_op(bn_idx, type='elementwise_add',
                            inputs={'X': [out_name],
                                    'Y': [bias_var.name]},
                            outputs={'Out': [bn_out]},
                            attrs={'axis': 1})
            i += 1
        program._bump_version()

    def _mark_test_mode(self, program):
        for block in program.blocks:
            for op in block.ops:
                if op.type in ('batch_norm', 'dropout'):
                    op.attrs['is_test'] = True
        program._bump_version()
