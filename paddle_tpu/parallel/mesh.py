"""Device-mesh management.

The TPU replacement for the reference's device list + NCCL communicator
bootstrap (paddle/fluid/platform/nccl_helper.h): a jax.sharding.Mesh whose
axes name the parallelism kinds (dp = data, mp = tensor, pp = pipeline
stage, sp = sequence). Collectives ride ICI within a host's mesh slice and
DCN across hosts — placement is XLA's job once shardings are annotated.
"""
import numpy as np

_current_mesh = None


def clean_spec(spec, mesh, ndim=None):
    """Sanitize a Variable.sharding tuple against a mesh: axis names not in
    the mesh degrade to None (replicated on that dim); optionally truncate
    to ndim. Shared by ParallelExecutor in_shardings and the lowering's
    with_sharding_constraint pass so both interpret specs identically."""
    axes = set(mesh.axis_names)

    def clean(entry):
        if isinstance(entry, (tuple, list)):
            kept = tuple(a for a in entry if a in axes)
            return kept or None
        return entry if entry in axes else None

    out = [clean(e) for e in spec]
    if ndim is not None:
        out = out[:ndim]
    return out


def set_mesh(mesh):
    global _current_mesh
    _current_mesh = mesh
    return mesh


def get_mesh(num_devices=None, axes=None, shape=None):
    """Build (or return the cached) mesh.

    axes defaults to 1-D ('dp',). Pass shape=dict(dp=4, mp=2) for
    multi-axis meshes.
    """
    global _current_mesh
    import jax
    from jax.sharding import Mesh
    if _current_mesh is not None and num_devices is None and shape is None:
        return _current_mesh
    devices = jax.devices()
    if shape:
        axes = tuple(shape.keys())
        dims = tuple(shape.values())
        n = int(np.prod(dims))
        mesh = Mesh(np.asarray(devices[:n]).reshape(dims), axes)
    else:
        n = num_devices or len(devices)
        axes = axes or ('dp',)
        mesh = Mesh(np.asarray(devices[:n]).reshape((n,)), axes)
    _current_mesh = mesh
    return mesh
