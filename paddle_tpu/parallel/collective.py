"""Functional collective API over mesh axes.

Parity: the reference exposes collectives implicitly through NCCL-backed
ops inserted by ParallelExecutor / distribute_transpiler
(paddle/fluid/platform/nccl_helper.h). Here they are thin, explicit
wrappers over jax.lax collectives for use inside shard_map'ed model code
(ring attention, ZeRO gathers, pipeline sends). Under plain jit SPMD you
normally don't call these — XLA inserts the collectives from shardings.
"""
import contextlib
import time

import jax
import jax.numpy as jnp

__all__ = ['all_reduce', 'all_gather', 'reduce_scatter', 'broadcast',
           'ring_permute', 'barrier', 'axis_index', 'axis_size',
           'observe_collective', 'timed_collective']


def observe_collective(op, seconds, payload_bytes=None):
    """Record one collective's measured wall into
    ``collective_seconds{op=}`` (OBSERVABILITY.md). The collective
    functions below only ever run under a trace — XLA owns their
    runtime wall — so the observations come from the call sites that
    CAN measure: standalone collective micro-timings in
    ``tools/partition_bench.py --zero`` (the overlap-fraction
    denominator) and host-side resharding paths."""
    from .. import observability as _obs
    reg = _obs.default_registry()
    reg.histogram('collective_seconds',
                  'measured wall per collective dispatch',
                  op=op).observe(seconds)
    if payload_bytes is not None:
        reg.counter('collective_bytes_total',
                    'payload bytes through measured collectives',
                    op=op).inc(int(payload_bytes))


@contextlib.contextmanager
def timed_collective(op, payload_bytes=None):
    """Time a block (a dispatched + blocked-on collective) into
    ``collective_seconds{op=}``."""
    t0 = time.perf_counter()
    yield
    observe_collective(op, time.perf_counter() - t0, payload_bytes)


def all_reduce(x, axis_name='dp', op='sum'):
    fn = {'sum': jax.lax.psum, 'max': jax.lax.pmax, 'min': jax.lax.pmin,
          'mean': jax.lax.pmean, 'avg': jax.lax.pmean}[op]
    return fn(x, axis_name)


def all_gather(x, axis_name='dp', axis=0, tiled=True):
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name='dp', axis=0):
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=axis,
                                tiled=True)


def broadcast(x, axis_name='dp', root=0):
    idx = jax.lax.axis_index(axis_name)
    return jax.lax.psum(jnp.where(idx == root, x, jnp.zeros_like(x)),
                        axis_name)


def ring_permute(x, axis_name='sp', offset=1):
    n = jax.lax.psum(1, axis_name)
    perm = [(i, (i + offset) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name, perm)


def barrier(axis_name='dp'):
    """A psum over a unit — forces cross-device synchronization."""
    return jax.lax.psum(jnp.ones(()), axis_name)


def axis_index(axis_name='dp'):
    return jax.lax.axis_index(axis_name)


def axis_size(axis_name='dp'):
    return jax.lax.psum(1, axis_name)
