from .mesh import get_mesh, set_mesh  # noqa
from .parallel_executor import ParallelExecutor  # noqa
from .transpiler import (DistributeTranspiler,  # noqa
                         DistributeTranspilerSimple, InferenceTranspiler,
                         memory_optimize, release_memory)
