"""Program inspection: pseudo-code pretty printer + graphviz export.

Parity: python/paddle/fluid/debuger.py (pprint_program_codes,
pprint_block_codes, draw_block_graphviz) reworked over the paddle_tpu IR
(framework.Program/Block/Operator instead of protobuf descs).
"""
from . import framework
from .graphviz import GraphPreviewGenerator

__all__ = ['pprint_program_codes', 'pprint_block_codes',
           'draw_block_graphviz']

_HL_HEAD = '\033[33m'
_HL_TAIL = '\033[0m'


def _repr_var(var):
    lod = ', lod=%d' % var.lod_level if getattr(var, 'lod_level', 0) \
        else ''
    return "%s[%s%s]  # %s" % (
        var.name, 'x'.join(str(d) for d in (var.shape or ())), lod,
        var.dtype)


def _repr_attr(name, value):
    if isinstance(value, framework.Block):
        return "%s=block_%d" % (name, value.idx)
    if hasattr(value, 'idx') and hasattr(value, 'ops'):
        return "%s=block_%d" % (name, value.idx)
    r = repr(value)
    if len(r) > 40:
        r = r[:37] + '...'
    return "%s=%s" % (name, r)


def repr_op(op):
    outs = ", ".join(n for ns in op.outputs.values() for n in ns)
    ins = ", ".join("%s=[%s]" % (slot, ",".join(ns))
                    for slot, ns in sorted(op.inputs.items()))
    attrs = ", ".join(_repr_attr(k, v)
                      for k, v in sorted(op.attrs.items()))
    return "%s = %s(%s)%s" % (outs or '_', op.type, ins,
                              ('  # ' + attrs) if attrs else '')


def pprint_block_codes(block, show_backward=False, highlights=None):
    highlights = set(highlights or [])
    lines = ["# block %d (parent %d)" % (block.idx, block.parent_idx)]
    lines.append("# variables:")
    for name, var in sorted(block.vars.items()):
        mark = ' (persistable)' if getattr(var, 'persistable', False) \
            else ''
        lines.append("#   " + _repr_var(var) + mark)
    for op in block.ops:
        # our IR's backward is one marker op (not per-op *_grad descs)
        if not show_backward and (op.type.endswith('_grad') or
                                  op.type == 'backward_marker'):
            continue
        text = repr_op(op)
        if op.type in highlights or \
                any(n in highlights for ns in op.outputs.values()
                    for n in ns):
            text = _HL_HEAD + text + _HL_TAIL
        lines.append(text)
        sub = op.attrs.get('sub_block')
        if sub is not None:
            for sl in pprint_block_codes(sub, show_backward,
                                         highlights).splitlines():
                lines.append("    " + sl)
    return "\n".join(lines)


def pprint_program_codes(program, show_backward=False):
    return "\n\n".join(pprint_block_codes(b, show_backward)
                       for b in program.blocks)


def draw_block_graphviz(block, highlights=None, path="./temp.dot"):
    """Write the block's dataflow graph as graphviz source."""
    highlights = set(highlights or [])
    g = GraphPreviewGenerator("program block %d" % block.idx)
    var_nodes = {}

    def var_node(name):
        if name not in var_nodes:
            var = block._find_var_recursive(name) \
                if hasattr(block, '_find_var_recursive') else None
            if var is not None and getattr(var, 'persistable', False):
                var_nodes[name] = g.add_param(
                    name, str(var.dtype), highlight=name in highlights)
            else:
                var_nodes[name] = g.add_arg(name,
                                            highlight=name in highlights)
        return var_nodes[name]

    for op in block.ops:
        op_node = g.add_op(op.type)
        for ns in op.inputs.values():
            for n in ns:
                g.add_edge(var_node(n), op_node)
        for ns in op.outputs.values():
            for n in ns:
                g.add_edge(op_node, var_node(n))
    g.graph.save(path)
    return path
