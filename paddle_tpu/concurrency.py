"""Go-style channels / select.

Parity: python/paddle/fluid/concurrency.py (Go, make_channel,
channel_send/recv/close, Select). The reference schedules goroutine
sub-blocks on the C++ threaded executor; on the XLA path a traced program
is single-dispatch, so channels here are HOST-side primitives for
pipelining readers/trainers around the device step (the same role the
reference's channels play in its CSP examples), built on queue.Queue.
``Go`` runs its body eagerly on a thread pool at run time.
"""
import contextlib
import time
import queue
import threading

__all__ = ['Go', 'make_channel', 'channel_send', 'channel_recv',
           'channel_close', 'Select']


class Channel(object):
    """Typed bounded channel. capacity=0 -> synchronous handoff."""

    def __init__(self, dtype, capacity=0):
        self.dtype = dtype
        self._q = queue.Queue(maxsize=capacity if capacity > 0 else 1)
        self._closed = threading.Event()
        self._sync = capacity == 0

    def send(self, value):
        # Poll with a timeout so a close() while we're blocked on a full
        # queue wakes us up instead of deadlocking the producer thread.
        while True:
            if self._closed.is_set():
                return False
            try:
                self._q.put(value, timeout=0.05)
                return True
            except queue.Full:
                continue

    def recv(self):
        while True:
            try:
                return True, self._q.get(timeout=0.05)
            except queue.Empty:
                if self._closed.is_set():
                    return False, None

    def close(self):
        self._closed.set()

    @property
    def closed(self):
        return self._closed.is_set() and self._q.empty()


def make_channel(dtype, capacity=0):
    return Channel(dtype, capacity)


def channel_send(channel, value, is_copy=False):
    if not isinstance(channel, Channel):
        raise TypeError("channel_send needs a Channel")
    return channel.send(value)


def channel_recv(channel, return_value=None):
    if not isinstance(channel, Channel):
        raise TypeError("channel_recv needs a Channel")
    ok, value = channel.recv()
    return value, ok


def channel_close(channel):
    channel.close()


class Go(object):
    """`with Go(): body()` — the body closure runs on a daemon thread
    (the host-side analogue of the reference's go_op sub-block)."""

    def __init__(self, name=None):
        self.name = name
        self._fns = []
        self._threads = []

    def __enter__(self):
        return self

    def run(self, fn, *args, **kwargs):
        self._fns.append((fn, args, kwargs))

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        for fn, args, kwargs in self._fns:
            t = threading.Thread(target=fn, args=args, kwargs=kwargs,
                                 daemon=True)
            t.start()
            self._threads.append(t)
        return True


class Select(object):
    """Poll several channel actions; run the first ready case.
    Parity (host-side): concurrency.py::Select."""

    def __init__(self, name=None):
        self._cases = []
        self._default = None

    @contextlib.contextmanager
    def case(self, channel_action_fn, channel, value=None, is_copy=False):
        body = []
        yield body.append
        self._cases.append((channel_action_fn, channel, value, body))

    @contextlib.contextmanager
    def default(self):
        body = []
        yield body.append
        self._default = body

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        return True

    def run(self):
        while True:
            for action, ch, value, body in self._cases:
                if action is channel_send:
                    if not ch._q.full():
                        action(ch, value)
                        for fn in body:
                            fn()
                        return True
                else:
                    if not ch._q.empty() or ch._closed.is_set():
                        _, ok = action(ch)
                        for fn in body:
                            fn()
                        return ok
            if self._default is not None:
                for fn in self._default:
                    fn()
                return True
            # nothing ready and no default: back off instead of busy-spin
            time.sleep(0.001)
