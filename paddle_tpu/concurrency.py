"""Go-style channels / select.

Parity: python/paddle/fluid/concurrency.py (Go, make_channel,
channel_send/recv/close, Select). The reference schedules goroutine
sub-blocks on the C++ threaded executor; on the XLA path a traced program
is single-dispatch, so channels here are HOST-side primitives for
pipelining readers/trainers around the device step (the same role the
reference's channels play in its CSP examples), built on queue.Queue.
``Go`` runs its body eagerly on a thread pool at run time.
"""
import collections
import contextlib
import time
import threading

__all__ = ['Go', 'make_channel', 'channel_send', 'channel_recv',
           'channel_close', 'Select']


class Channel(object):
    """Typed Go-style channel under one condition variable.

    capacity=0 is a TRUE rendezvous: send() returns only after a
    receiver has taken the value. close() is race-free with send — both
    take the same lock, so a send can never enqueue after close (the
    check-then-put race ADVICE r1 flagged in the queue.Queue version).
    Values queued before close() still drain through recv() (Go
    semantics); senders still blocked at close() withdraw their
    undelivered item and return False.
    """

    def __init__(self, dtype, capacity=0):
        self.dtype = dtype
        self.capacity = capacity
        self._cond = threading.Condition()
        self._items = collections.deque()   # (value, done_event | None)
        self._recv_waiting = 0
        self._is_closed = False

    def send(self, value):
        with self._cond:
            if self._is_closed:
                return False
            if self.capacity > 0:
                while len(self._items) >= self.capacity:
                    self._cond.wait()
                    if self._is_closed:
                        return False
                self._items.append((value, None))
                self._cond.notify_all()
                return True
            done = threading.Event()
            entry = (value, done)
            self._items.append(entry)
            self._cond.notify_all()
            while not done.is_set():
                if self._is_closed:
                    # withdraw if nobody took it; consumed wins otherwise.
                    # Identity scan, not deque.remove(): == on queued
                    # numpy payloads raises/ambiguates.
                    for idx, queued in enumerate(self._items):
                        if queued is entry:
                            del self._items[idx]
                            return False
                    # not queued -> a receiver popped it; done is being set
                self._cond.wait()
            return True

    def recv(self):
        with self._cond:
            self._recv_waiting += 1
            try:
                while not self._items:
                    if self._is_closed:
                        return False, None
                    self._cond.wait()
                value, done = self._items.popleft()
                if done is not None:
                    done.set()
                self._cond.notify_all()
                return True, value
            finally:
                self._recv_waiting -= 1

    def close(self):
        with self._cond:
            self._is_closed = True
            self._cond.notify_all()

    # ---- Select hooks ------------------------------------------------------
    def try_send(self, value):
        """Atomic non-blocking send: enqueue iff it can complete without
        waiting (room in a buffered channel, or a receiver already
        waiting on a rendezvous channel). Select's send cases use this —
        a separate can_send()-then-send() pair would race another
        selector into a blocked send."""
        with self._cond:
            if self._is_closed:
                return False
            if self.capacity > 0:
                if len(self._items) >= self.capacity:
                    return False
                self._items.append((value, None))
                self._cond.notify_all()
                return True
            if self._recv_waiting <= len(self._items):
                return False
            # a waiting receiver is guaranteed to take it; no need to
            # block for the rendezvous to finish
            self._items.append((value, None))
            self._cond.notify_all()
            return True

    def try_recv(self):
        """Atomic non-blocking recv for Select: (ready, ok, value).
        ready=False means nothing to take and the channel is open — a
        separate can_recv()-then-recv() pair would race another consumer
        into a blocked recv."""
        with self._cond:
            if self._items:
                value, done = self._items.popleft()
                if done is not None:
                    done.set()
                self._cond.notify_all()
                return True, True, value
            if self._is_closed:
                return True, False, None
            return False, False, None

    @property
    def closed(self):
        with self._cond:
            return self._is_closed and not self._items


def make_channel(dtype, capacity=0):
    return Channel(dtype, capacity)


def channel_send(channel, value, is_copy=False):
    if not isinstance(channel, Channel):
        raise TypeError("channel_send needs a Channel")
    return channel.send(value)


def channel_recv(channel, return_value=None):
    if not isinstance(channel, Channel):
        raise TypeError("channel_recv needs a Channel")
    ok, value = channel.recv()
    return value, ok


def channel_close(channel):
    channel.close()


class Go(object):
    """`with Go(): body()` — the body closure runs on a daemon thread
    (the host-side analogue of the reference's go_op sub-block)."""

    def __init__(self, name=None):
        self.name = name
        self._fns = []
        self._threads = []

    def __enter__(self):
        return self

    def run(self, fn, *args, **kwargs):
        self._fns.append((fn, args, kwargs))

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        for fn, args, kwargs in self._fns:
            t = threading.Thread(target=fn, args=args, kwargs=kwargs,
                                 daemon=True)
            t.start()
            self._threads.append(t)
        return True


class Select(object):
    """Poll several channel actions; run the first ready case.
    Parity (host-side): concurrency.py::Select."""

    def __init__(self, name=None):
        self._cases = []
        self._default = None

    @contextlib.contextmanager
    def case(self, channel_action_fn, channel, value=None, is_copy=False):
        body = []
        yield body.append
        self._cases.append((channel_action_fn, channel, value, body))

    @contextlib.contextmanager
    def default(self):
        body = []
        yield body.append
        self._default = body

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        return True

    def run(self):
        while True:
            for action, ch, value, body in self._cases:
                if action is channel_send:
                    if ch.try_send(value):
                        for fn in body:
                            fn()
                        return True
                else:
                    ready, ok, _val = ch.try_recv()
                    if ready:
                        for fn in body:
                            fn()
                        return ok
            if self._default is not None:
                for fn in self._default:
                    fn()
                return True
            # nothing ready and no default: back off instead of busy-spin
            time.sleep(0.001)
