"""convert_reader_to_recordio_file.

Parity: python/paddle/fluid/recordio_writer.py.
"""
import pickle

import numpy as np

from .reader_io import RecordIOWriter

__all__ = ['convert_reader_to_recordio_file',
           'convert_reader_to_recordio_files']


def convert_reader_to_recordio_file(filename, reader_creator, feeder,
                                    compressor=None, max_num_records=1000,
                                    feed_order=None, layout='ptrc'):
    """``layout='ptrc'`` (default) writes the repo's fast chunk format;
    ``layout='reference'`` writes the reference fluid recordio layout
    (recordio_compat: snappy-framed chunks of LoDTensor-bundle records)
    so the emitted file is consumable by the reference runtime."""
    if feed_order is None:
        feed_order = feeder.feed_names
    counter = 0
    if layout == 'reference':
        from .recordio_compat import (ReferenceRecordIOWriter, SNAPPY,
                                      pack_lod_tensor_record)
        from .lod import SequenceTensor
        comp = SNAPPY if compressor is None else compressor
        with ReferenceRecordIOWriter(filename, comp,
                                     max_num_records) as writer:
            for batch in reader_creator():
                res = feeder.feed(batch)
                tensors = []
                for name in feed_order:
                    v = res[name]
                    if isinstance(v, SequenceTensor):  # packed rows + lod
                        rows = v.to_dense_rows()
                        offs = [[0] + list(np.cumsum(
                            np.asarray(lv, dtype='int64')))
                            for lv in v.recursive_sequence_lengths()]
                        tensors.append((rows, offs))
                    else:
                        tensors.append(np.asarray(v))
                writer.write(pack_lod_tensor_record(tensors))
                counter += 1
        return counter
    with RecordIOWriter(filename, compressor, max_num_records) as writer:
        for batch in reader_creator():
            res = feeder.feed(batch)
            slots = [_serialize_slot(res[name]) for name in feed_order]
            writer.write(pickle.dumps(slots, protocol=4))
            counter += 1
    return counter


def _serialize_slot(v):
    """One feed value -> picklable PTRC slot. SequenceTensors are
    tagged so the LoD survives the round trip (padded data alone loses
    it — sequence ops on the read side need the lengths; the reader's
    _rebuild_slots inverts this)."""
    if getattr(v, 'lengths', None) is not None:
        return ('__seq__', np.asarray(v.data), np.asarray(v.lengths),
                None if v.sub_lengths is None else np.asarray(v.sub_lengths))
    return np.asarray(v.data) if hasattr(v, 'data') else np.asarray(v)


def convert_reader_to_recordio_files(filename, batch_per_file,
                                     reader_creator, feeder,
                                     compressor=None, max_num_records=1000,
                                     feed_order=None):
    if feed_order is None:
        feed_order = feeder.feed_names
    f_name, f_ext = filename.rsplit('.', 1) if '.' in filename else \
        (filename, 'recordio')
    lines = []
    f_idx = 0
    counter = 0
    for batch in reader_creator():
        lines.append(batch)
        if len(lines) == batch_per_file:
            filename = "%s-%05d.%s" % (f_name, f_idx, f_ext)
            with RecordIOWriter(filename, compressor,
                                max_num_records) as writer:
                for l in lines:
                    res = feeder.feed(l)
                    slots = [_serialize_slot(res[n]) for n in feed_order]
                    writer.write(pickle.dumps(slots, protocol=4))
                    counter += 1
                lines = []
                f_idx += 1
    return counter
