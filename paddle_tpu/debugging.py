"""Failure detection: debug-mode NaN/Inf guard.

Parity: paddle/fluid/platform/enforce.h + the FLAGS_check_nan_inf
per-op tensor checks (operators run under CheckNanInf when the flag is
set). TPU design: checks must live INSIDE the compiled step — there is
no per-op host boundary to hook — so when the guard is enabled the
lowering inserts a ``checkify.check`` after every float-producing op
(errors carry the op type, output name and input names), the Executor
compiles the step through ``checkify.checkify``, and the functionalized
error is re-raised on the host with that provenance.

Enable with ``fluid.check_nan_inf(True)``, the ``check_nan_inf()``
context manager, or ``PADDLE_TPU_CHECK_NAN_INF=1``.
"""
import contextlib
import os

__all__ = ['check_nan_inf', 'nan_checks_enabled', 'nan_guard']

_CHECK = [os.environ.get('PADDLE_TPU_CHECK_NAN_INF', '0') == '1']


def check_nan_inf(enable=True):
    """Globally enable/disable the per-op NaN/Inf guard (debug mode:
    steps recompile with checks and run slower)."""
    prev = _CHECK[0]
    _CHECK[0] = bool(enable)
    return prev


def nan_checks_enabled():
    return _CHECK[0]


@contextlib.contextmanager
def nan_guard():
    """Context manager form: NaN/Inf checks enabled inside the block."""
    prev = check_nan_inf(True)
    try:
        yield
    finally:
        check_nan_inf(prev)
