"""Deterministic fault injection: the test harness for the resilience
runtime.

Every recovery path (retry, corruption fallback, NaN policies,
auto-resume) must be exercisable in tier-1 on CPU — so faults are
injected deterministically, keyed by named SITES and hit counts, never
by wall clock or randomness:

- :func:`fault_plan` installs a :class:`FaultPlan`; production code
  calls :func:`maybe_fault(site)` at its injection points (checkpoint
  payload write/commit/read, reader pulls). With no plan installed the
  call is a near-free truthiness check.
- :func:`corrupt_checkpoint` / :func:`truncate_checkpoint` damage an
  on-disk checkpoint payload the way real bitrot/preemption does.
- :func:`nan_reader` / :func:`flaky_reader` wrap data readers to emit
  poisoned batches / transient I/O errors at chosen step indices.
- :class:`KillSwitch` raises :class:`SimulatedKill` at a chosen global
  step, modelling a preemption mid-training for auto-resume tests.
"""
import collections
import glob
import os
import re
import time

import numpy as np

__all__ = ['FaultInjected', 'FaultPlan', 'fault_plan', 'maybe_fault',
           'corrupt_checkpoint', 'truncate_checkpoint', 'nan_reader',
           'flaky_reader', 'SimulatedKill', 'KillSwitch']

# injection sites wired into the runtime
SITE_CKPT_WRITE = 'checkpoint.write'      # payload serialization
SITE_CKPT_COMMIT = 'checkpoint.commit'    # between payload and rename
SITE_CKPT_READ = 'checkpoint.read'        # payload deserialization
SITE_READER_NEXT = 'reader.next'          # program-reader batch pull
SITE_TRAINER_STEP = 'trainer.step'        # top of each train-loop step
#   ^ the preemption-delivery site: a plan with ``action=`` fires a
#   side effect (e.g. os.kill(os.getpid(), SIGTERM)) at an exact step,
#   so SIGTERM-mid-chunk recovery is deterministically testable
# serving runtime sites (SERVING.md "Failure domains & SLO guardrails")
SITE_SERVING_RUN = 'serving/run_batch'    # inside the per-attempt run
SITE_SERVING_LOAD = 'serving/load_model'  # model load / hot swap
SITE_SERVING_PAD = 'serving/pad'          # bucket padding stage
# remote-cell RPC sites (RESILIENCE.md "Cross-host elasticity"):
# delay= models a slow/partitioned link, error= a dropped frame or
# reset, and an error at send never touches the wire (retryable)
SITE_REMOTE_SEND = 'remote/send'          # client frame send
SITE_REMOTE_RECV = 'remote/recv'          # client reader pull
SITE_REMOTE_SPAWN = 'remote/spawn'        # spawn_cell provisioning
# autotuner site (COMPILER.md "Schedule search"): fires per candidate
# measurement, so a crashing/OOMing candidate is deterministically
# testable — the sweep must poison the entry and continue
SITE_TUNING_MEASURE = 'tuning/measure'    # per-candidate measurement


class FaultInjected(IOError):
    """The error type injected by default — an IOError subclass so the
    retry/fallback machinery treats it exactly like a real I/O fault,
    while tests can still assert it was synthetic."""

    def __init__(self, site, hit):
        super(FaultInjected, self).__init__(
            'injected fault at %s (hit %d)' % (site, hit))
        self.site = site
        self.hit = hit


class FaultPlan(object):
    """Which hits of which sites fault. ``at`` names 0-based hit
    indices; ``times`` faults the first N hits; ``every`` faults every
    Nth hit. Each matched hit raises ``error`` (a class instantiated
    with (site, hit) for FaultInjected, else called with no args; an
    instance is raised as-is). ``delay`` sleeps that many seconds at
    the injection point before raising — and with ``error=None`` it
    raises nothing at all, modelling a *wedged* (not failed) stage:
    the hang the serving watchdog and ``close(timeout=)`` escalation
    exist to bound."""

    def __init__(self):
        self._rules = collections.defaultdict(list)
        self.hits = collections.Counter()
        self.faults = collections.Counter()

    def inject(self, site, error=FaultInjected, at=None, times=None,
               every=None, delay=None, action=None):
        """``action`` is a zero-arg callable fired at the injection
        point (after ``delay``, before ``error``) — the side-effect
        channel: deliver a real signal, flip a flag, damage a file.
        With ``error=None`` the matched hit performs only the
        delay/action (a wedge, or a pure preemption delivery)."""
        if at is None and times is None and every is None:
            times = 1
        if error is None and delay is None and action is None:
            raise ValueError(
                'error=None requires delay= (a pure hang) or action= '
                '(a pure side effect)')
        self._rules[site].append({'error': error,
                                  'at': None if at is None
                                  else frozenset(at),
                                  'times': times, 'every': every,
                                  'delay': delay, 'action': action})
        return self

    def check(self, site):
        """Record a hit; return the error to raise, or None."""
        hit = self.hits[site]
        self.hits[site] += 1
        for rule in self._rules.get(site, ()):
            matched = (
                (rule['at'] is not None and hit in rule['at']) or
                (rule['times'] is not None and hit < rule['times']) or
                (rule['every'] is not None and
                 (hit + 1) % rule['every'] == 0))
            if not matched:
                continue
            self.faults[site] += 1
            if rule['delay']:
                time.sleep(rule['delay'])
            if rule.get('action') is not None:
                rule['action']()
            err = rule['error']
            if err is None:
                continue          # pure hang: no error to raise
            if isinstance(err, BaseException):
                return err
            if err is FaultInjected or (isinstance(err, type) and
                                        issubclass(err, FaultInjected)):
                return err(site, hit)
            return err()
        return None


_PLANS = []


class _PlanContext(object):
    def __init__(self, plan):
        self.plan = plan

    def __enter__(self):
        _PLANS.append(self.plan)
        return self.plan

    def __exit__(self, *exc):
        _PLANS.remove(self.plan)
        return False


def fault_plan(plan=None):
    """``with fault_plan() as plan: plan.inject(...)`` — installs the
    plan for the dynamic extent of the block."""
    return _PlanContext(plan or FaultPlan())


def maybe_fault(site):
    """Called at runtime injection points; raises per the active plans.
    No-op (one list truthiness check) when no plan is installed."""
    if not _PLANS:
        return
    for plan in tuple(_PLANS):
        err = plan.check(site)
        if err is not None:
            raise err


# ---- on-disk checkpoint damage -------------------------------------------
_SERIAL_RE = re.compile(r'^checkpoint_(\d+)$')


def _pick_serial_dir(checkpoint_dir, serial=None):
    if serial is not None:
        d = os.path.join(checkpoint_dir, 'checkpoint_%d' % serial)
        if not os.path.isdir(d):
            raise IOError('no checkpoint serial %d under %s'
                          % (serial, checkpoint_dir))
        return d
    serials = []
    for name in os.listdir(checkpoint_dir):
        m = _SERIAL_RE.match(name)
        if m and os.path.isdir(os.path.join(checkpoint_dir, name)):
            serials.append(int(m.group(1)))
    if not serials:
        raise IOError('no checkpoints under %s' % checkpoint_dir)
    return os.path.join(checkpoint_dir, 'checkpoint_%d' % max(serials))


def _payload_paths(serial_dir):
    paths = [p for p in glob.glob(os.path.join(serial_dir, '**', '*'),
                                  recursive=True)
             if os.path.isfile(p) and not p.endswith(
                 ('_MANIFEST.json', '_SUCCESS'))]
    if not paths:
        raise IOError('no payload files in %s' % serial_dir)
    # largest file == the tensor payload, the realistic bitrot target
    return sorted(paths, key=os.path.getsize, reverse=True)


def corrupt_checkpoint(checkpoint_dir, serial=None, nbytes=8,
                       path_contains=None):
    """Flip ``nbytes`` bytes in the middle of the (newest, unless
    ``serial`` given) checkpoint's largest payload file WITHOUT
    touching the manifest — exactly what bitrot/torn writes look like.
    ``path_contains`` picks a specific payload file by substring
    instead (e.g. one SHARD of a sharded checkpoint: the validator
    must then name exactly that shard). Returns the damaged file's
    path."""
    paths = _payload_paths(_pick_serial_dir(checkpoint_dir, serial))
    if path_contains is not None:
        paths = [p for p in paths if path_contains in p]
        if not paths:
            raise IOError('no payload file matching %r' % path_contains)
    target = paths[0]
    size = os.path.getsize(target)
    offset = max(0, size // 2 - nbytes // 2)
    with open(target, 'r+b') as f:
        f.seek(offset)
        block = f.read(nbytes)
        f.seek(offset)
        f.write(bytes(b ^ 0xFF for b in block))
        f.flush()
        os.fsync(f.fileno())
    return target


def truncate_checkpoint(checkpoint_dir, serial=None, keep_fraction=0.5):
    """Truncate the largest payload file (torn write / preempted
    writer). Returns the damaged file's path."""
    target = _payload_paths(_pick_serial_dir(checkpoint_dir, serial))[0]
    size = os.path.getsize(target)
    with open(target, 'r+b') as f:
        f.truncate(int(size * keep_fraction))
    return target


# ---- poisoned data -------------------------------------------------------
def _poison(value):
    arr = np.asarray(value)
    if arr.dtype.kind == 'f':
        return np.full_like(arr, np.nan)
    return value


def nan_reader(reader, at_steps, poison=_poison):
    """Wrap a (batched or per-sample) reader so the batches at 0-based
    indices in ``at_steps`` have every float payload replaced with NaN
    — the deterministic poisoned-batch source for anomaly-policy
    tests. Total batch count is unchanged."""
    at_steps = frozenset(at_steps)

    def poisoned_reader():
        for i, item in enumerate(reader()):
            if i not in at_steps:
                yield item
                continue
            if isinstance(item, list):  # a batch of samples
                yield [tuple(poison(v) for v in s) if isinstance(
                    s, tuple) else poison(s) for s in item]
            elif isinstance(item, tuple):
                yield tuple(poison(v) for v in item)
            else:
                yield poison(item)
    return poisoned_reader


def flaky_reader(reader, fail_at, error=FaultInjected):
    """Wrap a reader so pulling the item at each 0-based index in
    ``fail_at`` raises once — the NEXT pass over the reader succeeds at
    that index (a transient fault, which is what retry_reader must
    absorb). Error construction follows FaultPlan rules."""
    remaining = set(fail_at)

    def flaky():
        for i, item in enumerate(reader()):
            if i in remaining:
                remaining.discard(i)
                if isinstance(error, BaseException):
                    raise error
                if error is FaultInjected or (
                        isinstance(error, type) and
                        issubclass(error, FaultInjected)):
                    raise error(SITE_READER_NEXT, i)
                raise error()
            yield item
    return flaky


# ---- simulated preemption ------------------------------------------------
class SimulatedKill(BaseException):
    """Raised by KillSwitch. Derives from BaseException so no
    well-meaning ``except Exception`` recovery path inside the trainer
    can swallow a preemption — exactly like a real SIGKILL wouldn't
    be catchable."""

    def __init__(self, step):
        super(SimulatedKill, self).__init__(
            'simulated kill at global step %d' % step)
        self.step = step


class KillSwitch(object):
    """Event-handler wrapper that raises SimulatedKill once ``at_step``
    steps have completed (counted across epochs):

        trainer.train(..., event_handler=KillSwitch(5, my_handler))

    kills the run right after the 5th EndStepEvent.
    """

    def __init__(self, at_step, handler=None):
        self.at_step = at_step
        self.handler = handler
        self.steps_seen = 0

    def __call__(self, event):
        if self.handler is not None:
            self.handler(event)
        if type(event).__name__ == 'EndStepEvent':
            self.steps_seen += 1
            if self.steps_seen >= self.at_step:
                raise SimulatedKill(self.steps_seen)
