"""Atomic checkpoint commit protocol: manifest, CRCs, fsync, verify.

A checkpoint serial is only visible once it is COMPLETE: the payload is
written into a hidden temp dir next to the target, every file and
directory is fsynced, a JSON manifest recording per-tensor shape/dtype
and CRC32 payload checksums is written last, and the temp dir is
``os.rename``d into place (atomic on POSIX within a filesystem). A kill
at any point leaves either the old serials untouched or an ignorable
``.tmp_*`` dir — never a partially-visible checkpoint.

``verify_checkpoint`` recomputes the CRCs against the manifest; it is
the single validator shared by ``io.load_checkpoint`` (corruption
fallback) and ``tools/check_checkpoint.py`` (CLI).
"""
import binascii
import json
import os

import numpy as np

__all__ = ['MANIFEST_FILENAME', 'TMP_PREFIX', 'CheckpointCorruption',
           'tensor_crc32', 'file_crc32', 'fsync_tree', 'write_manifest',
           'read_manifest', 'verify_checkpoint']

MANIFEST_FILENAME = '_MANIFEST.json'
MANIFEST_VERSION = 1
# hidden prefix: never matches the checkpoint_<serial> pattern, so
# serial scans and pruning ignore in-flight commits
TMP_PREFIX = '.tmp_'


class CheckpointCorruption(IOError):
    """Manifest/CRC validation failed. ``errors`` lists every mismatch."""

    def __init__(self, dirname, errors):
        super(CheckpointCorruption, self).__init__(
            'corrupt checkpoint %s: %s' % (dirname, '; '.join(errors)))
        self.dirname = dirname
        self.errors = list(errors)


def tensor_crc32(arr):
    """CRC32 of an array's raw little-endian payload (C-contiguous)."""
    arr = np.ascontiguousarray(arr)
    return binascii.crc32(arr.tobytes()) & 0xFFFFFFFF


def file_crc32(path, chunk=1 << 20):
    crc = 0
    with open(path, 'rb') as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            crc = binascii.crc32(block, crc)
    return crc & 0xFFFFFFFF


def fsync_tree(root):
    """fsync every file and directory under (and including) ``root`` so
    the subsequent rename publishes fully-durable bytes."""
    for dirpath, _dirnames, filenames in os.walk(root, topdown=False):
        for fn in filenames:
            _fsync_path(os.path.join(dirpath, fn))
        _fsync_path(dirpath)


def _fsync_path(path):
    flags = os.O_RDONLY
    if os.path.isdir(path) and hasattr(os, 'O_DIRECTORY'):
        flags |= os.O_DIRECTORY
    try:
        fd = os.open(path, flags)
    except OSError:
        return  # e.g. sockets/fifos; nothing checkpoint-shaped
    try:
        os.fsync(fd)
    except OSError:
        pass  # some filesystems refuse dir fsync; rename still ordered
    finally:
        os.close(fd)


def _payload_files(dirname):
    """Every file under ``dirname`` except the manifest and the
    _SUCCESS marker, as manifest-keyed relative paths (sorted)."""
    out = []
    for dirpath, _dirnames, filenames in os.walk(dirname):
        for fn in filenames:
            rel = os.path.relpath(os.path.join(dirpath, fn), dirname)
            if rel in (MANIFEST_FILENAME, '_SUCCESS'):
                continue
            out.append(rel)
    return sorted(out)


def write_manifest(dirname, tensors=None, trainer_state=None,
                   backend=None, serial=None, mesh=None, rules=None):
    """Record the manifest for a fully-written payload in ``dirname``.

    ``tensors`` maps name -> numpy array (shape/dtype/CRC32 computed
    here — the npz backend passes the arrays it just serialized) OR
    name -> precomputed ``{'shape', 'dtype'[, 'crc32']}`` dict (the
    orbax backend records metadata without gathering sharded device
    arrays to the host; the sharded backend additionally records the
    resolved ``spec`` and a per-shard ``shards`` table with per-shard
    CRC32s). File-level CRC32 + size is recorded for every payload
    file. ``mesh`` (axis names + shape) and logical-axis ``rules``
    record the topology the payload was laid out for, so a restore on
    a different mesh knows what it is resharding.
    """
    import time
    manifest = {
        'version': MANIFEST_VERSION,
        'backend': backend,
        'serial': serial,
        'saved_at': time.time(),
        'tensors': {},
        'files': {},
    }
    if mesh is not None:
        manifest['mesh'] = mesh
    if rules is not None:
        manifest['rules'] = [list(r) for r in rules]
    for name, arr in (tensors or {}).items():
        if isinstance(arr, dict):
            entry = {'shape': list(arr['shape']),
                     'dtype': str(arr['dtype'])}
            for k in ('crc32', 'spec', 'shards'):
                if k in arr:
                    entry[k] = arr[k]
            manifest['tensors'][name] = entry
            continue
        arr = np.asarray(arr)
        manifest['tensors'][name] = {
            'shape': list(arr.shape),
            'dtype': str(arr.dtype),
            'crc32': tensor_crc32(arr),
        }
    for rel in _payload_files(dirname):
        path = os.path.join(dirname, rel)
        manifest['files'][rel] = {
            'size': os.path.getsize(path),
            'crc32': file_crc32(path),
        }
    if trainer_state is not None:
        manifest['trainer_state'] = trainer_state
    path = os.path.join(dirname, MANIFEST_FILENAME)
    with open(path, 'w') as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    return manifest


def read_manifest(dirname):
    """The parsed manifest, or None when absent/unreadable (legacy
    pre-manifest checkpoints keep loading)."""
    path = os.path.join(dirname, MANIFEST_FILENAME)
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def verify_checkpoint(dirname, check_tensors=True):
    """Validate ``dirname`` against its manifest.

    Returns the list of mismatch descriptions (empty == healthy).
    Missing manifest on a dir that has a ``_SUCCESS`` mark is reported
    as legacy-but-acceptable (empty list): pre-manifest checkpoints
    stay loadable. ``check_tensors`` additionally re-reads npz payloads
    and checks each tensor's CRC/shape/dtype.
    """
    manifest = read_manifest(dirname)
    if manifest is None:
        if os.path.exists(os.path.join(dirname, '_SUCCESS')):
            return []
        return ['missing manifest and _SUCCESS mark']
    errors = []
    on_disk = set(_payload_files(dirname))
    for rel, meta in sorted(manifest.get('files', {}).items()):
        path = os.path.join(dirname, rel)
        if rel not in on_disk:
            errors.append('missing payload file %s' % rel)
            continue
        size = os.path.getsize(path)
        if size != meta['size']:
            errors.append('%s: size %d != manifest %d'
                          % (rel, size, meta['size']))
            continue
        crc = file_crc32(path)
        if crc != meta['crc32']:
            errors.append('%s: crc32 %08x != manifest %08x'
                          % (rel, crc, meta['crc32']))
    extra = on_disk - set(manifest.get('files', {}))
    for rel in sorted(extra):
        errors.append('unmanifested payload file %s' % rel)
    if check_tensors:
        if manifest.get('backend') == 'sharded':
            # runs even with file-level errors present: the per-shard
            # check names the TENSOR a damaged shard belongs to
            from . import sharded as _sharded
            errors.extend(_sharded.verify_tensors(dirname, manifest))
        elif not errors:
            errors.extend(_verify_tensors(dirname, manifest))
    return errors


def _verify_tensors(dirname, manifest):
    """Per-tensor CRC/shape/dtype check for npz payloads. Orbax payloads
    are covered by the file CRCs (re-reading sharded arrays here would
    force a host gather)."""
    tensors = manifest.get('tensors') or {}
    if manifest.get('backend') != 'npz' or not tensors:
        return []
    npz_files = [rel for rel in manifest.get('files', {})
                 if rel.endswith('.npz')]
    errors = []
    seen = set()
    for rel in npz_files:
        try:
            data = np.load(os.path.join(dirname, rel),
                           allow_pickle=False)
        except (OSError, ValueError) as e:
            errors.append('%s: unreadable npz (%r)' % (rel, e))
            continue
        for name in data.files:
            meta = tensors.get(name)
            if meta is None:
                continue
            seen.add(name)
            arr = data[name]
            if list(arr.shape) != list(meta['shape']):
                errors.append('tensor %s: shape %s != manifest %s'
                              % (name, list(arr.shape), meta['shape']))
            elif str(arr.dtype) != meta['dtype']:
                errors.append('tensor %s: dtype %s != manifest %s'
                              % (name, arr.dtype, meta['dtype']))
            elif tensor_crc32(arr) != meta['crc32']:
                errors.append('tensor %s: payload crc mismatch' % name)
    for name in sorted(set(tensors) - seen):
        errors.append('tensor %s missing from payload' % name)
    return errors
