"""Sharded checkpoint payloads: per-shard files, host-side reassembly,
and topology-aware reshard planning (RESILIENCE.md "Sharded checkpoints
& topology portability").

The ``sharded`` checkpoint backend writes ONE ``.npy`` file per array
shard instead of gathering every (possibly mesh-distributed) array to a
single host buffer — the save path of a model sharded over N devices
never materializes a full replica. The manifest records, per tensor,
the global shape/dtype, the resolved sharding spec the arrays carried
at save time, and a shard table (file, index, CRC32); plus the mesh
(axis names + shape) and logical-axis rules, so a restore on a
DIFFERENT mesh knows exactly what it is resharding.

Everything here is numpy + stdlib on purpose: ``tools/reshard_ckpt.py``
converts checkpoints offline between topologies with no live device
mesh at all — resharding is pure slicing arithmetic. The device-side
twin of :func:`resolve_spec` is ``Partitioner.resolve_spec``
(partition/partitioner.py); both degrade unknown axes and non-divisible
dims to replicated, and ``tests/test_elastic.py`` pins their agreement.
"""
import itertools
import os

import numpy as np

from .checkpoint import tensor_crc32

__all__ = ['SHARD_DIR', 'resolve_spec', 'shard_layout', 'shard_state',
           'write_state', 'load_state', 'assemble_tensor',
           'verify_tensors', 'spec_signature',
           'write_state_multiprocess', 'merge_partial_tables',
           'PARTIAL_MANIFEST_FMT']

# payload files live under <serial_dir>/shards/; the name encodes the
# tensor ordinal, not the tensor name (var names like `fc_0.w_0@GRAD`
# are not filesystem-safe) — the manifest shard table is the only map
SHARD_DIR = 'shards'


def resolve_spec(spec, axes, extents, rules, shape):
    """Host-side spec resolution: per-dim mesh axes for ``shape`` on a
    mesh with ``axes``/``extents`` under logical-axis ``rules``.

    Mirrors ``Partitioner.resolve_spec``: mesh axes pass through,
    logical names resolve through the rules, anything unresolvable or
    non-divisible degrades to None (replicated on that dim).
    """
    from ..partition.rules import resolve_entry
    rules = tuple(tuple(r) for r in (rules or ()))
    out = [resolve_entry(e, tuple(axes), rules) for e in (spec or ())]
    out = out[:len(shape)]
    out += [None] * (len(shape) - len(out))
    for d, entry in enumerate(out):
        if entry is None:
            continue
        names = entry if isinstance(entry, (tuple, list)) else (entry,)
        e = int(np.prod([int(extents.get(a, 1)) for a in names]))
        if e <= 1 or int(shape[d]) % e != 0:
            out[d] = None
    return out


def _dim_cuts(spec, shape, extents):
    """Per-dim shard counts for a RESOLVED spec (every entry already a
    mesh axis name/tuple or None, divisibility already degraded)."""
    cuts = []
    for d, entry in enumerate(spec):
        if entry is None:
            cuts.append(1)
            continue
        names = entry if isinstance(entry, (tuple, list)) else (entry,)
        e = int(np.prod([int(extents.get(a, 1)) for a in names]))
        cuts.append(e if e > 1 and int(shape[d]) % e == 0 else 1)
    return cuts


def shard_layout(shape, spec, extents):
    """The shard index table a (shape, resolved-spec) pair splits into:
    a list of ``[[start, stop], ...]`` per-dim bounds, row-major over
    the per-dim cuts. Replicated (or scalar) arrays are ONE shard."""
    shape = [int(s) for s in shape]
    padded = (list(spec or ()) + [None] * len(shape))[:len(shape)]
    cuts = _dim_cuts(padded, shape, extents)
    per_dim = []
    for size, n in zip(shape, cuts):
        step = size // n
        per_dim.append([[i * step, (i + 1) * step] for i in range(n)])
    if not shape:
        return [[]]
    return [list(combo) for combo in itertools.product(*per_dim)]


def _normalize_index(index, shape):
    """A jax ``Shard.index`` (tuple of slices) -> ``[[start, stop]]``
    bounds per dim."""
    out = []
    for sl, size in zip(index, shape):
        start, stop, step = sl.indices(int(size))
        if step != 1:
            raise ValueError('strided shard index %r unsupported' % (sl,))
        out.append([int(start), int(stop)])
    return out


def _array_spec(val):
    """The sharding spec a live array actually carries: its
    NamedSharding PartitionSpec padded to ndim, else fully replicated."""
    sharding = getattr(val, 'sharding', None)
    spec = getattr(sharding, 'spec', None)
    ndim = int(getattr(val, 'ndim', np.ndim(val)))
    if spec is None:
        return [None] * ndim
    out = [list(e) if isinstance(e, tuple) else e for e in tuple(spec)]
    return (out + [None] * ndim)[:ndim]


def shard_state(state):
    """Plan the shard set of a state dict WITHOUT copying anything.

    Yields ``(name, val, spec, shards)`` where ``shards`` is a list of
    ``(bounds, extract)`` pairs — ``extract()`` returns the shard's
    numpy payload. Mesh-distributed jax arrays enumerate their unique
    addressable shards (no full-replica gather); everything else is one
    whole shard. A non-fully-addressable array (multi-process) falls
    back to a gathered single shard — the portable lowest common
    denominator."""
    for name in sorted(state):
        val = state[name]
        shape = tuple(int(s) for s in np.shape(val))
        addressable = getattr(val, 'addressable_shards', None)
        fully = getattr(val, 'is_fully_addressable', True)
        dev_set = getattr(getattr(val, 'sharding', None), 'device_set',
                          ())
        if addressable and fully and len(dev_set) > 1:
            seen = {}
            for sh in addressable:
                bounds = _normalize_index(sh.index, shape)
                key = tuple(tuple(b) for b in bounds)
                if key not in seen:
                    seen[key] = sh
            shards = [(list(list(b) for b in key),
                       (lambda s=sh: np.asarray(s.data)))
                      for key, sh in sorted(seen.items())]
            # a replicated-over-the-mesh array dedupes to one full shard
            yield name, val, _array_spec(val), shards
        else:
            bounds = [[0, s] for s in shape]
            yield name, val, [None] * len(shape), \
                [(bounds, (lambda v=val: np.asarray(v)))]


def write_state(dirname, state, dtypes=None):
    """Write every shard of ``state`` under ``dirname``/``shards``/ and
    return the manifest ``tensors`` table:

        name -> {shape, dtype, spec, shards: [{file, index, crc32}]}

    ``dtypes`` optionally overrides the recorded dtype per name (the
    runtime is 32-bit; the record keeps what was actually written)."""
    shard_root = os.path.join(dirname, SHARD_DIR)
    os.makedirs(shard_root, exist_ok=True)
    tensors = {}
    for t_idx, (name, val, spec, shards) in enumerate(shard_state(state)):
        entries = []
        dtype = None
        for s_idx, (bounds, extract) in enumerate(shards):
            arr = extract()
            dtype = str(arr.dtype)
            rel = '%s/t%04d_s%03d.npy' % (SHARD_DIR, t_idx, s_idx)
            np.save(os.path.join(dirname, rel), arr, allow_pickle=False)
            entries.append({'file': rel, 'index': bounds,
                            'crc32': tensor_crc32(arr)})
        tensors[name] = {
            'shape': [int(s) for s in np.shape(val)],
            'dtype': (dtypes or {}).get(name, dtype),
            'spec': spec,
            'shards': entries,
        }
    return tensors


PARTIAL_MANIFEST_FMT = 'partial_manifest_%03d.json'


def _global_shard_owners(val):
    """The GLOBAL shard table of a jax array: sorted unique bounds
    across every device of its sharding (addressable or not), each
    with the owning device — the lowest device id holding identical
    bounds. Every process computes the SAME table from the sharding
    alone, so concurrent multi-host writers agree on shard ordinals
    and on who writes what without any extra coordination; replicated
    arrays dedupe to one full shard owned by the host of device 0."""
    shape = tuple(int(s) for s in np.shape(val))
    imap = val.sharding.devices_indices_map(shape)
    owners = {}
    for dev, idx in imap.items():
        bounds = tuple(tuple(int(x) for x in b)
                       for b in _normalize_index(idx, shape))
        cur = owners.get(bounds)
        if cur is None or dev.id < cur.id:
            owners[bounds] = dev
    return sorted(owners.items())


def shard_state_local(state, process_index):
    """Multi-process twin of :func:`shard_state`: every process yields
    the same global ``(name, spec, bounds)`` plan; ``extract`` is None
    for shards another process owns. Host values and fully-addressable
    arrays are logically replicated across the pod — process 0 writes
    the single copy."""
    import jax
    for name in sorted(state):
        val = state[name]
        shape = tuple(int(s) for s in np.shape(val))
        if isinstance(val, jax.Array) and not val.is_fully_addressable:
            local = {}
            for sh in val.addressable_shards:
                b = tuple(tuple(int(x) for x in bb)
                          for bb in _normalize_index(sh.index, shape))
                local.setdefault(b, sh)
            shards = []
            for bounds, dev in _global_shard_owners(val):
                if int(dev.process_index) == int(process_index):
                    sh = local[bounds]
                    shards.append(
                        ([list(b) for b in bounds],
                         (lambda s=sh: np.asarray(s.data))))
                else:
                    shards.append(([list(b) for b in bounds], None))
            yield name, val, _array_spec(val), shards
        else:
            bounds = [[0, s] for s in shape]
            extract = (lambda v=val: np.asarray(v)) \
                if int(process_index) == 0 else None
            yield name, val, [None] * len(shape), [(bounds, extract)]


def write_state_multiprocess(dirname, state, process_index,
                             dtypes=None):
    """Concurrent multi-host payload write: THIS process writes only
    the shards it owns (file names carry the globally agreed tensor +
    shard ordinals, so writers can never collide) and returns its
    PARTIAL manifest tensors table — shape/dtype/spec for every
    tensor, shard entries only for locally written files. Process 0
    merges the partials with :func:`merge_partial_tables` after a
    barrier and alone writes the manifest."""
    import jax
    shard_root = os.path.join(dirname, SHARD_DIR)
    os.makedirs(shard_root, exist_ok=True)
    tensors = {}
    for t_idx, (name, val, spec, shards) in enumerate(
            shard_state_local(state, process_index)):
        entries = []
        dtype = str(np.dtype(val.dtype)) if isinstance(val, jax.Array) \
            else str(np.asarray(val).dtype)
        for s_idx, (bounds, extract) in enumerate(shards):
            if extract is None:
                continue          # another host owns (and writes) it
            arr = extract()
            dtype = str(arr.dtype)
            rel = '%s/t%04d_s%03d.npy' % (SHARD_DIR, t_idx, s_idx)
            np.save(os.path.join(dirname, rel), arr,
                    allow_pickle=False)
            entries.append({'file': rel, 'index': bounds,
                            'crc32': tensor_crc32(arr)})
        tensors[name] = {
            'shape': [int(s) for s in np.shape(val)],
            'dtype': (dtypes or {}).get(name, dtype),
            'spec': spec,
            'shards': entries,
        }
    return tensors


def merge_partial_tables(parts):
    """Union of per-process partial tensor tables into one manifest
    table (shard entries sorted by file so the merge is order-stable
    regardless of which process's partial arrives first)."""
    out = {}
    for tab in parts:
        for name, meta in (tab or {}).items():
            cur = out.get(name)
            if cur is None:
                cur = {'shape': meta['shape'], 'dtype': meta['dtype'],
                       'spec': meta['spec'], 'shards': []}
                out[name] = cur
            cur['shards'].extend(meta['shards'])
    for meta in out.values():
        meta['shards'] = sorted(meta['shards'],
                                key=lambda e: e['file'])
    return out


def write_resharded(dirname, state, specs, axes, extents, rules=None):
    """Write HOST arrays as the shard set a TARGET mesh would hold:
    each tensor's spec is resolved against (``axes``, ``extents``,
    ``rules``) and the array sliced accordingly — resharding as pure
    numpy arithmetic, no live device mesh required. This is the
    ``tools/reshard_ckpt.py`` engine. Returns the manifest ``tensors``
    table (same schema as :func:`write_state`)."""
    shard_root = os.path.join(dirname, SHARD_DIR)
    os.makedirs(shard_root, exist_ok=True)
    tensors = {}
    for t_idx, name in enumerate(sorted(state)):
        arr = np.asarray(state[name])
        spec = resolve_spec((specs or {}).get(name) or (), axes,
                            extents, rules, arr.shape)
        entries = []
        for s_idx, bounds in enumerate(
                shard_layout(arr.shape, spec, extents)):
            sel = tuple(slice(int(b[0]), int(b[1])) for b in bounds)
            shard = np.ascontiguousarray(arr[sel])
            rel = '%s/t%04d_s%03d.npy' % (SHARD_DIR, t_idx, s_idx)
            np.save(os.path.join(dirname, rel), shard,
                    allow_pickle=False)
            entries.append({'file': rel, 'index': [list(b)
                                                   for b in bounds],
                            'crc32': tensor_crc32(shard)})
        tensors[name] = {
            'shape': [int(s) for s in arr.shape],
            'dtype': str(arr.dtype),
            'spec': spec,
            'shards': entries,
        }
    return tensors


def assemble_tensor(dirname, meta):
    """Reassemble one tensor from its shard table into a host array."""
    shape = tuple(int(s) for s in meta['shape'])
    out = np.empty(shape, dtype=np.dtype(meta['dtype']))
    for entry in meta['shards']:
        arr = np.load(os.path.join(dirname, entry['file']),
                      allow_pickle=False)
        sel = tuple(slice(int(b[0]), int(b[1]))
                    for b in entry['index'])
        out[sel] = arr.reshape(out[sel].shape)
    return out


def load_state(dirname, manifest):
    """name -> host array for every tensor in a sharded manifest."""
    return {name: assemble_tensor(dirname, meta)
            for name, meta in (manifest.get('tensors') or {}).items()}


def verify_tensors(dirname, manifest):
    """Per-shard validation of a sharded checkpoint: every shard file
    present and loadable, shard shape matching its recorded index
    bounds, per-shard CRC32 matching, and the shard set tiling the
    full tensor (no holes, no double-writes). Errors NAME the broken
    shard — `corrupt one shard` must point at exactly that shard."""
    errors = []
    for name, meta in sorted((manifest.get('tensors') or {}).items()):
        shape = tuple(int(s) for s in meta.get('shape', ()))
        total = int(np.prod(shape)) if shape else 1
        covered = 0
        seen = set()
        shards = meta.get('shards') or []
        if not shards:
            errors.append('tensor %s: empty shard table' % name)
            continue
        for entry in shards:
            rel = entry.get('file', '?')
            tag = 'tensor %s shard %s' % (name, rel)
            path = os.path.join(dirname, rel)
            bounds = tuple(tuple(int(x) for x in b)
                           for b in entry.get('index', ()))
            if bounds in seen:
                errors.append('%s: duplicate shard index %r'
                              % (tag, bounds))
                continue
            seen.add(bounds)
            want_shape = tuple(b[1] - b[0] for b in bounds)
            try:
                arr = np.load(path, allow_pickle=False)
            except (OSError, ValueError) as e:
                errors.append('%s: unreadable (%r)' % (tag, e))
                continue
            if tuple(arr.shape) not in (want_shape,
                                        tuple(s for s in want_shape)):
                errors.append('%s: shape %s != index extents %s'
                              % (tag, list(arr.shape),
                                 list(want_shape)))
                continue
            if str(arr.dtype) != meta.get('dtype'):
                errors.append('%s: dtype %s != manifest %s'
                              % (tag, arr.dtype, meta.get('dtype')))
                continue
            if tensor_crc32(arr) != entry.get('crc32'):
                errors.append('%s: payload crc mismatch' % tag)
                continue
            covered += int(np.prod(want_shape)) if want_shape else 1
        if not any(e.startswith('tensor %s ' % name) or
                   e.startswith('tensor %s:' % name) for e in errors) \
                and covered != total:
            errors.append(
                'tensor %s: shards cover %d of %d elements'
                % (name, covered, total))
    return errors


def spec_signature(tensors):
    """Stable (name, spec) signature of a manifest tensor table — what
    check_checkpoint surfaces and reshard planning diffs against."""
    sig = []
    for name in sorted(tensors or {}):
        spec = (tensors[name].get('spec') or [])
        sig.append((name, tuple(
            tuple(e) if isinstance(e, list) else e for e in spec)))
    return tuple(sig)
