"""Fault-tolerant training runtime.

Production TPU training is preemption-driven by design: workers are
killed mid-step, filesystems flake, datasets hand back garbage batches.
This package makes the runtime survive all of that:

- :mod:`~paddle_tpu.resilience.retry` — transient-error retry with
  exponential backoff + jitter, used by checkpoint I/O and
  ``reader.retry_reader``.
- :mod:`~paddle_tpu.resilience.checkpoint` — the atomic checkpoint
  commit protocol (tmp dir -> fsync -> manifest with per-tensor CRC32s
  -> rename) and manifest verification, shared by ``io.save_checkpoint``
  and ``tools/check_checkpoint.py``.
- :mod:`~paddle_tpu.resilience.anomaly` — NaN/Inf and loss/grad-norm
  spike detection with a configurable policy (``raise`` /
  ``skip_batch`` / ``rollback_to_checkpoint``), wired through
  ``Executor.run`` and ``Trainer.train``.
- :mod:`~paddle_tpu.resilience.faultinject` — a deterministic
  fault-injection harness (I/O errors, corrupted/truncated checkpoint
  payloads, NaN batches, simulated kills) so every recovery path above
  is testable in tier-1.

See RESILIENCE.md for the full design.
"""
from .retry import retry, retry_call, RetryError  # noqa
from .checkpoint import (MANIFEST_FILENAME, write_manifest,  # noqa
                         read_manifest, verify_checkpoint,
                         tensor_crc32, file_crc32, fsync_tree,
                         CheckpointCorruption)
from .anomaly import (AnomalyError, AnomalyGuard, global_norm,  # noqa
                      executor_guard, observe_fetches,
                      any_active as anomaly_guard_active)
from .faultinject import (FaultPlan, fault_plan, maybe_fault,  # noqa
                          FaultInjected, corrupt_checkpoint,
                          truncate_checkpoint, nan_reader, flaky_reader,
                          SimulatedKill, KillSwitch,
                          SITE_SERVING_RUN, SITE_SERVING_LOAD,
                          SITE_SERVING_PAD, SITE_TRAINER_STEP)
from . import sharded  # noqa
from .autoresume import (CheckpointConfig,  # noqa
                         partitioner_for_manifest)

__all__ = [
    'retry', 'retry_call', 'RetryError',
    'write_manifest', 'read_manifest', 'verify_checkpoint',
    'tensor_crc32', 'file_crc32', 'fsync_tree', 'CheckpointCorruption',
    'MANIFEST_FILENAME',
    'AnomalyError', 'AnomalyGuard', 'global_norm', 'executor_guard',
    'FaultPlan', 'fault_plan', 'maybe_fault', 'FaultInjected',
    'corrupt_checkpoint', 'truncate_checkpoint', 'nan_reader',
    'flaky_reader', 'SimulatedKill', 'KillSwitch',
    'SITE_SERVING_RUN', 'SITE_SERVING_LOAD', 'SITE_SERVING_PAD',
    'SITE_TRAINER_STEP', 'sharded',
    'CheckpointConfig', 'partitioner_for_manifest',
]
