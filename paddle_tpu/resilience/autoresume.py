"""CheckpointConfig: the auto-resume contract between Trainer and io.

Parity: the reference trainer.py's CheckpointConfig (checkpoint_dir,
max_num_checkpoints, epoch_interval, step_interval). Extended with the
resilience knobs: backend selection, the secs-based rate limit,
``resume`` to opt out of auto-resume while keeping periodic saves, and
``preempt_save`` — when on (default), ``Trainer.train`` installs
SIGTERM/SIGINT handlers that finish the in-flight K-step chunk, commit
a checkpoint at the chunk boundary, journal ``preempt_save``, and
return cleanly; the resumed run is bit-identical to an uninterrupted
one.

The Trainer saves parameters + optimizer accumulators (persistables) +
its own progress (epoch, step, global step, RNG key) every
``step_interval`` steps and at every ``epoch_interval``-th epoch end;
on construction-with-existing-checkpoints it transparently restores the
newest uncorrupted serial and skips the already-completed steps.

:func:`partitioner_for_manifest` is the mesh-degradation recovery
entry: given the manifest a checkpoint recorded, it rebuilds the
recorded topology when the devices still exist, and otherwise the
largest data-parallel mesh that fits the shrunken fleet — restart
scripts size their Partitioner through it instead of crashing on a
mesh the machine no longer has.
"""

__all__ = ['CheckpointConfig', 'partitioner_for_manifest']


class CheckpointConfig(object):
    def __init__(self, checkpoint_dir=None, max_num_checkpoints=3,
                 epoch_interval=1, step_interval=10,
                 save_interval_secs=0, backend='auto', resume=True,
                 preempt_save=True):
        if checkpoint_dir is None:
            raise ValueError('CheckpointConfig needs a checkpoint_dir')
        if epoch_interval < 1 or step_interval < 1:
            raise ValueError('epoch_interval and step_interval must be '
                             '>= 1')
        self.checkpoint_dir = checkpoint_dir
        self.max_num_checkpoints = max_num_checkpoints
        self.epoch_interval = epoch_interval
        self.step_interval = step_interval
        self.save_interval_secs = save_interval_secs
        self.backend = backend
        self.resume = resume
        self.preempt_save = preempt_save

    def __repr__(self):
        return ('CheckpointConfig(dir=%r, max=%d, epoch_interval=%d, '
                'step_interval=%d)' % (self.checkpoint_dir,
                                       self.max_num_checkpoints,
                                       self.epoch_interval,
                                       self.step_interval))


def partitioner_for_manifest(manifest, place=None):
    """A Partitioner sized for resuming a checkpoint whose manifest
    recorded ``manifest['mesh']``.

    - recorded mesh still fits the local devices: the recorded
      topology is rebuilt exactly (same axes, same shape);
    - FEWER devices than recorded (mesh degradation after a partial
      outage): the largest 1-D data-parallel mesh over the surviving
      devices — ``load_checkpoint`` reshards the restored state onto
      it, so training continues instead of crashing;
    - no/1-device record: the classic ``Partitioner.for_place``
      single-device fallback.
    """
    import numpy as np
    import jax
    from ..partition import Partitioner

    mesh_meta = (manifest or {}).get('mesh') or {}
    shape = [int(s) for s in mesh_meta.get('shape') or (1,)]
    axes = tuple(mesh_meta.get('axes') or ('dp',))
    want = int(np.prod(shape))
    devices = jax.devices()
    if want <= 1 or len(devices) < 1:
        if place is not None:
            return Partitioner.for_place(place)
        return Partitioner(num_devices=1)
    if len(devices) >= want:
        from jax.sharding import Mesh
        arr = np.asarray(devices[:want]).reshape(shape)
        return Partitioner(mesh=Mesh(arr, axes))
    if len(devices) == 1 and place is not None:
        return Partitioner.for_place(place)
    return Partitioner(num_devices=len(devices))
