"""CheckpointConfig: the auto-resume contract between Trainer and io.

Parity: the reference trainer.py's CheckpointConfig (checkpoint_dir,
max_num_checkpoints, epoch_interval, step_interval). Extended with the
resilience knobs: backend selection, the secs-based rate limit, and
``resume`` to opt out of auto-resume while keeping periodic saves.

The Trainer saves parameters + optimizer accumulators (persistables) +
its own progress (epoch, step, global step, RNG key) every
``step_interval`` steps and at every ``epoch_interval``-th epoch end;
on construction-with-existing-checkpoints it transparently restores the
newest uncorrupted serial and skips the already-completed steps.
"""

__all__ = ['CheckpointConfig']


class CheckpointConfig(object):
    def __init__(self, checkpoint_dir=None, max_num_checkpoints=3,
                 epoch_interval=1, step_interval=10,
                 save_interval_secs=0, backend='auto', resume=True):
        if checkpoint_dir is None:
            raise ValueError('CheckpointConfig needs a checkpoint_dir')
        if epoch_interval < 1 or step_interval < 1:
            raise ValueError('epoch_interval and step_interval must be '
                             '>= 1')
        self.checkpoint_dir = checkpoint_dir
        self.max_num_checkpoints = max_num_checkpoints
        self.epoch_interval = epoch_interval
        self.step_interval = step_interval
        self.save_interval_secs = save_interval_secs
        self.backend = backend
        self.resume = resume

    def __repr__(self):
        return ('CheckpointConfig(dir=%r, max=%d, epoch_interval=%d, '
                'step_interval=%d)' % (self.checkpoint_dir,
                                       self.max_num_checkpoints,
                                       self.epoch_interval,
                                       self.step_interval))
