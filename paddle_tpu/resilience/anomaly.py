"""NaN/Inf and spike anomaly detection with a configurable policy.

Complements the compiled-in ``debugging.nan_guard`` (checkify inside
the XLA program, per-op provenance, always ``raise``): this guard lives
at the HOST boundary — it inspects feed batches before a step runs and
losses/grad-norms after — so it can react with policies the compiled
guard cannot: skip the poisoned batch, or roll the params back to the
last good checkpoint. The spike detector flags a loss/grad-norm that
jumps ``spike_factor``x above the rolling median — the classic
precursor of divergence that NaN checks alone miss.

The Executor calls :func:`observe_fetches` on every run (no-op unless a
guard is installed via :func:`executor_guard`), giving raw
``exe.run``-driven loops the same detection as ``Trainer.train``.
"""
import collections
import contextlib
import logging

import numpy as np

from .. import observability as _obs

__all__ = ['AnomalyError', 'AnomalyGuard', 'global_norm',
           'executor_guard', 'observe_fetches', 'any_active']

logger = logging.getLogger('paddle_tpu.resilience')


def _record_trip(guard, counter_key, kind, where, value=None):
    """One anomaly detection: bump the guard's local counter, the
    process registry, and journal the trip (policy included so a
    post-mortem can tell a logged skip from a rollback)."""
    guard.anomalies[counter_key] += 1
    _obs.default_registry().counter(
        'anomaly_trips_total', 'AnomalyGuard detections',
        kind=kind).inc()
    _obs.emit('anomaly', kind=kind, where=where, policy=guard.policy,
              value=value)
    _obs.flight.trip('anomaly', kind=kind, where=where,
                     policy=guard.policy)

POLICIES = ('raise', 'skip_batch', 'rollback_to_checkpoint')


class AnomalyError(FloatingPointError):
    """A non-finite or spiking value was detected under policy 'raise'.
    ``kind`` is 'nan_inf' or 'spike'; ``where`` names the tensor/stage."""

    def __init__(self, kind, where, value=None):
        super(AnomalyError, self).__init__(
            '%s anomaly at %s (value=%r)' % (kind, where, value))
        self.kind = kind
        self.where = where
        self.value = value


def global_norm(arrays):
    """sqrt(sum ||a||^2) over host/device arrays; NaN-propagating, so a
    poisoned gradient shows up as a non-finite norm."""
    total = 0.0
    for a in arrays:
        a = np.asarray(a, dtype=np.float64)
        total += float(np.sum(np.square(a)))
    return float(np.sqrt(total))


def _has_nonfinite(value):
    arr = np.asarray(value)
    if arr.dtype.kind not in 'fc':
        return False
    return not bool(np.isfinite(arr).all())


class AnomalyGuard(object):
    """Detection + policy. One instance per training run.

    policy: 'raise' | 'skip_batch' | 'rollback_to_checkpoint'
    check_feeds: inspect feed batches pre-step (catches poisoned input
        BEFORE it contaminates parameters — the only point where
        'skip_batch' can skip with zero side effects).
    check_metrics: inspect fetched losses/metrics post-step.
    spike_window / spike_factor: rolling-median spike detection over
        observed losses (and grad norms when the trainer monitors
        them); ``spike_window=0`` disables it. The window must hold at
        least ``min_history`` finite values before spikes fire, so
        early-training volatility doesn't trip it.
    monitor_gradients: ask the Trainer to fetch parameter gradients
        each step and feed their global norm through the same
        detection.
    """

    def __init__(self, policy='raise', check_feeds=True,
                 check_metrics=True, spike_window=25, spike_factor=25.0,
                 min_history=5, monitor_gradients=False):
        if policy not in POLICIES:
            raise ValueError('policy must be one of %s, got %r'
                             % (POLICIES, policy))
        self.policy = policy
        self.check_feeds = check_feeds
        self.check_metrics = check_metrics
        self.spike_factor = float(spike_factor)
        self.min_history = int(min_history)
        self.monitor_gradients = monitor_gradients
        self._loss_window = collections.deque(maxlen=spike_window or 1)
        self._norm_window = collections.deque(maxlen=spike_window or 1)
        self._spike_enabled = bool(spike_window)
        # counters exposed for logging/tests
        self.anomalies = collections.Counter()

    # ---- detection -------------------------------------------------------
    def inspect_feed(self, feed):
        """'nan_inf' if any float feed slot holds a non-finite value,
        else None. ``feed`` maps name -> host array / SequenceTensor."""
        for name, val in (feed or {}).items():
            data = getattr(val, 'data', val)  # SequenceTensor -> payload
            try:
                bad = _has_nonfinite(data)
            except (TypeError, ValueError):
                continue
            if bad:
                _record_trip(self, 'feed_nan', 'nan_inf',
                             'feed:%s' % name)
                logger.warning('anomaly: non-finite feed %r', name)
                return AnomalyError('nan_inf', 'feed:%s' % name)
        return None

    def inspect_loss(self, value, where='loss'):
        """Non-finite check + rolling-median spike check on a scalar."""
        try:
            scalar = float(np.asarray(value).ravel()[0])
        except (TypeError, ValueError, IndexError):
            return None
        if not np.isfinite(scalar):
            _record_trip(self, 'loss_nan', 'nan_inf', where, scalar)
            logger.warning('anomaly: non-finite %s (%r)', where, scalar)
            return AnomalyError('nan_inf', where, scalar)
        err = self._inspect_spike(self._loss_window, scalar, where)
        self._loss_window.append(abs(scalar))
        return err

    def inspect_grad_norm(self, norm):
        if not np.isfinite(norm):
            _record_trip(self, 'grad_nan', 'nan_inf', 'grad_norm', norm)
            logger.warning('anomaly: non-finite gradient norm')
            return AnomalyError('nan_inf', 'grad_norm', norm)
        err = self._inspect_spike(self._norm_window, norm, 'grad_norm')
        self._norm_window.append(abs(norm))
        return err

    def _inspect_spike(self, window, scalar, where):
        if not self._spike_enabled or len(window) < self.min_history:
            return None
        baseline = float(np.median(window))
        if baseline > 0 and abs(scalar) > self.spike_factor * baseline:
            _record_trip(self, 'spike', 'spike', where, scalar)
            logger.warning('anomaly: %s spike %.4g (median %.4g x%.1f)',
                           where, scalar, baseline, self.spike_factor)
            return AnomalyError('spike', where, scalar)
        return None

    # ---- executor hook ---------------------------------------------------
    def observe(self, fetch_names, fetches):
        """Executor-level check of every float fetch. Policy 'raise'
        raises; the softer policies only count/log here — skipping or
        rolling back is a trainer-loop decision (the update already ran
        by the time fetches exist)."""
        if not self.check_metrics:
            return
        for name, val in zip(fetch_names, fetches):
            data = getattr(val, 'data', val)
            try:
                bad = _has_nonfinite(data)
            except (TypeError, ValueError):
                continue
            if bad:
                _record_trip(self, 'fetch_nan', 'nan_inf',
                             'fetch:%s' % name)
                logger.warning('anomaly: non-finite fetch %r', name)
                if self.policy == 'raise':
                    raise AnomalyError('nan_inf', 'fetch:%s' % name)


# ---- executor integration ------------------------------------------------
_ACTIVE_GUARDS = []


def any_active():
    return bool(_ACTIVE_GUARDS)


@contextlib.contextmanager
def executor_guard(guard):
    """Install ``guard`` so Executor.run checks every fetch inside the
    block (the executor-level wiring for raw exe.run loops)."""
    _ACTIVE_GUARDS.append(guard)
    try:
        yield guard
    finally:
        _ACTIVE_GUARDS.remove(guard)


def observe_fetches(fetch_names, fetches):
    for g in tuple(_ACTIVE_GUARDS):
        g.observe(fetch_names, fetches)
