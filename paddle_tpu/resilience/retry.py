"""Transient-error retry: exponential backoff + deterministic jitter.

Checkpoint I/O and dataset readers fail transiently in production
(NFS/GCS hiccups, preempted sidecars); a bounded retry with backoff
turns those into latency instead of a dead trainer. The jitter is drawn
from a module-local PRNG so retry timing never perturbs ``random``'s
global stream (reader shuffles must stay reproducible).
"""
import functools
import logging
import random
import time

__all__ = ['retry', 'retry_call', 'RetryError']

logger = logging.getLogger('paddle_tpu.resilience')

_jitter_rng = random.Random(0x5EED)


class RetryError(RuntimeError):
    """All attempts exhausted — or the deadline left no room for the
    next backoff. ``last_error`` holds the final cause, ``attempts``
    how many times the callable ran, and ``deadline_exceeded`` whether
    the retry loop gave up early because sleeping again would overshoot
    the caller's deadline."""

    def __init__(self, fn_name, attempts, last_error,
                 deadline_exceeded=False):
        why = 'deadline left no room for retry %d' % (attempts + 1) \
            if deadline_exceeded else 'failed'
        super(RetryError, self).__init__(
            '%s %s after %d attempt(s): %r' % (fn_name, why, attempts,
                                               last_error))
        self.attempts = attempts
        self.last_error = last_error
        self.deadline_exceeded = deadline_exceeded


def retry(max_attempts=3, backoff=0.1, jitter=0.1, retry_on=(OSError,),
          sleep=time.sleep, on_retry=None, deadline=None):
    """Decorator: re-run the callable on ``retry_on`` errors.

    Attempt ``k`` (1-based) sleeps ``backoff * 2**(k-1) * (1 + U[0,
    jitter])`` before re-running. Non-matching exceptions propagate
    immediately; exhausting ``max_attempts`` raises :class:`RetryError`
    chaining the last cause. ``on_retry(attempt, error)`` is invoked
    before each sleep — the hook the tests use to count attempts.

    ``deadline`` (absolute ``time.monotonic()`` seconds) caps the total
    backoff: when the next sleep would overshoot it, the loop raises
    :class:`RetryError` (``deadline_exceeded=True``) immediately
    instead — retries must never spend a budget the caller no longer
    has (a serving client's request deadline, a checkpoint window).
    """
    if max_attempts < 1:
        raise ValueError('max_attempts must be >= 1, got %r'
                         % (max_attempts,))

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return retry_call(fn, args, kwargs,
                              max_attempts=max_attempts, backoff=backoff,
                              jitter=jitter, retry_on=retry_on,
                              sleep=sleep, on_retry=on_retry,
                              deadline=deadline)
        return wrapper
    return deco


def retry_call(fn, args=(), kwargs=None, max_attempts=3, backoff=0.1,
               jitter=0.1, retry_on=(OSError,), sleep=time.sleep,
               on_retry=None, deadline=None):
    """Functional form of :func:`retry` for one-off call sites."""
    kwargs = kwargs or {}
    last = None
    for attempt in range(1, max_attempts + 1):
        try:
            return fn(*args, **kwargs)
        except retry_on as e:  # noqa: B902 — tuple comes from caller
            last = e
            name = getattr(fn, '__name__', repr(fn))
            if attempt == max_attempts:
                raise RetryError(name, attempt, e) from e
            delay = backoff * (2 ** (attempt - 1))
            if jitter:
                delay *= 1.0 + _jitter_rng.uniform(0.0, jitter)
            if deadline is not None and \
                    time.monotonic() + delay > deadline:
                logger.warning(
                    'retry %d/%d of %s abandoned: %.3fs backoff would '
                    'overshoot the deadline', attempt, max_attempts,
                    name, delay)
                raise RetryError(name, attempt, e,
                                 deadline_exceeded=True) from e
            logger.warning('retry %d/%d of %s after %r (sleeping %.3fs)',
                           attempt, max_attempts, name, e, delay)
            if on_retry is not None:
                on_retry(attempt, e)
            if delay > 0:
                sleep(delay)
    raise RetryError(getattr(fn, '__name__', repr(fn)), max_attempts,
                     last)  # pragma: no cover — loop always returns/raises
