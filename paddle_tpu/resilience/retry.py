"""Transient-error retry: exponential backoff + deterministic jitter.

Checkpoint I/O and dataset readers fail transiently in production
(NFS/GCS hiccups, preempted sidecars); a bounded retry with backoff
turns those into latency instead of a dead trainer. The jitter is drawn
from a module-local PRNG so retry timing never perturbs ``random``'s
global stream (reader shuffles must stay reproducible).
"""
import functools
import logging
import random
import time

__all__ = ['retry', 'retry_call', 'RetryError']

logger = logging.getLogger('paddle_tpu.resilience')

_jitter_rng = random.Random(0x5EED)


class RetryError(RuntimeError):
    """All attempts exhausted. ``last_error`` holds the final cause and
    ``attempts`` how many times the callable ran."""

    def __init__(self, fn_name, attempts, last_error):
        super(RetryError, self).__init__(
            '%s failed after %d attempt(s): %r' % (fn_name, attempts,
                                                   last_error))
        self.attempts = attempts
        self.last_error = last_error


def retry(max_attempts=3, backoff=0.1, jitter=0.1, retry_on=(OSError,),
          sleep=time.sleep, on_retry=None):
    """Decorator: re-run the callable on ``retry_on`` errors.

    Attempt ``k`` (1-based) sleeps ``backoff * 2**(k-1) * (1 + U[0,
    jitter])`` before re-running. Non-matching exceptions propagate
    immediately; exhausting ``max_attempts`` raises :class:`RetryError`
    chaining the last cause. ``on_retry(attempt, error)`` is invoked
    before each sleep — the hook the tests use to count attempts.
    """
    if max_attempts < 1:
        raise ValueError('max_attempts must be >= 1, got %r'
                         % (max_attempts,))

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return retry_call(fn, args, kwargs,
                              max_attempts=max_attempts, backoff=backoff,
                              jitter=jitter, retry_on=retry_on,
                              sleep=sleep, on_retry=on_retry)
        return wrapper
    return deco


def retry_call(fn, args=(), kwargs=None, max_attempts=3, backoff=0.1,
               jitter=0.1, retry_on=(OSError,), sleep=time.sleep,
               on_retry=None):
    """Functional form of :func:`retry` for one-off call sites."""
    kwargs = kwargs or {}
    last = None
    for attempt in range(1, max_attempts + 1):
        try:
            return fn(*args, **kwargs)
        except retry_on as e:  # noqa: B902 — tuple comes from caller
            last = e
            name = getattr(fn, '__name__', repr(fn))
            if attempt == max_attempts:
                raise RetryError(name, attempt, e) from e
            delay = backoff * (2 ** (attempt - 1))
            if jitter:
                delay *= 1.0 + _jitter_rng.uniform(0.0, jitter)
            logger.warning('retry %d/%d of %s after %r (sleeping %.3fs)',
                           attempt, max_attempts, name, e, delay)
            if on_retry is not None:
                on_retry(attempt, e)
            if delay > 0:
                sleep(delay)
    raise RetryError(getattr(fn, '__name__', repr(fn)), max_attempts,
                     last)  # pragma: no cover — loop always returns/raises
