"""paddle_tpu — a TPU-native deep-learning framework with the capabilities
of Fluid-era PaddlePaddle (reference: /root/reference).

Compute path: JAX/XLA (+ Pallas kernels); runtime around it: Python + C++
(native data loader / recordio). See SURVEY.md and ARCHITECTURE.md.

Usage mirrors the reference:

    import paddle_tpu as paddle
    import paddle_tpu.fluid as fluid

    x = fluid.layers.data(name='x', shape=[13], dtype='float32')
    y = fluid.layers.fc(input=x, size=1)
    ...
    exe = fluid.Executor(fluid.TPUPlace(0))
"""
from . import framework
from . import ops  # registers all kernels
from .framework import (Program, Block, Variable, Operator,  # noqa
                        default_startup_program, default_main_program,
                        program_guard, switch_startup_program,
                        switch_main_program, get_var)
from .core.places import (TPUPlace, CPUPlace, CUDAPlace,  # noqa
                          CUDAPinnedPlace, is_compiled_with_cuda,
                          is_compiled_with_tpu)
from .executor import (Executor, Scope, global_scope, scope_guard,  # noqa
                       switch_scope, fetch_var)
from . import layers  # noqa
from . import initializer  # noqa
from . import regularizer  # noqa
from . import clip  # noqa
from . import optimizer  # noqa
from . import backward  # noqa
from .backward import append_backward, calc_gradient, gradients  # noqa
from .param_attr import ParamAttr, WeightNormParamAttr  # noqa
from . import unique_name  # noqa
from .data_feeder import DataFeeder  # noqa
from .lod import (SequenceTensor, create_lod_tensor,  # noqa
                  create_random_int_lodtensor)
from . import io  # noqa
from . import nets  # noqa
from . import metrics  # noqa
from . import evaluator  # noqa
from . import average  # noqa
from . import profiler  # noqa
from . import reader  # noqa
from . import dataset  # noqa
from .reader import batch  # noqa
from . import parallel  # noqa
from . import trainer  # noqa
from .trainer import Trainer  # noqa
from . import inferencer  # noqa
from .inferencer import Inferencer  # noqa
from . import serving  # noqa
from .serving import ModelServer  # noqa
from . import fleet  # noqa
from . import debugger  # noqa
from . import debugger as debuger  # noqa  (reference spelling)
from . import graphviz  # noqa
from . import net_drawer  # noqa
from . import concurrency  # noqa
from .parallel.parallel_executor import (ParallelExecutor,  # noqa
                                         ExecutionStrategy, BuildStrategy)
from .parallel.transpiler import (DistributeTranspiler,  # noqa
                                  InferenceTranspiler,
                                  SimpleDistributeTranspiler,
                                  memory_optimize, release_memory)
from . import transpiler  # noqa
from . import compiler  # noqa
from . import recordio_writer  # noqa
from . import contrib  # noqa
from . import resilience  # noqa
from .clip import ErrorClipByValue  # noqa

Tensor = SequenceTensor  # loose alias for scripts touching fluid.Tensor
# reference __init__.py:46 re-exports core.LoDTensor; SequenceTensor
# carries the imperative surface (set/set_lod/lod)
LoDTensor = SequenceTensor

__version__ = '0.1.0'

__all__ = [
    'Program', 'Block', 'Variable', 'Operator', 'default_startup_program',
    'default_main_program', 'program_guard', 'get_var', 'TPUPlace',
    'CPUPlace', 'CUDAPlace', 'CUDAPinnedPlace', 'Executor', 'global_scope',
    'scope_guard', 'fetch_var', 'layers', 'initializer', 'regularizer',
    'clip', 'optimizer', 'backward', 'append_backward', 'calc_gradient', 'gradients', 'ParamAttr',
    'WeightNormParamAttr', 'unique_name', 'DataFeeder', 'SequenceTensor',
    'LoDTensor', 'Tensor',
    'create_lod_tensor', 'create_random_int_lodtensor', 'io', 'nets',
    'metrics', 'evaluator', 'profiler', 'reader', 'dataset', 'batch',
    'ParallelExecutor', 'ExecutionStrategy', 'BuildStrategy',
    'DistributeTranspiler', 'SimpleDistributeTranspiler',
    'InferenceTranspiler', 'transpiler', 'recordio_writer', 'contrib',
    'memory_optimize', 'release_memory', 'resilience',
]
