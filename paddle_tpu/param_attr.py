"""ParamAttr / WeightNormParamAttr. Parity: python/paddle/fluid/param_attr.py."""
from .initializer import Constant, Xavier
from .regularizer import WeightDecayRegularizer

__all__ = ['ParamAttr', 'WeightNormParamAttr']


class ParamAttr(object):
    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, gradient_clip=None,
                 do_model_average=None, sharding=None):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.gradient_clip = gradient_clip
        self.do_model_average = do_model_average
        # TPU extension: PartitionSpec-like tuple of mesh axis names per
        # dim (e.g. (None, 'mp') to column-shard an fc weight). Consumed
        # by ParallelExecutor in_shardings and the lowering's
        # with_sharding_constraint pass.
        if isinstance(sharding, str):
            sharding = (sharding,)  # P('dp')-style: axis name on dim 0
        self.sharding = tuple(sharding) if sharding is not None else None

    def set_default_initializer(self, initializer):
        if initializer is None:
            if self.initializer is None:
                raise ValueError("ParamAttr.initializer is not set")
            return
        if self.initializer is not None:
            return
        self.initializer = initializer

    def set_default_param_initializer(self):
        self.set_default_initializer(Xavier())

    def set_default_bias_initializer(self):
        self.set_default_initializer(Constant(0.0))

    @staticmethod
    def to_attr(arg):
        if arg is None:
            return ParamAttr()
        elif isinstance(arg, (list, tuple)):
            return [ParamAttr.to_attr(a) for a in arg]
        elif isinstance(arg, ParamAttr):
            return arg
        elif isinstance(arg, str):
            return ParamAttr(name=arg)
        elif isinstance(arg, WeightDecayRegularizer):
            return ParamAttr(regularizer=arg)
        elif isinstance(arg, bool):
            # parity: reference param_attr.py returns False so that
            # `bias_attr=False` disables the bias entirely
            return ParamAttr.to_attr(None) if arg else False
        else:
            return ParamAttr(initializer=arg)

    _to_attr = to_attr

    def to_kwargs(self, with_initializer=False):
        kwargs = {
            'name': self.name,
            'optimize_attr': {'learning_rate': self.learning_rate},
            'regularizer': self.regularizer,
            'trainable': self.trainable,
            'gradient_clip_attr': self.gradient_clip,
            'do_model_average': self.do_model_average,
            'sharding': self.sharding,
        }
        if with_initializer:
            kwargs['initializer'] = self.initializer
        return kwargs


class WeightNormParamAttr(ParamAttr):
    """Weight-normalization reparameterization w = g * v / ||v||.

    Parity: python/paddle/fluid/param_attr.py (WeightNormParamAttr) and
    layer_helper.py:108-309 (_create_weight_normalize). Passing this attr
    to fc/conv splits the weight into direction ``v`` (original shape) and
    magnitude ``g`` (norm-shaped along ``dim``); both train, and the layer
    consumes the recomposed w. ``params_with_weight_norm`` collects the
    recomposed w Variables, mirroring the reference's bookkeeping.
    """
    params_with_weight_norm = []

    def __init__(self, dim=None, **kwargs):
        super(WeightNormParamAttr, self).__init__(**kwargs)
        self.dim = dim
