"""Gradient / error clipping.

Parity: python/paddle/fluid/clip.py — same class names and attr plumbing
(``set_gradient_clip``, per-param ``gradient_clip_attr``); clip ops append
after the backward marker and fuse into the step program.
"""
import copy

from . import framework, layers
from .framework import Variable

__all__ = ['ErrorClipByValue', 'GradientClipByValue', 'GradientClipByNorm',
           'GradientClipByGlobalNorm', 'append_gradient_clip_ops',
           'error_clip_callback', 'set_gradient_clip']


class BaseErrorClipAttr(object):
    def append_clip_op(self, block, grad_name):
        raise NotImplementedError()


class ErrorClipByValue(BaseErrorClipAttr):
    def __init__(self, max, min=None):
        max = float(max)
        min = -max if min is None else float(min)
        self.max, self.min = max, min

    def append_clip_op(self, block, grad_name):
        block.append_op(type='clip', inputs={'X': [grad_name]},
                        outputs={'Out': [grad_name]},
                        attrs={'min': self.min, 'max': self.max})


def error_clip_callback(block, context):
    grad = context['grad']
    param = context['param']
    error_clip = getattr(param, 'error_clip', None)
    if error_clip is not None:
        error_clip.append_clip_op(block, grad.name)


class BaseGradientClipAttr(object):
    def process_context(self, context, param, grad):
        pass

    def create_operators(self, param, grad):
        raise NotImplementedError()


class NullGradientClipAttr(BaseGradientClipAttr):
    def create_operators(self, param, grad):
        return param, grad


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        max = float(max)
        min = -max if min is None else float(min)
        self.max, self.min = max, min

    def create_operators(self, param, grad):
        new_grad = layers.clip(x=grad, min=self.min, max=self.max)
        return param, new_grad


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def create_operators(self, param, grad):
        new_grad = layers.clip_by_norm(x=grad, max_norm=self.clip_norm)
        return param, new_grad


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = clip_norm
        self.group_name = group_name

    def process_context(self, context, param, grad):
        if self.group_name not in context:
            context[self.group_name] = []
            context[self.group_name + "_clip_value"] = self.clip_norm
            context[self.group_name + "_clip"] = layers.fill_constant(
                shape=[1], dtype="float32", value=self.clip_norm)
        else:
            if not self.clip_norm == context[self.group_name + "_clip_value"]:
                raise ValueError(
                    "All parameters' 'clip_norm' of a same group should be "
                    "the same")
        local_norm = layers.reduce_sum(input=layers.pow(x=grad, factor=2.0))
        context[self.group_name].append(local_norm)
        self.context = context

    def create_operators(self, param, grad):
        group_scale_name = self.group_name + "_scale"
        if group_scale_name not in self.context:
            group_norm = layers.sums(input=self.context[self.group_name])
            group_norm = layers.sqrt(x=group_norm)
            clip_var = self.context[self.group_name + "_clip"]
            group_scale = layers.elementwise_div(
                x=clip_var,
                y=layers.elementwise_max(x=clip_var, y=group_norm))
            self.context[group_scale_name] = group_scale
        new_grad = layers.elementwise_mul(
            x=grad, y=self.context[group_scale_name])
        return param, new_grad


def set_gradient_clip(clip, param_list=None, program=None):
    if not isinstance(clip, BaseGradientClipAttr):
        raise TypeError("clip should be an instance of BaseGradientClipAttr")
    if program is None:
        program = framework.default_main_program()
    if param_list is None:
        param_list = program.global_block().all_parameters()
    if all(isinstance(elem, str) for elem in param_list):
        param_list = [program.global_block().var(elem)
                      for elem in param_list]
    if not all(isinstance(elem, framework.Parameter)
               for elem in param_list):
        raise TypeError("param_list should be a list of Parameter or "
                        "basestring(parameter's name)")
    for param in param_list:
        param.gradient_clip_attr = copy.deepcopy(clip)


def append_gradient_clip_ops(param_grad):
    context = dict()
    create_op_callbacks = []
    for p, g in param_grad:
        clip_attr = getattr(p, 'gradient_clip_attr', None)
        if clip_attr is None or getattr(p, 'sparse_grad', False):
            # sparse row-grads pass through unclipped (ref: clip ops are
            # LoDTensor-only)
            clip_attr = NullGradientClipAttr()
        if not isinstance(clip_attr, BaseGradientClipAttr):
            raise TypeError(
                "clip attribute should be an instance of "
                "BaseGradientClipAttr")
        clip_attr.process_context(context=context, param=p, grad=g)
        create_op_callbacks.append(lambda p=p, g=g, c=clip_attr:
                                   c.create_operators(p, g))
    return [callback() for callback in create_op_callbacks]
