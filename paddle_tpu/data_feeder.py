"""DataFeeder — host-side batch assembly.

Parity: python/paddle/fluid/data_feeder.py. Converts a minibatch (list of
example tuples) into the Executor feed dict. Sequence slots (lod_level>0)
become SequenceTensors with bucketed padded length (bounds XLA recompiles).
"""
import numpy as np

from .framework import Variable, default_main_program
from .lod import SequenceTensor, bucket_length

__all__ = ['DataFeeder']


class DataToLoDTensorConverter(object):
    def __init__(self, place, lod_level, shape, dtype):
        self.place = place
        self.lod_level = lod_level
        self.shape = [s for s in shape]
        self.dtype = dtype
        self.data = []

    def feed(self, data):
        self.data.append(data)

    def done(self):
        if self.lod_level == 0:
            arr = np.asarray(self.data, dtype=self.dtype)
            shape = [s for s in self.shape if s != -1]
            if shape and list(arr.shape[1:]) != shape and \
                    int(np.prod(arr.shape[1:])) == int(np.prod(shape)):
                arr = arr.reshape([arr.shape[0]] + shape)
            elif arr.ndim == 1 and shape == [1]:
                arr = arr[:, None]
            return arr
        if self.lod_level == 1:
            seqs = [np.asarray(s, dtype=self.dtype) for s in self.data]
            lens = np.asarray([len(s) for s in seqs], np.int32)
            max_len = bucket_length(int(lens.max()) if len(lens) else 1)
            feat = list(seqs[0].shape[1:]) if seqs[0].ndim > 1 else []
            trailing = [s for s in self.shape if s != -1]
            if not feat and trailing == [1]:
                feat = [1]
                seqs = [s[:, None] if s.ndim == 1 else s for s in seqs]
            out = np.zeros([len(seqs), max_len] + feat, dtype=self.dtype)
            for i, s in enumerate(seqs):
                out[i, :len(s)] = s
            return SequenceTensor(out, lens)
        # lod_level == 2: list of list of sequences
        from .lod import create_lod_tensor
        outer = [len(ex) for ex in self.data]
        inner = [len(s) for ex in self.data for s in ex]
        flat = [item for ex in self.data for s in ex for item in s]
        arr = np.asarray(flat, dtype=self.dtype)
        if arr.ndim == 1:
            arr = arr[:, None]
        return create_lod_tensor(arr, [outer, inner], self.place)


class DataFeeder(object):
    def __init__(self, feed_list, place, program=None):
        self.feed_dtypes = []
        self.feed_names = []
        self.feed_shapes = []
        self.feed_lod_level = []
        if program is None:
            program = default_main_program()
        for each_var in feed_list:
            if isinstance(each_var, str):
                each_var = program.global_block().var(each_var)
            if not isinstance(each_var, Variable):
                raise TypeError("Feed list should contain a list of "
                                "variable")
            self.feed_dtypes.append(each_var.dtype)
            self.feed_names.append(each_var.name)
            self.feed_lod_level.append(each_var.lod_level)
            self.feed_shapes.append(each_var.shape)
        self.place = place

    @staticmethod
    def _shape_dense(arr, shape):
        """The lod-0 reshape contract of DataToLoDTensorConverter.done,
        shared verbatim by the fast path so both produce identical
        arrays (pinned by tests/test_pipeline.py parity test)."""
        trailing = [s for s in shape if s != -1]
        if trailing and list(arr.shape[1:]) != trailing and \
                int(np.prod(arr.shape[1:])) == int(np.prod(trailing)):
            arr = arr.reshape([arr.shape[0]] + trailing)
        elif arr.ndim == 1 and trailing == [1]:
            arr = arr[:, None]
        return arr

    def _feed_dense_fast(self, iterable):
        """Fast path for already-batched dense inputs: one
        ``np.asarray`` + reshape per slot instead of per-row converter
        dispatch. Returns None whenever the input does not provably fit
        (any LoD slot, ragged rows, field-count mismatch) — the slow
        path then reproduces the classic behavior, including its error
        messages."""
        if any(l != 0 for l in self.feed_lod_level):
            return None
        n_slots = len(self.feed_names)
        if isinstance(iterable, np.ndarray):
            # a single pre-batched dense array feeds a 1-slot list with
            # zero per-row work
            if n_slots != 1 or iterable.dtype == object or \
                    iterable.ndim == 0:
                return None
            arr = np.asarray(iterable, dtype=self.feed_dtypes[0])
            return {self.feed_names[0]: self._shape_dense(
                arr, self.feed_shapes[0])}
        if not isinstance(iterable, (list, tuple)) or not iterable:
            return None
        first = iterable[0]
        if not isinstance(first, (list, tuple, np.ndarray)) or \
                len(first) != n_slots:
            return None
        try:
            if any(len(s) != n_slots for s in iterable):
                return None   # slow path raises the classic assert
        except TypeError:
            return None
        out = {}
        try:
            for i, (name, shape, dtype) in enumerate(zip(
                    self.feed_names, self.feed_shapes,
                    self.feed_dtypes)):
                col = [sample[i] for sample in iterable]
                arr = np.asarray(col, dtype=dtype)
                if arr.dtype == object:
                    return None          # ragged rows: not dense
                out[name] = self._shape_dense(arr, shape)
        except (ValueError, TypeError, IndexError, KeyError):
            return None   # let the slow path produce the classic error
        return out

    def feed(self, iterable, _force_slow=False):
        if not _force_slow:
            fast = self._feed_dense_fast(iterable)
            if fast is not None:
                return fast
        converters = []
        for lod_level, shape, dtype in zip(
                self.feed_lod_level, self.feed_shapes, self.feed_dtypes):
            converters.append(DataToLoDTensorConverter(
                place=self.place, lod_level=lod_level, shape=shape,
                dtype=dtype))
        for each_sample in iterable:
            assert len(each_sample) == len(converters), (
                "The number of fields in data (%s) does not match "
                "len(feed_list) (%s)" % (len(each_sample), len(converters)))
            for each_converter, each_slot in zip(converters, each_sample):
                each_converter.feed(each_slot)
        ret_dict = {}
        for each_name, each_converter in zip(self.feed_names, converters):
            ret_dict[each_name] = each_converter.done()
        return ret_dict

    def decorate_reader(self, reader, multi_devices=False,
                        num_places=None, drop_last=True):
        """Wrap a batch reader so it yields ready feed dicts.
        Parity: data_feeder.py::DataFeeder.decorate_reader (:153-176) —
        the reference groups ``num`` consecutive reader batches, one per
        device, and feed_parallel's them; the SPMD executor takes ONE
        mesh-sharded feed instead, so each group is concatenated into a
        single super-batch (device i's shard = original batch i). The
        trailing incomplete group is dropped (drop_last=True) or raises
        the reference's ValueError (drop_last=False)."""
        if multi_devices:
            import jax
            n = int(num_places or jax.device_count())

            def __reader_creator__():
                group = []
                for batch in reader():
                    group.append(batch)
                    if len(group) == n:
                        yield self.feed([row for b in group for row in b])
                        group = []
                if not drop_last and group:
                    raise ValueError(
                        "The data batch which cannot fit for devices "
                        "will be dropped is not implementation. Other "
                        "strategies are not implemented")
            return __reader_creator__

        def __reader_creator__():
            for item in reader():
                yield self.feed(item)
        return __reader_creator__
