"""append_backward — gradient construction.

Parity: python/paddle/fluid/backward.py. The reference builds one grad-op per
forward op (C++ GradOpMaker) and inserts them in reverse order. paddle_tpu
plants a single ``backward_marker`` op carrying (loss, params, grad names);
at lowering (core/lowering.py) the forward ops are replayed inside
``jax.value_and_grad(..., has_aux=True)`` so XLA sees one fused
forward+backward program. The public contract is identical: grad Variables
named ``<param>@GRAD`` exist in the block, ``(param, grad)`` pairs are
returned, and downstream passes (regularizer, clip, optimizer) append ops
that read/write those names.
"""
from . import framework
from .framework import Parameter, Variable, grad_var_name

__all__ = ['append_backward', 'calc_gradient', 'gradients']


def _create_grad_var(block, ref_var, name=None):
    return block.create_var(
        name=name or grad_var_name(ref_var.name), shape=ref_var.shape,
        dtype=ref_var.dtype, lod_level=ref_var.lod_level)


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    assert isinstance(loss, Variable), "loss must be a Variable"
    program = loss.block.program
    block = program.global_block()

    if parameter_list is not None:
        parameters = []
        for p in parameter_list:
            name = p.name if isinstance(p, Variable) else p
            parameters.append(block.var(name))
    else:
        parameters = [p for p in block.all_parameters() if p.trainable]

    no_grad = set()
    if no_grad_set:
        for item in no_grad_set:
            no_grad.add(item.name if isinstance(item, Variable) else item)
    parameters = [p for p in parameters if p.name not in no_grad]

    params_and_grads = []
    grad_names = []
    for p in parameters:
        g = _create_grad_var(block, p)
        params_and_grads.append((p, g))
        grad_names.append(g.name)

    # sparse embedding params (layers.embedding(is_sparse=True)): record
    # their lookup carriers so lowering differentiates the gathered ROWS
    # and the optimizer updates only touched rows (SelectedRows analog)
    sparse = {}
    sparse_names = set(p.name for p in parameters
                       if getattr(p, 'sparse_grad', False))
    if sparse_names:
        for op in block.ops:
            if op.type == 'lookup_table' and \
                    op.attrs.get('sparse_carrier'):
                w = op.inputs['W'][0]
                if w in sparse_names:
                    sparse.setdefault(w, []).append(
                        [op.inputs['Ids'][0],
                         op.attrs['sparse_carrier']])
        # a table consumed by any op OTHER than carrier-tagged lookups
        # (weight tying, a mixed is_sparse=False lookup, a read inside
        # a While/DynamicRNN sub-block) still needs the dense gradient:
        # drop it from the sparse set
        def _reads(op):
            names = list(op.input_arg_names)
            sub = op.attrs.get('sub_block')
            if sub is not None:
                for sop in sub.ops:
                    names.extend(_reads(sop))
            return names

        for op in block.ops:
            tagged_w = op.inputs['W'][0] if (
                op.type == 'lookup_table' and
                op.attrs.get('sparse_carrier')) else None
            for n in _reads(op):
                if n in sparse and n != tagged_w:
                    del sparse[n]

    block.append_op(
        type='backward_marker',
        inputs={'Loss': [loss]},
        outputs={},
        attrs={'params': [p.name for p in parameters],
               'grads': grad_names,
               'sparse': sparse})

    if callbacks is not None:
        for cb in callbacks:
            for p, g in params_and_grads:
                cb(block=block, context={'param': p, 'grad': g})

    return params_and_grads


def _as_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def calc_gradient(targets, inputs, target_gradients=None, no_grad_set=None):
    """Per-target gradients: d(targets)/d(inputs).

    Parity: python/paddle/fluid/backward.py:604 (calc_gradient), tested
    by tests/unittests/test_calc_gradient.py. The reference appends one
    grad-op per relevant forward op and renames internal grad vars on
    repeated calls; here a self-contained ``gradient_marker`` op is
    planted, and at lowering (core/lowering.py) the relevant op path is
    replayed under ``jax.vjp`` with ``inputs`` as leaves — no internal
    grad vars exist, so repeated calls compose trivially.

    ``target_gradients[i]`` (a Variable) seeds target i's cotangent;
    None means ones (the reference fills 1.0). Returns one grad Variable
    per input, or None where the input does not affect any target.
    """
    targets = _as_list(targets)
    inputs = _as_list(inputs)
    target_gradients = _as_list(target_gradients)
    if not targets:
        raise ValueError("calc_gradient needs at least one target")
    block = targets[0].block
    program = block.program
    if not target_gradients:
        target_gradients = [None] * len(targets)
    if len(target_gradients) != len(targets):
        raise ValueError(
            "Should have the same number of target_gradients as targets")
    for t, tg in zip(targets, target_gradients):
        if t.block.program is not program:
            raise ValueError("all targets must be in the same program")
        if tg is not None:
            ts, gs = tuple(t.shape), tuple(tg.shape)
            if len(ts) != len(gs) or any(
                    a != b for a, b in zip(ts, gs) if -1 not in (a, b)):
                raise ValueError(
                    "The shapes of target and target_gradient differ: "
                    "%s %s" % (t.name, tg.name))
    for v in inputs:
        if v.block.program is not program:
            raise ValueError("input must be in the same program as targets")

    no_grad = set()
    for item in (no_grad_set or ()):
        no_grad.add(item.name if isinstance(item, Variable) else item)

    from .core.lowering import find_op_path, op_reads, op_writes
    input_names = [v.name for v in inputs]
    target_names = [t.name for t in targets]
    fwd_ops = [o for o in block.ops if o.type != 'backward_marker']
    path, _ = find_op_path(fwd_ops, set(input_names), set(target_names),
                           no_grad)
    read_by_path = set()
    produced_by_path = set()
    for op in path:
        read_by_path.update(op_reads(op))
        produced_by_path.update(op_writes(op))
    # values the vjp replay reads from the environment (dependency edges
    # for remat segmentation / pruning): external reads + given cotangents
    deps = sorted((read_by_path - produced_by_path) - set(input_names))

    grad_vars, connected, out_grad_names = [], [], []
    for v in inputs:
        if v.name not in read_by_path and v.name not in target_names:
            grad_vars.append(None)  # input does not affect any target
            continue
        gname = grad_var_name(v.name)
        if block.has_var(gname):
            from . import unique_name
            gname = unique_name.generate(gname)
        g = _create_grad_var(block, v, name=gname)
        grad_vars.append(g)
        connected.append(v.name)
        out_grad_names.append(gname)

    if connected:
        block.append_op(
            type='gradient_marker',
            inputs={'Targets': list(target_names),
                    'Inputs': list(connected),
                    'TargetGrads': [tg.name for tg in target_gradients
                                    if tg is not None],
                    'Deps': [n for n in deps if block._find_var_recursive(n)
                             is not None]},
            outputs={'OutGrads': list(out_grad_names)},
            # targets/inputs/out_grads live ONLY in the op slots (the
            # kernel derives them there, so var renames stay coherent);
            # attrs carry what slots can't: the None-placeholder
            # alignment of target_grads and the no_grad cut set
            attrs={'target_grads': [None if tg is None else tg.name
                                    for tg in target_gradients],
                   'no_grad': sorted(no_grad)})
    return grad_vars


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """``fluid.gradients`` — public alias of :func:`calc_gradient`."""
    return calc_gradient(targets, inputs, target_gradients, no_grad_set)
