"""append_backward — gradient construction.

Parity: python/paddle/fluid/backward.py. The reference builds one grad-op per
forward op (C++ GradOpMaker) and inserts them in reverse order. paddle_tpu
plants a single ``backward_marker`` op carrying (loss, params, grad names);
at lowering (core/lowering.py) the forward ops are replayed inside
``jax.value_and_grad(..., has_aux=True)`` so XLA sees one fused
forward+backward program. The public contract is identical: grad Variables
named ``<param>@GRAD`` exist in the block, ``(param, grad)`` pairs are
returned, and downstream passes (regularizer, clip, optimizer) append ops
that read/write those names.
"""
from . import framework
from .framework import Parameter, Variable, grad_var_name

__all__ = ['append_backward']


def _create_grad_var(block, ref_var, name=None):
    return block.create_var(
        name=name or grad_var_name(ref_var.name), shape=ref_var.shape,
        dtype=ref_var.dtype, lod_level=ref_var.lod_level)


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    assert isinstance(loss, Variable), "loss must be a Variable"
    program = loss.block.program
    block = program.global_block()

    if parameter_list is not None:
        parameters = []
        for p in parameter_list:
            name = p.name if isinstance(p, Variable) else p
            parameters.append(block.var(name))
    else:
        parameters = [p for p in block.all_parameters() if p.trainable]

    no_grad = set()
    if no_grad_set:
        for item in no_grad_set:
            no_grad.add(item.name if isinstance(item, Variable) else item)
    parameters = [p for p in parameters if p.name not in no_grad]

    params_and_grads = []
    grad_names = []
    for p in parameters:
        g = _create_grad_var(block, p)
        params_and_grads.append((p, g))
        grad_names.append(g.name)

    # sparse embedding params (layers.embedding(is_sparse=True)): record
    # their lookup carriers so lowering differentiates the gathered ROWS
    # and the optimizer updates only touched rows (SelectedRows analog)
    sparse = {}
    sparse_names = set(p.name for p in parameters
                       if getattr(p, 'sparse_grad', False))
    if sparse_names:
        for op in block.ops:
            if op.type == 'lookup_table' and \
                    op.attrs.get('sparse_carrier'):
                w = op.inputs['W'][0]
                if w in sparse_names:
                    sparse.setdefault(w, []).append(
                        [op.inputs['Ids'][0],
                         op.attrs['sparse_carrier']])
        # a table consumed by any op OTHER than carrier-tagged lookups
        # (weight tying, a mixed is_sparse=False lookup, a read inside
        # a While/DynamicRNN sub-block) still needs the dense gradient:
        # drop it from the sparse set
        def _reads(op):
            names = list(op.input_arg_names)
            sub = op.attrs.get('sub_block')
            if sub is not None:
                for sop in sub.ops:
                    names.extend(_reads(sop))
            return names

        for op in block.ops:
            tagged_w = op.inputs['W'][0] if (
                op.type == 'lookup_table' and
                op.attrs.get('sparse_carrier')) else None
            for n in _reads(op):
                if n in sparse and n != tagged_w:
                    del sparse[n]

    block.append_op(
        type='backward_marker',
        inputs={'Loss': [loss]},
        outputs={},
        attrs={'params': [p.name for p in parameters],
               'grads': grad_names,
               'sparse': sparse})

    if callbacks is not None:
        for cb in callbacks:
            for p, g in params_and_grads:
                cb(block=block, context={'param': p, 'grad': g})

    return params_and_grads
