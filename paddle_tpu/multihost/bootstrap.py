"""Multi-host bootstrap: bounded-timeout handshake, pod barriers and
cross-host agreement checks (PARTITIONING.md "Multi-host meshes").

``jax.distributed.initialize`` with no guard rails hangs forever when
the coordinator never comes up — the worst possible failure mode for a
supervised pod (the launcher sees a silent, live, useless process).
:func:`initialize` wraps it in a bounded, retrying handshake that
raises a typed :class:`~.errors.BootstrapTimeout` instead, validates
the (process_id, num_processes) pair up front, records the
``multihost_peers`` gauge and a ``multihost`` ``bootstrap`` journal
event, and starts this host's heartbeat when a launcher provided a
shared heartbeat dir.

:func:`agreement_check` is the "same program everywhere" guard: each
host hashes its program fingerprint + mesh identity + logical-axis
rules, digests are compared via ``multihost_utils.process_allgather``,
and any divergent host fails fast with a typed
:class:`~.errors.HostMismatch` NAMING the minority hosts — a pod that
would otherwise wedge inside mismatched collectives dies at startup
with the culprit in the message.
"""
import hashlib
import os
import time

import numpy as np

from .. import observability as _obs
from .errors import BootstrapTimeout, HostMismatch
from .events import mh_emit
from .heartbeat import start_heartbeat

__all__ = ['initialize', 'barrier', 'broadcast_int',
           'agreement_check']

_BOOTSTRAPPED = False


def _already_initialized(err):
    return 'already initialized' in str(err).lower()


def _distributed_client_up():
    try:
        from jax._src import distributed as _dist
        return _dist.global_state.client is not None
    except Exception:  # noqa: BLE001 — private layout moved
        return False


def _wait_coordinator(coordinator_address, deadline):
    """Poll a TCP connect to the coordinator until ``deadline``.

    jaxlib's coordination client does not raise on a handshake
    deadline — it LOG(FATAL)s the whole process (client.h:80) — so a
    worker must prove the coordinator is reachable BEFORE handing
    control to ``jax.distributed.initialize``; only then can an
    unreachable coordinator surface as a catchable, typed error."""
    import socket
    host, _, port = coordinator_address.rpartition(':')
    port = int(port)
    host = host or '127.0.0.1'
    last = None
    while time.monotonic() < deadline:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.settimeout(max(0.1, min(1.0, deadline - time.monotonic())))
        try:
            s.connect((host, port))
            return None
        except OSError as e:
            last = e
            time.sleep(0.25)
        finally:
            s.close()
    return last or TimeoutError('coordinator never reachable')


def initialize(coordinator_address, num_processes, process_id,
               timeout=None, attempts=None, local_device_ids=None):
    """Join (or host) the pod's coordination service.

    Bounded handshake: each attempt gives ``jax.distributed`` an
    ``initialization_timeout`` of ``timeout`` seconds; after
    ``attempts`` failures a :class:`BootstrapTimeout` carries the
    coordinator address, rank and last underlying error. Defaults come
    from ``PTPU_BOOTSTRAP_TIMEOUT`` / ``PTPU_BOOTSTRAP_ATTEMPTS`` (60s,
    2 attempts). A single-process "pod" is a validated no-op. Returns
    True when a multi-process runtime is (or already was) up."""
    global _BOOTSTRAPPED
    num_processes = int(num_processes)
    process_id = int(process_id)
    if num_processes < 1:
        raise ValueError('num_processes must be >= 1, got %d'
                         % num_processes)
    if not 0 <= process_id < num_processes:
        raise ValueError(
            'trainer_id/process_id must be in [0, %d) but is %d — each '
            'launched process needs a distinct rank below the trainer '
            'count' % (num_processes, process_id))
    if num_processes == 1:
        return False
    import jax
    # NB: probe the distributed client, NOT jax.process_count() — the
    # latter initializes the backend, which with gloo collectives
    # configured fails hard before jax.distributed.initialize has run.
    if _BOOTSTRAPPED or _distributed_client_up():
        return True
    timeout = float(os.environ.get('PTPU_BOOTSTRAP_TIMEOUT', 60.0)
                    if timeout is None else timeout)
    attempts = int(os.environ.get('PTPU_BOOTSTRAP_ATTEMPTS', 2)
                   if attempts is None else attempts)
    attempts = max(1, attempts)
    t0 = time.monotonic()
    last = None
    for attempt in range(1, attempts + 1):
        if process_id != 0:
            # rank 0 hosts the coordination service itself; every
            # other rank first proves it can reach rank 0's socket
            err = _wait_coordinator(coordinator_address,
                                    time.monotonic() + timeout)
            if err is not None:
                last = err
                continue
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes, process_id=process_id,
                local_device_ids=local_device_ids,
                initialization_timeout=max(1, int(round(timeout))))
        except Exception as e:  # noqa: BLE001 — jaxlib raises several
            if _already_initialized(e):
                _BOOTSTRAPPED = True
                return True
            last = e
            try:
                jax.distributed.shutdown()
            except Exception:  # noqa: BLE001 — best-effort reset
                pass
            continue
        _BOOTSTRAPPED = True
        dur = time.monotonic() - t0
        _obs.default_registry().gauge(
            'multihost_peers',
            'hosts currently inside the heartbeat window'
        ).set(num_processes)
        mh_emit('bootstrap', host=process_id, world=num_processes,
                coordinator=str(coordinator_address), attempt=attempt,
                dur_s=round(dur, 6))
        start_heartbeat()
        return True
    mh_emit('bootstrap_timeout', host=process_id, world=num_processes,
            coordinator=str(coordinator_address), attempts=attempts,
            timeout_s=timeout)
    raise BootstrapTimeout(coordinator_address, process_id,
                           num_processes, attempts, timeout,
                           cause=last)


def barrier(name):
    """Pod-wide barrier (``multihost_utils.sync_global_devices``);
    no-op single-process. Emits a ``multihost`` ``barrier`` event."""
    import jax
    if jax.process_count() <= 1:
        return
    from jax.experimental import multihost_utils
    t0 = time.monotonic()
    multihost_utils.sync_global_devices(name)
    mh_emit('barrier', tag=name, world=jax.process_count(),
            dur_s=round(time.monotonic() - t0, 6))


def broadcast_int(name, value):
    """Process 0's ``value`` on every process (int); identity
    single-process. Used by the concurrent checkpoint path to agree on
    a serial before any host writes a shard."""
    import jax
    if jax.process_count() <= 1:
        return int(value)
    from jax.experimental import multihost_utils
    out = multihost_utils.broadcast_one_to_all(
        np.asarray(int(value), dtype=np.int64))
    return int(np.asarray(out))


def agreement_check(program=None, partitioner=None, extra=None,
                    tag='startup'):
    """Fail fast unless every host agrees on what it is about to run.

    The local digest covers the program fingerprint (when given), the
    partitioner's mesh identity + logical-axis rules (when given; the
    global device count otherwise) and any ``extra`` value. Digests are
    allgathered; hosts diverging from the majority (ties break toward
    process 0) raise :class:`HostMismatch` naming the divergent ranks.
    Returns the agreed digest hex. Single-process: local digest, no
    sync."""
    import jax
    payload = []
    if program is not None:
        payload.append(('program', str(program.fingerprint())))
    if partitioner is not None:
        payload.append(('mesh', repr(partitioner.mesh_meta())))
        payload.append(('rules', repr(partitioner.rules)))
    else:
        payload.append(('devices', str(len(jax.devices()))))
    if extra is not None:
        payload.append(('extra', repr(extra)))
    digest = hashlib.sha256(repr(sorted(payload)).encode()).digest()[:16]
    if jax.process_count() <= 1:
        return digest.hex()
    from jax.experimental import multihost_utils
    gathered = np.asarray(multihost_utils.process_allgather(
        np.frombuffer(digest, dtype=np.uint8)))
    hexes = [bytes(bytearray(gathered[i])).hex()
             for i in range(gathered.shape[0])]
    majority = max(hexes, key=lambda h: (hexes.count(h),
                                         h == hexes[0]))
    divergent = [i for i, h in enumerate(hexes) if h != majority]
    if divergent:
        mh_emit('agreement_fail', tag=tag, divergent=divergent,
                digests=hexes)
        raise HostMismatch(tag, divergent, hexes)
    mh_emit('barrier', tag='agreement:%s' % tag, world=len(hexes),
            digest=hexes[0][:12])
    return hexes[0]
