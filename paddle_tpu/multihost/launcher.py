"""Pod launcher + supervisor: spawn N host processes, watch their
heartbeats, survive whole-host loss (RESILIENCE.md "Surviving host
loss").

``launch`` is the engine behind ``tools/launch.py --nproc N``: it
spawns one worker process per "host" (CPU host devices via
``--xla_force_host_platform_device_count``), wires the rank/coordinator
/heartbeat env contract every worker reads, and then SUPERVISES:

- a worker exiting nonzero, or dying to a signal (kill -9), is caught
  by ``Popen.poll`` within one poll interval;
- a worker that is alive but WEDGED (stuck in a hung collective after a
  peer died, or spinning) stops touching its heartbeat file and ages
  past the bounded window (:class:`~.heartbeat.HostMonitor`).

Either way the supervisor declares the host lost (``host_lost`` journal
event with the detection latency), kills the remaining processes out of
their now-hung collectives, and — when relaunches remain — starts a new
GENERATION over the surviving host count with ``PTPU_RESUME=1``, so
workers restore the newest healthy sharded checkpoint on the degraded
mesh (``resilience.partitioner_for_manifest`` picks the mesh that fits
the smaller world).

Env contract exported to every worker (generation ``g``, rank ``r`` of
``w``): ``PTPU_NPROC=w``, ``PTPU_PROC_ID=r``,
``PTPU_COORD=host:port``, ``PTPU_HB_DIR``, ``PTPU_HB_INTERVAL``,
``PTPU_GENERATION=g``, ``PADDLE_TPU_DISTRIBUTED=1`` and (g > 0)
``PTPU_RESUME=1``.
"""
import os
import socket
import subprocess
import sys
import time

from .. import observability as _obs
from .events import mh_emit
from .heartbeat import DEFAULT_INTERVAL, HostMonitor

__all__ = ['free_port', 'launch', 'LaunchResult']


def free_port(host='127.0.0.1'):
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind((host, 0))
    port = s.getsockname()[1]
    s.close()
    return port


class LaunchResult(object):
    """Outcome of a (possibly multi-generation) launch: final exit
    code, plus one record per generation (world size, failed hosts and
    why, detection latency)."""

    def __init__(self, returncode, generations):
        self.returncode = int(returncode)
        self.generations = generations

    def __repr__(self):
        return 'LaunchResult(rc=%d, generations=%r)' % (
            self.returncode, self.generations)


def _spawn(cmd, rank, world, gen, port, hb_dir, hb_interval,
           devices_per_host, base_env, log_dir, extra_env):
    env = dict(base_env)
    env.update({
        'PTPU_NPROC': str(world),
        'PTPU_PROC_ID': str(rank),
        'PTPU_TRAINER_ID': str(rank),
        'PTPU_COORD': '127.0.0.1:%d' % port,
        'PTPU_HB_DIR': hb_dir,
        'PTPU_HB_INTERVAL': str(hb_interval),
        'PTPU_GENERATION': str(gen),
        'PADDLE_TPU_DISTRIBUTED': '1',
    })
    env.setdefault('JAX_PLATFORMS', 'cpu')
    flags = env.get('XLA_FLAGS', '')
    if 'xla_force_host_platform_device_count' not in flags:
        env['XLA_FLAGS'] = (
            flags + ' --xla_force_host_platform_device_count=%d'
            % devices_per_host).strip()
    if gen > 0:
        env['PTPU_RESUME'] = '1'
    # tracing env contract: the worker's train/run root parents under
    # the launcher's span (PTPU_TRACE_PARENT header) and journals into
    # its own per-rank file; PTPU_TRACE_SAMPLE rides base_env unchanged
    ctx = _obs.current_context()
    if ctx is not None:
        env[_obs.TRACE_PARENT_ENV] = ctx.to_header()
    if _obs.journal_active() and _obs.JOURNAL_ENV not in env:
        env[_obs.JOURNAL_ENV] = os.path.join(
            hb_dir, 'journal_g%d_r%d.jsonl' % (gen, rank))
    # telemetry env contract: a PTPU_TELEMETRY launch gives every
    # worker its own scrape endpoint, ports published as files under
    # the heartbeat dir (scan_port_dir / TelemetryAggregator.add_dir
    # pick them up); flight-recorder bundles land next to them
    if env.get(_obs.TELEMETRY_ENV):
        env.setdefault(_obs.TELEMETRY_DIR_ENV,
                       os.path.join(hb_dir, 'telemetry'))
        env.setdefault(_obs.FLIGHT_ENV,
                       os.path.join(hb_dir, 'flight'))
    env.update(extra_env or {})
    out = None
    if log_dir:
        out = open(os.path.join(
            log_dir, 'worker_g%d_r%d.log' % (gen, rank)), 'wb')
    proc = subprocess.Popen(cmd, env=env, stdout=out,
                            stderr=subprocess.STDOUT if out else None)
    proc._ptpu_log = out
    return proc


def _kill_all(procs, grace=5.0):
    for p in procs:
        if p.poll() is None:
            p.terminate()
    deadline = time.monotonic() + grace
    for p in procs:
        if p.poll() is None:
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
    for p in procs:
        log = getattr(p, '_ptpu_log', None)
        if log:
            log.close()


def launch(cmd, nproc, devices_per_host=1, heartbeat_window=10.0,
           heartbeat_interval=DEFAULT_INTERVAL, poll_interval=0.2,
           max_relaunches=0, startup_grace=180.0, workdir=None,
           log_dir=None, env=None):
    """Run ``cmd`` (argv list) as an ``nproc``-host pod; supervise;
    optionally relaunch degraded. Returns a :class:`LaunchResult`.

    ``max_relaunches`` > 0 makes the pod ELASTIC: each host loss spends
    one relaunch and restarts the surviving count as a new generation
    (workers see ``PTPU_RESUME=1`` and restore the newest checkpoint).
    ``startup_grace`` bounds how long a worker may run before its FIRST
    heartbeat (interpreter + jax import are slow; a missing file only
    counts as a loss after the grace)."""
    cmd = list(cmd)
    base = workdir or log_dir or '.'
    os.makedirs(base, exist_ok=True)
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
    world = int(nproc)
    gen = 0
    generations = []
    # root of the pod-wide span tree: every worker's train/run parents
    # under this via the PTPU_TRACE_PARENT header _spawn exports
    lspan = _obs.start_span('launch/run', nproc=world)
    try:
        return _launch_loop(cmd, world, devices_per_host,
                            heartbeat_window, heartbeat_interval,
                            poll_interval, max_relaunches,
                            startup_grace, base, log_dir, env,
                            generations)
    finally:
        lspan.end(generations=len(generations))


def _launch_loop(cmd, world, devices_per_host, heartbeat_window,
                 heartbeat_interval, poll_interval, max_relaunches,
                 startup_grace, base, log_dir, env, generations):
    gen = 0
    while True:
        port = free_port()
        hb_dir = os.path.join(base, 'hb_gen%d' % gen)
        os.makedirs(hb_dir, exist_ok=True)
        procs = [_spawn(cmd, r, world, gen, port, hb_dir,
                        heartbeat_interval, devices_per_host,
                        os.environ, log_dir, env)
                 for r in range(world)]
        monitor = HostMonitor(hb_dir, window=heartbeat_window,
                              expected=range(world))
        spawn_t = time.monotonic()
        last_alive = {r: spawn_t for r in range(world)}
        record = {'generation': gen, 'world': world, 'failed': {}}
        mh_emit('generation_start', generation=gen, world=world,
                port=port)
        failed = {}
        while True:
            time.sleep(poll_interval)
            now = time.monotonic()
            codes = [p.poll() for p in procs]
            scan = monitor.scan()
            for r, code in enumerate(codes):
                if code is None:
                    last_alive[r] = now
                if r in scan['ages']:
                    last_alive[r] = max(
                        last_alive[r], now - scan['ages'][r])
            for r, code in enumerate(codes):
                if code is not None and code != 0 and r not in failed:
                    failed[r] = ('exit:%s' % code, now - last_alive[r])
            for r in scan['stale']:
                # an exited-ok worker legitimately stops heartbeating
                if codes[r] is None and r not in failed:
                    failed[r] = ('heartbeat_stale:%.2fs'
                                 % scan['ages'][r],
                                 scan['ages'][r])
            if now - spawn_t > startup_grace:
                for r in scan['missing']:
                    if codes[r] is None and r not in failed:
                        failed[r] = ('heartbeat_missing', now - spawn_t)
            if failed:
                break
            if all(code == 0 for code in codes):
                generations.append(record)
                mh_emit('generation_done', generation=gen, world=world)
                _kill_all(procs)
                return LaunchResult(0, generations)
        for r, (reason, detect_s) in sorted(failed.items()):
            record['failed'][r] = reason
            mh_emit('host_lost', host=r, reason=reason,
                    generation=gen, detect_s=round(detect_s, 6),
                    window_s=heartbeat_window)
        generations.append(record)
        # survivors are (or will shortly be) wedged in collectives the
        # dead host can never join: kill them out so the next
        # generation starts from the checkpoint, not a hang
        _kill_all(procs)
        survivors = world - len(failed)
        if gen >= max_relaunches or survivors < 1:
            return LaunchResult(1, generations)
        gen += 1
        world = survivors
        mh_emit('relaunch', generation=gen, world=world)
