"""Remote-process serving cells: a ModelServer living in ANOTHER
process, proxied over a local socket so ``fleet.Router`` /
``ReplicaSupervisor`` manage it unchanged (SERVING.md "Fleet tier").

In-process replicas die with their thread; a HOST dies with all of its
replicas at once. :func:`spawn_cell` starts a worker process running
:func:`serve` (a plain ModelServer behind a length-prefixed pickle
protocol on 127.0.0.1) and returns a :class:`RemoteCell` — an object
with the cell surface the Router already speaks: ``submit`` returning
a future-like request, ``health``, ``load_score``, ``load_model``,
``warmup``, ``drain``, ``swap_model``, ``close``.

Failure mapping is the point: when the worker process dies (kill -9 of
a "host"), the proxy's reader thread sees the socket reset and fails
every in-flight future with the typed ``ServerClosed`` — exactly the
REQUEUEABLE error the fleet's requeue path expects — and ``health()``
raises, so the supervisor marks the replica DEAD and rebuilds it
through the factory (a fresh process). ``tools/chaos_bench.py
--kill-host`` drives this end to end.

The protocol is pickle over a loopback socket between processes of the
SAME user on the SAME machine (the launcher owns both ends) — it is an
IPC transport, not a network service; the listener binds 127.0.0.1 and
accepts exactly one connection.
"""
import os
import pickle
import socket
import struct
import subprocess
import sys
import tempfile
import threading
import time

from .. import observability as _obs
from ..serving.errors import (DeadlineExceeded, ServerClosed,
                              ServingError)

__all__ = ['RemoteCell', 'RemoteRequest', 'spawn_cell', 'serve']

_LEN = struct.Struct('>I')


def _send_msg(sock, obj, lock):
    blob = pickle.dumps(obj, protocol=4)
    with lock:
        sock.sendall(_LEN.pack(len(blob)) + blob)


def _recv_exact(sock, n):
    buf = b''
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError('remote cell connection closed')
        buf += chunk
    return buf


def _recv_msg(sock):
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return pickle.loads(_recv_exact(sock, n))


# ---- worker side ---------------------------------------------------------
def serve(port_file, place=None, kind='serve'):
    """Worker-process main loop: one server cell, one connection.

    ``kind`` picks the cell behind the protocol: ``'serve'`` is a
    plain ModelServer; ``'prefill'`` a
    :class:`~paddle_tpu.kvcache.prefill.PrefillServer` (prompt
    ingestion for disaggregated decode — the generic ``getattr``
    dispatch below covers its ``register_prefill`` op unchanged).

    Binds 127.0.0.1:0, publishes the port atomically through
    ``port_file``, serves requests until ``close`` or EOF. ``submit``
    is asynchronous server-side too — a waiter thread replies when the
    batch resolves, so one slow request never blocks control ops.

    When ``PTPU_JOURNAL`` names a path, the worker installs a
    RunJournal there for its lifetime: TraceContexts arriving on
    ``submit`` (pickled through the protocol) continue their tree in
    this process's own journal, flushed per message so a ``kill -9``
    leaves the in-flight ``span_begin`` on disk — the unclosed span
    trace_report reports for work that died with the host.

    When ``PTPU_TELEMETRY`` is truthy the worker also serves its own
    scrape endpoint (``/metrics`` / ``/health`` / ``/ledgers``),
    publishing the port through ``PTPU_TELEMETRY_DIR`` when set; the
    parent can also fetch it in-band with the ``telemetry_port`` op."""
    jpath = os.environ.get(_obs.JOURNAL_ENV)
    jnl = None
    if jpath:
        jnl = _obs.RunJournal(jpath)
        _obs.set_journal(jnl)
    tel = _obs.install_env_telemetry(name='cell-%d' % os.getpid())
    if kind == 'prefill':
        from ..kvcache.prefill import PrefillServer
        srv = PrefillServer(place=place)
    elif kind == 'serve':
        from ..serving import ModelServer
        srv = ModelServer(place=place)
    else:
        raise ValueError("cell kind must be 'serve' or 'prefill', "
                         'got %r' % (kind,))
    lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    lsock.bind(('127.0.0.1', 0))
    lsock.listen(1)
    port = lsock.getsockname()[1]
    tmp = port_file + '.tmp'
    with open(tmp, 'w') as f:
        f.write('%d\n' % port)
    os.rename(tmp, port_file)
    conn, _ = lsock.accept()
    lsock.close()
    send_lock = threading.Lock()

    def _reply(mid, ok, value):
        try:
            _send_msg(conn, {'id': mid, 'ok': ok, 'value': value},
                      send_lock)
        except (pickle.PicklingError, TypeError):
            _send_msg(conn, {'id': mid, 'ok': False,
                             'value': ServingError(repr(value))},
                      send_lock)
        except OSError:
            pass  # client went away; nothing left to tell

    def _wait_and_reply(mid, req, timeout):
        try:
            _reply(mid, True, req.result(timeout=timeout))
        except Exception as e:  # noqa: BLE001 — forwarded typed
            _reply(mid, False, e)

    try:
        while True:
            try:
                msg = _recv_msg(conn)
            except (ConnectionError, OSError):
                break
            mid, op = msg['id'], msg['op']
            args = msg.get('args', ())
            kwargs = msg.get('kwargs', {})
            if op == 'submit':
                try:
                    req = srv.submit(*args, **kwargs)
                except Exception as e:  # noqa: BLE001 — typed refusal
                    _reply(mid, False, e)
                    continue
                finally:
                    if jnl is not None:
                        jnl.flush()
                timeout = kwargs.get('deadline') or 60.0
                threading.Thread(
                    target=_wait_and_reply, args=(mid, req, timeout),
                    daemon=True).start()
                continue
            if op == 'ping':
                _reply(mid, True, os.getpid())
                continue
            if op == 'telemetry_port':
                _reply(mid, True,
                       tel.port if tel is not None else None)
                continue
            try:
                value = getattr(srv, op)(*args, **kwargs)
            except Exception as e:  # noqa: BLE001 — forwarded typed
                _reply(mid, False, e)
                if op == 'close':
                    break
                continue
            _reply(mid, True, value)
            if op == 'close':
                break
    finally:
        try:
            srv.close(timeout=5.0)
        except Exception:  # noqa: BLE001 — already closed
            pass
        conn.close()
        if tel is not None:
            tel.close()
        if jnl is not None:
            _obs.set_journal(None)
            jnl.close()


# ---- client side ---------------------------------------------------------
class RemoteRequest(object):
    """Future over a submit running in the remote cell. Raises the
    forwarded typed error — a dead cell process fails it with
    ``ServerClosed``, the fleet's requeueable error."""

    __slots__ = ('_event', '_value', '_error')

    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._error = None

    def done(self):
        return self._event.is_set()

    def _complete(self, ok, value):
        if ok:
            self._value = value
        else:
            self._error = value
        self._event.set()

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise DeadlineExceeded(
                'remote cell request timed out after %ss' % timeout)
        if self._error is not None:
            raise self._error
        return self._value


class RemoteCell(object):
    """Client proxy with the replica-cell surface the Router speaks.
    One reader thread demultiplexes replies; process death fails every
    pending future with ServerClosed and makes ``health()`` raise."""

    def __init__(self, proc, sock, name='remote-cell'):
        self.proc = proc
        self.name = name
        self.role = 'serve'        # spawn_cell sets 'prefill' for a
        # kind='prefill' worker; the Router's role-aware placement
        # reads it off the cell like any in-process server
        self.journal_path = None   # set by spawn_cell when tracing
        self._sock = sock
        self._send_lock = threading.Lock()
        self._lock = threading.Lock()
        self._pending = {}
        self._next_id = 0
        self._dead = None
        self._reader = threading.Thread(target=self._read_loop,
                                        daemon=True,
                                        name='ptpu-remote-cell')
        self._reader.start()

    @property
    def pid(self):
        return self.proc.pid

    def _read_loop(self):
        try:
            while True:
                msg = _recv_msg(self._sock)
                with self._lock:
                    req = self._pending.pop(msg['id'], None)
                if req is not None:
                    req._complete(msg['ok'], msg['value'])
        except (ConnectionError, OSError, pickle.UnpicklingError,
                EOFError) as e:
            self._fail_all(ServerClosed(
                'remote cell %r process died: %r' % (self.name, e)))

    def _fail_all(self, error):
        with self._lock:
            if self._dead is None:
                self._dead = error
            pending, self._pending = self._pending, {}
        for req in pending.values():
            req._complete(False, error)

    def _post(self, op, args, kwargs):
        with self._lock:
            if self._dead is not None:
                raise self._dead
            self._next_id += 1
            mid = self._next_id
            req = RemoteRequest()
            self._pending[mid] = req
        try:
            _send_msg(self._sock, {'id': mid, 'op': op, 'args': args,
                                   'kwargs': kwargs}, self._send_lock)
        except (OSError, ConnectionError) as e:
            err = ServerClosed('remote cell %r unreachable: %r'
                               % (self.name, e))
            self._fail_all(err)
            raise err
        return req

    def _call(self, op, *args, **kwargs):
        timeout = kwargs.pop('_timeout', 120.0)
        return self._post(op, args, kwargs).result(timeout=timeout)

    # ---- the cell surface the Router drives ----------------------------
    def submit(self, name, feeds, deadline=None, **kwargs):
        return self._post('submit', (name, feeds),
                          dict(kwargs, deadline=deadline))

    def infer(self, name, feeds, deadline=None, timeout=30.0):
        return self.submit(name, feeds,
                           deadline=deadline).result(timeout=timeout)

    def health(self):
        return self._call('health', _timeout=10.0)

    def telemetry_port(self):
        """The worker's scrape-endpoint port, or None when the cell
        was spawned without ``PTPU_TELEMETRY`` — feed it to
        :meth:`TelemetryAggregator.add_endpoint` for fleet rollups."""
        return self._call('telemetry_port', _timeout=10.0)

    def load_score(self, model_name=None):
        try:
            return self._call('load_score', model_name, _timeout=10.0)
        except ServerClosed:
            return float('inf')  # unroutable, not an exception path

    def load_model(self, name, dirname, model_filename=None,
                   params_filename=None):
        return self._call('load_model', name, dirname,
                          model_filename=model_filename,
                          params_filename=params_filename)

    def swap_model(self, name, dirname, model_filename=None,
                   params_filename=None):
        return self._call('swap_model', name, dirname,
                          model_filename=model_filename,
                          params_filename=params_filename)

    def register_prefill(self, name, spec):
        """Prefill-cell op: build the engine for ``name`` from its
        declarative spec dict in the worker process (the spec is plain
        data, so it pickles through the protocol untouched)."""
        return self._call('register_prefill', name, spec)

    def unload_model(self, name, timeout=None):
        return self._call('unload_model', name, timeout=timeout)

    def drain(self, name, timeout=None):
        return self._call('drain', name, timeout=timeout)

    def warmup(self, model_name=None, upto=None, timeout=300.0):
        return self._call('warmup', model_name, upto=upto,
                          timeout=timeout, _timeout=timeout + 10.0)

    def pause(self, model_name=None):
        return self._call('pause', model_name, _timeout=10.0)

    def resume(self, model_name=None):
        return self._call('resume', model_name, _timeout=10.0)

    def queue_depth(self, model_name):
        return self._call('queue_depth', model_name, _timeout=10.0)

    def models(self):
        return self._call('models', _timeout=10.0)

    def close(self, timeout=30.0):
        try:
            self._call('close', timeout=timeout,
                       _timeout=max(1.0, timeout) + 5.0)
        except (ServerClosed, DeadlineExceeded):
            pass  # already gone — close converges either way
        try:
            self.proc.wait(timeout=max(1.0, timeout))
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait()
        self._fail_all(ServerClosed('remote cell %r closed'
                                    % self.name))
        try:
            self._sock.close()
        except OSError:
            pass

    def kill(self):
        """Chaos hook: SIGKILL the whole cell process — the remote
        analogue of killing a host."""
        self.proc.kill()
        self.proc.wait()


def spawn_cell(name='remote-cell', devices=1, env=None,
               startup_timeout=180.0, kind='serve'):
    """Start a cell worker process and connect to it. The child forces
    the CPU backend with ``devices`` host devices (same recipe as the
    test workers); the parent blocks until the port file appears.
    ``kind='prefill'`` runs a prefill cell (prompt ingestion) instead
    of a ModelServer — the returned proxy carries ``role='prefill'``
    so the Router pins prefill placements to it."""
    workdir = tempfile.mkdtemp(prefix='ptpu_cell_')
    port_file = os.path.join(workdir, 'port')
    child_env = dict(os.environ)
    child_env.update(env or {})
    child_env.setdefault('JAX_PLATFORMS', 'cpu')
    # a journaling parent gets a journaling worker: each process writes
    # its OWN file; trace_report/timeline merge them by trace id.
    # PTPU_TRACE_SAMPLE rides the inherited environ unchanged, so the
    # worker agrees with the parent's sampling decisions.
    journal_path = child_env.get(_obs.JOURNAL_ENV)
    if not journal_path and _obs.journal_active():
        journal_path = os.path.join(workdir, 'journal.jsonl')
        child_env[_obs.JOURNAL_ENV] = journal_path
    flags = child_env.get('XLA_FLAGS', '')
    if 'xla_force_host_platform_device_count' not in flags:
        child_env['XLA_FLAGS'] = (
            flags + ' --xla_force_host_platform_device_count=%d'
            % devices).strip()
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    child_env['PYTHONPATH'] = os.pathsep.join(
        [root] + [p for p in
                  child_env.get('PYTHONPATH', '').split(os.pathsep)
                  if p])
    proc = subprocess.Popen(
        [sys.executable, '-m', 'paddle_tpu.multihost.remote',
         '--port-file', port_file, '--cell-kind', kind],
        env=child_env)
    deadline = time.monotonic() + startup_timeout
    while not os.path.exists(port_file):
        if proc.poll() is not None:
            raise ServerClosed(
                'remote cell %r exited rc=%s before publishing its '
                'port' % (name, proc.returncode))
        if time.monotonic() > deadline:
            proc.kill()
            raise ServerClosed(
                'remote cell %r did not come up within %.0fs'
                % (name, startup_timeout))
        time.sleep(0.05)
    with open(port_file) as f:
        port = int(f.read().strip())
    sock = socket.create_connection(('127.0.0.1', port), timeout=30.0)
    sock.settimeout(None)
    cell = RemoteCell(proc, sock, name=name)
    cell.role = kind
    cell.journal_path = journal_path
    return cell


def _main(argv=None):
    import argparse
    parser = argparse.ArgumentParser(
        description='paddle_tpu remote serving cell worker')
    parser.add_argument('--port-file', required=True)
    parser.add_argument('--cell-kind', default='serve',
                        choices=('serve', 'prefill'))
    args = parser.parse_args(argv)
    serve(args.port_file, kind=args.cell_kind)
    return 0


if __name__ == '__main__':
    # force the CPU backend BEFORE any jax backend initialization (the
    # image's sitecustomize pins a TPU plugin platform)
    import jax

    jax.config.update('jax_platforms',
                      os.environ.get('JAX_PLATFORMS', 'cpu') or 'cpu')
    sys.exit(_main())
