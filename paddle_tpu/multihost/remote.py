"""Remote-process serving cells: a ModelServer living in ANOTHER
process, proxied over a local socket so ``fleet.Router`` /
``ReplicaSupervisor`` manage it unchanged (SERVING.md "Fleet tier").

In-process replicas die with their thread; a HOST dies with all of its
replicas at once. :func:`spawn_cell` starts a worker process running
:func:`serve` (a plain ModelServer behind a length-prefixed pickle
protocol on 127.0.0.1) and returns a :class:`RemoteCell` — an object
with the cell surface the Router already speaks: ``submit`` returning
a future-like request, ``health``, ``load_score``, ``load_model``,
``warmup``, ``drain``, ``swap_model``, ``close``.

Failure mapping is the point: when the worker process dies (kill -9 of
a "host"), the proxy's reader thread sees the socket reset and fails
every in-flight future with the typed ``ServerClosed`` — exactly the
REQUEUEABLE error the fleet's requeue path expects — and ``health()``
raises, so the supervisor marks the replica DEAD and rebuilds it
through the factory (a fresh process). ``tools/chaos_bench.py
--kill-host`` drives this end to end.

The protocol is pickle over a loopback socket between processes of the
SAME user on the SAME machine (the launcher owns both ends) — it is an
IPC transport, not a network service; the listener binds 127.0.0.1 and
accepts exactly one connection.
"""
import os
import pickle
import socket
import struct
import subprocess
import sys
import tempfile
import threading
import time

from .. import observability as _obs
from ..resilience.faultinject import (FaultInjected, SITE_REMOTE_RECV,
                                      SITE_REMOTE_SEND,
                                      SITE_REMOTE_SPAWN, maybe_fault)
from ..resilience.retry import RetryError, retry_call
from ..serving.errors import (DeadlineExceeded, ServerClosed,
                              ServingError)
from .events import mh_emit
from .heartbeat import start_heartbeat, stop_heartbeat

__all__ = ['RemoteCell', 'RemoteRequest', 'spawn_cell', 'serve',
           'DEFAULT_IDLE_TIMEOUT']

_LEN = struct.Struct('>I')

# client-side reader wake-up bound (seconds): how long a recv may idle
# before the reader checks the peer process is still alive. Overridden
# per cell via spawn_cell(idle_timeout=) or PTPU_REMOTE_IDLE_TIMEOUT.
DEFAULT_IDLE_TIMEOUT = 5.0


def _idle_timeout(value=None):
    if value is not None:
        return float(value)
    return float(os.environ.get('PTPU_REMOTE_IDLE_TIMEOUT',
                                DEFAULT_IDLE_TIMEOUT))


def _send_msg(sock, obj, lock, fault_site=None):
    if fault_site is not None:
        # before serialization and the wire: an injected send fault
        # never emits bytes, so the framing stays intact (retryable)
        maybe_fault(fault_site)
    blob = pickle.dumps(obj, protocol=4)
    with lock:
        sock.sendall(_LEN.pack(len(blob)) + blob)


def _recv_exact(sock, n, started=False):
    """Read exactly ``n`` bytes. A socket timeout is only benign while
    NOTHING of the frame has arrived and the caller says no frame is in
    progress (``started=False``) — then it propagates as an idle tick
    for the caller's liveness check. A timeout (or EOF) after partial
    bytes means the peer died mid-frame: the stream can never re-sync,
    so it raises a typed torn-frame ConnectionError."""
    buf = b''
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except socket.timeout:
            if started or buf:
                raise ConnectionError(
                    'torn frame: peer went quiet after %d of %d '
                    'byte(s)' % (len(buf), n))
            raise
        if not chunk:
            if started or buf:
                raise ConnectionError(
                    'torn frame: connection closed after %d of %d '
                    'byte(s)' % (len(buf), n))
            raise ConnectionError('remote cell connection closed')
        buf += chunk
    return buf


def _recv_msg(sock, fault_site=None):
    if fault_site is not None:
        maybe_fault(fault_site)
    header = _recv_exact(sock, _LEN.size)
    (n,) = _LEN.unpack(header)
    # the length prefix arrived: from here on the frame is in progress
    # and any stall/EOF is torn, never an idle tick
    return pickle.loads(_recv_exact(sock, n, started=True))


# ---- worker side ---------------------------------------------------------
def serve(port_file, place=None, kind='serve'):
    """Worker-process main loop: one server cell, one connection.

    ``kind`` picks the cell behind the protocol: ``'serve'`` is a
    plain ModelServer; ``'prefill'`` a
    :class:`~paddle_tpu.kvcache.prefill.PrefillServer` (prompt
    ingestion for disaggregated decode — the generic ``getattr``
    dispatch below covers its ``register_prefill`` op unchanged).

    Binds 127.0.0.1:0, publishes the port atomically through
    ``port_file``, serves requests until ``close`` or EOF. ``submit``
    is asynchronous server-side too — a waiter thread replies when the
    batch resolves, so one slow request never blocks control ops.

    When ``PTPU_JOURNAL`` names a path, the worker installs a
    RunJournal there for its lifetime: TraceContexts arriving on
    ``submit`` (pickled through the protocol) continue their tree in
    this process's own journal, flushed per message so a ``kill -9``
    leaves the in-flight ``span_begin`` on disk — the unclosed span
    trace_report reports for work that died with the host.

    When ``PTPU_TELEMETRY`` is truthy the worker also serves its own
    scrape endpoint (``/metrics`` / ``/health`` / ``/ledgers``),
    publishing the port through ``PTPU_TELEMETRY_DIR`` when set; the
    parent can also fetch it in-band with the ``telemetry_port`` op."""
    jpath = os.environ.get(_obs.JOURNAL_ENV)
    jnl = None
    if jpath:
        jnl = _obs.RunJournal(jpath)
        _obs.set_journal(jnl)
    # fleet liveness contract: a cell spawned with a heartbeat dir
    # (PTPU_HB_DIR / PTPU_PROC_ID / PTPU_HB_INTERVAL) beats into it
    # from the very top — BEFORE the slow cell construction below — so
    # the prober sees the host live as early as possible
    start_heartbeat()
    tel = _obs.install_env_telemetry(name='cell-%d' % os.getpid())
    if kind == 'prefill':
        from ..kvcache.prefill import PrefillServer
        srv = PrefillServer(place=place)
    elif kind == 'serve':
        from ..serving import ModelServer
        # batch envelope contract: a cell standing in for a local
        # replica must accept the same request sizes the router's
        # local servers do, so the spawner exports the envelope into
        # the child env (RemoteBackend(env=...)) instead of the cell
        # guessing ModelServer defaults
        kw = {}
        if os.environ.get('PTPU_CELL_MAX_BATCH'):
            kw['max_batch_size'] = int(os.environ['PTPU_CELL_MAX_BATCH'])
        if os.environ.get('PTPU_CELL_MAX_QUEUE'):
            kw['max_queue_depth'] = int(os.environ['PTPU_CELL_MAX_QUEUE'])
        srv = ModelServer(place=place, **kw)
    else:
        raise ValueError("cell kind must be 'serve' or 'prefill', "
                         'got %r' % (kind,))
    lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    lsock.bind(('127.0.0.1', 0))
    lsock.listen(1)
    port = lsock.getsockname()[1]
    tmp = port_file + '.tmp'
    with open(tmp, 'w') as f:
        f.write('%d\n' % port)
    os.rename(tmp, port_file)
    conn, _ = lsock.accept()
    lsock.close()
    send_lock = threading.Lock()

    def _reply(mid, ok, value):
        try:
            _send_msg(conn, {'id': mid, 'ok': ok, 'value': value},
                      send_lock)
        except (pickle.PicklingError, TypeError):
            _send_msg(conn, {'id': mid, 'ok': False,
                             'value': ServingError(repr(value))},
                      send_lock)
        except OSError:
            pass  # client went away; nothing left to tell

    def _wait_and_reply(mid, req, timeout):
        try:
            _reply(mid, True, req.result(timeout=timeout))
        except Exception as e:  # noqa: BLE001 — forwarded typed
            _reply(mid, False, e)

    try:
        while True:
            try:
                msg = _recv_msg(conn)
            except (ConnectionError, OSError):
                break
            mid, op = msg['id'], msg['op']
            args = msg.get('args', ())
            kwargs = msg.get('kwargs', {})
            if op == 'submit':
                try:
                    req = srv.submit(*args, **kwargs)
                except Exception as e:  # noqa: BLE001 — typed refusal
                    _reply(mid, False, e)
                    continue
                finally:
                    if jnl is not None:
                        jnl.flush()
                timeout = kwargs.get('deadline') or 60.0
                threading.Thread(
                    target=_wait_and_reply, args=(mid, req, timeout),
                    daemon=True).start()
                continue
            if op == 'ping':
                _reply(mid, True, os.getpid())
                continue
            if op == 'telemetry_port':
                _reply(mid, True,
                       tel.port if tel is not None else None)
                continue
            try:
                value = getattr(srv, op)(*args, **kwargs)
            except Exception as e:  # noqa: BLE001 — forwarded typed
                _reply(mid, False, e)
                if op == 'close':
                    break
                continue
            _reply(mid, True, value)
            if op == 'close':
                break
    finally:
        try:
            srv.close(timeout=5.0)
        except Exception:  # noqa: BLE001 — already closed
            pass
        conn.close()
        stop_heartbeat()
        if tel is not None:
            tel.close()
        if jnl is not None:
            _obs.set_journal(None)
            jnl.close()


# ---- client side ---------------------------------------------------------
class RemoteRequest(object):
    """Future over a submit running in the remote cell. Raises the
    forwarded typed error — a dead cell process fails it with
    ``ServerClosed``, the fleet's requeueable error."""

    __slots__ = ('_event', '_value', '_error')

    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._error = None

    def done(self):
        return self._event.is_set()

    def _complete(self, ok, value):
        if ok:
            self._value = value
        else:
            self._error = value
        self._event.set()

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise DeadlineExceeded(
                'remote cell request timed out after %ss' % timeout)
        if self._error is not None:
            raise self._error
        return self._value


class RemoteCell(object):
    """Client proxy with the replica-cell surface the Router speaks.
    One reader thread demultiplexes replies; process death fails every
    pending future with ServerClosed and makes ``health()`` raise."""

    def __init__(self, proc, sock, name='remote-cell'):
        self.proc = proc
        self.name = name
        self.role = 'serve'        # spawn_cell sets 'prefill' for a
        # kind='prefill' worker; the Router's role-aware placement
        # reads it off the cell like any in-process server
        self.journal_path = None   # set by spawn_cell when tracing
        self._sock = sock
        self._send_lock = threading.Lock()
        self._lock = threading.Lock()
        self._pending = {}
        self._next_id = 0
        self._dead = None
        self._reader = threading.Thread(target=self._read_loop,
                                        daemon=True,
                                        name='ptpu-remote-cell')
        self._reader.start()

    @property
    def pid(self):
        return self.proc.pid

    def _read_loop(self):
        try:
            while True:
                try:
                    msg = _recv_msg(self._sock,
                                    fault_site=SITE_REMOTE_RECV)
                except socket.timeout:
                    # bounded idle tick (socket.timeout subclasses
                    # OSError, so it MUST be caught before the fatal
                    # clause below): nothing arrived inside the idle
                    # window — fine for a living idle peer, fatal for
                    # one whose process is gone with the socket
                    # half-open
                    if self.proc is not None \
                            and self.proc.poll() is not None:
                        raise ConnectionError(
                            'peer process exited rc=%s with the '
                            'socket half-open'
                            % self.proc.returncode)
                    continue
                with self._lock:
                    req = self._pending.pop(msg['id'], None)
                if req is not None:
                    req._complete(msg['ok'], msg['value'])
        except (ConnectionError, OSError, pickle.UnpicklingError,
                EOFError) as e:
            self._fail_all(ServerClosed(
                'remote cell %r process died: %r' % (self.name, e)))

    def _fail_all(self, error):
        with self._lock:
            if self._dead is None:
                self._dead = error
            pending, self._pending = self._pending, {}
        for req in pending.values():
            req._complete(False, error)

    def _post(self, op, args, kwargs):
        with self._lock:
            if self._dead is not None:
                raise self._dead
            self._next_id += 1
            mid = self._next_id
            req = RemoteRequest()
            self._pending[mid] = req
        try:
            _send_msg(self._sock, {'id': mid, 'op': op, 'args': args,
                                   'kwargs': kwargs}, self._send_lock,
                      fault_site=SITE_REMOTE_SEND)
        except FaultInjected:
            # an injected send fault fires before any bytes hit the
            # wire (see _send_msg), so the connection is still framed
            # and healthy: drop the orphaned pending slot and let the
            # caller (or _call_idempotent's retry) decide — FaultInjected
            # is an IOError, so this clause must precede OSError below
            with self._lock:
                self._pending.pop(mid, None)
            raise
        except (OSError, ConnectionError) as e:
            err = ServerClosed('remote cell %r unreachable: %r'
                               % (self.name, e))
            self._fail_all(err)
            raise err
        return req

    def _call(self, op, *args, **kwargs):
        timeout = kwargs.pop('_timeout', 120.0)
        return self._post(op, args, kwargs).result(timeout=timeout)

    def _call_idempotent(self, op, *args, **kwargs):
        """Read-only control ops (health, load_score, ...) retried
        with bounded backoff on transient transport faults.

        Only faults that provably never touched the wire are safely
        retryable on this protocol — anything that emitted partial
        bytes desyncs the length-prefixed framing and is terminal
        (ServerClosed via ``_fail_all``). In practice that means the
        ``remote/send`` injected faults plus pre-send errors; the
        retry is what keeps a control probe alive through a blip the
        fault plan (or a flaky loopback) models."""
        timeout = kwargs.pop('_timeout', 10.0)
        retries = _obs.default_registry().counter(
            'remote_rpc_retries_total',
            'idempotent remote-cell control ops retried after a '
            'transient transport fault')

        def _attempt():
            return self._post(op, args, kwargs).result(timeout=timeout)

        try:
            return retry_call(_attempt, max_attempts=3, backoff=0.05,
                              jitter=0.0, retry_on=(FaultInjected,),
                              on_retry=lambda a, e: retries.inc())
        except RetryError as e:
            raise ServerClosed(
                'remote cell %r control op %r kept faulting: %r'
                % (self.name, op, e.last_error)) from e

    # ---- the cell surface the Router drives ----------------------------
    def submit(self, name, feeds, deadline=None, **kwargs):
        return self._post('submit', (name, feeds),
                          dict(kwargs, deadline=deadline))

    def infer(self, name, feeds, deadline=None, timeout=30.0):
        return self.submit(name, feeds,
                           deadline=deadline).result(timeout=timeout)

    def ping(self):
        """Round-trip liveness probe; returns the worker's pid."""
        return self._call_idempotent('ping', _timeout=10.0)

    def health(self):
        return self._call_idempotent('health', _timeout=10.0)

    def telemetry_port(self):
        """The worker's scrape-endpoint port, or None when the cell
        was spawned without ``PTPU_TELEMETRY`` — feed it to
        :meth:`TelemetryAggregator.add_endpoint` for fleet rollups."""
        return self._call_idempotent('telemetry_port', _timeout=10.0)

    def load_score(self, model_name=None):
        try:
            return self._call_idempotent('load_score', model_name,
                                         _timeout=10.0)
        except ServerClosed:
            return float('inf')  # unroutable, not an exception path

    def load_model(self, name, dirname, model_filename=None,
                   params_filename=None):
        return self._call('load_model', name, dirname,
                          model_filename=model_filename,
                          params_filename=params_filename)

    def swap_model(self, name, dirname, model_filename=None,
                   params_filename=None):
        return self._call('swap_model', name, dirname,
                          model_filename=model_filename,
                          params_filename=params_filename)

    def register_prefill(self, name, spec):
        """Prefill-cell op: build the engine for ``name`` from its
        declarative spec dict in the worker process (the spec is plain
        data, so it pickles through the protocol untouched)."""
        return self._call('register_prefill', name, spec)

    def unload_model(self, name, timeout=None):
        return self._call('unload_model', name, timeout=timeout)

    def drain(self, name, timeout=None):
        return self._call('drain', name, timeout=timeout)

    def warmup(self, model_name=None, upto=None, timeout=300.0):
        return self._call('warmup', model_name, upto=upto,
                          timeout=timeout, _timeout=timeout + 10.0)

    def pause(self, model_name=None):
        return self._call('pause', model_name, _timeout=10.0)

    def resume(self, model_name=None):
        return self._call('resume', model_name, _timeout=10.0)

    def queue_depth(self, model_name):
        return self._call_idempotent('queue_depth', model_name,
                                     _timeout=10.0)

    def models(self):
        return self._call_idempotent('models', _timeout=10.0)

    def close(self, timeout=30.0):
        try:
            self._call('close', timeout=timeout,
                       _timeout=max(1.0, timeout) + 5.0)
        except (ServerClosed, DeadlineExceeded, FaultInjected):
            pass  # already gone — close converges either way
        try:
            self.proc.wait(timeout=max(1.0, timeout))
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait()
        self._fail_all(ServerClosed('remote cell %r closed'
                                    % self.name))
        try:
            self._sock.close()
        except OSError:
            pass
        # the reader wakes within one idle window (sock.close makes
        # its recv raise) — join so close() leaves zero stuck threads
        self._reader.join(timeout=_idle_timeout() + 5.0)

    def kill(self):
        """Chaos hook: SIGKILL the whole cell process — the remote
        analogue of killing a host."""
        self.proc.kill()
        self.proc.wait()


def _reap(proc):
    """Kill + wait: a ``kill()`` without the ``wait()`` leaves a
    zombie the parent carries until exit."""
    try:
        if proc.poll() is None:
            proc.kill()
        proc.wait(timeout=10.0)
    except (OSError, subprocess.TimeoutExpired):
        pass  # already reaped elsewhere, or unkillable — give up


def spawn_cell(name='remote-cell', devices=1, env=None,
               startup_timeout=180.0, kind='serve',
               heartbeat_dir=None, host_id=None,
               heartbeat_interval=None, idle_timeout=None):
    """Start a cell worker process and connect to it. The child forces
    the CPU backend with ``devices`` host devices (same recipe as the
    test workers); the parent blocks until the port file appears.
    ``kind='prefill'`` runs a prefill cell (prompt ingestion) instead
    of a ModelServer — the returned proxy carries ``role='prefill'``
    so the Router pins prefill placements to it.

    Elastic-fleet contracts (RESILIENCE.md "Cross-host elasticity"):
    ``heartbeat_dir``/``host_id``/``heartbeat_interval`` export the
    PTPU_HB_* env so the worker beats into the fleet heartbeat dir;
    the parent's active AOT cache dir (env OR ``coldstart.cache_scope``
    — the scope is a process-local override the child can't otherwise
    see) is exported as ``PTPU_AOT_CACHE`` so the remote ``warmup()``
    deserializes sealed executables instead of recompiling; the client
    socket gets a bounded ``idle_timeout`` (default
    PTPU_REMOTE_IDLE_TIMEOUT / 5s) so the reader can never block
    forever on a partitioned peer. Every failed spawn reaps the child
    (kill + wait) and journals a ``spawn_failed`` multihost event."""
    maybe_fault(SITE_REMOTE_SPAWN)
    t0 = time.monotonic()
    workdir = tempfile.mkdtemp(prefix='ptpu_cell_')
    port_file = os.path.join(workdir, 'port')
    child_env = dict(os.environ)
    child_env.update(env or {})
    child_env.setdefault('JAX_PLATFORMS', 'cpu')
    # a journaling parent gets a journaling worker: each process writes
    # its OWN file; trace_report/timeline merge them by trace id.
    # PTPU_TRACE_SAMPLE rides the inherited environ unchanged, so the
    # worker agrees with the parent's sampling decisions.
    journal_path = child_env.get(_obs.JOURNAL_ENV)
    if not journal_path and _obs.journal_active():
        journal_path = os.path.join(workdir, 'journal.jsonl')
        child_env[_obs.JOURNAL_ENV] = journal_path
    if heartbeat_dir is not None:
        child_env['PTPU_HB_DIR'] = str(heartbeat_dir)
        child_env['PTPU_PROC_ID'] = str(int(host_id or 0))
        if heartbeat_interval is not None:
            child_env['PTPU_HB_INTERVAL'] = str(heartbeat_interval)
    from ..fleet import coldstart as _coldstart  # lazy: fleet is heavy
    aot_dir = _coldstart.cache_dir()
    _coldstart.export_env(child_env)
    flags = child_env.get('XLA_FLAGS', '')
    if 'xla_force_host_platform_device_count' not in flags:
        child_env['XLA_FLAGS'] = (
            flags + ' --xla_force_host_platform_device_count=%d'
            % devices).strip()
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    child_env['PYTHONPATH'] = os.pathsep.join(
        [root] + [p for p in
                  child_env.get('PYTHONPATH', '').split(os.pathsep)
                  if p])
    proc = subprocess.Popen(
        [sys.executable, '-m', 'paddle_tpu.multihost.remote',
         '--port-file', port_file, '--cell-kind', kind],
        env=child_env)
    try:
        deadline = time.monotonic() + startup_timeout
        while not os.path.exists(port_file):
            if proc.poll() is not None:
                raise ServerClosed(
                    'remote cell %r exited rc=%s before publishing '
                    'its port' % (name, proc.returncode))
            if time.monotonic() > deadline:
                raise ServerClosed(
                    'remote cell %r did not come up within %.0fs'
                    % (name, startup_timeout))
            time.sleep(0.05)
        with open(port_file) as f:
            port = int(f.read().strip())
        sock = socket.create_connection(('127.0.0.1', port),
                                        timeout=30.0)
    except BaseException as e:
        # EVERY failed spawn reaps the child: the old code left a
        # zombie on startup timeout and leaked the process entirely
        # when create_connection failed after the port file appeared
        _reap(proc)
        mh_emit('spawn_failed', name=name, kind=kind, pid=proc.pid,
                reason=repr(e),
                dur_s=round(time.monotonic() - t0, 6))
        raise
    # bounded idle timeout: the reader wakes at least this often to
    # verify the peer process is alive instead of blocking forever
    sock.settimeout(_idle_timeout(idle_timeout))
    cell = RemoteCell(proc, sock, name=name)
    cell.role = kind
    cell.journal_path = journal_path
    dur_s = time.monotonic() - t0
    _obs.default_registry().histogram(
        'remote_spawn_seconds',
        'wall seconds from spawn_cell() to a connected remote cell'
    ).observe(dur_s)
    mh_emit('spawn', name=name, kind=kind, pid=proc.pid,
            host_id=host_id, aot_warm=bool(aot_dir),
            dur_s=round(dur_s, 6))
    return cell


def _main(argv=None):
    import argparse
    parser = argparse.ArgumentParser(
        description='paddle_tpu remote serving cell worker')
    parser.add_argument('--port-file', required=True)
    parser.add_argument('--cell-kind', default='serve',
                        choices=('serve', 'prefill'))
    args = parser.parse_args(argv)
    serve(args.port_file, kind=args.cell_kind)
    return 0


if __name__ == '__main__':
    # force the CPU backend BEFORE any jax backend initialization (the
    # image's sitecustomize pins a TPU plugin platform)
    import jax

    jax.config.update('jax_platforms',
                      os.environ.get('JAX_PLATFORMS', 'cpu') or 'cpu')
    sys.exit(_main())
