"""Host heartbeats: mtime-based liveness files, stdlib-only
(RESILIENCE.md "Surviving host loss").

Each worker process runs a :class:`HeartbeatWriter` daemon thread that
touches ``host_<id>.hb`` in a shared directory every interval; the
launcher's :class:`HostMonitor` reads nothing but file mtimes, so the
mechanism works over any shared filesystem and needs no sockets, no
collectives and no cooperation from a wedged worker — a host stuck in
a hung cross-host collective simply stops touching its file and ages
out within the bounded window.

Telemetry: ``host_heartbeat_age_seconds{host=...}`` gauge per scanned
host and the ``multihost_peers`` gauge (hosts currently inside the
window), both refreshed by :meth:`HostMonitor.scan`.
"""
import os
import re
import threading
import time

from .. import observability as _obs

__all__ = ['DEFAULT_INTERVAL', 'heartbeat_path', 'remove_heartbeat',
           'HeartbeatWriter', 'HostMonitor', 'start_heartbeat',
           'stop_heartbeat']

DEFAULT_INTERVAL = 0.5
_HB_RE = re.compile(r'^host_(\d+)\.hb$')


def heartbeat_path(dirname, host_id):
    return os.path.join(dirname, 'host_%03d.hb' % int(host_id))


def remove_heartbeat(dirname, host_id):
    """Retire a host's heartbeat file — a lost or scaled-in cell must
    leave the directory, or every future scan keeps reporting it stale
    (and its age gauge frozen). Returns whether a file was removed."""
    try:
        os.remove(heartbeat_path(dirname, host_id))
        return True
    except OSError:
        return False


class HeartbeatWriter(object):
    """Touches this host's heartbeat file every ``interval`` seconds
    from a daemon thread. ``start`` writes the first beat inline so a
    freshly spawned worker is visible before its first tick."""

    def __init__(self, dirname, host_id, interval=DEFAULT_INTERVAL):
        self.dirname = dirname
        self.host_id = int(host_id)
        self.interval = float(interval)
        self.path = heartbeat_path(dirname, host_id)
        self._stop = threading.Event()
        self._thread = None

    def beat(self):
        with open(self.path, 'w') as f:
            f.write('%d %.6f\n' % (os.getpid(), time.time()))
        # an explicit utime survives filesystems with coarse write
        # timestamps
        os.utime(self.path, None)

    def start(self):
        if self._thread is not None:
            return self
        os.makedirs(self.dirname, exist_ok=True)
        self.beat()

        def _loop():
            while not self._stop.wait(self.interval):
                try:
                    self.beat()
                except OSError:
                    pass  # transient shared-fs hiccup: retry next tick

        self._thread = threading.Thread(target=_loop, daemon=True,
                                        name='ptpu-heartbeat')
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval + 1.0)
            self._thread = None


class HostMonitor(object):
    """Supervisor-side scanner: classifies every expected host as
    alive, stale (heartbeat older than ``window``) or missing (no
    heartbeat file yet). ``expected`` defaults to whatever host files
    exist — pass the rank list for a launcher that must also notice a
    worker that never wrote its first beat."""

    def __init__(self, dirname, window=10.0, expected=None):
        self.dirname = dirname
        self.window = float(window)
        self.expected = None if expected is None \
            else sorted(int(h) for h in expected)
        reg = _obs.default_registry()
        self._g_peers = reg.gauge(
            'multihost_peers',
            'hosts currently inside the heartbeat window')
        self._reg = reg
        self._published = set()   # host ids with a live age gauge

    def ages(self, now=None):
        """host id -> heartbeat age in seconds, for every host file
        present in the directory."""
        now = time.time() if now is None else now
        out = {}
        try:
            names = os.listdir(self.dirname)
        except OSError:
            return out
        for name in names:
            m = _HB_RE.match(name)
            if not m:
                continue
            try:
                mtime = os.path.getmtime(
                    os.path.join(self.dirname, name))
            except OSError:
                continue  # racing a concurrent rewrite
            out[int(m.group(1))] = max(0.0, now - mtime)
        return out

    def scan(self, now=None):
        """One supervision pass: ``{'alive': [...], 'stale': [...],
        'missing': [...], 'ages': {host: age}}`` + gauge refresh."""
        ages = self.ages(now=now)
        expected = self.expected if self.expected is not None \
            else sorted(ages)
        alive, stale, missing = [], [], []
        for h in expected:
            age = ages.get(h)
            if age is None:
                missing.append(h)
            elif age > self.window:
                stale.append(h)
            else:
                alive.append(h)
        for h, age in sorted(ages.items()):
            self._reg.gauge(
                'host_heartbeat_age_seconds',
                'seconds since a host last touched its heartbeat',
                host=str(h)).set(round(age, 6))
        # retire gauges for hosts whose heartbeat file is gone (a
        # retired/relaunched-elsewhere host): a dashboard must agree
        # with scan() about which hosts exist, not show a frozen age
        for h in self._published - set(ages):
            self._reg.remove('host_heartbeat_age_seconds', host=str(h))
        self._published = set(ages)
        self._g_peers.set(len(alive))
        return {'alive': alive, 'stale': stale, 'missing': missing,
                'ages': ages}


_WRITER = None


def start_heartbeat(dirname=None, host_id=None, interval=None):
    """Start (once) this process's heartbeat from explicit args or the
    launcher-provided env (``PTPU_HB_DIR`` / ``PTPU_PROC_ID`` /
    ``PTPU_HB_INTERVAL``). Returns the writer, or None when no
    heartbeat directory is configured."""
    global _WRITER
    if _WRITER is not None:
        return _WRITER
    dirname = dirname if dirname is not None \
        else os.environ.get('PTPU_HB_DIR')
    if not dirname:
        return None
    host_id = int(host_id if host_id is not None
                  else os.environ.get('PTPU_PROC_ID', 0))
    interval = float(interval if interval is not None
                     else os.environ.get('PTPU_HB_INTERVAL',
                                         DEFAULT_INTERVAL))
    _WRITER = HeartbeatWriter(dirname, host_id,
                              interval=interval).start()
    return _WRITER


def stop_heartbeat():
    global _WRITER
    if _WRITER is not None:
        _WRITER.stop()
        _WRITER = None
