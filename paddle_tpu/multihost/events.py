"""``multihost`` journal events shared by workers AND the launcher.

The in-process journal (observability.journal) covers one process; a
pod is many. When ``PTPU_MULTIHOST_JOURNAL`` names a file on shared
storage, every emit ALSO appends one JSON line there (open-append-close
per record: O_APPEND writes under the pipe-buffer size are atomic, so
concurrent writers interleave whole lines, never bytes). The merged
stream is what ``tools/obs_report.py --require multihost`` gates on:
bootstrap / barrier / host_lost / relaunch events across the whole pod
in one place.
"""
import json
import os
import time

from .. import observability as _obs

__all__ = ['JOURNAL_ENV', 'mh_emit']

JOURNAL_ENV = 'PTPU_MULTIHOST_JOURNAL'


def mh_emit(action, **fields):
    """Emit a ``multihost`` event into the in-process journal (if one
    is installed) and the shared pod journal (if configured)."""
    _obs.emit('multihost', action=action, **fields)
    path = os.environ.get(JOURNAL_ENV)
    if not path:
        return
    rec = {'ev': 'multihost', 'action': action, 'pid': os.getpid(),
           'ts': round(time.time(), 6)}
    rec.update(fields)
    try:
        with open(path, 'a') as f:
            f.write(json.dumps(rec, sort_keys=True, default=repr)
                    + '\n')
    except OSError:
        pass  # telemetry must never take down the run
