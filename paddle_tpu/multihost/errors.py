"""Typed errors of the multi-host runtime (RESILIENCE.md "Surviving
host loss").

Every failure mode a pod launcher or a bootstrap handshake can hit has
a named exception carrying the identifying facts (host id, coordinator
address, divergent digests) — supervisors branch on TYPE, log messages
stay for humans. ``BootstrapTimeout`` replaces the silent hang a
worker used to sit in when the coordinator never came up.
"""

__all__ = ['MultihostError', 'BootstrapTimeout', 'HostMismatch',
           'HostLost']


class MultihostError(RuntimeError):
    """Base of every multi-host runtime failure."""


class BootstrapTimeout(MultihostError):
    """jax.distributed.initialize could not reach (or barrier with)
    the coordinator within the bounded handshake window."""

    def __init__(self, coordinator, process_id, num_processes,
                 attempts, timeout, cause=None):
        self.coordinator = coordinator
        self.process_id = int(process_id)
        self.num_processes = int(num_processes)
        self.attempts = int(attempts)
        self.timeout = float(timeout)
        self.cause = cause
        super(BootstrapTimeout, self).__init__(
            'multi-host bootstrap timed out: process %d/%d could not '
            'join coordinator %s within %.1fs (%d attempt(s))%s'
            % (self.process_id, self.num_processes, coordinator,
               self.timeout, self.attempts,
               '; last error: %r' % (cause,) if cause else ''))


class HostMismatch(MultihostError):
    """Cross-host agreement check failed: the named hosts computed a
    different (program fingerprint, mesh, rules) digest than the rest
    of the pod — running them together would wedge or silently diverge,
    so the job fails fast instead."""

    def __init__(self, tag, divergent, digests):
        self.tag = tag
        self.divergent = list(divergent)
        self.digests = list(digests)
        super(HostMismatch, self).__init__(
            'multi-host agreement check %r failed: host(s) %s diverge '
            'from the pod (digests: %s)'
            % (tag, ', '.join(str(h) for h in self.divergent),
               ', '.join('%d=%s' % (i, d[:12])
                         for i, d in enumerate(self.digests))))


class HostLost(MultihostError):
    """A supervised host died (nonzero exit) or stalled (stale
    heartbeat) — raised/recorded by the launcher supervisor."""

    def __init__(self, host, reason, age=None):
        self.host = int(host)
        self.reason = reason
        self.age = age
        super(HostLost, self).__init__(
            'host %d lost: %s%s' % (self.host, reason,
                                    '' if age is None
                                    else ' (heartbeat age %.2fs)' % age))
