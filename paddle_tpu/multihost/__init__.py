"""paddle_tpu.multihost — the multi-process (pod) runtime.

Makes "N hosts" a first-class, failure-tolerant deployment unit
(PARTITIONING.md "Multi-host meshes", RESILIENCE.md "Surviving host
loss"):

- :mod:`bootstrap` — bounded-timeout retrying
  ``jax.distributed.initialize`` (typed :class:`BootstrapTimeout`
  instead of a silent hang), pod barriers, and cross-host agreement
  checks (:func:`agreement_check` — program fingerprint + mesh + rules
  hashed and allgathered; a divergent host fails fast with
  :class:`HostMismatch` naming it).
- :mod:`heartbeat` — stdlib-only mtime heartbeat files in a shared
  dir; :class:`HostMonitor` classifies hosts alive/stale/missing
  within a bounded window.
- :mod:`launcher` — the ``tools/launch.py`` engine: spawn one process
  per host, supervise exits + heartbeats, kill survivors out of hung
  collectives on a host loss, relaunch a degraded generation that
  resumes from the newest sharded checkpoint.
- :mod:`remote` — a ModelServer cell in a REMOTE process behind a
  socket proxy, so ``fleet.Router`` survives whole-host loss of its
  replicas (``tools/chaos_bench.py --kill-host``).

The in-script surface stays reference-compatible:
``DistributeTranspiler.transpile`` routes through
:func:`bootstrap.initialize`, so existing multi-trainer scripts gain
the bounded handshake without changes.
"""
from .errors import (MultihostError, BootstrapTimeout,  # noqa: F401
                     HostMismatch, HostLost)
from .bootstrap import (initialize, barrier, broadcast_int,  # noqa
                        agreement_check)
from .heartbeat import (HeartbeatWriter, HostMonitor,  # noqa: F401
                        start_heartbeat, stop_heartbeat,
                        heartbeat_path, remove_heartbeat)
from .launcher import launch, free_port, LaunchResult  # noqa: F401
from .remote import RemoteCell, spawn_cell, serve  # noqa: F401
from .events import mh_emit, JOURNAL_ENV  # noqa: F401

__all__ = [
    'MultihostError', 'BootstrapTimeout', 'HostMismatch', 'HostLost',
    'initialize', 'barrier', 'broadcast_int', 'agreement_check',
    'HeartbeatWriter', 'HostMonitor', 'start_heartbeat',
    'stop_heartbeat', 'heartbeat_path', 'remove_heartbeat',
    'launch', 'free_port', 'LaunchResult',
    'RemoteCell', 'spawn_cell', 'serve',
    'mh_emit', 'JOURNAL_ENV',
]
