"""Minimal graphviz dot builder (no external binary needed).

Parity: python/paddle/fluid/graphviz.py — same Graph/Node/Edge surface;
``Graph.show``/``save`` write the .dot text (rendering to PNG requires a
dot binary, which this zero-egress image may lack, so saving the source
is the supported path).
"""

__all__ = ['Graph', 'Node', 'Edge', 'GraphPreviewGenerator']


def crepr(v):
    if isinstance(v, str):
        return '"%s"' % v
    return str(v)


class Rank(object):
    def __init__(self, kind, name, priority):
        self.kind = kind
        self.name = name
        self.priority = priority
        self.nodes = []


class Node(object):
    counter = 1

    def __init__(self, label, prefix, description="", **attrs):
        self.label = label
        self.name = "%s_%d" % (prefix, Node.counter)
        Node.counter += 1
        self.attrs = attrs
        self.attrs['label'] = label

    def __str__(self):
        attrs = ','.join('%s=%s' % (k, crepr(v))
                         for k, v in sorted(self.attrs.items()))
        return "%s [%s]" % (self.name, attrs)


class Edge(object):
    def __init__(self, source, target, **attrs):
        self.source = source
        self.target = target
        self.attrs = attrs

    def __str__(self):
        attrs = ','.join('%s=%s' % (k, crepr(v))
                         for k, v in sorted(self.attrs.items()))
        return "%s -> %s [%s]" % (self.source.name, self.target.name,
                                  attrs)


class Graph(object):
    rank_counter = 0

    def __init__(self, title, **attrs):
        self.title = title
        self.attrs = attrs
        self.nodes = []
        self.edges = []
        self.rank_groups = {}

    def code(self):
        lines = ["digraph G {"]
        for k, v in sorted(self.attrs.items()):
            lines.append("  %s=%s;" % (k, crepr(v)))
        for n in self.nodes:
            lines.append("  " + str(n))
        for e in self.edges:
            lines.append("  " + str(e))
        lines.append("}")
        return "\n".join(lines)

    def node(self, label, prefix="node", description="", **attrs):
        n = Node(label, prefix, description, **attrs)
        self.nodes.append(n)
        return n

    def edge(self, source, target, **attrs):
        e = Edge(source, target, **attrs)
        self.edges.append(e)
        return e

    def save(self, path):
        with open(path, 'w') as f:
            f.write(self.code())
        return path

    # parity alias: reference pipes through `dot`; we persist the source
    show = save

    def __str__(self):
        return self.code()


class GraphPreviewGenerator(object):
    """Parity: graphviz.py::GraphPreviewGenerator (data-flow previews)."""

    def __init__(self, title):
        self.graph = Graph(title, layout="dot")

    def add_param(self, name, data_type, highlight=False):
        return self.graph.node(
            "%s\n%s" % (name, data_type), prefix="param",
            shape="box", style="filled",
            fillcolor="yellow" if highlight else "lightgrey")

    def add_op(self, opType, **kwargs):
        # plain label: crepr() double-quotes, so HTML-like <...> markup
        # would render literally
        return self.graph.node(opType, prefix="op", shape="ellipse")

    def add_arg(self, name, highlight=False):
        return self.graph.node(name, prefix="arg", shape="box",
                               style="rounded")

    def add_edge(self, source, target, **kwargs):
        return self.graph.edge(source, target, **kwargs)
