"""Shape bucketing: pad variable client batch sizes into a small set of
power-of-two buckets so every inference run hits the Executor's
compiled-program cache.

XLA compiles one executable per input-shape signature; a serving
frontend that forwards raw client batch sizes (1, 3, 7, 12, ...) pays a
multi-second trace+compile for every new size. Rounding the batch
dimension up to the next power of two bounds the number of compiled
variants at ``log2(max_batch)`` while wasting at most 2x compute on the
padded rows — the classic serving trade (TVM / TensorRT / TF-Serving
all make it). Padding repeats the last real row by default so padded
rows stay in-distribution (no log(0) / division-by-zero surprises in
exotic nets). Pad-row *content* never affects real rows in a
row-independent net — each real row's value is exactly what the
bucket-sized run computes for it. One honest caveat: XLA selects
kernels per batch size, and a different kernel can round differently
at ~1 ulp (measured: the M=1 gemv path vs the M>=2 gemm path on CPU
differ by 4.8e-7 on O(1) values; rows are stable across all M>=2 and
across pad content). The serving tests pin full bit-exactness for
their nets; nets that straddle such a kernel boundary see at most
ulp-level drift vs the raw-size run — the same drift the reference
framework exhibits between its own per-batch-size recompiles. Set
``min_bucket=2`` to keep every run on the gemm path if run-to-run
consistency for 1-row requests matters more than 1-row latency.
"""
import numpy as np

from ..lod import SequenceTensor

__all__ = ['BucketPolicy', 'next_pow2', 'run_bucketed']


def next_pow2(n):
    """Smallest power of two >= n (n >= 1)."""
    if n < 1:
        raise ValueError('batch size must be >= 1, got %r' % (n,))
    return 1 << (int(n) - 1).bit_length()


class BucketPolicy(object):
    """Maps a raw batch size to its padded bucket size.

    ``min_bucket``/``max_bucket`` clamp the power-of-two ladder: a tiny
    floor avoids compiling near-duplicate small shapes, the ceiling is
    the largest batch a single run may carry (requests larger than
    ``max_bucket`` are rejected by the server's admission control).
    ``pad_mode`` is ``'edge'`` (repeat the last real row; default) or
    ``'zero'``.
    """

    def __init__(self, min_bucket=1, max_bucket=256, pad_mode='edge'):
        if min_bucket < 1 or max_bucket < min_bucket:
            raise ValueError('need 1 <= min_bucket <= max_bucket, got '
                             '%r..%r' % (min_bucket, max_bucket))
        if pad_mode not in ('edge', 'zero'):
            raise ValueError("pad_mode must be 'edge' or 'zero', got %r"
                             % (pad_mode,))
        self.min_bucket = next_pow2(min_bucket)
        self.max_bucket = next_pow2(max_bucket)
        self.pad_mode = pad_mode

    def bucket_for(self, n):
        """The bucket a batch of n rows pads into."""
        if n > self.max_bucket:
            raise ValueError('batch of %d rows exceeds max_bucket=%d'
                             % (n, self.max_bucket))
        return min(self.max_bucket, max(self.min_bucket, next_pow2(n)))

    def buckets(self, upto=None):
        """All bucket sizes up to ``upto`` (default: max_bucket) — the
        warmup set."""
        top = self.max_bucket if upto is None else min(
            self.max_bucket, next_pow2(upto))
        b, out = self.min_bucket, []
        while b <= top:
            out.append(b)
            b *= 2
        return out

    def __repr__(self):
        return ('BucketPolicy(min_bucket=%d, max_bucket=%d, pad_mode=%r)'
                % (self.min_bucket, self.max_bucket, self.pad_mode))


def batch_rows(feed):
    """The shared leading (batch) dimension of a dense feed dict, or
    None when the feed is not bucketable (sequence tensors, scalars,
    device arrays, or disagreeing leading dims)."""
    n = None
    if not feed:
        return None
    for val in feed.values():
        if isinstance(val, SequenceTensor):
            return None          # LoD batches don't pad row-wise
        if not isinstance(val, np.ndarray):
            if hasattr(val, 'shape') and not isinstance(val, (list, tuple)):
                return None      # device array: don't round-trip to host
            val = np.asarray(val)
        if val.ndim < 1:
            return None
        if n is None:
            n = int(val.shape[0])
        elif int(val.shape[0]) != n:
            return None
    return n


def pad_feed(feed, n, bucket, pad_mode='edge'):
    """Pad every feed's batch dim from n to ``bucket`` rows."""
    if bucket == n:
        return feed
    out = {}
    for name, val in feed.items():
        arr = np.asarray(val)
        if pad_mode == 'edge':
            pad = np.repeat(arr[-1:], bucket - n, axis=0)
        else:
            pad = np.zeros((bucket - n,) + arr.shape[1:], dtype=arr.dtype)
        out[name] = np.concatenate([arr, pad], axis=0)
    return out


def _strip(fetch, n, bucket):
    """Slice one fetch back to the real rows; None = not row-aligned."""
    if isinstance(fetch, SequenceTensor):
        if fetch.lengths is None and fetch._packed is None and \
                hasattr(fetch.data, 'shape') and \
                fetch.data.shape[:1] == (bucket,):
            return SequenceTensor(fetch.data[:n], None)
        return None              # real LoD output: padding polluted it
    if hasattr(fetch, 'shape') and tuple(fetch.shape[:1]) == (bucket,):
        return fetch[:n]
    return None


def _unsafe_memo(program):
    return program.__dict__.setdefault('_bucket_unsafe', set())


def run_bucketed(exe, program, feed, fetch_list, scope=None, policy=None,
                 return_numpy=True):
    """``Executor.run`` with the batch dim padded to a shape bucket and
    the results stripped back to the real rows.

    Exactness contract: callers get exactly the real rows of the
    bucket-sized run — pad content never bleeds in, and fetches that
    turn out not to be row-aligned re-run unpadded (see the module
    docstring for the one ulp-level XLA kernel-selection caveat vs the
    raw-size run). Feeds that can't be padded
    row-wise (LoD/sequence tensors, device arrays, disagreeing leading
    dims) and programs whose fetches turn out not to be row-aligned
    (e.g. a mean over the batch) fall back to the direct run — the
    latter is remembered per program fingerprint so the double-run
    happens at most once.
    """
    from .. import executor as _executor
    from .. import profiler as _prof
    scope = scope if scope is not None else _executor.global_scope()
    policy = policy or BucketPolicy()

    def direct():
        return exe.run(program, feed=feed, fetch_list=fetch_list,
                       scope=scope, return_numpy=return_numpy)

    n = batch_rows(feed)
    if n is None or n > policy.max_bucket or \
            program.fingerprint() in _unsafe_memo(program):
        return direct()
    bucket = policy.bucket_for(n)
    with _prof.serving_span('serving/pad'):
        padded = pad_feed(feed, n, bucket, policy.pad_mode)
    fetches = exe.run(program, feed=padded, fetch_list=fetch_list,
                      scope=scope, return_numpy=return_numpy)
    if bucket == n:
        return fetches
    stripped = [_strip(f, n, bucket) for f in fetches]
    if any(s is None for s in stripped):
        # A fetch is not per-row (reduced over the batch, or carries
        # LoD): the padded rows changed its value. Re-run unpadded for
        # exactness and never pad this program again.
        _unsafe_memo(program).add(program.fingerprint())
        return direct()
    return stripped
