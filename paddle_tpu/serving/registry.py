"""Multi-model registry: ``save_inference_model`` artifacts loaded into
isolated per-model scopes, addressable by name.

One process serves M models; each gets its own Scope (parameters never
collide across models even when layers share auto-generated names) while
all of them share ONE Executor so padded batches land in a single
compiled-program cache.
"""
import threading

import numpy as np

from .. import io as _io
from ..executor import Scope
from ..framework import Variable
from .errors import ModelNotFound

__all__ = ['LoadedModel', 'ModelRegistry']


class LoadedModel(object):
    """A servable model: inference program + feed/fetch interface + its
    private scope. ``feed_specs`` maps feed name -> (per-row shape,
    dtype) — the batch dim stripped — so warmup can synthesize feeds.
    ``batchable`` flips to False the first time a fetch turns out not to
    be row-aligned (the batcher then runs its requests one at a time,
    unpadded, for exactness)."""

    def __init__(self, name, program, feed_names, fetch_vars, scope):
        self.name = name
        self.program = program
        self.feed_names = list(feed_names)
        self.fetch_vars = list(fetch_vars)
        self.scope = scope
        self.batchable = True
        self.feed_specs = {}
        block = program.global_block()
        for fname in self.feed_names:
            var = block._find_var_recursive(fname)
            if var is None:
                continue
            shape = tuple(var.shape)
            if shape and shape[0] in (-1, None):
                shape = shape[1:]
            self.feed_specs[fname] = (shape, var.dtype)

    def synthetic_feed(self, batch_size, fill=0.5):
        """A feed dict of ``batch_size`` rows for warmup. Returns None
        when any non-batch dim is dynamic (can't synthesize)."""
        feed = {}
        for fname in self.feed_names:
            spec = self.feed_specs.get(fname)
            if spec is None:
                return None
            shape, dtype = spec
            if any(d is None or d < 0 for d in shape):
                return None
            if np.issubdtype(np.dtype(dtype), np.integer):
                arr = np.zeros((batch_size,) + shape, dtype=dtype)
            else:
                arr = np.full((batch_size,) + shape, fill, dtype=dtype)
            feed[fname] = arr
        return feed

    @property
    def fetch_names(self):
        return [f.name if isinstance(f, Variable) else f
                for f in self.fetch_vars]


class ModelRegistry(object):
    """Thread-safe name -> LoadedModel map."""

    def __init__(self):
        self._lock = threading.RLock()
        self._models = {}

    def load(self, name, dirname, executor, model_filename=None,
             params_filename=None, partitioner=None):
        """Load a ``save_inference_model`` directory under ``name`` into
        a fresh private scope. With a ``partitioner`` over a real mesh,
        the loaded parameters are distributed across it right here
        (:meth:`Partitioner.shard_scope`) — mp/dp-annotated weights
        land sharded, the rest replicated — so a model too big for one
        chip is servable (PARTITIONING.md)."""
        scope = Scope()
        program, feed_names, fetch_vars = _io.load_inference_model(
            dirname, executor, model_filename=model_filename,
            params_filename=params_filename, scope=scope)
        return self.register(name, program, feed_names, fetch_vars,
                             scope, partitioner=partitioner)

    def register(self, name, program, feed_names, fetch_vars, scope,
                 partitioner=None):
        """Register an already-built (program, scope) pair — the
        in-process path used by tests and by trainers that promote a
        model to serving without a disk round-trip. A real-mesh
        ``partitioner`` distributes the scope's parameters before the
        model goes live."""
        if partitioner is not None and partitioner.active:
            partitioner.shard_scope(scope, program)
        model = LoadedModel(name, program, feed_names, fetch_vars, scope)
        with self._lock:
            self._models[name] = model
        return model

    def replace(self, name, model):
        """Atomically swap the entry under ``name`` for an
        already-built :class:`LoadedModel` (hot model swap: the worker
        re-reads the registry per batch, so queued requests flow onto
        the replacement without a drop). Returns the new model."""
        with self._lock:
            self._models[name] = model
        return model

    def get(self, name):
        with self._lock:
            model = self._models.get(name)
        if model is None:
            raise ModelNotFound('no model registered as %r (have: %s)'
                                % (name, sorted(self._models) or '-'))
        return model

    def unload(self, name):
        with self._lock:
            return self._models.pop(name, None)

    def names(self):
        with self._lock:
            return sorted(self._models)

    def __contains__(self, name):
        with self._lock:
            return name in self._models

    def __len__(self):
        with self._lock:
            return len(self._models)
