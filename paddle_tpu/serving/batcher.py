"""Dynamic micro-batching: a thread-safe bounded request queue plus the
coalescing logic that packs compatible requests into one padded batch.

The batcher is where serving throughput comes from: N concurrent
clients each sending a handful of rows become one bucket-shaped
Executor.run. Requests coalesce only when *compatible* — same feed
names, per-row shapes, and dtypes — so the merged tensor concatenates
cleanly along the batch dim and the compiled-program cache key stays
bucket-shaped.
"""
import collections
import threading
import time

import numpy as np

from .errors import ServerOverloaded, ServerClosed

__all__ = ['InferenceRequest', 'MicroBatcher', 'merge_requests',
           'split_fetches']


def _now():
    return time.monotonic()


class InferenceRequest(object):
    """One client call: dense feeds + an optional absolute deadline.
    Completed exactly once (result or error); ``result()`` blocks the
    calling client thread on an Event, never a busy-wait."""

    __slots__ = ('feeds', 'n', 'signature', 'deadline', 'submit_time',
                 '_event', '_result', '_error', 'warmup', 'probe',
                 'trace', '_qspan')

    def __init__(self, feeds, n, deadline=None, warmup=False,
                 trace=None):
        self.feeds = feeds
        self.n = n
        self.signature = tuple(sorted(
            (name, arr.shape[1:], str(arr.dtype))
            for name, arr in feeds.items()))
        self.deadline = deadline          # absolute time.monotonic()
        self.submit_time = _now()
        self.warmup = warmup
        self.probe = False    # admitted as a half-open breaker probe
        self.trace = trace    # TraceContext propagated from the caller
        self._qspan = None    # serving/request span, ended by _complete
        self._event = threading.Event()
        self._result = None
        self._error = None

    def expired(self, now=None):
        return self.deadline is not None and \
            (now if now is not None else _now()) > self.deadline

    def set_result(self, fetches):
        if self._qspan is not None:
            self._qspan.end(ok=True)
        self._result = fetches
        self._event.set()

    def set_error(self, error):
        # an errored completion still closes the serving/request span
        # (with the error name), so only work that died with its whole
        # process shows up as an UNCLOSED span in trace_report
        if self._qspan is not None:
            self._qspan.end(error=type(error).__name__)
        self._error = error
        self._event.set()

    def done(self):
        return self._event.is_set()

    def result(self, timeout=None):
        """Block until completed; raises the server-side error if the
        request failed, TimeoutError if ``timeout`` elapses first."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                'inference result not ready within %.3fs' % timeout)
        if self._error is not None:
            raise self._error
        return self._result

    def latency(self):
        return _now() - self.submit_time


class MicroBatcher(object):
    """Bounded per-model queue + batch assembly, drained by one worker.

    Admission (``submit``) is the load-shedding point: a full queue
    raises :class:`ServerOverloaded` without enqueueing, so an
    overloaded server's cost per rejected request is one lock
    acquisition. ``next_batch`` blocks until work arrives, drops
    requests whose deadline already passed (completing them with
    :class:`DeadlineExceeded`), then greedily coalesces compatible
    requests up to ``max_rows`` — waiting at most ``batch_timeout`` for
    stragglers once it holds at least one request.
    """

    def __init__(self, max_queue_depth=128):
        self.max_queue_depth = max_queue_depth
        self._queue = collections.deque()
        self._cond = threading.Condition()
        self._closed = False
        self._paused = False

    # ---- producer side ---------------------------------------------------
    def submit(self, request):
        with self._cond:
            if self._closed:
                raise ServerClosed('server is shut down')
            if len(self._queue) >= self.max_queue_depth:
                raise ServerOverloaded(
                    'queue depth %d at limit; request shed'
                    % len(self._queue))
            self._queue.append(request)
            self._cond.notify()
        return request

    def depth(self):
        with self._cond:
            return len(self._queue)

    # ---- control ---------------------------------------------------------
    def pause(self):
        """Stop draining (maintenance / drain-control). Queued and new
        requests wait; admission control and deadlines still apply."""
        with self._cond:
            self._paused = True

    def resume(self):
        with self._cond:
            self._paused = False
            self._cond.notify_all()

    def close(self):
        """Begin graceful shutdown: no new submissions; the worker keeps
        draining until the queue is empty, then ``next_batch`` returns
        None."""
        with self._cond:
            self._closed = True
            self._paused = False
            self._cond.notify_all()

    def drain_pending(self):
        """Pop and return every still-queued request — the shutdown
        escalation path: when the worker is wedged and can't drain the
        queue, the caller fails these futures itself (typed
        ServerClosed) instead of leaving clients blocked forever."""
        with self._cond:
            pending = list(self._queue)
            self._queue.clear()
        return pending

    # ---- consumer side (the model's worker thread) -----------------------
    def _pop_ready(self, expired_out):
        """Pop the next non-expired request; expired ones go to
        ``expired_out``. Caller holds the lock."""
        now = _now()
        while self._queue:
            req = self._queue.popleft()
            if req.expired(now):
                expired_out.append(req)
            else:
                return req
        return None

    def next_batch(self, max_rows, batch_timeout=0.0):
        """Block for the next ``(batch, expired)`` pair. ``batch`` is a
        non-empty list of compatible requests, or None once the queue is
        closed and fully drained. ``expired`` holds requests whose
        deadline passed in the queue — the caller completes them with
        :class:`DeadlineExceeded` and counts them."""
        expired = []
        with self._cond:
            while True:
                if not self._paused:
                    first = self._pop_ready(expired)
                    if first is not None:
                        break
                    if self._closed:
                        return None, expired
                    if expired:
                        # nothing runnable but requests died in queue:
                        # hand them back NOW (batch empty) so the worker
                        # completes them with DeadlineExceeded instead
                        # of sitting on them until the next live request
                        return [], expired
                elif self._closed and not self._queue:
                    return None, expired
                self._cond.wait(timeout=0.05)
            batch, rows = [first], first.n
            if first.warmup:
                # warmup requests are shape probes: each must run alone
                # at exactly its bucket size, never merged into a
                # bigger (different-bucket) batch
                return batch, expired
            # greedy coalesce; brief straggler wait while under-full
            wait_until = _now() + max(0.0, batch_timeout)
            while rows < max_rows:
                nxt = None
                if self._queue and not self._paused:
                    if self._queue[0].expired():
                        expired.append(self._queue.popleft())
                        continue
                    if not self._queue[0].warmup and \
                            self._queue[0].signature == first.signature \
                            and rows + self._queue[0].n <= max_rows:
                        nxt = self._queue.popleft()
                    else:
                        break          # head incompatible: keep FIFO order
                if nxt is not None:
                    batch.append(nxt)
                    rows += nxt.n
                    continue
                remaining = wait_until - _now()
                if remaining <= 0 or self._closed:
                    break
                self._cond.wait(timeout=remaining)
        return batch, expired


def merge_requests(batch):
    """Concatenate the batch's feeds along the leading dim. Returns
    (feed dict, total rows, row slices per request)."""
    total = sum(r.n for r in batch)
    slices, offset = [], 0
    for r in batch:
        slices.append((offset, offset + r.n))
        offset += r.n
    if len(batch) == 1:
        return dict(batch[0].feeds), total, slices
    feed = {}
    for name in batch[0].feeds:
        feed[name] = np.concatenate([r.feeds[name] for r in batch],
                                    axis=0)
    return feed, total, slices


def split_fetches(fetches, slices, total_rows, bucket):
    """Split a bucket-shaped run's fetches back into per-request lists.
    Returns None when any fetch is not row-aligned (its leading dim is
    not the bucket size) — the caller must fall back to per-request
    exact runs."""
    for f in fetches:
        if not (hasattr(f, 'shape') and tuple(f.shape[:1]) == (bucket,)):
            return None
    return [[f[a:b] for f in fetches] for a, b in slices]
