"""Hung-batch watchdog: per-stage deadlines over the serving worker's
in-flight work.

A wedged ``Executor.run`` (device lockup, a pathological compile, an
NFS mount gone away mid-read) used to be invisible: the worker thread
blocks forever, every queued client waits forever, and ``close()``
hangs on ``w.join()``. The watchdog makes that failure mode bounded:

- Workers bracket each stage (pad, batch run) with
  :meth:`Watchdog.enter` / :meth:`Watchdog.exit`, declaring a deadline.
- One daemon thread scans the in-flight table every ``poll_interval``
  seconds. A stage past its deadline is *tripped*: popped from the
  table and handed to ``on_trip`` (the server fails the batch's
  futures with :class:`~paddle_tpu.serving.errors.WatchdogTimeout`,
  opens the model's breaker, and marks the worker wedged).
- A tripped stage's :meth:`exit` returns None, telling the (possibly
  much later) worker its results were already disclaimed.
- :meth:`trip_all` force-trips entries regardless of deadline — the
  ``close(timeout=)`` / drain escalation path uses it to fail in-flight
  futures before abandoning a wedged worker.

The scan is deliberately pull-based (no timers armed per batch): one
thread, one lock, O(in-flight) per tick — in-flight is bounded by the
model count. :meth:`check` is public so tests can drive scans
deterministically without sleeping on the poll interval.
"""
import threading
import time

__all__ = ['Watchdog']


class Watchdog(object):
    """In-flight stage table + the scanning thread.

    ``on_trip(entry)`` receives the popped entry dict: ``model``,
    ``stage``, ``batch``, ``timeout``, ``start``, ``deadline``,
    ``error`` (None for a genuine deadline trip; the forced error for
    :meth:`trip_all`), ``overrun`` (seconds past the deadline).
    """

    def __init__(self, poll_interval=0.05, on_trip=None,
                 clock=time.monotonic):
        self.poll_interval = poll_interval
        self.on_trip = on_trip
        self._clock = clock
        self._lock = threading.Lock()
        self._inflight = {}       # token -> entry dict
        self._seq = 0
        self._stop = threading.Event()
        self._thread = None
        self.trips = 0            # total stages tripped (all models)

    # ---- worker bracket --------------------------------------------------
    def enter(self, model, stage, timeout, batch):
        """Register an in-flight stage; returns an opaque token. Starts
        the scanning thread lazily on first use. ``timeout=None``
        disables the deadline (the entry is still force-trippable)."""
        now = self._clock()
        with self._lock:
            token = self._seq
            self._seq += 1
            self._inflight[token] = {
                'model': model, 'stage': stage, 'batch': batch,
                'timeout': timeout, 'start': now,
                'deadline': None if timeout is None else now + timeout,
                'error': None,
            }
            started = self._thread is not None
        if not started:
            self._ensure_thread()
        return token

    def exit(self, token):
        """Unregister a stage. Returns the entry, or None if the
        watchdog already tripped it (futures failed on the worker's
        behalf — do not complete them)."""
        with self._lock:
            return self._inflight.pop(token, None)

    # ---- scanning --------------------------------------------------------
    def check(self, now=None):
        """One scan: pop every entry past its deadline and fire
        ``on_trip`` for each. Returns the tripped entries. Public so
        tests drive the clock instead of sleeping."""
        now = self._clock() if now is None else now
        tripped = []
        with self._lock:
            for token, entry in list(self._inflight.items()):
                if entry['deadline'] is not None and \
                        now > entry['deadline']:
                    tripped.append(self._inflight.pop(token))
        for entry in tripped:
            entry['overrun'] = now - entry['deadline']
            self._fire(entry)
        return tripped

    def trip_all(self, model=None, error=None):
        """Force-trip every in-flight entry (optionally one model's),
        deadline or not — the shutdown/abandon escalation. ``error``
        rides on the entry for ``on_trip`` to raise instead of the
        default WatchdogTimeout."""
        now = self._clock()
        with self._lock:
            victims = [self._inflight.pop(token)
                       for token, entry in list(self._inflight.items())
                       if model is None or entry['model'] == model]
        for entry in victims:
            entry['error'] = error
            entry['overrun'] = 0.0 if entry['deadline'] is None \
                else max(0.0, now - entry['deadline'])
            self._fire(entry)
        return victims

    def _fire(self, entry):
        self.trips += 1
        cb = self.on_trip
        if cb is not None:
            cb(entry)

    # ---- lifecycle -------------------------------------------------------
    def _ensure_thread(self):
        with self._lock:
            if self._thread is not None or self._stop.is_set():
                return
            self._thread = threading.Thread(
                target=self._loop, name='serve-watchdog', daemon=True)
            self._thread.start()

    def _loop(self):
        while not self._stop.wait(self.poll_interval):
            self.check()

    def stop(self, timeout=1.0):
        """Stop the scanning thread (server close). Idempotent."""
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout)

    def inflight(self):
        with self._lock:
            return len(self._inflight)
