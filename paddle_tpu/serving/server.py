"""ModelServer: the batched, shape-bucketed inference serving runtime.

Request path::

    client thread --submit()--> per-model MicroBatcher (bounded queue)
        --worker thread--> coalesce compatible requests
        --> pad to power-of-two bucket (BucketPolicy)
        --> shared Executor.run (ONE compiled-program cache, locked)
        --> strip pad rows, split per request, set results

Design points:

- One worker thread per model serializes that model's scope (the
  Executor donates state buffers per run; serialization makes that
  safe) while different models run concurrently on the shared Executor.
- Admission control sheds load at the door: ``max_queue_depth`` bounds
  memory and tail latency, per-request deadlines bound time-in-queue,
  and both failure modes surface as typed errors.
- ``warmup()`` pushes one synthetic request per shape bucket through
  the *public* path before traffic, so the first real user never pays a
  trace+compile.
- Transient run failures (``retry_on``, default OSError — NFS/GCS
  hiccups under checkpoint-backed embedding stores) are absorbed by
  :func:`resilience.retry_call` with exponential backoff.
"""
import threading
import time

import numpy as np

from .. import profiler as _prof
from ..core import places as _places
from ..executor import Executor
from ..lod import SequenceTensor
from ..resilience import retry_call
from .batcher import (InferenceRequest, MicroBatcher, merge_requests,
                      split_fetches)
from .bucketing import BucketPolicy, pad_feed
from .errors import DeadlineExceeded, ServerClosed, ServingError
from .registry import ModelRegistry
from .stats import ServingStats

__all__ = ['ModelServer']


class ModelServer(object):
    """Serve N models from one process with dynamic micro-batching.

    Parameters
    ----------
    place : TPUPlace/CPUPlace, optional
        Device the shared Executor runs on.
    max_batch_size : int
        Largest bucket a single run may carry; also the coalescing cap.
    max_queue_depth : int
        Per-model admission limit; a full queue raises ServerOverloaded.
    batch_timeout : float
        Seconds a worker waits for stragglers once it holds at least one
        request and the batch is under-full. Latency/occupancy knob.
    policy : BucketPolicy, optional
        Shape-bucket ladder; defaults to pow2 buckets up to
        ``max_batch_size``.
    retry_attempts / retry_backoff / retry_on
        Transient-failure retry for each batch run
        (:mod:`paddle_tpu.resilience`).
    """

    def __init__(self, place=None, max_batch_size=64, max_queue_depth=128,
                 batch_timeout=0.002, policy=None, retry_attempts=2,
                 retry_backoff=0.05, retry_on=(OSError,)):
        self.place = place or _places.TPUPlace(0)
        self.executor = Executor(self.place)
        self.policy = policy or BucketPolicy(max_bucket=max_batch_size)
        if self.policy.max_bucket < max_batch_size:
            raise ValueError(
                'policy.max_bucket=%d < max_batch_size=%d: the largest '
                'batch could not be bucketed'
                % (self.policy.max_bucket, max_batch_size))
        self.max_batch_size = max_batch_size
        self.max_queue_depth = max_queue_depth
        self.batch_timeout = batch_timeout
        self.retry_attempts = retry_attempts
        self.retry_backoff = retry_backoff
        self.retry_on = tuple(retry_on)
        self.registry = ModelRegistry()
        self.stats = ServingStats()
        self._batchers = {}            # model name -> MicroBatcher
        self._workers = {}             # model name -> Thread
        self._lock = threading.RLock()
        self._closed = False

    # ---- model management ------------------------------------------------
    def load_model(self, name, dirname, model_filename=None,
                   params_filename=None):
        """Load a ``save_inference_model`` directory and start serving
        it under ``name``."""
        model = self.registry.load(name, dirname, self.executor,
                                   model_filename=model_filename,
                                   params_filename=params_filename)
        self._start_worker(model)
        return model

    def register_model(self, name, program, feed_names, fetch_vars,
                       scope):
        """Serve an in-memory (program, scope) pair — no disk round
        trip. The scope must hold the program's parameters."""
        model = self.registry.register(name, program, feed_names,
                                       fetch_vars, scope)
        self._start_worker(model)
        return model

    def unload_model(self, name):
        """Stop serving ``name``; its queued requests drain first."""
        with self._lock:
            batcher = self._batchers.pop(name, None)
            worker = self._workers.pop(name, None)
        if batcher is not None:
            batcher.close()
        if worker is not None:
            worker.join()
        return self.registry.unload(name)

    def models(self):
        return self.registry.names()

    def _start_worker(self, model):
        with self._lock:
            if self._closed:
                raise ServerClosed('server is shut down')
            batcher = MicroBatcher(max_queue_depth=self.max_queue_depth)
            self._batchers[model.name] = batcher
            worker = threading.Thread(
                target=self._worker_loop, args=(model, batcher),
                name='serve-%s' % model.name, daemon=True)
            self._workers[model.name] = worker
            worker.start()

    # ---- client surface --------------------------------------------------
    def submit(self, model_name, feeds, deadline=None, _warmup=False):
        """Enqueue one request; returns an :class:`InferenceRequest`
        future. ``deadline`` is relative seconds — the request fails
        with DeadlineExceeded if no worker launches it in time. Raises
        ServerOverloaded / ServerClosed / ModelNotFound synchronously.
        """
        model = self.registry.get(model_name)
        with self._lock:
            if self._closed:
                raise ServerClosed('server is shut down')
            batcher = self._batchers.get(model_name)
        if batcher is None:
            raise ServerClosed('model %r is unloaded' % model_name)
        feeds, n = self._normalize_feeds(model, feeds)
        abs_deadline = None if deadline is None \
            else time.monotonic() + deadline
        req = InferenceRequest(feeds, n, deadline=abs_deadline,
                               warmup=_warmup)
        try:
            batcher.submit(req)
        except ServingError:
            self.stats.record_shed()
            raise
        self.stats.record_submitted()
        return req

    def infer(self, model_name, feeds, deadline=None, timeout=30.0):
        """Synchronous convenience: submit + wait."""
        return self.submit(model_name, feeds, deadline=deadline).result(
            timeout=timeout)

    def _normalize_feeds(self, model, feeds):
        if not isinstance(feeds, dict):
            raise ValueError("feeds must be {'feed_name': array}")
        missing = [n for n in model.feed_names if n not in feeds]
        if missing:
            raise ValueError('model %r is missing feeds %s'
                             % (model.name, missing))
        out, n = {}, None
        for name in model.feed_names:
            val = feeds[name]
            if isinstance(val, SequenceTensor):
                raise ValueError(
                    'ModelServer serves dense batches; feed %r is a '
                    'LoD/sequence tensor — use Executor.run directly'
                    % name)
            arr = np.asarray(val)
            if arr.ndim < 1:
                raise ValueError('feed %r must have a batch dim' % name)
            if n is None:
                n = int(arr.shape[0])
            elif int(arr.shape[0]) != n:
                raise ValueError(
                    'feeds disagree on batch size: %d vs %d rows'
                    % (n, int(arr.shape[0])))
            out[name] = arr
        if n > self.max_batch_size:
            raise ValueError(
                'request of %d rows exceeds max_batch_size=%d — split '
                'it client-side' % (n, self.max_batch_size))
        return out, n

    # ---- warmup ----------------------------------------------------------
    def warmup(self, model_name=None, upto=None, timeout=300.0):
        """Pre-compile every shape bucket (one synthetic request per
        bucket through the public path) so live traffic never pays a
        compile. Returns ``{model: [bucket sizes warmed]}``; models
        whose feed shapes are dynamic (unsynthesizable) are skipped."""
        names = [model_name] if model_name is not None else self.models()
        warmed = {}
        with _prof.serving_span('serving/warmup'):
            pending = []
            for name in names:
                model = self.registry.get(name)
                warmed[name] = []
                for bucket in self.policy.buckets(
                        upto or self.max_batch_size):
                    if bucket > self.max_batch_size:
                        break
                    feed = model.synthetic_feed(bucket)
                    if feed is None:
                        break
                    pending.append(
                        self.submit(name, feed, _warmup=True))
                    warmed[name].append(bucket)
            for req in pending:
                req.result(timeout=timeout)
        return {k: v for k, v in warmed.items() if v}

    # ---- ops control -----------------------------------------------------
    def pause(self, model_name=None):
        """Stop draining (all models, or one): maintenance/drain
        control. Admission and deadlines keep applying."""
        for name in ([model_name] if model_name else list(self._batchers)):
            self._batchers[name].pause()

    def resume(self, model_name=None):
        for name in ([model_name] if model_name else list(self._batchers)):
            self._batchers[name].resume()

    def queue_depth(self, model_name):
        return self._batchers[model_name].depth()

    def cache_info(self):
        return self.executor.cache_info()

    def stats_dict(self):
        return self.stats.as_dict(cache_info=self.executor.cache_info())

    def report(self):
        return self.stats.report(cache_info=self.executor.cache_info())

    def close(self):
        """Graceful shutdown: reject new requests, drain every queue,
        join the workers."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            batchers = list(self._batchers.values())
            workers = list(self._workers.values())
        for b in batchers:
            b.close()
        for w in workers:
            w.join()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # ---- worker ----------------------------------------------------------
    def _worker_loop(self, model, batcher):
        while True:
            batch, expired = batcher.next_batch(
                self.max_batch_size if model.batchable else 1,
                batch_timeout=self.batch_timeout)
            for req in expired:
                self.stats.record_expired()
                req.set_error(DeadlineExceeded(
                    'deadline passed after %.3fs in queue'
                    % req.latency()))
            if batch is None:
                return
            if not batch:
                continue          # only expired requests this round
            try:
                self._run_batch(model, batch)
            except Exception as e:           # noqa: BLE001 — worker must
                # never die: every queued client is waiting on it
                self.stats.record_failed(len(batch))
                for req in batch:
                    if not req.done():
                        req.set_error(e)

    def _exe_run(self, model, feed):
        return self.executor.run(model.program, feed=feed,
                                 fetch_list=model.fetch_vars,
                                 scope=model.scope)

    def _run_guarded(self, model, feed):
        """One Executor.run with transient-failure retry."""
        def _on_retry(attempt, error):
            self.stats.record_retry()
        return retry_call(self._exe_run, (model, feed),
                          max_attempts=self.retry_attempts,
                          backoff=self.retry_backoff,
                          retry_on=self.retry_on, on_retry=_on_retry)

    def _run_batch(self, model, batch):
        feed, rows, slices = merge_requests(batch)
        bucket = self.policy.bucket_for(rows) if model.batchable else rows
        with _prof.serving_span('serving/pad'):
            padded = pad_feed(feed, rows, bucket, self.policy.pad_mode)
        t0 = time.monotonic()
        with _prof.serving_span('serving/batch_run'):
            fetches = self._run_guarded(model, padded)
        self.stats.record_batch(rows, bucket, time.monotonic() - t0)
        parts = split_fetches(fetches, slices, rows, bucket)
        if parts is None:
            # a fetch isn't row-aligned (reduced over the batch): the
            # padded/merged run polluted it. Serve each request alone,
            # unpadded — exactness over throughput — and remember.
            model.batchable = False
            for req in batch:
                with _prof.serving_span('serving/exact_fallback'):
                    out = self._run_guarded(model, req.feeds)
                self._complete(req, out)
            return
        for req, part in zip(batch, parts):
            self._complete(req, part)

    def _complete(self, req, fetches):
        latency = req.latency()
        if not req.warmup:
            self.stats.record_completed(latency)
            _prof.record_serving_event('serving/request', latency)
        req.set_result(fetches)
