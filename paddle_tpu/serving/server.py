"""ModelServer: the batched, shape-bucketed inference serving runtime.

Request path::

    client thread --submit()--> per-model MicroBatcher (bounded queue)
        --worker thread--> coalesce compatible requests
        --> pad to power-of-two bucket (BucketPolicy)
        --> shared Executor.run (ONE compiled-program cache, locked)
        --> strip pad rows, split per request, set results

Design points:

- One worker thread per model serializes that model's scope (the
  Executor donates state buffers per run; serialization makes that
  safe) while different models run concurrently on the shared Executor.
- Admission control sheds load at the door: ``max_queue_depth`` bounds
  memory and tail latency, per-request deadlines bound time-in-queue,
  and both failure modes surface as typed errors.
- ``warmup()`` pushes one synthetic request per shape bucket through
  the *public* path before traffic, so the first real user never pays a
  trace+compile.
- Transient run failures (``retry_on``, default OSError — NFS/GCS
  hiccups under checkpoint-backed embedding stores) are absorbed by
  :func:`resilience.retry_call` with exponential backoff, capped by the
  batch's earliest request deadline.

SLO guardrails (SERVING.md "Failure domains & SLO guardrails"):

- A per-model :class:`~paddle_tpu.serving.breaker.CircuitBreaker`
  wraps the batch run: a model whose every batch errors stops burning
  retries in the hot loop — new requests shed with typed
  :class:`CircuitOpen` at admission until half-open probes prove the
  model healthy again.
- A :class:`~paddle_tpu.serving.watchdog.Watchdog` thread bounds every
  stage (pad, batch run) with a deadline: a wedged ``Executor.run``
  gets its futures failed (:class:`WatchdogTimeout`), its breaker
  opened, and its worker marked wedged instead of hanging clients.
- ``health()`` reports per-model ready/degraded/open/draining state;
  ``drain()`` completes queued work then unloads; ``swap_model()``
  flips a replacement in atomically without dropping the queue;
  ``close(timeout=)`` escalates graceful drain -> fail-pending ->
  abandon-worker so shutdown is bounded even against a wedged worker.
- The worker loop is threaded with deterministic fault-injection sites
  (``serving/run_batch``, ``serving/load_model``, ``serving/pad``) so
  ``tests/test_chaos.py`` and ``tools/chaos_bench.py`` can kill
  batches mid-flight and assert the guardrails hold.
"""
import logging
import threading
import time

import numpy as np

from .. import observability as _obs
from .. import profiler as _prof
from ..core import places as _places
from ..executor import Executor, Scope
from ..io import load_inference_model as _load_inference_model
from ..lod import SequenceTensor
from ..resilience import retry_call
from ..resilience import faultinject as _fi
from .batcher import (InferenceRequest, MicroBatcher, merge_requests,
                      split_fetches)
from .breaker import CLOSED, HALF_OPEN, OPEN, CircuitBreaker
from .bucketing import BucketPolicy, pad_feed
from .errors import (CircuitOpen, DeadlineExceeded, ServerClosed,
                     ServingError, WatchdogTimeout)
from .registry import LoadedModel, ModelRegistry
from .stats import ServingStats
from .watchdog import Watchdog

__all__ = ['ModelServer', 'DEFAULT_STAGE_TIMEOUTS']

logger = logging.getLogger('paddle_tpu.serving')

# per-stage watchdog deadlines (seconds); keys double as the
# fault-injection site names. The run stage covers retries, so its
# budget bounds the whole retry storm, not one attempt.
DEFAULT_STAGE_TIMEOUTS = {
    _fi.SITE_SERVING_PAD: 10.0,
    _fi.SITE_SERVING_RUN: 120.0,
}


class ModelServer(object):
    """Serve N models from one process with dynamic micro-batching.

    Parameters
    ----------
    place : TPUPlace/CPUPlace, optional
        Device the shared Executor runs on.
    max_batch_size : int
        Largest bucket a single run may carry; also the coalescing cap.
    max_queue_depth : int
        Per-model admission limit; a full queue raises ServerOverloaded.
    batch_timeout : float
        Seconds a worker waits for stragglers once it holds at least one
        request and the batch is under-full. Latency/occupancy knob.
    policy : BucketPolicy, optional
        Shape-bucket ladder; defaults to pow2 buckets up to
        ``max_batch_size``.
    retry_attempts / retry_backoff / retry_on
        Transient-failure retry for each batch run
        (:mod:`paddle_tpu.resilience`).
    breaker_config : dict, optional
        Per-model :class:`CircuitBreaker` kwargs (failure_threshold,
        window, failure_rate, cooldown, probe_successes, max_probes).
    stage_timeouts : dict, optional
        Watchdog deadline per stage, merged over
        :data:`DEFAULT_STAGE_TIMEOUTS`; None disables a stage's
        deadline.
    watchdog_poll : float
        Watchdog scan interval (seconds).
    """

    def __init__(self, place=None, max_batch_size=64, max_queue_depth=128,
                 batch_timeout=0.002, policy=None, retry_attempts=2,
                 retry_backoff=0.05, retry_on=(OSError,),
                 breaker_config=None, stage_timeouts=None,
                 watchdog_poll=0.05, partitioner=None):
        self.place = place or _places.TPUPlace(0)
        # PARTITIONING.md: a real-mesh partitioner makes this server
        # sharded end to end — loaded models distribute their params
        # across the mesh, and every bucket's program compiles as a
        # sharded computation through the SAME Executor cache (warmup
        # pre-pays one compile per (bucket, program, sharding, mesh)).
        self.partitioner = partitioner
        self.executor = Executor(self.place, partitioner=partitioner)
        self.policy = policy or BucketPolicy(max_bucket=max_batch_size)
        if self.policy.max_bucket < max_batch_size:
            raise ValueError(
                'policy.max_bucket=%d < max_batch_size=%d: the largest '
                'batch could not be bucketed'
                % (self.policy.max_bucket, max_batch_size))
        self.max_batch_size = max_batch_size
        self.max_queue_depth = max_queue_depth
        self.batch_timeout = batch_timeout
        self.retry_attempts = retry_attempts
        self.retry_backoff = retry_backoff
        self.retry_on = tuple(retry_on)
        self.breaker_config = dict(breaker_config or {})
        self.stage_timeouts = dict(DEFAULT_STAGE_TIMEOUTS)
        self.stage_timeouts.update(stage_timeouts or {})
        self.registry = ModelRegistry()
        self.stats = ServingStats()
        self.watchdog = Watchdog(poll_interval=watchdog_poll,
                                 on_trip=self._on_watchdog_trip)
        self._batchers = {}            # model name -> MicroBatcher
        self._workers = {}             # model name -> Thread
        self._breakers = {}            # model name -> CircuitBreaker
        self._draining = set()         # models mid-drain
        self._wedged = set()           # models whose worker overran
        self._trip_counts = {}         # model name -> watchdog trips
        self._abandoned = []           # worker threads close() gave up on
        self._lock = threading.RLock()
        self._closed = False
        # live telemetry: /health merges this server's readiness doc
        # (weakly registered — GC'd servers drop out on their own)
        _obs.telemetry.register_health_provider(
            'server-%x' % id(self), self)

    # ---- model management ------------------------------------------------
    def load_model(self, name, dirname, model_filename=None,
                   params_filename=None):
        """Load a ``save_inference_model`` directory and start serving
        it under ``name``."""
        _fi.maybe_fault(_fi.SITE_SERVING_LOAD)
        model = self.registry.load(name, dirname, self.executor,
                                   model_filename=model_filename,
                                   params_filename=params_filename,
                                   partitioner=self.partitioner)
        self._start_worker(model)
        return model

    def register_model(self, name, program, feed_names, fetch_vars,
                       scope):
        """Serve an in-memory (program, scope) pair — no disk round
        trip. The scope must hold the program's parameters (they are
        distributed over the server's mesh when one is configured)."""
        model = self.registry.register(name, program, feed_names,
                                       fetch_vars, scope,
                                       partitioner=self.partitioner)
        self._start_worker(model)
        return model

    def unload_model(self, name, timeout=None):
        """Stop serving ``name``; its queued requests drain first (see
        :meth:`drain` for the timeout escalation)."""
        return self.drain(name, timeout=timeout)

    def drain(self, name, timeout=None):
        """Graceful per-model shutdown: stop admission, let the worker
        complete every queued request, then unload and return the
        model. With ``timeout`` (seconds), a worker still running past
        it is escalated: in-flight and queued futures fail with typed
        errors and the worker thread is abandoned — ``drain`` returns
        instead of hanging on a wedged model."""
        self.registry.get(name)            # raises ModelNotFound
        with self._lock:
            self._draining.add(name)
            batcher = self._batchers.pop(name, None)
            worker = self._workers.pop(name, None)
        try:
            with _prof.serving_span('serving/drain'):
                if batcher is not None:
                    batcher.close()
                if worker is not None:
                    worker.join(timeout)
                    if worker.is_alive():
                        self._abandon_worker(name, batcher, worker)
            _obs.emit('serving_drain', model=name)
            return self.registry.unload(name)
        finally:
            with self._lock:
                self._draining.discard(name)
                self._breakers.pop(name, None)
                self._wedged.discard(name)

    def swap_model(self, name, dirname, model_filename=None,
                   params_filename=None, validate=True):
        """Hot model swap: load the replacement artifact into a fresh
        Scope, validate it off the serving path, then flip the registry
        entry atomically. The worker re-reads the registry per batch,
        so queued requests flow onto the replacement without a drop —
        and a bad deploy (unloadable or failing validation) raises
        here while the old model keeps serving untouched."""
        self.registry.get(name)            # raises ModelNotFound
        with _prof.serving_span('serving/swap'):
            _fi.maybe_fault(_fi.SITE_SERVING_LOAD)
            scope = Scope()
            program, feed_names, fetch_vars = _load_inference_model(
                dirname, self.executor, model_filename=model_filename,
                params_filename=params_filename, scope=scope)
            if self.partitioner is not None and self.partitioner.active:
                self.partitioner.shard_scope(scope, program)
            candidate = LoadedModel(name, program, feed_names,
                                    fetch_vars, scope)
            if validate:
                feed = candidate.synthetic_feed(1)
                if feed is not None:
                    # a bad deploy raises HERE, before the flip
                    self.executor.run(program, feed=feed,
                                      fetch_list=fetch_vars, scope=scope)
            new = self.registry.replace(name, candidate)
        breaker = self._breakers.get(name)
        if breaker is not None:
            breaker.reset('model swapped')
        with self._lock:
            self._wedged.discard(name)
        _obs.emit('serving_swap', model=name, dirname=dirname)
        return new

    def models(self):
        return self.registry.names()

    def breaker(self, name):
        """The model's :class:`CircuitBreaker` (introspection: tests
        and the chaos harness assert on its transition log)."""
        return self._breakers[name]

    def _start_worker(self, model):
        with self._lock:
            if self._closed:
                raise ServerClosed('server is shut down')
            batcher = MicroBatcher(max_queue_depth=self.max_queue_depth)
            breaker = CircuitBreaker(
                name=model.name,
                on_transition=self._on_breaker_transition,
                **self.breaker_config)
            self._batchers[model.name] = batcher
            self._breakers[model.name] = breaker
            worker = threading.Thread(
                target=self._worker_loop, args=(model.name, batcher),
                name='serve-%s' % model.name, daemon=True)
            self._workers[model.name] = worker
            worker.start()
        self.stats.record_breaker_state(model.name, CLOSED)

    # ---- client surface --------------------------------------------------
    def submit(self, model_name, feeds, deadline=None, _warmup=False,
               trace=None):
        """Enqueue one request; returns an :class:`InferenceRequest`
        future. ``deadline`` is relative seconds — the request fails
        with DeadlineExceeded if no worker launches it in time.
        ``trace`` is an optional parent :class:`TraceContext` (a fleet
        router's request span; pickles through a RemoteCell hop) —
        this submission becomes a ``serving/request`` child span.
        Raises ServerOverloaded / ServerClosed / ModelNotFound /
        CircuitOpen synchronously.
        """
        model = self.registry.get(model_name)
        with self._lock:
            if self._closed:
                raise ServerClosed('server is shut down')
            if model_name in self._draining:
                raise ServerClosed('model %r is draining' % model_name)
            batcher = self._batchers.get(model_name)
        if batcher is None:
            raise ServerClosed('model %r is unloaded' % model_name)
        feeds, n = self._normalize_feeds(model, feeds)
        abs_deadline = None if deadline is None \
            else time.monotonic() + deadline
        req = InferenceRequest(feeds, n, deadline=abs_deadline,
                               warmup=_warmup)
        if not _warmup:
            qspan = _obs.start_span('serving/request', parent=trace,
                                    activate=False, model=model_name,
                                    rows=n)
            if qspan.context is not None:
                req._qspan = qspan
                req.trace = qspan.context
        breaker = self._breakers.get(model_name)
        if breaker is not None and not _warmup:
            try:
                req.probe = breaker.admit()
            except CircuitOpen:
                self.stats.record_breaker_rejected(model_name)
                if req._qspan is not None:
                    req._qspan.end(error='CircuitOpen')
                raise
        try:
            batcher.submit(req)
        except ServingError:
            if req.probe:
                breaker.release_probe()
            self.stats.record_shed()
            if req._qspan is not None:
                req._qspan.end(error='shed')
            raise
        self.stats.record_submitted()
        return req

    def infer(self, model_name, feeds, deadline=None, timeout=30.0):
        """Synchronous convenience: submit + wait."""
        return self.submit(model_name, feeds, deadline=deadline).result(
            timeout=timeout)

    def _normalize_feeds(self, model, feeds):
        if not isinstance(feeds, dict):
            raise ValueError("feeds must be {'feed_name': array}")
        missing = [n for n in model.feed_names if n not in feeds]
        if missing:
            raise ValueError('model %r is missing feeds %s'
                             % (model.name, missing))
        out, n = {}, None
        for name in model.feed_names:
            val = feeds[name]
            if isinstance(val, SequenceTensor):
                raise ValueError(
                    'ModelServer serves dense batches; feed %r is a '
                    'LoD/sequence tensor — use Executor.run directly'
                    % name)
            arr = np.asarray(val)
            if arr.ndim < 1:
                raise ValueError('feed %r must have a batch dim' % name)
            if n is None:
                n = int(arr.shape[0])
            elif int(arr.shape[0]) != n:
                raise ValueError(
                    'feeds disagree on batch size: %d vs %d rows'
                    % (n, int(arr.shape[0])))
            out[name] = arr
        if n > self.max_batch_size:
            raise ValueError(
                'request of %d rows exceeds max_batch_size=%d — split '
                'it client-side' % (n, self.max_batch_size))
        return out, n

    # ---- warmup ----------------------------------------------------------
    def warmup(self, model_name=None, upto=None, timeout=300.0,
               autotune=False):
        """Pre-compile every shape bucket (one synthetic request per
        bucket through the public path) so live traffic never pays a
        compile. Returns ``{model: [bucket sizes warmed]}``; models
        whose feed shapes are dynamic (unsynthesizable) are skipped.

        Before the first bucket compiles, the on-disk tuning cache
        (COMPILER.md) is preloaded, so every warmup compile — and every
        later live compile — runs under the autotuned per-shape configs
        instead of re-deriving defaults: fast cold-start is the whole
        point of paying the tuning search offline.

        ``autotune=True`` additionally runs the measured schedule
        search (:class:`~..compiler.tuning.Autotuner.tune_if_missing`)
        for every model × bucket *before* that bucket's warmup compile
        — only buckets with no cached entry for this device kind pay a
        search, so the second warmup of a process (or any process that
        preloaded a populated on-disk cache) does zero searches."""
        from ..compiler import tuning as _ctuning
        from ..observability import perf as _perf
        t0 = time.monotonic()
        tuned = _ctuning.default_cache().preload()
        tuner = _ctuning.Autotuner() if autotune else None
        searches = 0
        names = [model_name] if model_name is not None else self.models()
        warmed = {}
        # perf observatory: when this process is already observing
        # (capture on, or a journal installed) warmup ledgers every
        # bucket it compiles — per-bucket flops/bytes land in the book
        # and as perf_ledger events before any live traffic
        _n_ledgers0 = len(_perf.book())
        with _perf.capture_scope(_perf.capture_enabled()
                                 or _obs.journal_active()), \
                _prof.serving_span('serving/warmup'):
            pending = []
            for name in names:
                model = self.registry.get(name)
                warmed[name] = []
                for bucket in self.policy.buckets(
                        upto or self.max_batch_size):
                    if bucket > self.max_batch_size:
                        break
                    feed = model.synthetic_feed(bucket)
                    if feed is None:
                        break
                    if tuner is not None:
                        _, searched = tuner.tune_if_missing(
                            model.program, feed, model.fetch_vars,
                            scope=model.scope, name=name)
                        searches += int(searched)
                    pending.append(
                        self.submit(name, feed, _warmup=True))
                    warmed[name].append(bucket)
            for req in pending:
                req.result(timeout=timeout)
        warmed = {k: v for k, v in warmed.items() if v}
        _obs.emit('serving_warmup',
                  models=len(warmed),
                  buckets=sum(len(v) for v in warmed.values()),
                  tuning_entries=tuned,
                  autotune_searches=searches,
                  perf_ledgers=len(_perf.book()) - _n_ledgers0,
                  dur_s=round(time.monotonic() - t0, 6))
        return warmed

    # ---- ops control -----------------------------------------------------
    def pause(self, model_name=None):
        """Stop draining (all models, or one): maintenance/drain
        control. Admission and deadlines keep applying."""
        for name in ([model_name] if model_name else list(self._batchers)):
            self._batchers[name].pause()

    def resume(self, model_name=None):
        for name in ([model_name] if model_name else list(self._batchers)):
            self._batchers[name].resume()

    def queue_depth(self, model_name):
        return self._batchers[model_name].depth()

    def cache_info(self):
        return self.executor.cache_info()

    def stats_dict(self):
        return self.stats.as_dict(cache_info=self.executor.cache_info())

    def report(self):
        return self.stats.report(cache_info=self.executor.cache_info())

    # ---- health / readiness ----------------------------------------------
    def health(self):
        """Readiness snapshot: ``{'status': ..., 'models': {name:
        {...}}}``. Per-model ``state`` is one of ``ready`` (breaker
        closed, worker live), ``degraded`` (breaker half-open, or the
        watchdog tripped a stage and the worker may be wedged),
        ``open`` (breaker open: admission sheds), ``draining`` (drain
        in progress). The same signal feeds the
        ``serving_breaker_state`` / ``serving_watchdog_trips_total``
        metrics, so a scraper and this call never disagree.

        The whole per-model row — queue depth, breaker state, wedged
        flag — is read under ONE server-lock pass (the breaker and
        batcher locks are leaves acquired inside it), so a router
        polling ``health()`` never routes on a torn read where the
        depth belongs to one instant and the breaker to another."""
        models = {}
        names = self.registry.names()
        with self._lock:
            closed = self._closed
            for name in names:
                breaker = self._breakers.get(name)
                bstate = breaker.state if breaker is not None else CLOSED
                if name in self._draining:
                    state = 'draining'
                elif bstate == OPEN:
                    state = 'open'
                elif bstate == HALF_OPEN or name in self._wedged:
                    state = 'degraded'
                else:
                    state = 'ready'
                batcher = self._batchers.get(name)
                worker = self._workers.get(name)
                models[name] = {
                    'state': state,
                    'breaker': bstate,
                    'queue_depth': batcher.depth() if batcher else 0,
                    'worker_alive': bool(worker and worker.is_alive()),
                    'wedged': name in self._wedged,
                    'watchdog_trips': self._trip_counts.get(name, 0),
                }
        return {'status': 'closed' if closed else 'serving',
                'models': models}

    def load_score(self, model_name=None):
        """Cheap routing signal for a fleet front-end: the queued work
        a new request would sit behind, or ``inf`` when this server
        should not be routed to at all (closed, model draining or
        unloaded, breaker open, worker wedged or dead). A half-open
        breaker adds ``max_queue_depth`` so probing replicas rank
        behind every healthy one without being unroutable. With
        ``model_name=None`` the scores of all served models are
        summed (server-level load). One lock pass, same consistency
        contract as :meth:`health`."""
        with self._lock:
            if self._closed:
                return float('inf')
            names = [model_name] if model_name is not None \
                else list(self._batchers)
            score = 0.0
            for name in names:
                batcher = self._batchers.get(name)
                if batcher is None or name in self._draining:
                    return float('inf')
                worker = self._workers.get(name)
                if name in self._wedged or \
                        (worker is not None and not worker.is_alive()):
                    return float('inf')
                breaker = self._breakers.get(name)
                bstate = breaker.state if breaker is not None else CLOSED
                if bstate == OPEN:
                    return float('inf')
                score += batcher.depth()
                if bstate == HALF_OPEN:
                    score += self.max_queue_depth
            return score

    # ---- guardrail callbacks ---------------------------------------------
    def _on_breaker_transition(self, name, to_state, reason):
        self.stats.record_breaker_transition(name, to_state, reason)
        if to_state == OPEN:
            # breaker opening is crash-adjacent: freeze a postmortem
            # bundle (ring + metrics + unclosed spans) while the
            # evidence is still in memory
            _obs.flight.trip('breaker_open', model=name, reason=reason)

    def _on_watchdog_trip(self, entry):
        name = entry['model']
        forced = entry.get('error')
        err = forced if forced is not None else WatchdogTimeout(
            'model %r: %s exceeded its %.3fs deadline (%.3fs over); '
            'in-flight batch failed, breaker opened'
            % (name, entry['stage'], entry['timeout'],
               entry.get('overrun', 0.0)))
        # open the breaker and record the trip BEFORE failing the
        # futures: a client woken by the error must observe a breaker
        # that already tripped (health() and metrics agree with it)
        with self._lock:
            self._wedged.add(name)
            self._trip_counts[name] = self._trip_counts.get(name, 0) + 1
        if forced is None:
            breaker = self._breakers.get(name)
            if breaker is not None:
                breaker.trip('watchdog: %s overran' % entry['stage'])
        pending = [req for req in entry['batch'] if not req.done()]
        if pending:
            self.stats.record_failed(len(pending))
        self.stats.record_watchdog_trip(
            name, stage=entry['stage'], failed=len(pending),
            overrun=entry.get('overrun', 0.0))
        _obs.flight.trip('watchdog', model=name, stage=entry['stage'],
                         failed=len(pending),
                         overrun=entry.get('overrun', 0.0))
        for req in pending:
            req.set_error(err)
        logger.warning('watchdog tripped %s on model %r (%d futures '
                       'failed)', entry['stage'], name, len(pending))

    def _abandon_worker(self, name, batcher, worker):
        """Escalation: the worker outlived its join timeout. Fail its
        in-flight futures and everything still queued, then give the
        (daemon) thread up — shutdown must not hang on a wedged run."""
        self.watchdog.trip_all(
            model=name,
            error=ServerClosed(
                'server closed while the batch was in flight; worker '
                '%r abandoned' % name))
        pending = batcher.drain_pending() if batcher is not None else []
        cancelled = 0
        for req in pending:
            if not req.done():
                req.set_error(ServerClosed(
                    'server closed before the request ran; worker %r '
                    'abandoned' % name))
                cancelled += 1
        if cancelled:
            self.stats.record_cancelled(cancelled)
        with self._lock:
            self._abandoned.append(worker)
        _obs.emit('serving_abandoned_worker', model=name,
                  cancelled=cancelled)
        logger.error('abandoned wedged worker %r (%d queued futures '
                     'failed)', worker.name, cancelled)

    def close(self, timeout=30.0):
        """Shutdown with bounded escalation: reject new requests, drain
        every queue, join the workers — and if a worker is still alive
        once ``timeout`` seconds have elapsed (wedged in a run), fail
        its in-flight and queued futures with :class:`ServerClosed` and
        abandon the thread instead of hanging forever. ``timeout=None``
        restores the wait-forever behavior."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            batchers = dict(self._batchers)
            workers = dict(self._workers)
        for b in batchers.values():
            b.close()
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        for name, w in workers.items():
            w.join(None if deadline is None
                   else max(0.0, deadline - time.monotonic()))
            if w.is_alive():
                self._abandon_worker(name, batchers.get(name), w)
        self.watchdog.stop()
        _obs.telemetry.unregister_health_provider('server-%x' % id(self))
        # push buffered journal tail to disk: a SIGTERM'd or killed
        # replica must not lose the spans of its last in-flight batch
        j = _obs.get_journal()
        if j is not None:
            j.flush()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # ---- worker ----------------------------------------------------------
    def _current_model(self, name):
        try:
            return self.registry.get(name)
        except ServingError:
            return None

    def _worker_loop(self, name, batcher):
        while True:
            model = self._current_model(name)
            max_rows = self.max_batch_size \
                if (model is None or model.batchable) else 1
            batch, expired = batcher.next_batch(
                max_rows, batch_timeout=self.batch_timeout)
            breaker = self._breakers.get(name)
            for req in expired:
                self.stats.record_expired()
                if req.probe and breaker is not None:
                    breaker.release_probe()   # the probe never ran
                req.set_error(DeadlineExceeded(
                    'deadline passed after %.3fs in queue'
                    % req.latency()))
            if batch is None:
                return
            if not batch:
                continue          # only expired requests this round
            # re-read the registry so a hot swap lands between batches
            model = self._current_model(name)
            if model is None:
                err = ServerClosed('model %r was unloaded' % name)
                for req in batch:
                    if not req.done():
                        req.set_error(err)
                continue
            try:
                self._run_batch(model, batch)
            except Exception as e:           # noqa: BLE001 — worker must
                # never die: every queued client is waiting on it.
                # Record the breaker outcome BEFORE failing the futures
                # so a client woken by the error observes a breaker
                # that already counted it.
                if breaker is not None:
                    breaker.record_failure()
                self.stats.record_failed(len(batch))
                for req in batch:
                    if not req.done():
                        req.set_error(e)
                with self._lock:
                    self._wedged.discard(name)
            else:
                # success was recorded on the breaker inside
                # _run_batch, before any future completed
                with self._lock:
                    self._wedged.discard(name)

    def _exe_run(self, model, feed):
        _fi.maybe_fault(_fi.SITE_SERVING_RUN)
        return self.executor.run(model.program, feed=feed,
                                 fetch_list=model.fetch_vars,
                                 scope=model.scope)

    def _run_guarded(self, model, feed, deadline=None):
        """One Executor.run with transient-failure retry, backoff
        capped by the batch's earliest request deadline."""
        def _on_retry(attempt, error):
            self.stats.record_retry()
            # a zero-length marker span under the active serving/run
            # span: the retry storm is visible in the request's tree
            ctx = _obs.current_context()
            if ctx is not None:
                _obs.emit_span('serving/retry', 0.0, parent=ctx,
                               attempt=attempt,
                               error=type(error).__name__)
        return retry_call(self._exe_run, (model, feed),
                          max_attempts=self.retry_attempts,
                          backoff=self.retry_backoff,
                          retry_on=self.retry_on, on_retry=_on_retry,
                          deadline=deadline)

    def _earliest_deadline(self, batch):
        deadlines = [r.deadline for r in batch if r.deadline is not None]
        return min(deadlines) if deadlines else None

    def _run_batch(self, model, batch):
        """Run one coalesced batch. Returns True when the watchdog
        tripped a stage mid-flight — the futures are already failed, so
        the caller must not complete (or count) them again.

        Tracing: each traced request gets a ``serving/queue`` span for
        its time-in-queue; the batch itself runs under ONE
        ``serving/batch`` span (parented to the first traced request)
        ``span_link``-ed to every request it serves — the N↔1 coalesce
        is a link, not a parent edge. The batch span is active on this
        worker thread, so pad/run and Executor child spans nest."""
        now = time.monotonic()
        for r in batch:
            if r.trace is not None:
                _obs.emit_span('serving/queue', now - r.submit_time,
                               parent=r.trace, model=model.name)
        traced = [r.trace for r in batch if r.trace is not None]
        bspan = None
        if traced:
            bspan = _obs.start_span('serving/batch', parent=traced[0],
                                    model=model.name,
                                    requests=len(batch))
            for t in traced:
                _obs.link(bspan, t)
        try:
            return self._run_batch_stages(model, batch, bspan)
        finally:
            if bspan is not None:
                bspan.end()

    def _run_batch_stages(self, model, batch, bspan):
        feed, rows, slices = merge_requests(batch)
        bucket = self.policy.bucket_for(rows) if model.batchable else rows
        deadline = self._earliest_deadline(batch)
        token = self.watchdog.enter(
            model.name, _fi.SITE_SERVING_PAD,
            self.stage_timeouts.get(_fi.SITE_SERVING_PAD), batch)
        pspan = _obs.start_span('serving/pad', rows=rows,
                                bucket=bucket) \
            if bspan is not None else None
        try:
            with _prof.serving_span('serving/pad'):
                _fi.maybe_fault(_fi.SITE_SERVING_PAD)
                padded = pad_feed(feed, rows, bucket,
                                  self.policy.pad_mode)
        finally:
            pad_entry = self.watchdog.exit(token)
            if pspan is not None:
                pspan.end()
        if pad_entry is None:
            return True
        t0 = time.monotonic()
        token = self.watchdog.enter(
            model.name, _fi.SITE_SERVING_RUN,
            self.stage_timeouts.get(_fi.SITE_SERVING_RUN), batch)
        rspan = _obs.start_span('serving/run', rows=rows,
                                bucket=bucket) \
            if bspan is not None else None
        try:
            with _prof.serving_span('serving/batch_run'):
                fetches = self._run_guarded(model, padded,
                                            deadline=deadline)
        finally:
            run_entry = self.watchdog.exit(token)
            if rspan is not None:
                rspan.end()
        if run_entry is None:
            return True
        breaker = self._breakers.get(model.name)
        if breaker is not None:
            # count the success BEFORE completing any future, so a
            # client woken by its result observes a consistent breaker
            breaker.record_success()
        self.stats.record_batch(rows, bucket, time.monotonic() - t0)
        parts = split_fetches(fetches, slices, rows, bucket)
        if parts is None:
            # a fetch isn't row-aligned (reduced over the batch): the
            # padded/merged run polluted it. Serve each request alone,
            # unpadded — exactness over throughput — and remember.
            model.batchable = False
            for req in batch:
                token = self.watchdog.enter(
                    model.name, _fi.SITE_SERVING_RUN,
                    self.stage_timeouts.get(_fi.SITE_SERVING_RUN),
                    [req])
                espan = _obs.start_span('serving/exact_run',
                                        parent=req.trace, rows=req.n) \
                    if req.trace is not None else None
                try:
                    with _prof.serving_span('serving/exact_fallback'):
                        out = self._run_guarded(model, req.feeds,
                                                deadline=req.deadline)
                finally:
                    entry = self.watchdog.exit(token)
                    if espan is not None:
                        espan.end()
                if entry is None:
                    continue           # tripped: future already failed
                self._complete(req, out)
            return False
        for req, part in zip(batch, parts):
            self._complete(req, part)
        return False

    def _complete(self, req, fetches):
        latency = req.latency()
        if not req.warmup:
            trace_id = req.trace.trace_id \
                if (req.trace is not None and req.trace.sampled) else None
            self.stats.record_completed(latency, trace=trace_id)
            _prof.record_serving_event('serving/request', latency)
        req.set_result(fetches)
