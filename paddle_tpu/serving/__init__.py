"""Batched, shape-bucketed inference serving runtime.

The training stack compiles one XLA program per (program, feed-shape)
signature; this package is the layer that keeps *serving* traffic
inside that cache:

- :mod:`~paddle_tpu.serving.bucketing` — power-of-two shape buckets +
  ``run_bucketed`` (pad, run, strip; exact results).
- :mod:`~paddle_tpu.serving.registry` — multi-model registry over
  ``save_inference_model`` artifacts, one isolated scope per model.
- :mod:`~paddle_tpu.serving.batcher` — bounded request queues + dynamic
  micro-batching of compatible requests.
- :mod:`~paddle_tpu.serving.server` — :class:`ModelServer`: worker
  threads, admission control (load shedding + deadlines), warmup,
  transient-failure retry, health/drain/swap, stats.
- :mod:`~paddle_tpu.serving.breaker` — per-model
  :class:`CircuitBreaker` (closed -> open -> half-open probes), shed
  at admission as typed :class:`CircuitOpen`.
- :mod:`~paddle_tpu.serving.watchdog` — :class:`Watchdog`: per-stage
  deadlines over in-flight batches; a wedged run fails its futures
  (:class:`WatchdogTimeout`) instead of hanging clients and
  ``close()``.
- :mod:`~paddle_tpu.serving.stats` — request/batch latency histograms,
  occupancy, bucket distribution, compile-cache hit rate, guardrail
  counters.

See SERVING.md for the architecture, tuning, and the "Failure domains
& SLO guardrails" design.
"""
from .errors import (ServingError, ServerOverloaded,  # noqa
                     DeadlineExceeded, ModelNotFound, ServerClosed,
                     CircuitOpen, WatchdogTimeout)
from .bucketing import BucketPolicy, next_pow2, run_bucketed  # noqa
from .registry import LoadedModel, ModelRegistry  # noqa
from .batcher import InferenceRequest, MicroBatcher  # noqa
from .breaker import CircuitBreaker  # noqa
from .watchdog import Watchdog  # noqa
from .stats import LatencyHistogram, ServingStats  # noqa
from .server import ModelServer, DEFAULT_STAGE_TIMEOUTS  # noqa

__all__ = [
    'ServingError', 'ServerOverloaded', 'DeadlineExceeded',
    'ModelNotFound', 'ServerClosed', 'CircuitOpen', 'WatchdogTimeout',
    'BucketPolicy', 'next_pow2', 'run_bucketed',
    'LoadedModel', 'ModelRegistry',
    'InferenceRequest', 'MicroBatcher',
    'CircuitBreaker', 'Watchdog',
    'LatencyHistogram', 'ServingStats',
    'ModelServer', 'DEFAULT_STAGE_TIMEOUTS',
]
