"""Batched, shape-bucketed inference serving runtime.

The training stack compiles one XLA program per (program, feed-shape)
signature; this package is the layer that keeps *serving* traffic
inside that cache:

- :mod:`~paddle_tpu.serving.bucketing` — power-of-two shape buckets +
  ``run_bucketed`` (pad, run, strip; exact results).
- :mod:`~paddle_tpu.serving.registry` — multi-model registry over
  ``save_inference_model`` artifacts, one isolated scope per model.
- :mod:`~paddle_tpu.serving.batcher` — bounded request queues + dynamic
  micro-batching of compatible requests.
- :mod:`~paddle_tpu.serving.server` — :class:`ModelServer`: worker
  threads, admission control (load shedding + deadlines), warmup,
  transient-failure retry, stats.
- :mod:`~paddle_tpu.serving.stats` — request/batch latency histograms,
  occupancy, bucket distribution, compile-cache hit rate.

See SERVING.md for the architecture and tuning guide.
"""
from .errors import (ServingError, ServerOverloaded,  # noqa
                     DeadlineExceeded, ModelNotFound, ServerClosed)
from .bucketing import BucketPolicy, next_pow2, run_bucketed  # noqa
from .registry import LoadedModel, ModelRegistry  # noqa
from .batcher import InferenceRequest, MicroBatcher  # noqa
from .stats import LatencyHistogram, ServingStats  # noqa
from .server import ModelServer  # noqa

__all__ = [
    'ServingError', 'ServerOverloaded', 'DeadlineExceeded',
    'ModelNotFound', 'ServerClosed',
    'BucketPolicy', 'next_pow2', 'run_bucketed',
    'LoadedModel', 'ModelRegistry',
    'InferenceRequest', 'MicroBatcher',
    'LatencyHistogram', 'ServingStats',
    'ModelServer',
]
