"""Per-server serving statistics: counters, latency histograms, batch
occupancy, bucket distribution — the numbers an operator tunes
``max_batch_size`` / ``batch_timeout`` / bucket bounds against.

Everything is guarded by one lock and recorded from worker threads;
``as_dict()`` / ``report()`` snapshot consistently. Latency histograms
use power-of-two millisecond buckets (0.25ms, 0.5ms, ... 32s) — the
same log-2 philosophy as shape bucketing: bounded cardinality, constant
relative resolution.
"""
import threading

from .. import observability as _obs
from .breaker import STATE_CODES

__all__ = ['LatencyHistogram', 'ServingStats']

# histogram bucket upper bounds in milliseconds: 0.25ms .. 32768ms + inf
_HIST_EDGES_MS = [0.25 * (2 ** i) for i in range(18)]


class LatencyHistogram(object):
    """Log-2 latency histogram (milliseconds). Not self-locking — the
    owning ServingStats serializes access."""

    def __init__(self):
        self.counts = [0] * (len(_HIST_EDGES_MS) + 1)
        self.count = 0
        self.total_ms = 0.0
        self.max_ms = 0.0

    def record(self, seconds):
        ms = seconds * 1000.0
        self.count += 1
        self.total_ms += ms
        self.max_ms = max(self.max_ms, ms)
        for i, edge in enumerate(_HIST_EDGES_MS):
            if ms <= edge:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def quantile(self, q):
        """Approximate quantile: the upper edge of the bucket holding
        the q-th sample (ms)."""
        if not self.count:
            return 0.0
        target, seen = q * self.count, 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target and c:
                return _HIST_EDGES_MS[i] if i < len(_HIST_EDGES_MS) \
                    else self.max_ms
        return self.max_ms

    def as_dict(self):
        return {
            'count': self.count,
            'mean_ms': self.total_ms / self.count if self.count else 0.0,
            'p50_ms': self.quantile(0.50),
            'p99_ms': self.quantile(0.99),
            'max_ms': self.max_ms,
        }


class ServingStats(object):
    """One instance per ModelServer; every mutation happens under
    ``_lock`` so the 8-thread soak can't tear counters."""

    def __init__(self):
        self._lock = threading.Lock()
        self.submitted = 0
        self.completed = 0
        self.shed = 0          # rejected at admission (ServerOverloaded)
        self.expired = 0       # deadline passed before a worker ran it
        self.failed = 0        # run raised after retries
        self.retries = 0       # transient failures absorbed by retry
        self.breaker_rejected = 0  # shed by an open circuit breaker
        self.cancelled = 0         # failed by close()/abandon escalation
        self.watchdog_trips = 0    # stages tripped past their deadline
        self.breaker_transitions = {}   # to_state -> count
        self.batches = 0
        self.batched_rows = 0      # real rows carried by all batches
        self.padded_rows = 0       # pad rows added by bucketing
        self.bucket_counts = {}    # bucket size -> batches launched
        self.request_latency = LatencyHistogram()  # submit -> result set
        self.batch_latency = LatencyHistogram()    # one executor run
        # process registry mirrors (OBSERVABILITY.md): the per-server
        # counters above stay the exact per-ModelServer surface; these
        # aggregate across every server in the process.
        reg = _obs.default_registry()
        self._m = {
            'submitted': reg.counter('serving_requests_submitted_total',
                                     'requests admitted to a queue'),
            'completed': reg.counter('serving_requests_completed_total',
                                     'requests answered'),
            'shed': reg.counter('serving_requests_shed_total',
                                'requests rejected at admission'),
            'expired': reg.counter('serving_requests_expired_total',
                                   'requests whose deadline passed'),
            'failed': reg.counter('serving_requests_failed_total',
                                  'requests failed after retries'),
            'retries': reg.counter('serving_retries_total',
                                   'transient batch-run retries'),
            'breaker_rejected': reg.counter(
                'serving_breaker_rejected_total',
                'requests shed by an open circuit breaker'),
            'cancelled': reg.counter(
                'serving_requests_cancelled_total',
                'requests failed by close()/abandon escalation'),
            'batches': reg.counter('serving_batches_total',
                                   'device batches launched'),
            'rows': reg.counter('serving_batch_rows_total',
                                'real rows carried by batches'),
            'padded': reg.counter('serving_padded_rows_total',
                                  'pad rows added by bucketing'),
            'request_lat': reg.histogram('serving_request_seconds',
                                         'submit -> result latency'),
            'batch_lat': reg.histogram('serving_batch_seconds',
                                       'one batched executor run'),
        }

    # ---- recording (worker/client threads) -------------------------------
    def record_submitted(self, n=1):
        with self._lock:
            self.submitted += n
        self._m['submitted'].inc(n)
        _obs.emit('serving_admit', n=n)

    def record_shed(self, n=1):
        with self._lock:
            self.shed += n
        self._m['shed'].inc(n)
        _obs.emit('serving_shed', n=n)

    def record_expired(self, n=1):
        with self._lock:
            self.expired += n
        self._m['expired'].inc(n)
        _obs.emit('serving_expired', n=n)

    def record_failed(self, n=1):
        with self._lock:
            self.failed += n
        self._m['failed'].inc(n)
        _obs.emit('serving_failed', n=n)

    def record_retry(self, n=1):
        with self._lock:
            self.retries += n
        self._m['retries'].inc(n)
        _obs.emit('serving_retry', n=n)

    def record_breaker_rejected(self, model, n=1):
        with self._lock:
            self.breaker_rejected += n
        self._m['breaker_rejected'].inc(n)
        _obs.emit('serving_breaker_rejected', model=model, n=n)

    def record_cancelled(self, n=1):
        with self._lock:
            self.cancelled += n
        self._m['cancelled'].inc(n)
        _obs.emit('serving_cancelled', n=n)

    def record_breaker_state(self, model, state):
        """Publish the per-model breaker gauge (0 closed / 1 half-open
        / 2 open) without counting a transition — the init path."""
        _obs.default_registry().gauge(
            'serving_breaker_state',
            'circuit state per model: 0 closed / 1 half-open / 2 open',
            model=model).set(STATE_CODES[state])

    def record_breaker_transition(self, model, to_state, reason=''):
        with self._lock:
            self.breaker_transitions[to_state] = \
                self.breaker_transitions.get(to_state, 0) + 1
        self.record_breaker_state(model, to_state)
        _obs.default_registry().counter(
            'serving_breaker_transitions_total',
            'circuit-breaker state transitions',
            model=model, to=to_state).inc()
        _obs.emit('serving_breaker', model=model, to=to_state,
                  reason=reason)

    def record_watchdog_trip(self, model, stage='', failed=0,
                             overrun=0.0):
        with self._lock:
            self.watchdog_trips += 1
        _obs.default_registry().counter(
            'serving_watchdog_trips_total',
            'in-flight stages failed past their deadline',
            model=model).inc()
        _obs.emit('serving_watchdog_trip', model=model, stage=stage,
                  failed=failed, overrun_s=round(overrun, 6))

    def record_batch(self, rows, bucket, seconds):
        with self._lock:
            self.batches += 1
            self.batched_rows += rows
            self.padded_rows += bucket - rows
            self.bucket_counts[bucket] = \
                self.bucket_counts.get(bucket, 0) + 1
            self.batch_latency.record(seconds)
        self._m['batches'].inc()
        self._m['rows'].inc(rows)
        self._m['padded'].inc(bucket - rows)
        self._m['batch_lat'].observe(seconds)
        _obs.emit('serving_batch', rows=rows, bucket=bucket,
                  dur_s=round(seconds, 6))

    def record_completed(self, latency_seconds, n=1, trace=None):
        with self._lock:
            self.completed += n
            for _ in range(n):
                self.request_latency.record(latency_seconds)
        self._m['completed'].inc(n)
        # the trace id rides the latency bucket as an exemplar, so a
        # bad p99 resolves to a concrete trace (OBSERVABILITY.md)
        self._m['request_lat'].observe(latency_seconds, exemplar=trace)

    # ---- snapshots -------------------------------------------------------
    def occupancy(self):
        """Mean fraction of each launched batch that was real rows."""
        total = self.batched_rows + self.padded_rows
        return self.batched_rows / total if total else 0.0

    def as_dict(self, cache_info=None):
        with self._lock:
            d = {
                'requests': {
                    'submitted': self.submitted,
                    'completed': self.completed,
                    'shed': self.shed,
                    'expired': self.expired,
                    'failed': self.failed,
                    'retries': self.retries,
                    'breaker_rejected': self.breaker_rejected,
                    'cancelled': self.cancelled,
                },
                'guardrails': {
                    'watchdog_trips': self.watchdog_trips,
                    'breaker_transitions': dict(
                        self.breaker_transitions),
                },
                'batches': {
                    'count': self.batches,
                    'rows': self.batched_rows,
                    'padded_rows': self.padded_rows,
                    'occupancy': self.occupancy(),
                    'bucket_counts': dict(self.bucket_counts),
                },
                'latency': {
                    'request': self.request_latency.as_dict(),
                    'batch': self.batch_latency.as_dict(),
                },
            }
        if cache_info is not None:
            lookups = cache_info.hits + cache_info.misses
            d['compile_cache'] = {
                'hits': cache_info.hits,
                'misses': cache_info.misses,
                'size': cache_info.size,
                'hit_rate': cache_info.hits / lookups if lookups else 0.0,
            }
        return d

    def report(self, cache_info=None):
        """Human-readable dashboard, profiler-report style."""
        d = self.as_dict(cache_info=cache_info)
        r, b, lat = d['requests'], d['batches'], d['latency']
        g = d['guardrails']
        lines = [
            '----------------->     Serving Report     <-----------------',
            'requests: %(submitted)d submitted, %(completed)d completed, '
            '%(shed)d shed, %(expired)d expired, %(failed)d failed, '
            '%(retries)d retries' % r,
            'guardrails: %d breaker-rejected, %d cancelled, '
            '%d watchdog trips, breaker transitions %s'
            % (r['breaker_rejected'], r['cancelled'],
               g['watchdog_trips'],
               ', '.join('%s->%d' % (k, v) for k, v in sorted(
                   g['breaker_transitions'].items())) or '-'),
            'batches:  %d launched, %d rows (+%d pad), occupancy %.1f%%'
            % (b['count'], b['rows'], b['padded_rows'],
               100.0 * b['occupancy']),
            'buckets:  %s' % (', '.join(
                '%d->%d' % (k, v)
                for k, v in sorted(b['bucket_counts'].items())) or '-'),
            'latency:  request p50 %.2fms p99 %.2fms max %.2fms | '
            'batch p50 %.2fms p99 %.2fms max %.2fms'
            % (lat['request']['p50_ms'], lat['request']['p99_ms'],
               lat['request']['max_ms'], lat['batch']['p50_ms'],
               lat['batch']['p99_ms'], lat['batch']['max_ms']),
        ]
        if 'compile_cache' in d:
            c = d['compile_cache']
            lines.append(
                'compile cache: %d hits / %d misses (%d programs), '
                'hit rate %.1f%%' % (c['hits'], c['misses'], c['size'],
                                     100.0 * c['hit_rate']))
        return '\n'.join(lines)
