"""Serving error taxonomy.

Every failure a client can observe maps to exactly one of these, so
callers can branch on type (shed vs. expired vs. model bug) instead of
parsing messages.
"""

__all__ = ['ServingError', 'ServerOverloaded', 'DeadlineExceeded',
           'ModelNotFound', 'ServerClosed']


class ServingError(RuntimeError):
    """Base class for all serving-runtime errors."""


class ServerOverloaded(ServingError):
    """Admission control rejected the request: the model's queue is at
    ``max_queue_depth``. Load was shed at the door — the request was
    never enqueued and cost the server nothing. Clients should back off
    and retry."""


class DeadlineExceeded(ServingError):
    """The request's deadline passed before a worker could run it. The
    batch it would have joined was never launched on its behalf."""


class ModelNotFound(ServingError, KeyError):
    """No model registered under the requested name."""

    def __str__(self):
        # KeyError.__str__ repr()s the message; keep it readable
        return RuntimeError.__str__(self)


class ServerClosed(ServingError):
    """The server is shut down (or shutting down) and accepts no new
    requests."""
