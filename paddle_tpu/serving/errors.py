"""Serving error taxonomy.

Every failure a client can observe maps to exactly one of these, so
callers can branch on type (shed vs. expired vs. model bug) instead of
parsing messages.
"""

__all__ = ['ServingError', 'ServerOverloaded', 'DeadlineExceeded',
           'ModelNotFound', 'ServerClosed', 'CircuitOpen',
           'WatchdogTimeout']


class ServingError(RuntimeError):
    """Base class for all serving-runtime errors."""


class ServerOverloaded(ServingError):
    """Admission control rejected the request: the model's queue is at
    ``max_queue_depth``. Load was shed at the door — the request was
    never enqueued and cost the server nothing. Clients should back off
    and retry."""


class DeadlineExceeded(ServingError):
    """The request's deadline passed before a worker could run it. The
    batch it would have joined was never launched on its behalf."""


class ModelNotFound(ServingError, KeyError):
    """No model registered under the requested name."""

    def __str__(self):
        # KeyError.__str__ repr()s the message; keep it readable
        return RuntimeError.__str__(self)


class ServerClosed(ServingError):
    """The server is shut down (or shutting down) and accepts no new
    requests."""


class CircuitOpen(ServingError):
    """The model's circuit breaker is open (or probing in half-open):
    recent batches failed hard enough that the server refuses to burn
    device time on this model. The request was shed at admission — it
    cost the server one lock acquisition. ``retry_after`` (seconds,
    may be None) hints when the breaker's next half-open probe window
    starts; clients should back off at least that long."""

    def __init__(self, message, retry_after=None):
        super(CircuitOpen, self).__init__(message)
        self.retry_after = retry_after


class WatchdogTimeout(ServingError):
    """The batch carrying this request exceeded its per-stage deadline
    and the watchdog failed it. The worker thread may still be wedged
    inside the stage; the model's breaker is opened so no new work
    piles onto it."""
