"""Per-model circuit breaker: stop burning device time on a model
whose batches keep failing.

State machine (SERVING.md "Failure domains & SLO guardrails")::

            consecutive failures >= failure_threshold
            OR windowed failure rate >= failure_rate
    CLOSED ------------------------------------------> OPEN
      ^                                                 |
      | probe_successes consecutive                     | cooldown
      | probe successes                                 v
      +----------------------------- HALF_OPEN <--------+
                                        |
                                        | any probe failure
                                        +--------------> OPEN

- CLOSED: everything is admitted; outcomes are tallied (a consecutive
  counter plus a sliding window of the last ``window`` outcomes).
- OPEN: :meth:`admit` raises :class:`CircuitOpen` — the request is
  shed at the server's admission door before it touches a queue. After
  ``cooldown`` seconds the next ``state`` read transitions to
  HALF_OPEN.
- HALF_OPEN: at most ``max_probes`` requests are admitted at a time as
  probes. ``probe_successes`` consecutive successes re-close the
  breaker; a single failure re-opens it (restarting the cooldown).

Determinism: no hidden wall-clock reads — the clock is injectable, and
every transition lands in :attr:`transitions` so tests and the chaos
harness can assert the exact open → half-open → closed schedule.
Thread-safety: one lock; ``admit``/``record_*`` are called from client
and worker threads concurrently.
"""
import collections
import threading
import time

from .errors import CircuitOpen

__all__ = ['CircuitBreaker', 'CLOSED', 'HALF_OPEN', 'OPEN', 'STATE_CODES']

CLOSED, HALF_OPEN, OPEN = 'closed', 'half_open', 'open'

# gauge encoding for serving_breaker_state{model=...} (OBSERVABILITY.md)
STATE_CODES = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker(object):
    """One breaker per served model.

    Parameters
    ----------
    name : str
        The model name (labels metrics/journal events).
    failure_threshold : int
        Consecutive hard failures that open the breaker.
    window / failure_rate :
        Sliding window of the last ``window`` outcomes; once full, a
        failure pushing the fraction of failures to >= ``failure_rate``
        also opens the breaker (catches steady partial failure that
        never runs ``failure_threshold`` in a row).
    cooldown : float
        Seconds to stay OPEN before probing (HALF_OPEN).
    probe_successes : int
        Consecutive successful probes that re-close the breaker.
    max_probes : int
        Probes admitted concurrently while HALF_OPEN.
    clock : callable
        Monotonic time source (injectable for deterministic tests).
    on_transition : callable, optional
        ``on_transition(name, to_state, reason)`` — the server wires
        this into metrics + the run journal.
    """

    def __init__(self, name='', failure_threshold=5, window=20,
                 failure_rate=0.5, cooldown=1.0, probe_successes=2,
                 max_probes=1, clock=time.monotonic, on_transition=None):
        if failure_threshold < 1:
            raise ValueError('failure_threshold must be >= 1')
        if not 0.0 < failure_rate:
            raise ValueError('failure_rate must be > 0')
        self.name = name
        self.failure_threshold = failure_threshold
        self.failure_rate = failure_rate
        self.cooldown = cooldown
        self.probe_successes = probe_successes
        self.max_probes = max_probes
        self._clock = clock
        self._on_transition = on_transition
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive = 0
        self._window = collections.deque(maxlen=max(1, int(window)))
        self._opened_at = None
        self._probes_inflight = 0
        self._probe_streak = 0
        self.transitions = []     # [(to_state, reason), ...] in order

    # ---- state -----------------------------------------------------------
    @property
    def state(self):
        """Current state; reading it performs the time-based
        OPEN -> HALF_OPEN transition once ``cooldown`` has elapsed."""
        with self._lock:
            self._maybe_half_open()
            return self._state

    def _maybe_half_open(self):
        # caller holds the lock
        if self._state == OPEN and \
                self._clock() - self._opened_at >= self.cooldown:
            self._transition(HALF_OPEN, 'cooldown elapsed')

    def _transition(self, to, reason):
        # caller holds the lock
        self._state = to
        self.transitions.append((to, reason))
        if to == OPEN:
            self._opened_at = self._clock()
        if to in (HALF_OPEN, CLOSED):
            self._probes_inflight = 0
            self._probe_streak = 0
        if to == CLOSED:
            self._consecutive = 0
            self._window.clear()
        cb = self._on_transition
        if cb is not None:
            cb(self.name, to, reason)

    # ---- admission (client threads) --------------------------------------
    def admit(self):
        """Gate one request. Raises :class:`CircuitOpen` when the
        breaker is OPEN, or HALF_OPEN with all probe slots taken.
        Returns True when the admission took a half-open probe slot
        (the caller marks the request so an expiry can release it)."""
        with self._lock:
            self._maybe_half_open()
            if self._state == CLOSED:
                return False
            if self._state == HALF_OPEN:
                if self._probes_inflight < self.max_probes:
                    self._probes_inflight += 1
                    return True
                raise CircuitOpen(
                    'model %r: breaker half-open, %d probe(s) already '
                    'in flight' % (self.name, self._probes_inflight),
                    retry_after=0.0)
            remaining = self.cooldown - (self._clock() - self._opened_at)
            raise CircuitOpen(
                'model %r: breaker open (%d consecutive failures); '
                'probing in %.3fs' % (self.name, self._consecutive,
                                      max(0.0, remaining)),
                retry_after=max(0.0, remaining))

    def release_probe(self):
        """Undo one :meth:`admit` that never reached a worker (the
        enqueue itself failed): frees the half-open probe slot."""
        with self._lock:
            if self._state == HALF_OPEN and self._probes_inflight > 0:
                self._probes_inflight -= 1

    # ---- outcomes (worker threads) ---------------------------------------
    def record_success(self):
        with self._lock:
            self._maybe_half_open()
            if self._state == CLOSED:
                self._consecutive = 0
                self._window.append(False)
            elif self._state == HALF_OPEN:
                if self._probes_inflight > 0:
                    self._probes_inflight -= 1
                self._probe_streak += 1
                if self._probe_streak >= self.probe_successes:
                    self._transition(
                        CLOSED, '%d probe successes' % self._probe_streak)
            # OPEN: a straggler from before the trip — ignore

    def record_failure(self):
        with self._lock:
            self._maybe_half_open()
            if self._state == CLOSED:
                self._consecutive += 1
                self._window.append(True)
                if self._consecutive >= self.failure_threshold:
                    self._transition(
                        OPEN, '%d consecutive failures'
                        % self._consecutive)
                elif len(self._window) == self._window.maxlen:
                    rate = sum(self._window) / float(len(self._window))
                    if rate >= self.failure_rate:
                        self._transition(
                            OPEN, 'windowed failure rate %.2f' % rate)
            elif self._state == HALF_OPEN:
                self._transition(OPEN, 'probe failed')
            # OPEN: already tripped — ignore

    def trip(self, reason='tripped'):
        """Force OPEN regardless of counters (watchdog path)."""
        with self._lock:
            if self._state != OPEN:
                self._transition(OPEN, reason)
            else:
                self._opened_at = self._clock()   # restart the cooldown

    def reset(self, reason='reset'):
        """Force CLOSED with clean counters (hot model swap installs a
        fresh replacement that earned a clean slate)."""
        with self._lock:
            if self._state != CLOSED:
                self._transition(CLOSED, reason)
            self._consecutive = 0
            self._window.clear()

    # ---- introspection ---------------------------------------------------
    def snapshot(self):
        with self._lock:
            self._maybe_half_open()
            return {
                'state': self._state,
                'consecutive_failures': self._consecutive,
                'window': list(self._window),
                'probes_inflight': self._probes_inflight,
                'probe_streak': self._probe_streak,
                'transitions': list(self.transitions),
            }

    def __repr__(self):
        return 'CircuitBreaker(%r, state=%r)' % (self.name, self.state)
