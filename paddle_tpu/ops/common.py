"""Shared kernel helpers (SequenceTensor transparency, broadcasting)."""
import jax.numpy as jnp

from ..lod import SequenceTensor


def unwrap(v):
    """Return dense data for kernels that are layout-transparent."""
    return v.data if isinstance(v, SequenceTensor) else v


def f32(x):
    """Upcast a bf16 activation for kernels whose math wants f32
    (losses, softmax, normalization statistics). No-op otherwise."""
    import jax.numpy as jnp
    return x.astype(jnp.float32) if getattr(x, 'dtype', None) == \
        jnp.bfloat16 else x


def rewrap(template, data):
    if isinstance(template, SequenceTensor):
        if template.packed_mode:
            return SequenceTensor.from_packed(data, template.offsets())
        return SequenceTensor(data, template.lengths, template.sub_lengths)
    return data


def seq_of(*vals):
    for v in vals:
        if isinstance(v, SequenceTensor):
            return v
    return None


def bcast_y(x, y, axis):
    """Fluid elementwise broadcast: y's shape matches a contiguous slice of
    x's shape starting at ``axis`` (trailing 1s in y are squeezed).
    Parity: paddle/fluid/operators/elementwise_op_function.h."""
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    if y.ndim == 0 or x.shape == y.shape:
        return y
    if axis is None or axis == -1:
        axis = x.ndim - y.ndim
    ys = list(y.shape)
    while ys and axis + len(ys) > x.ndim and ys[-1] == 1:
        ys.pop()
    new_shape = [1] * axis + ys + [1] * (x.ndim - axis - len(ys))
    return y.reshape(new_shape)
