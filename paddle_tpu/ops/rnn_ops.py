"""Recurrent op kernels: dynamic_lstm(p), dynamic_gru, gru_unit, lstm_unit.

Parity: paddle/fluid/operators/{lstm,lstmp,gru,gru_unit,lstm_unit}_op.*.
The reference sorts sequences by length into batches and steps a CPU/CUDA
cell kernel; here each RNN is one ``lax.scan`` over the padded time axis
with a carried mask — XLA fuses the per-step gate math into the recurrent
matmul, and the whole scan lives on-device (no host round trips).

Gate layouts follow the reference exactly:
  lstm   Weight [H, 4H] = {W_ch, W_ih, W_fh, W_oh} — gate chunks are
         (candidate, input, forget, output) (ref lstm_op.cc:125,
         lstm_kernel.h: state = in*ig + prev*fg). Peephole bias [1, 7H] =
         [b_c b_i b_f b_o | W_ic W_fc W_oc]. candidate_activation acts on
         the candidate chunk; cell_activation on the cell state feeding
         the output (ref lstm_compute.cc active_node/active_state).
  gru    Weight [H, 3H] = {W_uh W_rh | W_ch}; h = (1-u)*h_prev + u*c
         (ref gru_kernel.h:62: out = prev - u*prev + u*c).
  lstm_unit  X chunks are (i, f, o, g) (ref lstm_unit_op.h:63-67).
"""
import jax
import jax.numpy as jnp

from ..core.registry import register_kernel
from ..lod import SequenceTensor
from .common import unwrap
from .sequence_ops import masked_reverse

_ACT = {
    'sigmoid': jax.nn.sigmoid,
    'tanh': jnp.tanh,
    'relu': jax.nn.relu,
    'identity': lambda x: x,
    None: lambda x: x,
}


def _mask_t(lengths, T, dtype):
    """[T, B, 1] time-major step mask."""
    return (jnp.arange(T)[:, None] <
            jnp.asarray(lengths)[None, :]).astype(dtype)[..., None]


def _lstm_scan(x, lengths, w, b, h0, c0, use_peep, gact, cact, candact,
               proj=None, pact=None):
    """Shared lstm/lstmp scan. x: [B, T, 4H] pre-projected inputs.
    Returns (recurrent_out [B,T,R], cell [B,T,H])."""
    H = w.shape[1] // 4
    gate_b = b[:, :4 * H]
    if use_peep:
        w_ic, w_fc, w_oc = (b[0, 4 * H:5 * H], b[0, 5 * H:6 * H],
                            b[0, 6 * H:7 * H])
    B, T = x.shape[0], x.shape[1]
    xt = jnp.swapaxes(x, 0, 1) + gate_b           # [T, B, 4H]
    mask = _mask_t(lengths, T, x.dtype)

    # Default-activation, non-peephole, non-projected cells take the fused
    # Pallas kernel (ops/pallas_kernels.py): recurrent matmul + all gates
    # in one kernel launch per step.
    fused_ok = (not use_peep and proj is None
                and gact is jax.nn.sigmoid and cact is jnp.tanh
                and candact is jnp.tanh)

    def step(carry, inp):
        r_prev, c_prev = carry
        xg, m = inp
        if fused_ok:
            from .pallas_kernels import fused_lstm_cell
            h, c = fused_lstm_cell(xg, r_prev, c_prev, w)
            r = h
        else:
            g = xg + r_prev @ w
            gc, gi, gf, go = jnp.split(g, 4, axis=-1)  # (c, i, f, o)
            if use_peep:
                gi = gi + c_prev * w_ic
                gf = gf + c_prev * w_fc
            i = gact(gi)
            f = gact(gf)
            c = candact(gc) * i + c_prev * f
            if use_peep:
                go = go + c * w_oc
            o = gact(go)
            h = o * cact(c)
            r = pact(h @ proj) if proj is not None else h
        r = m * r + (1 - m) * r_prev
        c = m * c + (1 - m) * c_prev
        return (r, c), (r, c)

    (_, _), (rs, cs) = jax.lax.scan(step, (h0, c0), (xt, mask))
    return jnp.swapaxes(rs, 0, 1), jnp.swapaxes(cs, 0, 1)


@register_kernel('dynamic_lstm')
def _dynamic_lstm(ctx):
    st = ctx.input('Input')
    if not isinstance(st, SequenceTensor):
        raise TypeError("dynamic_lstm needs a SequenceTensor input")
    x = jnp.asarray(st.data)                      # [B, T, 4H]
    w = jnp.asarray(unwrap(ctx.input('Weight')))  # [H, 4H]
    b = jnp.asarray(unwrap(ctx.input('Bias')))    # [1, 4H] or [1, 7H]
    H = w.shape[0]
    use_peep = bool(ctx.attr('use_peepholes', True)) and b.shape[-1] == 7 * H
    is_rev = bool(ctx.attr('is_reverse', False))
    gact = _ACT[ctx.attr('gate_activation', 'sigmoid')]
    cact = _ACT[ctx.attr('cell_activation', 'tanh')]
    candact = _ACT[ctx.attr('candidate_activation', 'tanh')]

    if is_rev:
        x = masked_reverse(x, st.lengths)
    B = x.shape[0]
    h0 = jnp.asarray(unwrap(ctx.input('H0'))) if ctx.has_input('H0') \
        else jnp.zeros((B, H), x.dtype)
    c0 = jnp.asarray(unwrap(ctx.input('C0'))) if ctx.has_input('C0') \
        else jnp.zeros((B, H), x.dtype)
    hs, cs = _lstm_scan(x, st.lengths, w, b, h0, c0, use_peep, gact, cact,
                        candact)
    if is_rev:
        hs = masked_reverse(hs, st.lengths)
        cs = masked_reverse(cs, st.lengths)
    ctx.set_output('Hidden', SequenceTensor(hs, st.lengths))
    ctx.set_output('Cell', SequenceTensor(cs, st.lengths))
    if ctx.output_names('BatchGate'):
        ctx.set_output('BatchGate', jnp.zeros((1,), x.dtype))
    if ctx.output_names('BatchCellPreAct'):
        ctx.set_output('BatchCellPreAct', jnp.zeros((1,), x.dtype))


@register_kernel('dynamic_lstmp')
def _dynamic_lstmp(ctx):
    st = ctx.input('Input')
    x = jnp.asarray(st.data)                          # [B, T, 4H]
    w = jnp.asarray(unwrap(ctx.input('Weight')))      # [P, 4H]
    wp = jnp.asarray(unwrap(ctx.input('ProjWeight')))  # [H, P]
    b = jnp.asarray(unwrap(ctx.input('Bias')))
    H, P = wp.shape
    use_peep = bool(ctx.attr('use_peepholes', True)) and b.shape[-1] == 7 * H
    is_rev = bool(ctx.attr('is_reverse', False))
    gact = _ACT[ctx.attr('gate_activation', 'sigmoid')]
    cact = _ACT[ctx.attr('cell_activation', 'tanh')]
    candact = _ACT[ctx.attr('candidate_activation', 'tanh')]
    pact = _ACT[ctx.attr('proj_activation', 'tanh')]

    if is_rev:
        x = masked_reverse(x, st.lengths)
    B = x.shape[0]
    r0 = jnp.asarray(unwrap(ctx.input('H0'))) if ctx.has_input('H0') \
        else jnp.zeros((B, P), x.dtype)
    c0 = jnp.asarray(unwrap(ctx.input('C0'))) if ctx.has_input('C0') \
        else jnp.zeros((B, H), x.dtype)
    rs, cs = _lstm_scan(x, st.lengths, w, b, r0, c0, use_peep, gact, cact,
                        candact, proj=wp, pact=pact)
    if is_rev:
        rs = masked_reverse(rs, st.lengths)
        cs = masked_reverse(cs, st.lengths)
    ctx.set_output('Projection', SequenceTensor(rs, st.lengths))
    ctx.set_output('Cell', SequenceTensor(cs, st.lengths))


@register_kernel('dynamic_gru')
def _dynamic_gru(ctx):
    st = ctx.input('Input')
    x = jnp.asarray(st.data)                      # [B, T, 3H]
    w = jnp.asarray(unwrap(ctx.input('Weight')))  # [H, 3H]
    b = jnp.asarray(unwrap(ctx.input('Bias'))) if ctx.has_input('Bias') \
        else 0.0
    H = w.shape[0]
    is_rev = bool(ctx.attr('is_reverse', False))
    gact = _ACT[ctx.attr('gate_activation', 'sigmoid')]
    cact = _ACT[ctx.attr('activation', 'tanh')]
    w_g, w_c = _gru_weight_chunks(w, H)

    if is_rev:
        x = masked_reverse(x, st.lengths)
    B, T = x.shape[0], x.shape[1]
    xt = jnp.swapaxes(x, 0, 1) + b                # [T, B, 3H]
    mask = _mask_t(st.lengths, T, x.dtype)
    h0 = jnp.asarray(unwrap(ctx.input('H0'))) if ctx.has_input('H0') \
        else jnp.zeros((B, H), x.dtype)

    def step(h_prev, inp):
        xg, m = inp
        g = gact(xg[:, :2 * H] + h_prev @ w_g)
        u, r = g[:, :H], g[:, H:]
        c = cact(xg[:, 2 * H:] + (r * h_prev) @ w_c)
        h = (1 - u) * h_prev + u * c   # ref gru_kernel.h:62
        h = m * h + (1 - m) * h_prev
        return h, h

    _, hs = jax.lax.scan(step, h0, (xt, mask))
    hs = jnp.swapaxes(hs, 0, 1)
    if is_rev:
        hs = masked_reverse(hs, st.lengths)
    ctx.set_output('Hidden', SequenceTensor(hs, st.lengths))


def _gru_weight_chunks(w, H):
    """Reference gru weight layout (gru_op.h / gru_unit_op.h, mirrored
    by the unittests' w.flatten() chunking): the [H, 3H] parameter is
    a CONTIGUOUS [H, 2H] update/reset block followed by an [H, H]
    candidate block — not column slices."""
    flat = w.reshape(-1)
    return (flat[:2 * H * H].reshape(H, 2 * H),
            flat[2 * H * H:].reshape(H, H))


@register_kernel('gru_unit')
def _gru_unit(ctx):
    x = jnp.asarray(unwrap(ctx.input('Input')))        # [B, 3H]
    h_prev = jnp.asarray(unwrap(ctx.input('HiddenPrev')))
    w = jnp.asarray(unwrap(ctx.input('Weight')))       # [H, 3H]
    H = w.shape[0]
    b = jnp.asarray(unwrap(ctx.input('Bias'))) if ctx.has_input('Bias') \
        else 0.0
    gact = _ACT[ctx.attr('gate_activation', 'sigmoid')]
    cact = _ACT[ctx.attr('activation', 'tanh')]
    w_ur, w_cand = _gru_weight_chunks(w, H)
    xg = x + b
    g = gact(xg[:, :2 * H] + h_prev @ w_ur)
    u, r = g[:, :H], g[:, H:]
    rhp = r * h_prev
    c = cact(xg[:, 2 * H:] + rhp @ w_cand)
    h = (1 - u) * h_prev + u * c   # ref gru_unit_op.h: u*(c-h_p)+h_p
    ctx.set_output('Gate', jnp.concatenate([u, r, c], axis=-1))
    ctx.set_output('ResetHiddenPrev', rhp)
    ctx.set_output('Hidden', h)


@register_kernel('lstm_unit')
def _lstm_unit(ctx):
    """Single LSTM step. X = fc([x_t, h_prev]) [B, 4H]; gate chunks
    (i, f, o, g) per ref lstm_unit_op.h:63-67; forget_bias added to f."""
    x = jnp.asarray(unwrap(ctx.input('X')))
    c_prev = jnp.asarray(unwrap(ctx.input('C_prev')))
    fb = float(ctx.attr('forget_bias', 0.0))
    gi, gf, go, gg = jnp.split(x, 4, axis=-1)
    i = jax.nn.sigmoid(gi)
    f = jax.nn.sigmoid(gf + fb)
    c = f * c_prev + i * jnp.tanh(gg)
    o = jax.nn.sigmoid(go)
    h = o * jnp.tanh(c)
    ctx.set_output('C', c)
    ctx.set_output('H', h)
