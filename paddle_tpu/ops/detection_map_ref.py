"""Exact detection mAP algorithm (host side, numpy).

Parity: paddle/fluid/operators/detection_map_op.h — GetBoxes,
CalcTrueAndFalsePositive, CalcMAP (integral + 11point), including the
difficult-box rules and the reference's class-participation quirks:

- detections matched per (image, class) by MAX IoU against CLIPPED det
  boxes, strict ``> overlap_threshold``;
- a match to an already-visited gt is a false positive;
- a match to a difficult gt (when evaluate_difficult=False) contributes
  NEITHER tp nor fp (excluded from precision denominators);
- a class participates in the mean only if it has recorded detections
  AND its positive count differs from ``background_label``.

This is the accumulation backend of evaluator.DetectionMAP (the TPU
mapping of the reference op's AccumPosCount/AccumTruePos/AccumFalsePos
LoD state): :class:`DetectionMAPState` carries (score, flag) lists per
class across batches on the host, while the in-XLA kernel
(detection_ops._detection_map) computes the same math for a single call
with static shapes.
"""
import numpy as np

__all__ = ['DetectionMAPState', 'detection_map_numpy']


def _jaccard(box1, box2):
    """box: (xmin, ymin, xmax, ymax). Reference JaccardOverlap."""
    if box2[0] > box1[2] or box2[2] < box1[0] or \
            box2[1] > box1[3] or box2[3] < box1[1]:
        return 0.0
    ixmin = max(box1[0], box2[0])
    iymin = max(box1[1], box2[1])
    ixmax = min(box1[2], box2[2])
    iymax = min(box1[3], box2[3])
    inter = (ixmax - ixmin) * (iymax - iymin)
    a1 = (box1[2] - box1[0]) * (box1[3] - box1[1])
    a2 = (box2[2] - box2[0]) * (box2[3] - box2[1])
    return inter / (a1 + a2 - inter)


def _clip(box):
    return [min(max(float(v), 0.0), 1.0) for v in box]


class DetectionMAPState(object):
    """Per-class positive counts + (score, flag) tp/fp lists, accumulated
    across update() calls (reference: the Accum* op outputs)."""

    def __init__(self, overlap_threshold=0.5, evaluate_difficult=True,
                 ap_version='integral', class_num=None,
                 background_label=0):
        self.overlap_threshold = float(overlap_threshold)
        self.evaluate_difficult = bool(evaluate_difficult)
        self.ap_version = ap_version
        self.class_num = class_num
        self.background_label = background_label
        self.reset()

    def reset(self):
        self.pos_count = {}
        self.true_pos = {}
        self.false_pos = {}

    # -- per-batch update ----------------------------------------------------
    def update(self, detections, labels):
        """detections: list (one per image) of [D_i, 6] arrays
        (label, score, xmin, ymin, xmax, ymax); labels: list of [G_i, 5]
        (label, xmin..) or [G_i, 6] (label, is_difficult, xmin..)."""
        gt_boxes = []
        for gt in labels:
            gt = np.asarray(gt, np.float32)
            per_class = {}
            for row in gt:
                label = int(row[0])
                if gt.shape[1] == 6:
                    box = list(row[2:6])
                    difficult = abs(float(row[1])) >= 1e-6
                else:
                    box = list(row[1:5])
                    difficult = False
                per_class.setdefault(label, []).append((box, difficult))
            gt_boxes.append(per_class)

        det_boxes = []
        for det in detections:
            det = np.asarray(det, np.float32)
            per_class = {}
            for row in det:
                per_class.setdefault(int(row[0]), []).append(
                    (float(row[1]), list(row[2:6])))
            det_boxes.append(per_class)

        for per_class in gt_boxes:
            for label, boxes in per_class.items():
                if self.evaluate_difficult:
                    count = len(boxes)
                else:
                    count = sum(1 for _, diff in boxes if not diff)
                if count == 0:
                    continue
                self.pos_count[label] = self.pos_count.get(label, 0) \
                    + count

        for img_gt, img_det in zip(gt_boxes, det_boxes):
            for label, preds in img_det.items():
                tp = self.true_pos.setdefault(label, [])
                fp = self.false_pos.setdefault(label, [])
                if not img_gt or label not in img_gt:
                    for score, _ in preds:
                        tp.append((score, 0))
                        fp.append((score, 1))
                    continue
                matched = img_gt[label]
                visited = [False] * len(matched)
                for score, box in sorted(preds, key=lambda p: -p[0]):
                    box = _clip(box)
                    max_overlap, max_idx = -1.0, 0
                    for j, (gbox, _) in enumerate(matched):
                        ov = _jaccard(box, gbox)
                        if ov > max_overlap:
                            max_overlap, max_idx = ov, j
                    if max_overlap > self.overlap_threshold:
                        difficult = matched[max_idx][1]
                        if self.evaluate_difficult or not difficult:
                            if not visited[max_idx]:
                                tp.append((score, 1))
                                fp.append((score, 0))
                                visited[max_idx] = True
                            else:
                                tp.append((score, 0))
                                fp.append((score, 1))
                        # difficult match, not evaluated: no tp, no fp
                    else:
                        tp.append((score, 0))
                        fp.append((score, 1))

    # -- mAP -----------------------------------------------------------------
    def value(self):
        m_ap, count = 0.0, 0
        for label, num_pos in sorted(self.pos_count.items()):
            if num_pos == self.background_label or \
                    label not in self.true_pos:
                continue
            tp = sorted(self.true_pos[label], key=lambda p: -p[0])
            fp = sorted(self.false_pos[label], key=lambda p: -p[0])
            tp_sum = np.cumsum([f for _, f in tp])
            fp_sum = np.cumsum([f for _, f in fp])
            if len(tp_sum) == 0:
                count += 1
                continue
            precision = tp_sum / np.maximum(tp_sum + fp_sum, 1e-20)
            recall = tp_sum / float(num_pos)
            if self.ap_version == '11point':
                ap = 0.0
                for j in range(11):
                    mask = recall >= j / 10.0
                    p = float(precision[mask].max()) if mask.any() else 0.0
                    ap += p / 11.0
                m_ap += ap
            else:  # integral
                ap, prev_recall = 0.0, 0.0
                for p, r in zip(precision, recall):
                    if abs(r - prev_recall) > 1e-6:
                        ap += p * abs(r - prev_recall)
                    prev_recall = r
                m_ap += ap
            count += 1
        return m_ap / count if count else 0.0


def detection_map_numpy(detections, labels, class_num=None,
                        overlap_threshold=0.5, evaluate_difficult=True,
                        ap_version='integral', background_label=0):
    """One-shot mAP over a batch (lists of per-image arrays)."""
    state = DetectionMAPState(overlap_threshold, evaluate_difficult,
                              ap_version, class_num, background_label)
    state.update(detections, labels)
    return state.value()
