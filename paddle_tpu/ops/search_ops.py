"""Beam search ops — static-width beams on dense [B*K, ...] rows.

Parity: paddle/fluid/operators/{beam_search_op,beam_search_decode_op}.cc.
The reference keeps candidates in 2-level LoD tensors (batch -> beams)
whose widths shrink as beams finish; the TPU design keeps a FIXED beam
width K: row r = batch (r // K), beam slot (r % K). Finished beams
(pre_id == end_id) emit end_id with a frozen score, so every shape is
static and the whole decode loop compiles into one lax.while_loop.

Parent pointers are a first-class output here (slot 'parent_idx');
the reference recovers parentage from LoD offsets instead.
"""
import jax
import jax.numpy as jnp

from ..core.registry import register_kernel
from ..lod import SequenceTensor

_NEG = -1e9


def _rows(v):
    d = v.data if isinstance(v, SequenceTensor) else v
    return jnp.asarray(d)


@register_kernel('beam_search')
def _beam_search(ctx):
    pre_ids = _rows(ctx.input('pre_ids')).reshape(-1)          # [B*K]
    ids = _rows(ctx.input('ids'))                              # [B*K, C]
    scores = _rows(ctx.input('scores'))                        # [B*K, C]
    if ids.ndim == 3:
        ids = ids[..., 0]
    if scores.ndim == 3:
        scores = scores[..., 0]
    K = int(ctx.attr('beam_size'))
    end_id = int(ctx.attr('end_id'))
    BK, C = ids.shape
    B = BK // K

    finished = (pre_ids == end_id)
    # finished beams contribute exactly one candidate: (end_id, score
    # frozen at the beam's accumulated value, stored in scores[:, 0])
    ids = jnp.where(finished[:, None], end_id, ids)
    frozen = jnp.where(jnp.arange(C)[None, :] == 0,
                       scores[:, 0][:, None],
                       jnp.full_like(scores, _NEG))
    scores = jnp.where(finished[:, None], frozen, scores)

    flat_scores = scores.reshape(B, K * C)
    top_scores, flat_idx = jax.lax.top_k(flat_scores, K)       # [B, K]
    # parent as a GLOBAL row index (batch offset included) so the decode
    # backtrack can follow it directly across the [B*K] row space
    parent = (flat_idx // C).astype(jnp.int32) + \
        (jnp.arange(B, dtype=jnp.int32) * K)[:, None]
    tok = jnp.take_along_axis(ids.reshape(B, K * C), flat_idx,
                              axis=1).astype(jnp.int32)
    ctx.set_output('selected_ids', tok.reshape(BK, 1))
    ctx.set_output('selected_scores', top_scores.reshape(BK, 1))
    if ctx.output_names('parent_idx'):
        ctx.set_output('parent_idx', parent.reshape(BK, 1))


@register_kernel('beam_search_decode')
def _beam_search_decode(ctx):
    """Backtrack tensor arrays of (ids, scores, parents) written once per
    decode step. SentenceIds: SequenceTensor [B*K, cap] — beam r holds the
    full token path of (batch r//K, slot r%K); SentenceScores carries each
    beam's final accumulated score per position."""
    ids_arr = ctx.input('Ids')
    scores_arr = ctx.input('Scores')
    parents_arr = ctx.input('Parents')
    if not (isinstance(ids_arr, dict) and 'buf' in ids_arr):
        raise TypeError("beam_search_decode expects tensor arrays "
                        "(array_write the step outputs)")
    if parents_arr is None:
        raise ValueError("beam_search_decode needs the Parents array "
                         "(pass parent_idx from layers.beam_search)")
    ids_buf = ids_arr['buf'][..., 0] if ids_arr['buf'].ndim == 3 \
        else ids_arr['buf']                                    # [cap, BK]
    par_buf = parents_arr['buf'][..., 0] \
        if parents_arr['buf'].ndim == 3 else parents_arr['buf']
    sc_buf = scores_arr['buf'][..., 0] \
        if scores_arr['buf'].ndim == 3 else scores_arr['buf']
    n = ids_arr['len']
    cap, BK = ids_buf.shape

    # walk backwards: slot r follows its parent chain; steps >= n frozen
    def back(slot, t):
        active = t < n
        tok = jnp.take_along_axis(ids_buf[t], slot, axis=0)
        par = jnp.take_along_axis(par_buf[t], slot, axis=0)
        new_slot = jnp.where(active, par.astype(jnp.int32), slot)
        tok = jnp.where(active, tok, 0)
        return new_slot, tok

    # final beams are identity slots within each batch group
    slot0 = jnp.arange(BK, dtype=jnp.int32)
    _, toks_rev = jax.lax.scan(back, slot0,
                               jnp.arange(cap - 1, -1, -1))
    toks = jnp.flip(jnp.swapaxes(toks_rev, 0, 1), axis=1)      # [BK, cap]
    lengths = jnp.full((BK,), 1, jnp.int32) * n.astype(jnp.int32)
    final_scores = sc_buf[jnp.maximum(n - 1, 0)]               # [BK]
    ctx.set_output('SentenceIds', SequenceTensor(toks, lengths))
    ctx.set_output('SentenceScores', SequenceTensor(
        jnp.broadcast_to(final_scores[:, None], toks.shape), lengths))
