"""Beam search ops — static-width beams on dense [B*K, ...] rows.

Parity: paddle/fluid/operators/{beam_search_op,beam_search_decode_op}.cc.
The reference keeps candidates in 2-level LoD tensors (batch -> beams)
whose widths shrink as beams finish; the TPU design keeps a FIXED beam
width K: row r = batch (r // K), beam slot (r % K). Finished beams
(pre_id == end_id) emit end_id with a frozen score, so every shape is
static and the whole decode loop compiles into one lax.while_loop.

Parent pointers are a first-class output here (slot 'parent_idx');
the reference recovers parentage from LoD offsets instead.
"""
import jax
import jax.numpy as jnp

from ..core.registry import register_kernel
from ..lod import SequenceTensor

_NEG = -1e9


def _rows(v):
    d = v.data if isinstance(v, SequenceTensor) else v
    return jnp.asarray(d)


def _beam_search_dynamic(ctx, pre):
    """Reference-exact dynamic path (operators/beam_search_op.cc):
    2-level LoD candidates, per-source top-K across live beams, finished
    beams pruned so row counts SHRINK. Engaged on the eager executor
    (host-interpreted While) where values are concrete and shapes may
    change every step; the static [B*K] path below covers jitted decodes.
    """
    import numpy as np
    ids = np.asarray(_rows(ctx.input('ids')))
    scores = np.asarray(_rows(ctx.input('scores')), np.float32)
    if ids.ndim == 1:
        ids = ids[:, None]
    if scores.ndim == 1:
        scores = scores[:, None]
    K = int(ctx.attr('beam_size'))
    end_id = int(ctx.attr('end_id'))
    level = int(ctx.attr('level', 0))
    offs = pre.offsets()
    # ToAbsOffset (beam_search_op.cc:30): level-0 entries index the next
    # level's entries, not rows; compose down to absolute ROW offsets so
    # every live beam row of a source is scanned (from step 2 on,
    # lod[0]=[0,1,2] over lod[1]=[0,K,2K] must become [0,K,2K])
    high = [int(o) for o in offs[level]]
    for lv in range(level + 1, len(offs)):
        nxt = offs[lv]
        high = [int(nxt[i]) for i in high]
    N, C = ids.shape
    pre_data = np.asarray(pre.data).reshape(-1)

    buckets = [[] for _ in range(N)]   # per parent row, selected items
    for s in range(len(high) - 1):
        items = [(r, int(ids[r, d]), float(scores[r, d]))
                 for r in range(high[s], high[s + 1]) for d in range(C)]
        items.sort(key=lambda it: -it[2])
        for it in items[:K]:
            buckets[it[0]].append(it)
    for r in range(N):                 # PruneEndidCandidates
        if int(pre_data[r]) == end_id:
            buckets[r] = []

    out_ids, out_scores, parents, low = [], [], [], [0]
    for r in range(N):
        # beam_search_op.cc:64-69 re-sorts each parent bucket by
        # (offset, id) before emitting; within a bucket offsets are
        # equal, so the reference order is id-ascending
        for it in sorted(buckets[r], key=lambda it: (it[0], it[1])):
            out_ids.append(it[1])
            out_scores.append(it[2])
            parents.append(r)
        low.append(len(out_ids))
    # output lod[0] = the ABS high_level (parent-row offsets — also the
    # index space of lod[1]'s buckets), exactly like the reference
    lod = [high, low]
    ctx.set_output('selected_ids', SequenceTensor.from_packed(
        jnp.asarray(np.array(out_ids, np.int32).reshape(-1, 1)), lod))
    ctx.set_output('selected_scores', SequenceTensor.from_packed(
        jnp.asarray(np.array(out_scores, np.float32).reshape(-1, 1)), lod))
    if ctx.output_names('parent_idx'):
        ctx.set_output('parent_idx', SequenceTensor.from_packed(
            jnp.asarray(np.array(parents, np.int32).reshape(-1, 1)), lod))


@register_kernel('beam_search')
def _beam_search(ctx):
    pre = ctx.input('pre_ids')
    if isinstance(pre, SequenceTensor) and pre.packed_mode and \
            len(pre.offsets()) >= 2 and \
            not isinstance(pre.data, jax.core.Tracer):
        _beam_search_dynamic(ctx, pre)
        return
    pre_ids = _rows(ctx.input('pre_ids')).reshape(-1)          # [B*K]
    ids = _rows(ctx.input('ids'))                              # [B*K, C]
    scores = _rows(ctx.input('scores'))                        # [B*K, C]
    if ids.ndim == 3:
        ids = ids[..., 0]
    if scores.ndim == 3:
        scores = scores[..., 0]
    K = int(ctx.attr('beam_size'))
    end_id = int(ctx.attr('end_id'))
    BK, C = ids.shape
    B = BK // K

    finished = (pre_ids == end_id)
    # finished beams contribute exactly one candidate: (end_id, score
    # frozen at the beam's accumulated value, stored in scores[:, 0])
    ids = jnp.where(finished[:, None], end_id, ids)
    frozen = jnp.where(jnp.arange(C)[None, :] == 0,
                       scores[:, 0][:, None],
                       jnp.full_like(scores, _NEG))
    scores = jnp.where(finished[:, None], frozen, scores)

    flat_scores = scores.reshape(B, K * C)
    top_scores, flat_idx = jax.lax.top_k(flat_scores, K)       # [B, K]
    # parent as a GLOBAL row index (batch offset included) so the decode
    # backtrack can follow it directly across the [B*K] row space
    parent = (flat_idx // C).astype(jnp.int32) + \
        (jnp.arange(B, dtype=jnp.int32) * K)[:, None]
    tok = jnp.take_along_axis(ids.reshape(B, K * C), flat_idx,
                              axis=1).astype(jnp.int32)
    ctx.set_output('selected_ids', tok.reshape(BK, 1))
    ctx.set_output('selected_scores', top_scores.reshape(BK, 1))
    if ctx.output_names('parent_idx'):
        ctx.set_output('parent_idx', parent.reshape(BK, 1))


def _beam_search_decode_dynamic(ctx, ids_list, scores_list):
    """Reference-exact PackAllSteps (operators/beam_search_decode_op.h):
    walk the per-step LoD trees, closing a sentence when a prefix has no
    children; emit all sentences per source with a fresh 2-level LoD."""
    import numpy as np
    steps = []
    for st_i, st_s in zip(ids_list, scores_list):
        offs = st_i.offsets() if isinstance(st_i, SequenceTensor) else None
        ivals = np.asarray(
            st_i.data if isinstance(st_i, SequenceTensor) else st_i
        ).reshape(-1)
        svals = np.asarray(
            st_s.data if isinstance(st_s, SequenceTensor) else st_s,
            np.float32).reshape(-1)
        steps.append((ivals, svals, offs))
    src_num = len(steps[0][2][0]) - 1

    def make_sentence(node):
        words, scs = [], []
        while node is not None:
            words.append(node[0])
            scs.append(node[1])
            node = node[2]
        return words[::-1], scs[::-1]

    prefixes = []                      # per source: list of leaf nodes
    sentences = [[] for _ in range(src_num)]
    for ivals, svals, offs in steps:   # PackTwoSteps per step
        high, low = offs[0], offs[1] if len(offs) > 1 else None
        new_prefixes = []
        for s in range(src_num):
            src_start, src_end = int(high[s]), int(high[s + 1])
            nodes = []
            if not prefixes:           # first step: roots
                for r in range(src_start, src_end):
                    nodes.append((int(ivals[r]), float(svals[r]), None))
            else:
                pref = prefixes[s]
                for pi, prefix in enumerate(pref):
                    c0 = int(low[src_start + pi])
                    c1 = int(low[src_start + pi + 1])
                    if c0 == c1:       # finished: collect the sentence
                        sentences[s].append(make_sentence(prefix))
                    else:
                        for r in range(c0, c1):
                            nodes.append((int(ivals[r]), float(svals[r]),
                                          prefix))
            new_prefixes.append(nodes)
        prefixes = new_prefixes
    for s in range(src_num):           # append surviving prefixes
        for node in prefixes[s]:
            sentences[s].append(make_sentence(node))

    src_lod, sent_lod = [0], [0]
    id_data, sc_data = [], []
    for s in range(src_num):
        for words, scs in sentences[s]:
            id_data.extend(words)
            sc_data.extend(scs)
            sent_lod.append(sent_lod[-1] + len(words))
        src_lod.append(src_lod[-1] + len(sentences[s]))
    lod = [src_lod, sent_lod]
    ctx.set_output('SentenceIds', SequenceTensor.from_packed(
        jnp.asarray(np.array(id_data, np.int32)), lod))
    ctx.set_output('SentenceScores', SequenceTensor.from_packed(
        jnp.asarray(np.array(sc_data, np.float32)), lod))


@register_kernel('beam_search_decode')
def _beam_search_decode(ctx):
    """Backtrack tensor arrays of (ids, scores, parents) written once per
    decode step. SentenceIds: SequenceTensor [B*K, cap] — beam r holds the
    full token path of (batch r//K, slot r%K); SentenceScores carries each
    beam's final accumulated score per position."""
    ids_arr = ctx.input('Ids')
    scores_arr = ctx.input('Scores')
    parents_arr = ctx.input('Parents')
    if isinstance(ids_arr, dict) and 'list' in ids_arr:
        ids_list = [e for e in ids_arr['list'] if e is not None]
        sc_list = [e for e in scores_arr['list'] if e is not None]
        if ids_list and isinstance(ids_list[0], SequenceTensor) and \
                ids_list[0].packed_mode:
            _beam_search_decode_dynamic(ctx, ids_list, sc_list)
            return
        # uniform elements: fall through to the static backtrack
        from .control_flow_ops import _list_to_buf
        ids_arr = _list_to_buf(ids_arr)
        scores_arr = _list_to_buf(scores_arr)
        if isinstance(parents_arr, dict) and 'list' in parents_arr:
            parents_arr = _list_to_buf(parents_arr)
    if not (isinstance(ids_arr, dict) and 'buf' in ids_arr):
        raise TypeError("beam_search_decode expects tensor arrays "
                        "(array_write the step outputs)")
    if parents_arr is None:
        raise ValueError("beam_search_decode needs the Parents array "
                         "(pass parent_idx from layers.beam_search)")
    ids_buf = ids_arr['buf'][..., 0] if ids_arr['buf'].ndim == 3 \
        else ids_arr['buf']                                    # [cap, BK]
    par_buf = parents_arr['buf'][..., 0] \
        if parents_arr['buf'].ndim == 3 else parents_arr['buf']
    sc_buf = scores_arr['buf'][..., 0] \
        if scores_arr['buf'].ndim == 3 else scores_arr['buf']
    n = ids_arr['len']
    cap, BK = ids_buf.shape

    # walk backwards: slot r follows its parent chain; steps >= n frozen
    def back(slot, t):
        active = t < n
        tok = jnp.take_along_axis(ids_buf[t], slot, axis=0)
        par = jnp.take_along_axis(par_buf[t], slot, axis=0)
        new_slot = jnp.where(active, par.astype(jnp.int32), slot)
        tok = jnp.where(active, tok, 0)
        return new_slot, tok

    # final beams are identity slots within each batch group
    slot0 = jnp.arange(BK, dtype=jnp.int32)
    _, toks_rev = jax.lax.scan(back, slot0,
                               jnp.arange(cap - 1, -1, -1))
    toks = jnp.flip(jnp.swapaxes(toks_rev, 0, 1), axis=1)      # [BK, cap]
    lengths = jnp.full((BK,), 1, jnp.int32) * n.astype(jnp.int32)
    final_scores = sc_buf[jnp.maximum(n - 1, 0)]               # [BK]
    ctx.set_output('SentenceIds', SequenceTensor(toks, lengths))
    ctx.set_output('SentenceScores', SequenceTensor(
        jnp.broadcast_to(final_scores[:, None], toks.shape), lengths))
