"""Kernel library: one traceable JAX kernel per op type.

Importing this package registers every kernel (parity with the reference's
static op registry in paddle/fluid/operators/*_op.cc).
"""
from . import common  # noqa
from . import math_ops  # noqa
from . import tensor_ops  # noqa
from . import nn_ops  # noqa
from . import optim_ops  # noqa
from . import sequence_ops  # noqa
from . import rnn_ops  # noqa
from . import control_flow_ops  # noqa
from . import crf_ops  # noqa
from . import ctc_ops  # noqa
from . import search_ops  # noqa
from . import detection_ops  # noqa
from . import collective_ops  # noqa
from . import zero_ops  # noqa
from . import misc_ops  # noqa

from ..core.registry import registered_ops  # noqa
