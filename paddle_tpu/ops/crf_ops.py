"""Linear-chain CRF, Viterbi decoding, chunk evaluation.

Parity: paddle/fluid/operators/{linear_chain_crf_op,crf_decoding_op,
chunk_eval_op}.{h,cc}.

Transition parameter layout (linear_chain_crf_op.h): row 0 = start
weights, row 1 = end weights, rows 2.. = [tag_num x tag_num] transition
matrix. The reference walks LoD'd sequences on the CPU; here everything
is a masked lax.scan over the padded [B, T, ...] batch, differentiable by
JAX autodiff (no hand-written backward needed).
"""
import jax
import jax.numpy as jnp

from ..core.registry import register_kernel
from ..lod import SequenceTensor


def _emission(ctx, slot='Emission'):
    st = ctx.input(slot)
    if not isinstance(st, SequenceTensor):
        raise TypeError("%s must be a SequenceTensor" % slot)
    return st


def _labels_dense(label):
    lab = label.data if isinstance(label, SequenceTensor) else label
    lab = jnp.asarray(lab)
    if lab.ndim == 3:
        lab = lab[..., 0]
    return lab.astype(jnp.int32)


@register_kernel('linear_chain_crf')
def _linear_chain_crf(ctx):
    """LogLikelihood output = negative log-likelihood per sequence [B, 1]
    (a cost, as in the reference — book 07 minimizes its mean)."""
    em = _emission(ctx)
    trans = jnp.asarray(ctx.input('Transition'))
    label = _labels_dense(ctx.input('Label'))
    x = jnp.asarray(em.data)                     # [B, T, S]
    B, T, S = x.shape
    lengths = jnp.asarray(em.lengths, jnp.int32)
    start, end, w = trans[0], trans[1], trans[2:]
    mask = (jnp.arange(T)[None, :] < lengths[:, None])        # [B, T]

    # ---- partition function: masked forward algorithm in log space
    alpha0 = start[None, :] + x[:, 0, :]                      # [B, S]

    def fwd(alpha, t):
        nxt = jax.scipy.special.logsumexp(
            alpha[:, :, None] + w[None, :, :], axis=1) + x[:, t, :]
        keep = mask[:, t][:, None]
        return jnp.where(keep, nxt, alpha), None

    alphaT, _ = jax.lax.scan(fwd, alpha0, jnp.arange(1, T))
    logZ = jax.scipy.special.logsumexp(alphaT + end[None, :], axis=1)

    # ---- gold path score
    em_score = jnp.sum(jnp.take_along_axis(
        x, label[..., None], axis=2)[..., 0] * mask, axis=1)
    prev, cur = label[:, :-1], label[:, 1:]
    trans_score = jnp.sum(w[prev, cur] * mask[:, 1:], axis=1)
    first_tag = label[:, 0]
    last_idx = jnp.maximum(lengths - 1, 0)
    last_tag = jnp.take_along_axis(label, last_idx[:, None], axis=1)[:, 0]
    score = em_score + trans_score + start[first_tag] + end[last_tag]

    nll = logZ - score
    ctx.set_output('LogLikelihood', nll[:, None])
    # intermediates kept for API parity (autodiff supersedes them)
    ctx.set_output('Alpha', alphaT)
    ctx.set_output('EmissionExps', jnp.exp(x - jnp.max(x)))
    ctx.set_output('TransitionExps', jnp.exp(trans - jnp.max(trans)))


@register_kernel('crf_decoding')
def _crf_decoding(ctx):
    """Viterbi decode. Without Label: the best path [B, T, 1] (masked).
    With Label: per-position 1 where label == path, 0 elsewhere
    (crf_decoding_op.h:60-63)."""
    em = _emission(ctx)
    trans = jnp.asarray(ctx.input('Transition'))
    x = jnp.asarray(em.data)
    B, T, S = x.shape
    lengths = jnp.asarray(em.lengths, jnp.int32)
    start, end, w = trans[0], trans[1], trans[2:]
    mask = (jnp.arange(T)[None, :] < lengths[:, None])

    delta0 = start[None, :] + x[:, 0, :]

    def viterbi(delta, t):
        cand = delta[:, :, None] + w[None, :, :]              # [B, S, S]
        best_prev = jnp.argmax(cand, axis=1).astype(jnp.int32)
        nxt = jnp.max(cand, axis=1) + x[:, t, :]
        keep = mask[:, t][:, None]
        delta_new = jnp.where(keep, nxt, delta)
        return delta_new, best_prev                            # bp per t

    deltaT, bps = jax.lax.scan(viterbi, delta0, jnp.arange(1, T))
    # bps: [T-1, B, S] back-pointers; add end weights at each row's last
    # valid position by scoring deltaT (frozen past each length) + end
    last_tag = jnp.argmax(deltaT + end[None, :], axis=1).astype(jnp.int32)

    # backtrack from each sequence's end; positions past the end hold the
    # frozen carry, which is exactly the tag at length-1
    def back(tag, t):
        bp_t = bps[t]                                          # [B, S]
        prev = jnp.take_along_axis(bp_t, tag[:, None], axis=1)[:, 0]
        # only step back while t+1 < length (t indexes bps for step t+1)
        active = (t + 1) < lengths
        tag_new = jnp.where(active, prev, tag)
        return tag_new, tag_new

    _, rev_path = jax.lax.scan(back, last_tag,
                               jnp.arange(T - 2, -1, -1))
    path = jnp.concatenate(
        [jnp.flip(jnp.swapaxes(rev_path, 0, 1), axis=1),
         last_tag[:, None]], axis=1)                           # [B, T]
    path = jnp.where(mask, path, 0)

    label = ctx.input('Label')
    if label is not None:
        lab = _labels_dense(label)
        out = jnp.where(mask, (lab == path).astype(jnp.int32), 0)
    else:
        out = path.astype(jnp.int32)
    ctx.set_output('ViterbiPath',
                   SequenceTensor(out[..., None], lengths))


# ---- chunk evaluation -----------------------------------------------------------
def _chunk_marks(tags, types, valid, scheme, prev_tags, prev_types,
                 prev_valid, next_tags, next_types, next_valid):
    """start/end flags for well-formed chunk sequences.
    Parity (well-formed subset): chunk_eval_op.h ChunkBegin/ChunkEnd."""
    same_prev = prev_valid & (prev_types == types)
    same_next = next_valid & (next_types == types)
    if scheme == 'iob':       # B=0, I=1
        start = valid & ((tags == 0) | (~same_prev))
        end = valid & ((~same_next) | (next_tags == 0))
    elif scheme == 'ioe':     # I=0, E=1
        start = valid & ((~same_prev) | (prev_tags == 1))
        end = valid & ((tags == 1) | (~same_next))
    elif scheme == 'iobes':   # B=0, I=1, E=2, S=3
        start = valid & ((tags == 0) | (tags == 3))
        end = valid & ((tags == 2) | (tags == 3))
    else:                     # plain: maximal same-type runs
        start = valid & (~same_prev)
        end = valid & (~same_next)
    return start, end


@register_kernel('chunk_eval')
def _chunk_eval(ctx):
    """Precision/recall/F1 over extracted chunks.
    Parity: paddle/fluid/operators/chunk_eval_op.h (well-formed
    sequences; excluded_chunk_types respected)."""
    inf = ctx.input('Inference')
    lab = ctx.input('Label')
    scheme = (ctx.attr('chunk_scheme', 'IOB') or 'IOB').lower()
    num_types = int(ctx.attr('num_chunk_types'))
    excluded = set(int(e) for e in ctx.attr('excluded_chunk_types', []))
    tag_counts = {'iob': 2, 'ioe': 2, 'iobes': 4, 'plain': 1}
    ntag = tag_counts[scheme]

    st = inf if isinstance(inf, SequenceTensor) else lab
    lengths = jnp.asarray(st.lengths, jnp.int32)
    T = st.data.shape[1]
    seq_mask = (jnp.arange(T)[None, :] < lengths[:, None])

    def analyze(ids):
        ids = _labels_dense(ids)
        types = ids // ntag
        tags = ids % ntag
        o_label = num_types * ntag
        valid = seq_mask & (ids < o_label) & (types < num_types)
        for e in excluded:
            valid = valid & (types != e)
        pad = lambda a, v: jnp.pad(a, ((0, 0), (1, 1)),
                                   constant_values=v)
        pt, ptyp, pv = pad(tags, 0)[:, :-2], pad(types, -1)[:, :-2], \
            pad(valid, False)[:, :-2]
        nt, ntyp, nv = pad(tags, 0)[:, 2:], pad(types, -1)[:, 2:], \
            pad(valid, False)[:, 2:]
        start, end = _chunk_marks(tags, types, valid, scheme, pt, ptyp,
                                  pv, nt, ntyp, nv)
        # chunk end position for the chunk starting at t: the first end
        # flag at t' >= t (reverse scan carries the next end index)
        def rev(carry, t):
            e_t = jnp.where(end[:, t], t, carry)
            return e_t, e_t

        init = jnp.full((ids.shape[0],), T, jnp.int32)
        _, ends_rev = jax.lax.scan(rev, init, jnp.arange(T - 1, -1, -1))
        chunk_end = jnp.flip(jnp.swapaxes(ends_rev, 0, 1), axis=1)
        return start, types, chunk_end

    i_start, i_type, i_end = analyze(inf)
    l_start, l_type, l_end = analyze(lab)
    n_inf = jnp.sum(i_start)
    n_lab = jnp.sum(l_start)
    correct = jnp.sum(i_start & l_start & (i_type == l_type) &
                      (i_end == l_end))
    precision = correct / jnp.maximum(n_inf, 1)
    recall = correct / jnp.maximum(n_lab, 1)
    f1 = 2 * precision * recall / jnp.maximum(precision + recall, 1e-10)
    ctx.set_output('Precision', precision.reshape(1).astype(jnp.float32))
    ctx.set_output('Recall', recall.reshape(1).astype(jnp.float32))
    ctx.set_output('F1-Score', f1.reshape(1).astype(jnp.float32))
    ctx.set_output('NumInferChunks', n_inf.reshape(1).astype(jnp.int32))
    ctx.set_output('NumLabelChunks', n_lab.reshape(1).astype(jnp.int32))
    ctx.set_output('NumCorrectChunks',
                   correct.reshape(1).astype(jnp.int32))
