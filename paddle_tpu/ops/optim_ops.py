"""Optimizer update kernels.

Parity: paddle/fluid/operators/{sgd,momentum,adam,adamax,adagrad,
decayed_adagrad,adadelta,rmsprop,ftrl}_op.* — each writes ParamOut (and
accumulator outs) back to the persistable state, so the whole update fuses
into the step's single XLA program (no separate optimizer dispatch).
"""
import jax
import jax.numpy as jnp

from ..core.registry import register_kernel
from ..core.lowering import SparseRows
from .common import unwrap


def _lr(ctx):
    lr = unwrap(ctx.input('LearningRate'))
    return lr.reshape(()) if hasattr(lr, 'reshape') else lr


def _flat_items(g, d):
    """[(rows [N, D], ids [N])] from a SparseRows' possibly-nested items."""
    out = []
    for rows, ids in g.items:
        out.append((jnp.asarray(rows).reshape(-1, d),
                    jnp.asarray(ids).reshape(-1).astype(jnp.int32)))
    return out


def _all_rows(g, d):
    """One (rows, ids) pair spanning ALL lookups of the table, so the
    moment update sees each id exactly once per step (reference
    MergeAdd merges the whole SelectedRows, not per-lookup)."""
    items = _flat_items(g, d)
    if len(items) == 1:
        return items[0]
    return (jnp.concatenate([r for r, _ in items], axis=0),
            jnp.concatenate([i for _, i in items], axis=0))


def _merge_rows(rows, ids, vocab):
    """Merge duplicate ids with STATIC shapes (TPU-native SelectedRows
    merge, ref math/selected_rows_functor.cc MergeAdd): sort by id,
    segment-sum each run onto its first occurrence, and emit id=vocab
    (out of bounds -> dropped by XLA scatter) for non-start slots."""
    n = ids.shape[0]
    order = jnp.argsort(ids)
    sid = ids[order]
    srow = rows[order]
    start = jnp.concatenate([jnp.ones((1,), bool), sid[1:] != sid[:-1]])
    first_idx = jax.lax.associative_scan(
        jnp.maximum, jnp.where(start, jnp.arange(n), 0))
    agg = jnp.zeros_like(srow).at[first_idx].add(srow)
    out_ids = jnp.where(start, sid, vocab)
    return agg, out_ids


@register_kernel('sgd')
def _sgd(ctx):
    p, g = unwrap(ctx.input('Param')), unwrap(ctx.input('Grad'))
    if isinstance(g, SparseRows):
        # SelectedRows SGD (ref sgd_op.h sparse branch): touch only the
        # gathered rows; duplicate ids accumulate in the scatter-add
        lr = _lr(ctx)
        for rows, ids in _flat_items(g, p.shape[1]):
            p = p.at[ids].add((-lr * rows).astype(p.dtype))
        ctx.set_output('ParamOut', p)
        return
    ctx.set_output('ParamOut', p - _lr(ctx) * g.astype(p.dtype))


@register_kernel('momentum')
def _momentum(ctx):
    p, g = unwrap(ctx.input('Param')), unwrap(ctx.input('Grad'))
    v = unwrap(ctx.input('Velocity'))
    mu = ctx.attr('mu')
    lr = _lr(ctx)
    v_out = mu * v + g
    if ctx.attr('use_nesterov', False):
        p_out = p - (g + mu * v_out) * lr
    else:
        p_out = p - lr * v_out
    ctx.set_output('ParamOut', p_out)
    ctx.set_output('VelocityOut', v_out)


@register_kernel('adam')
def _adam(ctx):
    p, g = unwrap(ctx.input('Param')), unwrap(ctx.input('Grad'))
    m1, m2 = unwrap(ctx.input('Moment1')), unwrap(ctx.input('Moment2'))
    b1p = unwrap(ctx.input('Beta1Pow')).reshape(())
    b2p = unwrap(ctx.input('Beta2Pow')).reshape(())
    b1, b2 = ctx.attr('beta1', 0.9), ctx.attr('beta2', 0.999)
    eps = ctx.attr('epsilon', 1e-8)
    lr = _lr(ctx)
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    if isinstance(g, SparseRows):
        # lazy-mode sparse Adam (ref adam_op.h SparseAdamFunctor):
        # moments decay and the param moves ONLY on touched rows;
        # duplicates are merged ACROSS all lookups of the table first
        # (SelectedRows MergeAdd), so each id decays/steps once per step
        rows, ids = _all_rows(g, p.shape[1])
        agg, sids = _merge_rows(rows, ids, g.vocab)
        sel = jnp.clip(sids, 0, g.vocab - 1)
        m1r = b1 * m1[sel] + (1 - b1) * agg
        m2r = b2 * m2[sel] + (1 - b2) * jnp.square(agg)
        p = p.at[sids].set(
            (p[sel] - lr_t * m1r / (jnp.sqrt(m2r) + eps))
            .astype(p.dtype))
        m1 = m1.at[sids].set(m1r)
        m2 = m2.at[sids].set(m2r)
        ctx.set_output('ParamOut', p)
        ctx.set_output('Moment1Out', m1)
        ctx.set_output('Moment2Out', m2)
        return
    m1o = b1 * m1 + (1 - b1) * g
    m2o = b2 * m2 + (1 - b2) * jnp.square(g)
    ctx.set_output('ParamOut', p - lr_t * m1o / (jnp.sqrt(m2o) + eps))
    ctx.set_output('Moment1Out', m1o)
    ctx.set_output('Moment2Out', m2o)


@register_kernel('adamax')
def _adamax(ctx):
    p, g = unwrap(ctx.input('Param')), unwrap(ctx.input('Grad'))
    m = unwrap(ctx.input('Moment'))
    inf_norm = unwrap(ctx.input('InfNorm'))
    b1p = unwrap(ctx.input('Beta1Pow')).reshape(())
    b1, b2 = ctx.attr('beta1', 0.9), ctx.attr('beta2', 0.999)
    eps = ctx.attr('epsilon', 1e-8)
    lr = _lr(ctx)
    m_out = b1 * m + (1 - b1) * g
    # ref adamax_op.h:57-58: eps folds into the DECAYED term inside the
    # max (|g|.cwiseMax(beta2*inf + eps)), not onto the denominator
    inf_out = jnp.maximum(b2 * inf_norm + eps, jnp.abs(g))
    ctx.set_output('ParamOut',
                   p - (lr / (1 - b1p)) * m_out / inf_out)
    ctx.set_output('MomentOut', m_out)
    ctx.set_output('InfNormOut', inf_out)


@register_kernel('adagrad')
def _adagrad(ctx):
    p, g = unwrap(ctx.input('Param')), unwrap(ctx.input('Grad'))
    m = unwrap(ctx.input('Moment'))
    eps = ctx.attr('epsilon', 1e-6)
    lr = _lr(ctx)
    if isinstance(g, SparseRows):
        # SelectedRows Adagrad (ref adagrad_op.h sparse branch): rows
        # merged across all lookups accumulate into the moment and move
        # only touched rows
        rows, ids = _all_rows(g, p.shape[1])
        agg, sids = _merge_rows(rows, ids, g.vocab)
        sel = jnp.clip(sids, 0, g.vocab - 1)
        m_r = m[sel] + jnp.square(agg)
        p = p.at[sids].set(
            (p[sel] - lr * agg / (jnp.sqrt(m_r) + eps))
            .astype(p.dtype))
        m = m.at[sids].set(m_r)
        ctx.set_output('ParamOut', p)
        ctx.set_output('MomentOut', m)
        return
    m_out = m + jnp.square(g)
    ctx.set_output('ParamOut', p - lr * g / (jnp.sqrt(m_out) + eps))
    ctx.set_output('MomentOut', m_out)


@register_kernel('decayed_adagrad')
def _decayed_adagrad(ctx):
    p, g = unwrap(ctx.input('Param')), unwrap(ctx.input('Grad'))
    m = unwrap(ctx.input('Moment'))
    decay = ctx.attr('decay', 0.95)
    eps = ctx.attr('epsilon', 1e-6)
    m_out = decay * m + (1 - decay) * jnp.square(g)
    ctx.set_output('ParamOut', p - _lr(ctx) * g / (jnp.sqrt(m_out) + eps))
    ctx.set_output('MomentOut', m_out)


@register_kernel('adadelta')
def _adadelta(ctx):
    p, g = unwrap(ctx.input('Param')), unwrap(ctx.input('Grad'))
    avg_sq_grad = unwrap(ctx.input('AvgSquaredGrad'))
    avg_sq_upd = unwrap(ctx.input('AvgSquaredUpdate'))
    rho = ctx.attr('rho', 0.95)
    eps = ctx.attr('epsilon', 1e-6)
    asg = rho * avg_sq_grad + (1 - rho) * jnp.square(g)
    update = -jnp.sqrt((avg_sq_upd + eps) / (asg + eps)) * g
    asu = rho * avg_sq_upd + (1 - rho) * jnp.square(update)
    ctx.set_output('ParamOut', p + update)
    ctx.set_output('AvgSquaredGradOut', asg)
    ctx.set_output('AvgSquaredUpdateOut', asu)


@register_kernel('rmsprop')
def _rmsprop(ctx):
    p, g = unwrap(ctx.input('Param')), unwrap(ctx.input('Grad'))
    ms = unwrap(ctx.input('MeanSquare'))
    mom = unwrap(ctx.input('Moment'))
    rho = ctx.attr('decay', 0.95)
    eps = ctx.attr('epsilon', 1e-6)
    momentum = ctx.attr('momentum', 0.0)
    lr = _lr(ctx)
    ms_out = rho * ms + (1 - rho) * jnp.square(g)
    mom_out = momentum * mom + lr * g / jnp.sqrt(ms_out + eps)
    ctx.set_output('ParamOut', p - mom_out)
    ctx.set_output('MeanSquareOut', ms_out)
    ctx.set_output('MomentOut', mom_out)


@register_kernel('ftrl')
def _ftrl(ctx):
    p, g = unwrap(ctx.input('Param')), unwrap(ctx.input('Grad'))
    sq_accum = unwrap(ctx.input('SquaredAccumulator'))
    lin_accum = unwrap(ctx.input('LinearAccumulator'))
    l1 = ctx.attr('l1', 0.0)
    l2 = ctx.attr('l2', 0.0)
    lr_power = ctx.attr('lr_power', -0.5)
    lr = _lr(ctx)
    new_accum = sq_accum + jnp.square(g)
    lin_out = lin_accum + g - (
        jnp.power(new_accum, -lr_power) - jnp.power(sq_accum, -lr_power)
    ) / lr * p
    x = jnp.clip(lin_out, -l1, l1) - lin_out
    y = jnp.power(new_accum, -lr_power) / lr + 2 * l2
    ctx.set_output('ParamOut', x / y)
    ctx.set_output('SquaredAccumOut', new_accum)
    ctx.set_output('LinearAccumOut', lin_out)


@register_kernel('sign')
def _sign(ctx):
    ctx.set_output('Out', jnp.sign(unwrap(ctx.input('X'))))


@register_kernel('sqrt_op')
def _sqrt_op(ctx):
    ctx.set_output('Out', jnp.sqrt(unwrap(ctx.input('X'))))
