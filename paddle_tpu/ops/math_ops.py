"""Math / elementwise / activation / reduction kernels.

Parity: paddle/fluid/operators/{elementwise_*,activation,mul,matmul,reduce_*,
sum,scale,cast,clip,cumsum,cos_sim,...}_op.* — re-expressed as jnp traces so
XLA fuses them into neighbouring matmuls (HBM-bandwidth win; no hand
scheduling).
"""
import jax
import jax.numpy as jnp

from ..core.registry import register_kernel
from .common import unwrap, rewrap, seq_of, bcast_y


# ---- elementwise binary ---------------------------------------------------------
def _elementwise(name, fn):
    @register_kernel(name)
    def _k(ctx, fn=fn):
        x, y = ctx.input('X'), ctx.input('Y')
        tmpl = seq_of(x, y)
        xd, yd = unwrap(x), unwrap(y)
        axis = ctx.attr('axis', -1)
        from ..lod import SequenceTensor
        if (isinstance(x, SequenceTensor) and not x.packed_mode
                and not isinstance(y, SequenceTensor)
                and axis not in (None, -1) and axis >= 1):
            # IR shapes follow the reference's packed [total, ...] layout;
            # runtime data is padded [B, T, ...] so dims >= 1 shift by one.
            # packed-mode data IS the reference layout: no shift.
            axis += 1
        if (isinstance(x, SequenceTensor) and not x.packed_mode
                and not isinstance(y, SequenceTensor) and axis == 0
                and getattr(yd, 'ndim', 0) >= 1 and xd.ndim >= 2
                and _prod(yd.shape) == xd.shape[0] * xd.shape[1]):
            # reference row-broadcast: y is one value per PACKED row
            # ([total]); padded rows are [B, T] row-major, same order
            # (attention weight scaling in benchmark/fluid
            # machine_translation's simple_attention)
            yd = jnp.asarray(yd).reshape(
                (xd.shape[0], xd.shape[1]) + (1,) * (xd.ndim - 2))
            axis = -1
        yd = bcast_y(xd, yd, axis)
        out = fn(jnp.asarray(xd), yd)
        if ctx.attr('scale', None) not in (None, 1.0):
            out = out * ctx.attr('scale')
        ctx.set_output('Out', rewrap(tmpl, out) if tmpl is not None else out)


_elementwise('elementwise_add', jnp.add)
_elementwise('elementwise_sub', jnp.subtract)
_elementwise('elementwise_mul', jnp.multiply)
_elementwise('elementwise_div', jnp.divide)
_elementwise('elementwise_max', jnp.maximum)
_elementwise('elementwise_min', jnp.minimum)
_elementwise('elementwise_pow', jnp.power)


def _logical(name, fn, unary=False):
    @register_kernel(name)
    def _k(ctx, fn=fn, unary=unary):
        x = unwrap(ctx.input('X'))
        out = fn(x) if unary else fn(x, unwrap(ctx.input('Y')))
        ctx.set_output('Out', out.astype(jnp.bool_))


_logical('logical_and', jnp.logical_and)
_logical('logical_or', jnp.logical_or)
_logical('logical_xor', jnp.logical_xor)
_logical('logical_not', jnp.logical_not, unary=True)


@register_kernel('compare')
@register_kernel('less_than')
@register_kernel('less_equal')
@register_kernel('greater_than')
@register_kernel('greater_equal')
@register_kernel('equal')
@register_kernel('not_equal')
def _compare(ctx):
    op = {'less_than': jnp.less, 'less_equal': jnp.less_equal,
          'greater_than': jnp.greater, 'greater_equal': jnp.greater_equal,
          'equal': jnp.equal, 'not_equal': jnp.not_equal}[ctx.op.type]
    x, y = unwrap(ctx.input('X')), unwrap(ctx.input('Y'))
    ctx.set_output('Out', op(jnp.asarray(x), jnp.asarray(y)))


# ---- activations ----------------------------------------------------------------
_ACTS = {
    'sigmoid': jax.nn.sigmoid,
    'logsigmoid': jax.nn.log_sigmoid,
    'exp': jnp.exp,
    'relu': jax.nn.relu,
    'tanh': jnp.tanh,
    'tanh_shrink': lambda x: x - jnp.tanh(x),
    'sqrt': jnp.sqrt,
    'abs': jnp.abs,
    'ceil': jnp.ceil,
    'floor': jnp.floor,
    'cos': jnp.cos,
    'sin': jnp.sin,
    'round': jnp.round,
    'reciprocal': lambda x: 1.0 / x,
    'log': jnp.log,
    'square': jnp.square,
    'softplus': jax.nn.softplus,
    'softsign': jax.nn.soft_sign,
}


def _register_acts():
    for name, fn in _ACTS.items():
        @register_kernel(name)
        def _k(ctx, fn=fn):
            x = ctx.input('X')
            ctx.set_output('Out', rewrap(x, fn(unwrap(x))))


_register_acts()


@register_kernel('brelu')
def _brelu(ctx):
    x = ctx.input('X')
    t_min, t_max = ctx.attr('t_min', 0.0), ctx.attr('t_max', 24.0)
    ctx.set_output('Out', rewrap(x, jnp.clip(unwrap(x), t_min, t_max)))


@register_kernel('leaky_relu')
def _leaky_relu(ctx):
    x = ctx.input('X')
    alpha = ctx.attr('alpha', 0.02)
    ctx.set_output('Out', rewrap(x, jax.nn.leaky_relu(unwrap(x), alpha)))


@register_kernel('soft_relu')
def _soft_relu(ctx):
    x = ctx.input('X')
    threshold = ctx.attr('threshold', 40.0)
    xd = jnp.clip(unwrap(x), -threshold, threshold)
    ctx.set_output('Out', rewrap(x, jnp.log1p(jnp.exp(xd))))


@register_kernel('elu')
def _elu(ctx):
    x = ctx.input('X')
    ctx.set_output('Out', rewrap(x, jax.nn.elu(unwrap(x),
                                               ctx.attr('alpha', 1.0))))


@register_kernel('relu6')
def _relu6(ctx):
    x = ctx.input('X')
    ctx.set_output('Out', rewrap(x, jnp.clip(unwrap(x), 0,
                                             ctx.attr('threshold', 6.0))))


@register_kernel('pow')
def _pow(ctx):
    x = ctx.input('X')
    ctx.set_output('Out', rewrap(x, jnp.power(unwrap(x),
                                              ctx.attr('factor', 1.0))))


@register_kernel('stanh')
def _stanh(ctx):
    x = ctx.input('X')
    a = ctx.attr('scale_a', 2.0 / 3.0)
    b = ctx.attr('scale_b', 1.7159)
    ctx.set_output('Out', rewrap(x, b * jnp.tanh(a * unwrap(x))))


@register_kernel('hard_shrink')
def _hard_shrink(ctx):
    x = ctx.input('X')
    t = ctx.attr('threshold', 0.5)
    xd = unwrap(x)
    ctx.set_output('Out', rewrap(x, jnp.where(jnp.abs(xd) > t, xd, 0.0)))


@register_kernel('softshrink')
def _softshrink(ctx):
    x = ctx.input('X')
    lam = ctx.attr('lambda', 0.5)
    xd = unwrap(x)
    out = jnp.where(xd > lam, xd - lam, jnp.where(xd < -lam, xd + lam, 0.0))
    ctx.set_output('Out', rewrap(x, out))


@register_kernel('thresholded_relu')
def _thresholded_relu(ctx):
    x = ctx.input('X')
    t = ctx.attr('threshold', 1.0)
    xd = unwrap(x)
    ctx.set_output('Out', rewrap(x, jnp.where(xd > t, xd, 0.0)))


@register_kernel('hard_sigmoid')
def _hard_sigmoid(ctx):
    x = ctx.input('X')
    slope = ctx.attr('slope', 0.2)
    offset = ctx.attr('offset', 0.5)
    ctx.set_output('Out', rewrap(x, jnp.clip(slope * unwrap(x) + offset,
                                             0.0, 1.0)))


@register_kernel('swish')
def _swish(ctx):
    x = ctx.input('X')
    beta = ctx.attr('beta', 1.0)
    xd = unwrap(x)
    ctx.set_output('Out', rewrap(x, xd * jax.nn.sigmoid(beta * xd)))


# ---- matmul family --------------------------------------------------------------
@register_kernel('mul')
def _mul(ctx):
    """fc matmul. X flattened by x_num_col_dims, Y by y_num_col_dims.
    Parity: operators/mul_op.cc. Feeds the MXU directly.

    Sequence inputs: the reference packs time into dim 0 ([total, D]); our
    runtime layout is padded [B, T, D], so the time dim joins the row dims
    and the result stays a SequenceTensor."""
    x_in, y = ctx.input('X'), unwrap(ctx.input('Y'))
    x = unwrap(x_in)
    xd = ctx.attr('x_num_col_dims', 1)
    yd = ctx.attr('y_num_col_dims', 1)
    from ..lod import SequenceTensor
    is_seq = isinstance(x_in, SequenceTensor)
    if is_seq and not x_in.packed_mode:
        xd += 1  # [B, T] both count as row dims
    # packed mode keeps the reference's [total, D] layout: xd stays 1
    xs, ys = x.shape, y.shape
    x2 = x.reshape((_prod(xs[:xd]), _prod(xs[xd:])))
    y2 = y.reshape((_prod(ys[:yd]), _prod(ys[yd:])))
    from ..core.amp import mxu_compute
    out = mxu_compute(jnp.matmul, x2, y2)
    out = out.reshape(tuple(xs[:xd]) + tuple(ys[yd:]))
    ctx.set_output('Out', rewrap(x_in, out) if is_seq else out)


def _prod(t):
    r = 1
    for v in t:
        r *= int(v)
    return r


@register_kernel('matmul')
def _matmul(ctx):
    x, y = unwrap(ctx.input('X')), unwrap(ctx.input('Y'))
    tx, ty = ctx.attr('transpose_X', False), ctx.attr('transpose_Y', False)
    alpha = ctx.attr('alpha', 1.0)
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    if x.ndim == 1:
        x = x[None, :]
    if y.ndim == 1:
        y = y[:, None]
    if tx:
        x = jnp.swapaxes(x, -1, -2)
    if ty:
        y = jnp.swapaxes(y, -1, -2)
    from ..core.amp import mxu_compute
    out = mxu_compute(jnp.matmul, x, y)
    if alpha != 1.0:
        out = out * alpha
    ctx.set_output('Out', out)


# ---- reductions -----------------------------------------------------------------
def _reduce(name, fn):
    @register_kernel(name)
    def _k(ctx, fn=fn):
        x = unwrap(ctx.input('X'))
        dim = ctx.attr('dim', None)
        keep_dim = ctx.attr('keep_dim', False)
        reduce_all = ctx.attr('reduce_all', False)
        if reduce_all or dim is None:
            axis = None
        else:
            axis = tuple(dim) if isinstance(dim, (list, tuple)) else (dim,)
        out = fn(x, axis=axis, keepdims=keep_dim)
        ctx.set_output('Out', out)


_reduce('reduce_sum', jnp.sum)
_reduce('reduce_mean', jnp.mean)
_reduce('reduce_max', jnp.max)
_reduce('reduce_min', jnp.min)
_reduce('reduce_prod', jnp.prod)


@register_kernel('mean')
def _mean(ctx):
    from .common import f32
    x_in = ctx.input('X')
    x = f32(unwrap(x_in))
    from ..lod import SequenceTensor
    if isinstance(x_in, SequenceTensor):
        # average over REAL tokens only (reference means over the packed
        # [total, ...] rows, which has no padding)
        T = x.shape[1]
        m = (jnp.arange(T)[None, :] <
             jnp.asarray(x_in.lengths)[:, None])
        m = m.reshape(m.shape + (1,) * (x.ndim - 2)).astype(x.dtype)
        denom = jnp.maximum(jnp.sum(m), 1.0) * _prod(x.shape[2:])
        ctx.set_output('Out',
                       (jnp.sum(x * m) / denom).reshape((1,)))
        return
    ctx.set_output('Out', jnp.mean(x).reshape((1,)))


@register_kernel('sum')
def _sum(ctx):
    xs = [unwrap(v) for v in ctx.inputs('X')]
    out = xs[0]
    for v in xs[1:]:
        out = out + v
    tmpl = seq_of(*ctx.inputs('X'))
    ctx.set_output('Out', rewrap(tmpl, out) if tmpl is not None else out)


@register_kernel('scale')
def _scale(ctx):
    x = ctx.input('X')
    s = ctx.attr('scale', 1.0)
    bias = ctx.attr('bias', 0.0)
    bias_after = ctx.attr('bias_after_scale', True)
    xd = unwrap(x)
    out = xd * s + bias if bias_after else (xd + bias) * s
    ctx.set_output('Out', rewrap(x, out))


@register_kernel('clip')
def _clip(ctx):
    x = ctx.input('X')
    ctx.set_output('Out', rewrap(x, jnp.clip(unwrap(x), ctx.attr('min'),
                                             ctx.attr('max'))))


@register_kernel('clip_by_norm')
def _clip_by_norm(ctx):
    x = unwrap(ctx.input('X'))
    max_norm = ctx.attr('max_norm')
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    scale = jnp.minimum(max_norm / jnp.maximum(norm, 1e-12), 1.0)
    ctx.set_output('Out', x * scale)


@register_kernel('cumsum')
def _cumsum(ctx):
    x = unwrap(ctx.input('X'))
    axis = ctx.attr('axis', -1)
    out = jnp.cumsum(x, axis=axis)
    if ctx.attr('reverse', False):
        out = jnp.flip(jnp.cumsum(jnp.flip(x, axis), axis=axis), axis)
    if ctx.attr('exclusive', False):
        out = out - x
    ctx.set_output('Out', out)


@register_kernel('cos_sim')
def _cos_sim(ctx):
    x, y = unwrap(ctx.input('X')), unwrap(ctx.input('Y'))
    xn = jnp.sqrt(jnp.sum(jnp.square(x), -1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(y), -1, keepdims=True))
    xy = jnp.sum(x * y, -1, keepdims=True)
    ctx.set_output('Out', xy / jnp.maximum(xn * yn, 1e-12))
    ctx.set_output('XNorm', xn)
    ctx.set_output('YNorm', yn)


@register_kernel('square_error_cost')
def _square_error_cost(ctx):
    x, y = unwrap(ctx.input('X')), unwrap(ctx.input('Label'))
    ctx.set_output('Out', jnp.square(x - y))


@register_kernel('smooth_l1')
def _smooth_l1(ctx):
    x, y = unwrap(ctx.input('X')), unwrap(ctx.input('Y'))
    sigma = ctx.attr('sigma', 1.0)
    s2 = sigma * sigma
    diff = x - y
    if ctx.has_input('InsideWeight'):
        diff = diff * unwrap(ctx.input('InsideWeight'))
    ad = jnp.abs(diff)
    loss = jnp.where(ad < 1.0 / s2, 0.5 * s2 * diff * diff, ad - 0.5 / s2)
    if ctx.has_input('OutsideWeight'):
        loss = loss * unwrap(ctx.input('OutsideWeight'))
    ctx.set_output('Out', jnp.sum(loss.reshape(loss.shape[0], -1), -1,
                                  keepdims=True))
    if ctx.output_names('Diff'):
        ctx.set_output('Diff', diff)


@register_kernel('l2_normalize')
@register_kernel('norm')
def _l2_normalize(ctx):
    x = unwrap(ctx.input('X'))
    eps = ctx.attr('epsilon', 1e-10)
    if ctx.has_input('Scale'):
        # reference norm_op.cc (SSD cross-channel norm): per spatial
        # position, out = Scale[c] * x / sqrt(sum_c x^2 + eps)
        scale = unwrap(ctx.input('Scale')).reshape(1, -1, 1, 1)
        denom = jnp.sqrt(jnp.sum(jnp.square(x), axis=1,
                                 keepdims=True) + eps)
        ctx.set_output('Out', scale * x / denom)
        return
    axis = ctx.attr('axis', -1)
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True))
    out = x / jnp.maximum(norm, eps)
    ctx.set_output('Out', out)
    if ctx.output_names('Norm'):
        ctx.set_output('Norm', norm)


@register_kernel('iou_similarity')
def _iou_similarity(ctx):
    x, y = unwrap(ctx.input('X')), unwrap(ctx.input('Y'))
    area = lambda b: jnp.maximum(b[..., 2] - b[..., 0], 0) * \
        jnp.maximum(b[..., 3] - b[..., 1], 0)
    xe = x[:, None, :]
    ye = y[None, :, :]
    lt = jnp.maximum(xe[..., :2], ye[..., :2])
    rb = jnp.minimum(xe[..., 2:], ye[..., 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area(xe) + area(ye) - inter
    ctx.set_output('Out', inter / jnp.maximum(union, 1e-10))


@register_kernel('bilinear_tensor_product')
def _bilinear_tensor_product(ctx):
    x, y, w = (unwrap(ctx.input('X')), unwrap(ctx.input('Y')),
               unwrap(ctx.input('Weight')))
    out = jnp.einsum('bi,oij,bj->bo', x, w, y)
    if ctx.has_input('Bias'):
        out = out + unwrap(ctx.input('Bias'))
    ctx.set_output('Out', out)


@register_kernel('conv_shift')
def _conv_shift(ctx):
    x, y = unwrap(ctx.input('X')), unwrap(ctx.input('Y'))
    b, m = x.shape
    n = y.shape[1]
    half = (n - 1) // 2
    idx = (jnp.arange(m)[:, None] + jnp.arange(-half, n - half)[None, :]) % m
    ctx.set_output('Out', jnp.einsum('bmn,bn->bm', x[:, idx], y))
