"""Tensor manipulation / creation kernels.

Parity: paddle/fluid/operators/{fill_constant,assign,cast,concat,split,
reshape,transpose,pad,one_hot,gather,scatter,top_k,uniform_random,
gaussian_random,lookup_table,...}_op.*
"""
import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register_kernel
from ..core.lowering import runtime_dtype
from .common import unwrap, rewrap


@register_kernel('fill_constant')
def _fill_constant(ctx):
    shape = [int(s) for s in ctx.attr('shape', [1])]
    dtype = runtime_dtype(ctx.attr('dtype', 'float32'))
    value = ctx.attr('value', 0.0)
    ctx.set_output('Out', jnp.full(shape, value, dtype=dtype))


@register_kernel('fill_constant_batch_size_like')
def _fill_constant_bsl(ctx):
    ref = unwrap(ctx.input('Input'))
    shape = [int(s) for s in ctx.attr('shape')]
    in_idx = ctx.attr('input_dim_idx', 0)
    out_idx = ctx.attr('output_dim_idx', 0)
    shape[out_idx] = ref.shape[in_idx]
    dtype = runtime_dtype(ctx.attr('dtype', 'float32'))
    ctx.set_output('Out', jnp.full(shape, ctx.attr('value', 0.0),
                                   dtype=dtype))


@register_kernel('fill_zeros_like')
def _fill_zeros_like(ctx):
    x = ctx.input('X')
    ctx.set_output('Out', rewrap(x, jnp.zeros_like(unwrap(x))))


@register_kernel('assign')
def _assign(ctx):
    ctx.set_output('Out', ctx.input('X'))


@register_kernel('assign_value')
def _assign_value(ctx):
    import numpy as np
    shape = ctx.attr('shape')
    dtype = runtime_dtype(ctx.attr('dtype', 'float32'))
    # reference assign_value_op carries the payload in the attr list
    # keyed by dtype (assign_value_op.cc: fp32_values / int32_values)
    values = ctx.attr('values')
    if values is None:
        key = 'int32_values' if np.dtype(dtype).kind in 'iu' \
            else 'fp32_values'
        values = ctx.attr(key)
    ctx.set_output('Out', jnp.asarray(np.array(values), dtype=dtype)
                   .reshape(shape))


@register_kernel('cast')
def _cast(ctx):
    x = ctx.input('X')
    dtype = runtime_dtype(ctx.attr('out_dtype', ctx.out_dtype('Out')))
    ctx.set_output('Out', rewrap(x, unwrap(x).astype(dtype)))


@register_kernel('concat')
def _concat(ctx):
    ins = ctx.inputs('X')
    xs = [unwrap(v) for v in ins]
    axis = ctx.attr('axis', 0)
    from ..lod import SequenceTensor
    seq = next((v for v in ins if isinstance(v, SequenceTensor)), None)
    if seq is not None:
        # fluid axes address the packed [total, D] layout; our runtime is
        # padded [B, T, D], so feature axes (>= 1) shift right by one
        if axis == 0 and all(isinstance(v, SequenceTensor) for v in ins):
            # batch concat: pad every input to the common max T, then
            # stack batches AND their lengths (reference row-concat on
            # the LoD axis keeps per-sequence lengths of every input)
            max_t = max(int(x.shape[1]) for x in xs)
            xs = [jnp.pad(x, [(0, 0), (0, max_t - x.shape[1])] +
                          [(0, 0)] * (x.ndim - 2)) for x in xs]
            out = jnp.concatenate(xs, axis=0)
            lengths = jnp.concatenate(
                [jnp.asarray(v.lengths) for v in ins])
            subs = None
            if all(v.sub_lengths is not None for v in ins):
                # level-2: sub_lengths are [B, padded_outer]; pad to the
                # common outer length and stack batches like the data
                max_o = max(int(v.sub_lengths.shape[1]) for v in ins)
                subs = jnp.concatenate(
                    [jnp.pad(jnp.asarray(v.sub_lengths),
                             [(0, 0), (0, max_o - v.sub_lengths.shape[1])])
                     for v in ins])
            ctx.set_output('Out', SequenceTensor(out, lengths, subs))
            return
        rt_axis = axis + 1 if axis >= 1 else axis
        out = jnp.concatenate(xs, axis=rt_axis)
        ctx.set_output('Out', SequenceTensor(out, seq.lengths,
                                             seq.sub_lengths))
    else:
        ctx.set_output('Out', jnp.concatenate(xs, axis=axis))


@register_kernel('split')
def _split(ctx):
    x = unwrap(ctx.input('X'))
    axis = ctx.attr('axis', 0)
    sections = ctx.attr('sections', None)
    num = ctx.attr('num', 0)
    names = ctx.output_names('Out')
    if sections:
        idx = []
        acc = 0
        for s in sections[:-1]:
            acc += s
            idx.append(acc)
        parts = jnp.split(x, idx, axis=axis)
    else:
        parts = jnp.split(x, num or len(names), axis=axis)
    for i, p in enumerate(parts):
        ctx.set_output('Out', p, idx=i)


@register_kernel('reshape')
def _reshape(ctx):
    x = unwrap(ctx.input('X'))
    if ctx.has_input('Shape'):
        # runtime Shape input (reference reshape_op.cc: wins over the
        # attr). Static-shape design: the value must be concrete at
        # trace time — the Executor binds shape-like feeds statically.
        sval = unwrap(ctx.input('Shape'))
        if isinstance(sval, jax.core.Tracer):
            raise NotImplementedError(
                "reshape(actual_shape=...) needs a trace-time-static "
                "shape; feed the shape tensor directly (the Executor "
                "binds shape-like feeds statically) or pass shape=")
        shape = [int(s) for s in np.asarray(sval).ravel()]
    else:
        shape = list(ctx.attr('shape'))
    # fluid semantics: 0 means copy input dim; -1 infers
    for i, s in enumerate(shape):
        if s == 0:
            shape[i] = x.shape[i]
    ctx.set_output('Out', x.reshape(shape))


@register_kernel('squeeze')
def _squeeze(ctx):
    x = unwrap(ctx.input('X'))
    axes = ctx.attr('axes', None)
    ctx.set_output('Out', jnp.squeeze(x, axis=tuple(axes) if axes else None))


@register_kernel('unsqueeze')
def _unsqueeze(ctx):
    x = unwrap(ctx.input('X'))
    out = x
    for a in sorted(ctx.attr('axes')):
        out = jnp.expand_dims(out, a)
    ctx.set_output('Out', out)


@register_kernel('transpose')
def _transpose(ctx):
    x = unwrap(ctx.input('X'))
    ctx.set_output('Out', jnp.transpose(x, ctx.attr('axis')))


@register_kernel('pad')
def _pad(ctx):
    x = unwrap(ctx.input('X'))
    paddings = ctx.attr('paddings')
    pads = [(paddings[2 * i], paddings[2 * i + 1]) for i in range(x.ndim)]
    ctx.set_output('Out', jnp.pad(x, pads,
                                  constant_values=ctx.attr('pad_value', 0.0)))


@register_kernel('crop')
def _crop(ctx):
    x = unwrap(ctx.input('X'))
    offsets = ctx.attr('offsets')
    shape = ctx.attr('shape')
    slices = tuple(slice(o, o + s) for o, s in zip(offsets, shape))
    ctx.set_output('Out', x[slices])


@register_kernel('one_hot')
def _one_hot(ctx):
    x = unwrap(ctx.input('X'))
    depth = ctx.attr('depth')
    idx = x.reshape(x.shape[:-1]) if x.shape and x.shape[-1] == 1 else x
    ctx.set_output('Out', jax.nn.one_hot(idx, depth, dtype='float32'))


@register_kernel('gather')
def _gather(ctx):
    x = unwrap(ctx.input('X'))
    idx = unwrap(ctx.input('Index')).astype('int32')
    idx = idx.reshape((-1,))
    ctx.set_output('Out', jnp.take(x, idx, axis=0))


@register_kernel('scatter')
def _scatter(ctx):
    x = unwrap(ctx.input('X'))
    idx = unwrap(ctx.input('Ids')).astype('int32').reshape((-1,))
    upd = unwrap(ctx.input('Updates'))
    ctx.set_output('Out', x.at[idx].set(upd))


@register_kernel('top_k')
def _top_k(ctx):
    x = unwrap(ctx.input('X'))
    k = ctx.attr('k', 1)
    vals, idx = jax.lax.top_k(x, k)
    ctx.set_output('Out', vals)
    ctx.set_output('Indices', idx.astype('int32'))


@register_kernel('multiplex')
def _multiplex(ctx):
    ids = unwrap(ctx.input('Ids')).astype('int32').reshape((-1,))
    xs = jnp.stack([unwrap(v) for v in ctx.inputs('X')], axis=0)
    rows = jnp.arange(ids.shape[0])
    ctx.set_output('Out', xs[ids, rows])


@register_kernel('uniform_random')
@register_kernel('uniform_random_batch_size_like')
def _uniform_random(ctx):
    shape = [int(s) for s in ctx.attr('shape')]
    if ctx.op.type.endswith('batch_size_like'):
        ref = unwrap(ctx.input('Input'))
        shape[ctx.attr('output_dim_idx', 0)] = \
            ref.shape[ctx.attr('input_dim_idx', 0)]
    dtype = runtime_dtype(ctx.attr('dtype', 'float32'))
    lo, hi = ctx.attr('min', -1.0), ctx.attr('max', 1.0)
    seed = ctx.attr('seed', 0)
    key = jax.random.PRNGKey(seed) if seed else ctx.next_rng()
    ctx.set_output('Out', jax.random.uniform(key, shape, dtype=dtype,
                                             minval=lo, maxval=hi))


@register_kernel('gaussian_random')
@register_kernel('gaussian_random_batch_size_like')
def _gaussian_random(ctx):
    shape = [int(s) for s in ctx.attr('shape')]
    if ctx.op.type.endswith('batch_size_like'):
        ref = unwrap(ctx.input('Input'))
        shape[ctx.attr('output_dim_idx', 0)] = \
            ref.shape[ctx.attr('input_dim_idx', 0)]
    dtype = runtime_dtype(ctx.attr('dtype', 'float32'))
    mean, std = ctx.attr('mean', 0.0), ctx.attr('std', 1.0)
    seed = ctx.attr('seed', 0)
    key = jax.random.PRNGKey(seed) if seed else ctx.next_rng()
    ctx.set_output('Out', mean + std * jax.random.normal(key, shape,
                                                         dtype=dtype))


@register_kernel('truncated_gaussian_random')
def _truncated_gaussian_random(ctx):
    shape = [int(s) for s in ctx.attr('shape')]
    dtype = runtime_dtype(ctx.attr('dtype', 'float32'))
    mean, std = ctx.attr('mean', 0.0), ctx.attr('std', 1.0)
    seed = ctx.attr('seed', 0)
    key = jax.random.PRNGKey(seed) if seed else ctx.next_rng()
    ctx.set_output('Out', mean + std * jax.random.truncated_normal(
        key, -2.0, 2.0, shape, dtype=dtype))


@register_kernel('lookup_table')
def _lookup_table(ctx):
    """Embedding. Parity: operators/lookup_table_op.* (padding_idx rows
    return zeros). Sequence inputs keep their lengths.

    Sparse path (is_sparse=True, ref lookup_table_op.cc:37): during the
    grad replay a zero 'carrier' with the OUTPUT's shape is added; the
    carrier is a differentiated arg (core/lowering.py), so its gradient
    IS the per-row cotangent and the dense [vocab, d] table gradient is
    never materialized."""
    w = unwrap(ctx.input('W'))
    ids_in = ctx.input('Ids')
    ids = unwrap(ids_in).astype('int32')
    squeeze_last = ids.shape and ids.shape[-1] == 1
    if squeeze_last:
        ids = ids.reshape(ids.shape[:-1])
    padding_idx = ctx.attr('padding_idx', None)
    out = jnp.take(w, jnp.clip(ids, 0, w.shape[0] - 1), axis=0)
    carrier = ctx.attr('sparse_carrier')
    if carrier and carrier in ctx.env:
        # carrier joins BEFORE the padding mask, so the mask's autodiff
        # zeroes padding-row cotangents exactly like the dense path
        out = jax.lax.stop_gradient(out) + ctx.env[carrier]
    if padding_idx is not None and padding_idx >= 0:
        out = jnp.where((ids == padding_idx)[..., None],
                        jnp.zeros_like(out), out)
    ctx.set_output('Out', rewrap(ids_in, out))


@register_kernel('reverse')
def _reverse(ctx):
    x = unwrap(ctx.input('X'))
    axis = ctx.attr('axis')
    axes = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    ctx.set_output('Out', jnp.flip(x, axes))


@register_kernel('increment')
def _increment(ctx):
    x = jnp.asarray(unwrap(ctx.input('X')))
    step = ctx.attr('step', 1.0)
    ctx.set_output('Out', x + jnp.asarray(step).astype(x.dtype))


@register_kernel('is_empty')
def _is_empty(ctx):
    x = unwrap(ctx.input('X'))
    ctx.set_output('Out', jnp.asarray(x.size == 0))


@register_kernel('shape')
def _shape(ctx):
    x = unwrap(ctx.input('Input'))
    ctx.set_output('Out', jnp.asarray(x.shape, dtype='int32'))


@register_kernel('arg_max')
def _arg_max(ctx):
    x = unwrap(ctx.input('X'))
    ctx.set_output('Out', jnp.argmax(x, axis=ctx.attr('axis', -1))
                   .astype('int32'))


@register_kernel('arg_min')
def _arg_min(ctx):
    x = unwrap(ctx.input('X'))
    ctx.set_output('Out', jnp.argmin(x, axis=ctx.attr('axis', -1))
                   .astype('int32'))


@register_kernel('print')
def _print(ctx):
    """Parity: operators/print_op.cc TensorPrint — a real host-side print
    via jax.debug.callback (fires per execution, also under jit).
    print_phase='backward' is accepted but grad printing is not wired:
    the fused-backward design has no per-op grad stream to tap; use a
    fetch on the grad var instead."""
    x = ctx.input('X')
    val = unwrap(x)
    msg = ctx.attr('message', '') or ''
    first_n = int(ctx.attr('first_n', -1) or -1)
    summarize = int(ctx.attr('summarize', -1) or -1)
    show_name = bool(ctx.attr('print_tensor_name', True))
    show_type = bool(ctx.attr('print_tensor_type', True))
    show_shape = bool(ctx.attr('print_tensor_shape', True))
    show_lod = bool(ctx.attr('print_tensor_lod', True))
    phase = str(ctx.attr('print_phase', 'both') or 'both').lower()
    var_name = (ctx.op.inputs.get('X') or ['?'])[0]
    var_name = getattr(var_name, 'name', var_name)
    lengths = getattr(x, 'lengths', None)
    if phase in ('forward', 'both'):
        # counter lives on THIS op instance (first_n is per-op and dies
        # with the program, like the reference op's times_ member)
        count = ctx.op.__dict__.setdefault('_print_count', [0])

        def _emit(arr, lens=None):
            # reference print_op.cc: only a POSITIVE first_n limits
            if first_n > 0 and count[0] >= first_n:
                return
            count[0] += 1
            parts = [msg] if msg else []
            if show_name:
                parts.append("Tensor[%s]" % var_name)
            if show_shape:
                parts.append("shape: %s" % (tuple(arr.shape),))
            if show_type:
                parts.append("dtype: %s" % arr.dtype)
            if show_lod and lens is not None:
                parts.append("lod: %s" % (np.asarray(lens).tolist(),))
            flat = np.asarray(arr).ravel()
            if summarize >= 0:
                flat = flat[:summarize]
            parts.append("data: %s" % np.array2string(flat, threshold=20))
            import sys
            print("  ".join(parts), file=sys.stderr)

        if show_lod and lengths is not None:
            # lengths may itself be traced — route it through the
            # callback like the data
            jax.debug.callback(_emit, val, lengths)
        else:
            jax.debug.callback(_emit, val)
    ctx.set_output('Out', x)


@register_kernel('feed')
@register_kernel('fetch')
def _feed_fetch(ctx):
    ctx.set_output('Out', ctx.input('X'))


@register_kernel('expand')
def _expand(ctx):
    """Parity: paddle/fluid/operators/expand_op.h (tile per dim)."""
    x = ctx.input('X')
    times = [int(t) for t in ctx.attr('expand_times')]
    ctx.set_output('Out', rewrap(x, jnp.tile(unwrap(x), times)))
